"""graftwire: hardened stdlib HTTP/1.1 ingress over ``StereoService``.

ROADMAP item 4's wire protocol, with the same discipline as the rest of
the serving stack — stdlib only (``http.server`` + ``threading``), no new
dependencies, and every hostile-client defense proven by the wire chaos
battery rather than claimed:

- **hard content-length cap before any buffering** (``RAFT_HTTP_BODY_MAX``):
  an oversize declaration is 413 without reading a single body byte, and
  a missing/chunked length is 411 — the ingress never reads an unbounded
  body;
- **bounded, deadline-guarded streaming body read**: the connection
  carries a per-read socket timeout (``RAFT_HTTP_READ_TIMEOUT_MS``; a
  stalled client costs one timeout, not a pinned acceptor thread) AND
  the whole body must land within ``BODY_DEADLINE_FACTOR`` read-timeouts
  — a slow-loris trickling one byte per timeout is evicted at the
  deadline, not at heat death;
- **decode offload**: JPEG/PNG decode — 33 ms/sample at serving shapes
  (BASELINE.md), the host-path cap — runs in a small bounded worker pool
  (the serving twin of the PR 5 ``_Uploader``: overlap host work with
  device work, keep the acceptor thread on socket duty), behind the
  decompression-bomb guard (header-declared pixels vs
  ``RAFT_DECODE_MAX_PIXELS``, rejected before the decoder allocates);
- **per-tenant admission quotas**: a token bucket per ``X-Raft-Tenant``
  (``RAFT_TENANT_RATE`` = ``rate[:burst]`` requests/s), checked on the
  headers BEFORE the body is read — a quota-blown tenant costs the
  server a header parse, not an upload; the tenant map is LRU-bounded so
  hostile tenant-name churn cannot grow memory;
- **honest status mapping** (serve/wire.py): queue_full /
  service_draining are 503 + Retry-After, quota is 429, admission
  rejects are 400 with the existing stable codes, expired deadlines are
  504 — the PR 3 structured response serializes to the wire unchanged;
- **every response is structured JSON**, including the parse-failure
  paths inside ``http.server`` itself (``send_error`` is overridden):
  a header flood is a JSON 431, not an HTML apology;
- **SIGTERM rides the PR 9 drain** (serve_stereo.py ``--http_port``):
  late requests get 503 ``service_draining``, admitted rows run to
  their segment-boundary exits, the listener then stops accepting and
  the process exits 0.

Observability: the frontend shares the service's ONE registry —
``raft_http_responses_total{status=,code=}`` (exactly one increment per
request that reached routing, ``client_disconnect`` included, which is
what lets the chaos storm reconcile counters with wire outcomes),
per-tenant admission counters, body-byte and decode-time instruments —
and every request's trace opens at the wire (``ingress_read`` /
``decode`` spans precede the service's ``admission`` span on the same
timeline).

All four knobs are host-side serving behavior, resolved once at frontend
construction (explicit config > env > default, named-ValueError parsing),
and registered in ``analysis/knobs.py`` ``SERVE_ENV_KNOBS`` with the
stays-out-of-the-fingerprint rationale: none of them shapes a compiled
program.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from raft_stereo_tpu.obs.deck import thread_stacks
from raft_stereo_tpu.obs.tracing import NULL_TRACE
# ONE sanitizer (obs/usage.py) shared by quota keys, usage accounting
# and metric labels — re-exported here under its historical name.
from raft_stereo_tpu.obs.usage import sanitize_tenant  # noqa: F401
from raft_stereo_tpu.serve import wire
from raft_stereo_tpu.serve.supervise import _parse_number

logger = logging.getLogger(__name__)

DEFAULT_HTTP_PORT = 8080
DEFAULT_BODY_MAX = 64 << 20          # 64 MiB: two full-res PNGs + slack
DEFAULT_READ_TIMEOUT_MS = 5_000.0

#: The whole body must arrive within this many read-timeouts: the
#: per-read socket timeout alone only catches a FULLY stalled client — a
#: slow-loris sending one byte per (timeout - epsilon) would hold the
#: acceptor for body_len timeouts without it.
BODY_DEADLINE_FACTOR = 8

#: Streaming body read chunk; bounds the per-read allocation.
READ_CHUNK = 64 << 10

#: Bound on read-and-discard of a rejected request's unread body (the
#: Go stdlib's maxPostHandlerReadBytes idea): closing a socket with
#: unread receive-buffer data emits TCP RST, which can destroy the
#: structured rejection in flight — so rejects drain up to this much
#: declared body first. Bodies larger than this still risk the RST
#: (draining them fully would hand rejected clients unbounded upload
#: bandwidth, the opposite of what the caps are for).
REJECT_DRAIN_MAX = 256 << 10

#: Upper bound on one request's wait for its service response. The
#: service contractually resolves every Future (supervision, PR 9), so
#: this is a last-ditch acceptor-thread guard, not a policy knob.
RESPONSE_WAIT_S = 600.0

#: Wait for a decode-pool slot + decode itself. Decode of an admitted
#: (cap-checked) image is bounded work; this bounds pool-backlog waits.
DECODE_WAIT_S = 60.0

#: JSON codes for responses generated inside http.server's own parsing
#: (our send_error override maps the numeric status to a stable code).
_HTTP_ERROR_CODES = {
    400: "bad_request",
    408: "read_timeout",
    411: "length_required",
    414: "uri_too_long",
    431: "too_many_headers",
    501: "unsupported_method",
    505: "bad_http_version",
}


def resolve_http_port(value: Optional[int] = None) -> int:
    """Effective listen port: explicit config wins (0 = ephemeral, the
    test/bench path), else ``RAFT_HTTP_PORT``, else 8080."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_HTTP_PORT", "").strip()
    if not raw:
        return DEFAULT_HTTP_PORT
    return _parse_number("RAFT_HTTP_PORT", raw, int)


def resolve_body_max(value: Optional[int] = None) -> int:
    """Effective content-length cap in bytes: explicit config wins, else
    ``RAFT_HTTP_BODY_MAX``, else 64 MiB."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_HTTP_BODY_MAX", "").strip()
    if not raw:
        return DEFAULT_BODY_MAX
    return _parse_number("RAFT_HTTP_BODY_MAX", raw, int)


def resolve_read_timeout_ms(value: Optional[float] = None) -> float:
    """Effective per-read socket timeout in ms: explicit config wins,
    else ``RAFT_HTTP_READ_TIMEOUT_MS``, else 5 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_HTTP_READ_TIMEOUT_MS", "").strip()
    if not raw:
        return DEFAULT_READ_TIMEOUT_MS
    return _parse_number("RAFT_HTTP_READ_TIMEOUT_MS", raw, float)


def resolve_tenant_rate(value: Optional[str] = None
                        ) -> Optional[Tuple[float, float]]:
    """Effective per-tenant quota as ``(rate_per_s, burst)``: explicit
    config wins, else ``RAFT_TENANT_RATE``, else None (unlimited — the
    single-operator default; a fleet sets it).  Format ``rate[:burst]``,
    e.g. ``"10"`` or ``"10:20"``; burst defaults to ``max(1, rate)``.
    Malformed values raise a ValueError naming the variable."""
    raw = value if value is not None else \
        os.environ.get("RAFT_TENANT_RATE", "").strip()
    if not raw:
        return None
    rate_s, _, burst_s = str(raw).partition(":")
    rate = _parse_number("RAFT_TENANT_RATE", rate_s.strip(), float)
    if rate <= 0:
        raise ValueError(
            f"RAFT_TENANT_RATE rate must be positive, got {raw!r}")
    burst = (_parse_number("RAFT_TENANT_RATE", burst_s.strip(), float)
             if burst_s.strip() else max(1.0, rate))
    if burst < 1:
        raise ValueError(
            f"RAFT_TENANT_RATE burst must be >= 1, got {raw!r}")
    return rate, burst


@dataclasses.dataclass(frozen=True)
class HttpConfig:
    """Ingress knobs. Every ``None`` resolves env > default at
    construction; all of it is host-side serving behavior — no compiled
    program's bytes depend on any field (the SERVE_ENV_KNOBS rationale).
    """

    host: str = "127.0.0.1"           # bind address; CLI widens to 0.0.0.0
    port: Optional[int] = None        # None -> RAFT_HTTP_PORT; 0 = ephemeral
    body_max: Optional[int] = None    # None -> RAFT_HTTP_BODY_MAX
    read_timeout_ms: Optional[float] = None  # None -> RAFT_HTTP_READ_TIMEOUT_MS
    tenant_rate: Optional[str] = None  # None -> RAFT_TENANT_RATE; "" = off
    decode_workers: int = 2           # decode offload pool width
    decode_max_pixels: Optional[int] = None  # None -> RAFT_DECODE_MAX_PIXELS
    max_tenants: int = 1024           # bound on quota buckets + labels
    max_connections: int = 128        # concurrent-connection cap (handler
    #                                   threads); excess connections get an
    #                                   immediate 503 ``overloaded``


class _TokenBucket:
    """Classic continuous-refill token bucket; caller holds the map lock
    (the bucket itself is plain state, not self-locking)."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t_last = now

    def refill(self, now: float) -> float:
        self.tokens = min(self.burst,
                          self.tokens + max(0.0, now - self.t_last)
                          * self.rate)
        self.t_last = now
        return self.tokens

    def consume(self, now: float) -> bool:
        if self.refill(now) >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class TenantQuotas:
    """Bounded per-tenant token buckets on the wall clock (quota is
    an operational rate, like drain deadlines — a FakeClock session must
    not freeze refill). ``limit=None`` admits everything.

    Two hostile-cardinality defenses, both capped at ``max_tenants``:

    - bucket map: when full, a NEW tenant claims a slot only by
      losslessly evicting a bucket that has refilled to full burst
      (re-creating such a bucket would start full anyway); if every
      tracked bucket still holds spent state, the newcomer shares one
      overflow bucket — so churning fresh tenant names can never reset a
      tracked tenant's spent tokens back to a full burst;
    - metric labels (:meth:`label`): the first ``max_tenants`` distinct
      names keep their own label, later names share ``__other__`` — the
      metrics registry keeps every (name, labels) instrument forever, so
      the label set must be bounded here, quota configured or not.
    """

    OVERFLOW_LABEL = "__other__"

    def __init__(self, limit: Optional[Tuple[float, float]],
                 max_tenants: int = 1024):
        self.limit = limit
        self.max_tenants = max_tenants
        self._buckets: "OrderedDict[str, _TokenBucket]" = OrderedDict()
        self._overflow: Optional[_TokenBucket] = None
        self._labels: set = set()
        self._lock = threading.Lock()

    def label(self, tenant: str) -> str:
        """Metric-safe tenant label: the name itself while the label set
        has room, the shared overflow label after."""
        with self._lock:
            if tenant in self._labels:
                return tenant
            if len(self._labels) < self.max_tenants:
                self._labels.add(tenant)
                return tenant
            return self.OVERFLOW_LABEL

    def _bucket_for(self, tenant: str, rate: float, burst: float,
                    now: float) -> _TokenBucket:
        # Caller holds self._lock.
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            self._buckets.move_to_end(tenant)
            return bucket
        if len(self._buckets) >= self.max_tenants:
            for name, b in self._buckets.items():  # LRU -> MRU order
                if b.refill(now) >= b.burst:
                    del self._buckets[name]
                    break
            else:
                if self._overflow is None:
                    self._overflow = _TokenBucket(rate, burst, now)
                return self._overflow
        bucket = self._buckets[tenant] = _TokenBucket(rate, burst, now)
        return bucket

    def admit(self, tenant: str) -> bool:
        if self.limit is None:
            return True
        rate, burst = self.limit
        now = time.monotonic()
        with self._lock:
            return self._bucket_for(tenant, rate, burst, now).consume(now)

    def would_admit(self, tenant: str) -> bool:
        """Non-consuming peek for the Expect: 100-continue gate: would
        ``admit`` succeed right now? The token is only spent by the real
        ``admit`` once the body arrives (a race between peek and spend
        just means the later real check rejects — never a double
        spend)."""
        if self.limit is None:
            return True
        rate, burst = self.limit
        now = time.monotonic()
        with self._lock:
            return self._bucket_for(tenant, rate, burst,
                                    now).refill(now) >= 1.0

    def status(self) -> Dict:
        with self._lock:
            n = len(self._buckets)
            overflow = self._overflow is not None
        return {"limit": (None if self.limit is None
                          else {"rate_per_s": self.limit[0],
                                "burst": self.limit[1]}),
                "tenants_tracked": n,
                "max_tenants": self.max_tenants,
                "overflow_bucket_active": overflow}


class _IngressHandler(BaseHTTPRequestHandler):
    """One connection's request loop. ``frontend`` and ``timeout`` are
    stamped on the per-frontend subclass (``HttpFrontend`` builds it), so
    the stdlib machinery applies the per-read socket timeout in
    ``setup()`` for free."""

    protocol_version = "HTTP/1.1"
    server_version = "raft-stereo-tpu"
    sys_version = ""
    frontend: "HttpFrontend" = None  # type: ignore[assignment]

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        logger.debug("http %s %s", self.address_string(), fmt % args)

    def _count_response(self, status: int, code: str) -> None:
        self.frontend.registry.counter(
            "raft_http_responses_total",
            "HTTP responses by status and structured code",
            status=str(status), code=code).inc()

    def send_error(self, code, message=None, explain=None):
        """Structured-JSON replacement for the stdlib HTML error page —
        this is also the path http.server's OWN parser failures take
        (header floods -> 431, oversized request lines -> 414), so even
        a request that never reached routing gets a structured body."""
        stable = _HTTP_ERROR_CODES.get(code, f"http_{code}")
        self._send_json(code, {"status": "rejected", "code": stable,
                               "message": message or stable},
                        code_label=stable, close=True)

    def _send_json(self, status: int, doc, code_label: str,
                   close: bool = False,
                   headers: Optional[Dict[str, str]] = None,
                   content_type: str = "application/json",
                   head: bool = False) -> None:
        status = int(status)  # http.server hands HTTPStatus enums to
        #                       send_error; labels must be plain digits
        body = (doc if isinstance(doc, bytes)
                else json.dumps(doc, default=str).encode("utf-8"))
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            if close:
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            if not head:  # HEAD: the GET twin's headers, no body
                self.wfile.write(body)
        except (BrokenPipeError, ConnectionError, socket.timeout,
                TimeoutError, OSError):
            # The client vanished mid-response (the chaos storm's
            # disconnect fault): the request still gets exactly ONE
            # accounting entry, the thread survives, the connection dies.
            self.close_connection = True
            code_label = "client_disconnect"
        self._count_response(status, code_label)

    # -- routing -----------------------------------------------------------

    def _route(self):
        path = self.path.split("?", 1)[0]
        fe = self.frontend
        if self.command != "POST" and self._declares_body():
            # A bodyless-verb request smuggling a body would leave its
            # bytes unread and a keep-alive reuse would parse them as
            # the next request line — two accounting entries for one
            # request. Drain (bounded) and close after answering.
            self._drain_rejected_body()
            self.close_connection = True
        if self.command in ("GET", "HEAD"):
            # HEAD is the header-only GET twin (RFC 9110): LB and
            # uptime probes commonly use HEAD (`curl -I`), and a 405 on
            # /healthz would rotate a healthy instance out.
            head = self.command == "HEAD"
            if path == "/healthz":
                return self._send_json(
                    200, fe.status_doc(), code_label="healthz",
                    head=head)
            if path == "/metrics":
                return self._send_json(
                    200, fe.service.metrics_text().encode("utf-8"),
                    code_label="metrics",
                    content_type="text/plain; version=0.0.4", head=head)
            # Operator plane (graftdeck, DESIGN.md r15): read-only,
            # bounded debug endpoints — same dispatch boundary, same
            # connection caps, same counted accounting as every other
            # route (a debug endpoint is still a hostile-client surface).
            if path == "/debug/ticks":
                return self._send_json(
                    200, fe.debug_ticks_doc(self.path),
                    code_label="debug_ticks", head=head)
            if path == "/debug/usage":
                return self._send_json(
                    200, fe.service.session.usage.doc(),
                    code_label="debug_usage", head=head)
            if path == "/debug/stacks":
                return self._send_json(
                    200, thread_stacks(),
                    code_label="debug_stacks", head=head)
            if path == "/debug/config":
                return self._send_json(
                    200, fe.debug_config_doc(),
                    code_label="debug_config", head=head)
            if head:  # 405/404 bodies would desync strict HEAD framing
                label = ("method_not_allowed" if path == "/v1/stereo"
                         else "unknown_route")
                return self._send_json(
                    405 if path == "/v1/stereo" else 404, b"",
                    code_label=label, close=True, head=True)
            if path == "/v1/stereo":
                return self._reject(405, "method_not_allowed",
                                    "stereo requests are POST")
            return self._reject(404, "unknown_route",
                                f"no route {path!r}")
        if self.command == "POST":
            if path == "/v1/stereo":
                return self._do_stereo()
            if path in ("/healthz", "/metrics") or \
                    path.startswith("/debug/"):
                return self._reject(405, "method_not_allowed",
                                    f"{path} is GET")
            return self._reject(404, "unknown_route", f"no route {path!r}")
        return self._reject(405, "method_not_allowed",
                            f"method {self.command} is not supported")

    #: Body bytes consumed for the CURRENT request (class default covers
    #: paths that never read a body); _read_body advances it so the
    #: reject-path drain knows how much declared body is still unread.
    _body_consumed = 0

    def _dispatch(self):
        """Crash-proof boundary around routing: an unexpected exception
        becomes a 500 and a counted crash, never a dead acceptor thread
        (the wire chaos battery asserts the crash counter stays 0)."""
        self._body_consumed = 0  # keep-alive: reset per request
        try:
            self._route()
        except Exception as e:  # noqa: BLE001 — the acceptor boundary
            self.frontend.registry.counter(
                "raft_http_handler_crashes_total",
                "unexpected exceptions escaping request routing").inc()
            logger.exception("unhandled ingress error")
            self._send_json(500, {"status": "error", "code": "internal",
                                  "message": f"{type(e).__name__}: {e}"},
                            code_label="internal", close=True)

    # EVERY verb routes through _dispatch: the crash-to-structured-500
    # boundary and the per-request _body_consumed reset must cover all
    # of them (a keep-alive connection reuses this handler instance).
    do_GET = do_POST = do_HEAD = _dispatch
    do_PUT = do_DELETE = do_PATCH = _dispatch

    def handle_expect_100(self):
        """A client politely asking before uploading gets every
        header-stage verdict BEFORE a 100 invites a doomed body — the
        SAME gate set ``_do_stereo`` runs (one shared copy, peek mode:
        quota is checked non-consuming and rejects skip the drain, the
        client is still waiting to send)."""
        if (self.command == "POST"
                and self.path.split("?", 1)[0] == "/v1/stereo"
                and self._gate_stereo_headers(peek=True) is None):
            return False
        return super().handle_expect_100()

    def _drain_rejected_body(self) -> None:
        """Read-and-discard (bounded) what remains of a rejected
        request's declared body: closing with unread receive-buffer
        data emits TCP RST, which can destroy the structured response
        before the client reads it. Every read is under the socket
        timeout, so a client that declared a body and sent nothing
        costs at most one timeout."""
        req_headers = getattr(self, "headers", None)
        raw_len = req_headers.get("Content-Length") if req_headers else None
        try:
            declared = int(raw_len)
        except (TypeError, ValueError):
            return
        budget = min(declared - self._body_consumed, REJECT_DRAIN_MAX)
        deadline = time.monotonic() + self.frontend.body_deadline_s
        while budget > 0 and time.monotonic() < deadline:
            try:
                chunk = self.rfile.read1(min(budget, READ_CHUNK))
            except (OSError, ValueError):
                return
            if not chunk:
                return
            budget -= len(chunk)
            # Advance the consumed count: a second drain on the same
            # request (route-level then reject-level) must be a no-op,
            # not a blocking re-read of an empty socket.
            self._body_consumed += len(chunk)

    def _declares_body(self) -> bool:
        """Does the request declare body bytes on the wire? Truthiness
        of the raw header is not enough — ``Content-Length: 0`` is a
        benign bodyless declaration some clients send on every request,
        and treating it as a smuggled body would force a reconnect per
        keep-alive probe."""
        if self.headers.get("Transfer-Encoding"):
            return True
        raw = self.headers.get("Content-Length")
        if raw is None:
            return False
        try:
            return int(raw) > 0
        except ValueError:
            return True  # unparseable: assume bytes may follow

    def _reject(self, status: int, code: str, message: str,
                headers: Optional[Dict[str, str]] = None,
                drain: bool = True) -> None:
        # Wire-level rejections close the connection: the request body
        # (if any) was not necessarily fully consumed, and a keep-alive
        # reuse would parse leftover body bytes as the next request
        # line. The bounded drain first, so small-bodied clients get
        # the structured answer instead of a mid-upload RST.
        if drain:
            self._drain_rejected_body()
        self._send_json(status, {"status": "rejected", "code": code,
                                 "message": message},
                        code_label=code, close=True, headers=headers)

    # -- the stereo POST ---------------------------------------------------

    def _read_body(self, length: int) -> bytes:
        """Bounded, deadline-guarded streaming read. The per-read socket
        timeout (connection-level, from ``setup()``) catches a fully
        stalled client; the total deadline catches the slow-loris that
        stays just under it. Short reads (client closed early) are
        ``truncated_body``."""
        deadline = time.monotonic() + \
            self.frontend.body_deadline_s
        chunks = []
        remaining = length
        while remaining > 0:
            if time.monotonic() >= deadline:
                raise wire.WireRejected(
                    "read_timeout",
                    f"request body did not arrive within "
                    f"{self.frontend.body_deadline_s:.1f}s",
                    http_status=408)
            try:
                # read1, not read: a buffered read(n) loops raw recvs
                # until n bytes arrive, and a client trickling one byte
                # per (timeout - epsilon) would keep a single 64 KiB
                # read alive ~indefinitely without ever tripping the
                # socket timeout OR the deadline check above. read1 does
                # at most ONE raw recv, so the deadline is re-checked at
                # least once per per-read timeout no matter how slowly
                # bytes arrive.
                chunk = self.rfile.read1(min(remaining, READ_CHUNK))
            except (socket.timeout, TimeoutError):
                raise wire.WireRejected(
                    "read_timeout",
                    "socket read stalled past the per-read timeout",
                    http_status=408) from None
            if not chunk:
                raise wire.WireRejected(
                    "truncated_body",
                    f"client closed after {length - remaining} of "
                    f"{length} declared body bytes")
            chunks.append(chunk)
            remaining -= len(chunk)
            self._body_consumed = length - remaining
        return b"".join(chunks)

    def _gate_stereo_headers(self, peek: bool) -> Optional[int]:
        """The header-stage gates for POST /v1/stereo — quota first (a
        blown quota costs the server a header parse, never an upload),
        then chunked 411, Content-Length parse/negative/cap, and the
        media type (an unsupported one must not cost a body_max-sized
        read before its 415; the codec re-checks after the body lands,
        so it stays correct standalone).

        ONE copy shared by both callers so the gate sets cannot drift:
        ``_do_stereo`` (``peek=False``: quota consumes, rejects drain)
        and the ``Expect: 100-continue`` hook (``peek=True``: quota is
        a non-consuming peek — the token is spent by the real check
        once the body arrives — and no drain, the client is still
        waiting to send). Returns the validated Content-Length, or
        ``None`` when a rejection was sent."""
        fe = self.frontend
        drain = not peek
        tenant = sanitize_tenant(self.headers.get("X-Raft-Tenant"))
        ok = (fe.quotas.would_admit(tenant) if peek
              else fe.quotas.admit(tenant))
        if not ok:
            # Counted in the tenant series from BOTH callers: an
            # Expect-gated 429 is still a quota rejection served to
            # that tenant, and curl sends Expect by default for
            # multipart bodies.
            fe.registry.counter(
                "raft_http_tenant_requests_total",
                "stereo requests by tenant and admission outcome",
                tenant=fe.quotas.label(tenant),
                outcome="quota_exceeded").inc()
            self._reject(
                429, "quota_exceeded",
                f"tenant {tenant!r} is over its admission rate",
                headers={"Retry-After":
                         str(wire.RETRY_AFTER_S["quota_exceeded"])},
                drain=drain)
            return None
        if self.headers.get("Transfer-Encoding"):
            self._reject(
                411, "length_required",
                "chunked bodies are not accepted — send Content-Length",
                drain=drain)
            return None
        raw_len = self.headers.get("Content-Length")
        if raw_len is None:
            self._reject(411, "length_required",
                         "POST /v1/stereo requires Content-Length",
                         drain=drain)
            return None
        try:
            length = int(raw_len)
        except ValueError:
            self._reject(
                400, "bad_content_length",
                f"Content-Length must be an integer, got {raw_len!r}",
                drain=drain)
            return None
        if length < 0:
            self._reject(400, "bad_content_length",
                         f"negative Content-Length {length}", drain=drain)
            return None
        if length > fe.body_max:
            self._reject(
                413, "body_too_large",
                f"declared body of {length} bytes exceeds the cap of "
                f"{fe.body_max} (RAFT_HTTP_BODY_MAX)", drain=drain)
            return None
        media, _ = wire.parse_content_type(self.headers.get("Content-Type"))
        if media not in wire.SUPPORTED_MEDIA:
            self._reject(
                415, "unsupported_media_type",
                f"content-type {media or '(none)'!r} is not one of "
                f"{', '.join(wire.SUPPORTED_MEDIA)}", drain=drain)
            return None
        return length

    def _do_stereo(self) -> None:
        fe = self.frontend
        tenant = sanitize_tenant(self.headers.get("X-Raft-Tenant"))

        tenant_label = fe.quotas.label(tenant)

        def tenant_count(outcome: str) -> None:
            fe.registry.counter(
                "raft_http_tenant_requests_total",
                "stereo requests by tenant and admission outcome",
                tenant=tenant_label, outcome=outcome).inc()

        length = self._gate_stereo_headers(peek=False)
        if length is None:
            return

        # Ingress trace: opened at the wire so the read/decode phases
        # join the same timeline the service's admission span lands on.
        trace = fe.service.tracer.start_request(
            self.headers.get("X-Raft-Id"))
        try:
            body = self._read_body(length)
        except wire.WireRejected as e:
            trace.finish(status="rejected", code=e.code)
            # No drain on a read timeout: the client already proved it
            # stalls, a drain attempt would just burn a second timeout
            # before the eviction.
            return self._reject(e.http_status, e.code, str(e),
                                drain=(e.code != "read_timeout"))
        fe.registry.counter(
            "raft_http_body_bytes_total",
            "request body bytes read off the wire").inc(len(body))
        # Per-tenant wire accounting (obs/usage.py): request-body bytes
        # in; the response bytes land below once encoded.
        fe.service.session.usage.add_bytes(
            fe.service.session.usage.label(tenant), n_in=len(body))
        trace.mark("ingress_read", bytes=len(body), tenant=tenant)

        try:
            parsed = wire.parse_stereo_request(
                self.headers.get("Content-Type"), self.headers, body)
        except wire.WireRejected as e:
            trace.finish(status="rejected", code=e.code)
            return self._reject(e.http_status, e.code, str(e))
        if (trace is not NULL_TRACE and trace.request_id is None
                and parsed["id"] is not None):
            # The trace opened at the wire, before the body existed; a
            # body-carried id is backfilled so the ring/sink stays
            # grep-able by request id either way. The disabled-tracing
            # singleton is slotted (assignment would raise), so it is
            # skipped — it records nothing to grep anyway.
            trace.request_id = parsed["id"]

        # Decode offload: the acceptor thread submits and waits; the
        # bounded pool does the pixel work (and the bomb guard runs in
        # the pool behind the header parse, before any allocation). The
        # two images are SEPARATE tasks: one combined task would
        # serialize ~2x33 ms of decode even with an idle worker.
        t0 = time.monotonic()
        try:
            futs = tuple(
                fe.decode_pool.submit(wire.decode_canonical, data, name,
                                      fe.decode_max_pixels)
                for name, data in (("left", parsed["left"]),
                                   ("right", parsed["right"])))
        except RuntimeError:
            # stop() shut the pool down between this handler's body read
            # and its decode submit: a structured late-drain response,
            # not a handler crash.
            trace.finish(status="rejected", code="service_stopped")
            return self._reject(
                503, "service_stopped",
                "ingress stopped before decode could be scheduled",
                headers={"Retry-After": "1"})
        try:
            with trace.span("decode"):
                left, right = (f.result(timeout=DECODE_WAIT_S)
                               for f in futs)
        except wire.WireRejected as e:
            for f in futs:
                f.cancel()
            trace.finish(status="rejected", code=e.code)
            return self._reject(e.http_status, e.code, str(e))
        except FuturesTimeout:
            for f in futs:
                f.cancel()
            trace.finish(status="rejected", code="decode_timeout")
            return self._reject(
                503, "decode_timeout",
                "decode pool backlogged past its wait bound",
                headers={"Retry-After": "1"})
        except Exception as e:  # noqa: BLE001 — hostile-bytes boundary
            for f in futs:
                f.cancel()
            trace.finish(status="rejected", code="bad_image")
            return self._reject(400, "bad_image",
                                f"decode failed: {type(e).__name__}: {e}")
        fe.decode_hist.observe(time.monotonic() - t0)

        # The sanitized tenant key joins the request here and rides it
        # through admission into the scheduler rows — per-tenant device
        # seconds, outcome counters and the /debug/usage rollup all key
        # on it (obs/usage.py).
        request = {"id": parsed["id"], "left": left, "right": right,
                   "tenant": tenant, "_trace": trace}
        if parsed["deadline_ms"] is not None:
            request["deadline_ms"] = parsed["deadline_ms"]
        # graftstream session affinity: keep-alive POSTs carrying one
        # X-Raft-Session value form a stream — consecutive frames land
        # in the same (tenant, session) slot and warm-start.  The id is
        # sanitized by the StreamManager with the same bounded-label
        # discipline as tenants, so hostile session-name churn cannot
        # grow memory or /metrics (the table is LRU+TTL bounded).
        stream_id = self.headers.get("X-Raft-Session")
        if stream_id:
            request["stream"] = stream_id
        if parsed["converge_tol"] is not None:
            request["converge_tol"] = parsed["converge_tol"]
        tenant_count("admitted")
        try:
            resp = fe.service.submit(request).result(
                timeout=RESPONSE_WAIT_S)
        except FuturesTimeout:
            # The service contract (PR 9 supervision) resolves every
            # Future; this bound exists so even a contract violation
            # costs one structured 500, never a permanently pinned
            # acceptor thread. Finish the trace explicitly: the service
            # never saw the Future resolve, so nobody else will record
            # the single most diagnostic timeline in the ring.
            trace.finish(status="error", code="ingress_timeout")
            return self._reject(
                500, "ingress_timeout",
                f"no service response within {RESPONSE_WAIT_S:.0f}s")
        status = wire.http_status_for(resp)
        retry_after = wire.retry_after_for(resp)
        payload = wire.encode_response(resp)
        fe.service.session.usage.add_bytes(
            fe.service.session.usage.label(tenant), n_out=len(payload))
        self._send_json(
            status, payload,
            code_label=("ok" if resp.get("status") == "ok"
                        else str(resp.get("code", "unknown"))),
            headers=({"Retry-After": str(retry_after)}
                     if retry_after is not None else None))


class _IngressServer(ThreadingHTTPServer):
    """Thread-per-connection listener with quiet, counted error
    handling: a client that vanishes mid-parse is routine (counted as a
    disconnect), anything else is a counted crash with a traceback —
    never a silent dead thread.

    Connections are capped by a semaphore (``HttpConfig.max_connections``
    slots, stamped by :class:`HttpFrontend`): every per-connection
    defense (read timeout, body deadline) bounds ONE connection, so
    without an aggregate cap an attacker holding many sockets open just
    inside those deadlines would pin unbounded handler threads. A
    connection over the cap costs one minimal 503 ``overloaded`` write
    on the acceptor thread, never a handler thread."""

    daemon_threads = True
    allow_reuse_address = True
    frontend: "HttpFrontend" = None  # type: ignore[assignment]
    conn_slots: threading.Semaphore = None  # type: ignore[assignment]

    _OVERLOADED_BODY = json.dumps(
        {"status": "rejected", "code": "overloaded",
         "message": "concurrent-connection limit reached"}).encode()
    _OVERLOADED_RESPONSE = (
        b"HTTP/1.1 503 Service Unavailable\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: " + str(len(_OVERLOADED_BODY)).encode() +
        b"\r\nRetry-After: 1\r\nConnection: close\r\n\r\n" +
        _OVERLOADED_BODY)

    def process_request(self, request, client_address):
        if not self.conn_slots.acquire(blocking=False):
            try:
                request.sendall(self._OVERLOADED_RESPONSE)
            except OSError:
                pass
            finally:
                self.frontend.registry.counter(
                    "raft_http_responses_total",
                    "HTTP responses by status and structured code",
                    status="503", code="overloaded").inc()
                self.shutdown_request(request)
            return
        try:
            super().process_request(request, client_address)
        except BaseException:
            self.conn_slots.release()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self.conn_slots.release()

    def handle_error(self, request, client_address):
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, socket.timeout,
                            TimeoutError, BrokenPipeError)):
            logger.debug("connection error from %s: %s",
                         client_address, exc)
            return
        self.frontend.registry.counter(
            "raft_http_handler_crashes_total",
            "unexpected exceptions escaping request routing").inc()
        logger.exception("unhandled error on connection from %s",
                         client_address)


class HttpFrontend:
    """The listener + decode pool + quota state around one
    :class:`~raft_stereo_tpu.serve.service.StereoService`.

    Construction binds the socket (so ``port`` is final — ephemeral
    ``port=0`` included — before :meth:`start` spawns the serve loop);
    ``stop()`` stops accepting, closes the listener and tears down the
    decode pool. Draining is the SERVICE's state (PR 9): call
    ``service.begin_drain()`` / ``service.drain()`` and this frontend
    starts answering 503 ``service_draining`` through the very same
    submit path in-process callers see.
    """

    def __init__(self, service, cfg: Optional[HttpConfig] = None):
        # Function-scope import (GL001-safe): frame_utils imports cv2 at
        # module top, and `import raft_stereo_tpu.serve` must not
        # hard-depend on the image stack.
        from raft_stereo_tpu.data.frame_utils import \
            resolve_decode_max_pixels
        self.service = service
        self.cfg = cfg or HttpConfig()
        self.registry = service.registry
        self.body_max = resolve_body_max(self.cfg.body_max)
        self.read_timeout_s = resolve_read_timeout_ms(
            self.cfg.read_timeout_ms) / 1e3
        self.body_deadline_s = self.read_timeout_s * BODY_DEADLINE_FACTOR
        self.decode_max_pixels = resolve_decode_max_pixels(
            self.cfg.decode_max_pixels)
        self.quotas = TenantQuotas(
            resolve_tenant_rate(self.cfg.tenant_rate),
            max_tenants=self.cfg.max_tenants)
        self.decode_pool = ThreadPoolExecutor(
            max_workers=max(1, self.cfg.decode_workers),
            thread_name_prefix="stereo-decode")
        self.decode_hist = self.registry.histogram(
            "raft_http_decode_seconds",
            "offloaded image-decode latency (bounded reservoir)",
            reservoir=512)
        handler = type("BoundIngressHandler", (_IngressHandler,), {
            "frontend": self,
            "timeout": self.read_timeout_s,  # per-read socket timeout
        })
        self._server = _IngressServer(
            (self.cfg.host, resolve_http_port(self.cfg.port)), handler)
        self._server.frontend = self
        self._server.conn_slots = threading.Semaphore(
            max(1, self.cfg.max_connections))
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def host(self) -> str:
        return self._server.server_address[0]

    def start(self) -> "HttpFrontend":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="stereo-http-listener", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting (the drain contract's final step), close the
        listening socket, tear down the decode pool. In-flight handler
        threads finish their current responses (a handler losing the
        race to the pool shutdown gets a structured 503
        ``service_stopped``, never a crash)."""
        t = self._thread
        if t is not None:
            # BaseServer.shutdown() blocks on an event only
            # serve_forever() sets — calling it when start() never ran
            # (e.g. an embedder's finally between construction and
            # start) would deadlock forever.
            self._server.shutdown()
        self._server.server_close()
        self.decode_pool.shutdown(wait=False)
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "HttpFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _ingress_block(self) -> Dict:
        return {
            "endpoint": f"{self.host}:{self.port}",
            "body_max_bytes": self.body_max,
            "read_timeout_ms": self.read_timeout_s * 1e3,
            "body_deadline_ms": self.body_deadline_s * 1e3,
            "decode_workers": self.cfg.decode_workers,
            "decode_max_pixels": self.decode_max_pixels,
            "max_connections": self.cfg.max_connections,
            "quota": self.quotas.status(),
        }

    def status_doc(self) -> Dict:
        """The /healthz body: the service's own status document plus the
        ingress block (the wire-side numbers an operator tunes)."""
        doc = self.service.status()
        doc["ingress"] = self._ingress_block()
        return doc

    # -- operator-plane debug endpoints (graftdeck, DESIGN.md r15) ---------

    def debug_ticks_doc(self, raw_path: str = "") -> Dict:
        """GET /debug/ticks: the tick flight-deck ring (bounded by the
        ring size; ``?n=<k>`` bounds it further)."""
        from urllib.parse import parse_qs
        n = None
        query = raw_path.partition("?")[2]
        if query:
            raw_n = (parse_qs(query, keep_blank_values=False)
                     .get("n", [None])[0])
            if raw_n is not None:
                try:
                    n = max(1, int(raw_n))
                except ValueError:
                    n = None  # a hostile ?n= is ignored, never a 500
        return self.service.session.deck.doc(n)

    def debug_config_doc(self) -> Dict:
        """GET /debug/config: the resolved-knob snapshot an operator
        diffs against what they THINK is deployed — session + service +
        ingress config, fingerprint, breaker trips, batch-bucket
        ladder, program-cache contents.  Read-only and bounded."""
        svc = self.service
        doc = svc.session.config_doc()
        doc["schema"] = 1
        doc["service_cfg"] = dataclasses.asdict(svc.cfg)
        doc["ingress"] = self._ingress_block()
        return doc

"""graftstream — long-lived stereo stream sessions (ROADMAP item 2).

Every frame of a video previously paid all ``valid_iters`` cold
refinement iterations even though the reference forward has always taken
``flow_init`` (RAFT-Stereo's warm-start tracking mode).  This module
turns the engine into a realtime tracking service:

- **warm-start sessions**: a client stamps consecutive frames with one
  ``X-Raft-Session`` header (wire) or ``request["stream"]`` (in-process);
  each served frame's 1/8-res disparity is held HOST-side in a bounded
  session table and seeds the next frame's ``coords1`` through the
  ``prepare_warm`` program (serve/session.py ``build_program``) — a
  separate program kind with its own cache/ledger rows and warmup entry,
  because the flow operand makes it a different traced program (the PR 3
  stale-program lesson).  The seed is x-only with a zero y channel baked
  into the program, which preserves the ``flow_y == 0`` invariant the
  fused motion encoder relies on — so warm carries ride the SAME advance
  and epilogue programs as cold rows and can share their device batches;

- **convergence early exit**: the batched advance program returns a
  per-row delta-flow norm (segment-mean ``|delta_x|`` per iteration)
  alongside its coords-sum completion barrier; at segment boundaries a
  row whose norm fell below the request's tolerance exits through the
  normal batched epilogue with the honest quality label
  ``converged:<iters actually run>``.  The tolerance is compared on the
  HOST, so ``RAFT_CONVERGE_TOL`` never shapes a compiled program and
  stays out of the program fingerprint (the knob registry's rationale);

- **bounded session table**: global LRU cap (``RAFT_STREAM_SESSIONS``),
  per-tenant cap riding the PR 10/12 tenant machinery (hostile session-id
  churn cannot grow host memory or ``/metrics`` labels — session ids are
  sanitized with the same bounded-label discipline as tenants), TTL
  expiry on the session clock (``RAFT_STREAM_TTL_MS``), sessions die on
  service stop/drain, and a deposit landing after its session expired is
  a counted drop, never a resurrection;

- **supervision-compatible**: the held ``flow_init`` rides the request
  dict, so a PR 9 generation bounce harvests and re-admits warm rows
  WITH their seed (chaos-pinned) — a bounced stream stays warm.

Memory bound: one session holds one ``(1, H/8, W/8, 1)`` float32 field —
~196 KiB at full resolution (2016x2976), so the default 128-session cap
bounds the table at ~25 MiB host RAM worst-case.

The sequential (``max_batch == 1``) twin of the scheduler's warm path is
:func:`stream_infer`: prepare/prepare_warm + advance segments + epilogue
on the b=1 programs, with the same convergence/deadline exits — used by
the worker-pool service mode and ``demo.py --video``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.obs.usage import sanitize_tenant
from raft_stereo_tpu.serve.degrade import SAFETY
from raft_stereo_tpu.serve.guard import is_kernel_failure
from raft_stereo_tpu.serve.session import (InferenceFailed, InferenceResult,
                                           SessionError)
# ONE named-ValueError parser for env knobs (the SLURM_CPUS_PER_TASK
# convention) — shared with the supervision/http knob resolvers; the
# ``os.environ`` reads stay LITERAL at each resolve_* site below so
# GL002's registry cross-check can see them.
from raft_stereo_tpu.serve.supervise import _parse_number

import logging

logger = logging.getLogger(__name__)

#: Bounded session table default: covers a realistic rig fleet while
#: bounding worst-case host memory at ~25 MiB of held flow fields.
DEFAULT_STREAM_SESSIONS = 128

#: Idle sessions expire after this long (session clock): a camera that
#: went away must not pin its slot until eviction pressure arrives.
DEFAULT_STREAM_TTL_MS = 60_000.0

#: Default convergence tolerance for warm frames: segment-mean
#: per-iteration |delta_x| at 1/8 res, in pixels.  0 disables the early
#: exit (the norm is >= 0, and the comparison is strict <).
DEFAULT_CONVERGE_TOL = 0.01


def resolve_stream_sessions(value: Optional[int] = None) -> int:
    """Effective global session-table cap: explicit config wins, else
    ``RAFT_STREAM_SESSIONS``, else 128.  Host-side table sizing only —
    no compiled program depends on it (HOST_ENV_KNOBS rationale)."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_STREAM_SESSIONS", "").strip()
    if not raw:
        return DEFAULT_STREAM_SESSIONS
    n = _parse_number("RAFT_STREAM_SESSIONS", raw, int)
    if n < 1:
        raise ValueError(f"RAFT_STREAM_SESSIONS must be >= 1, got {n}")
    return n


def resolve_stream_ttl_ms(value: Optional[float] = None) -> float:
    """Effective idle-session TTL in ms: explicit config wins, else
    ``RAFT_STREAM_TTL_MS``, else 60 s."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_STREAM_TTL_MS", "").strip()
    if not raw:
        return DEFAULT_STREAM_TTL_MS
    ttl = _parse_number("RAFT_STREAM_TTL_MS", raw, float)
    if ttl <= 0:
        raise ValueError(f"RAFT_STREAM_TTL_MS must be > 0, got {ttl}")
    return ttl


def resolve_converge_tol(value: Optional[float] = None) -> float:
    """Effective warm-frame convergence tolerance: explicit config wins,
    else ``RAFT_CONVERGE_TOL``, else 0.01 px/iter.  The tolerance is a
    HOST-side comparison against the norm the advance program already
    returns — it never changes the advance program, which is exactly why
    it lives in HOST_ENV_KNOBS and not in the program fingerprint (had
    the monitor been compiled against the tolerance, it would have to be
    part of the program key instead)."""
    if value is not None:
        return float(value)
    raw = os.environ.get("RAFT_CONVERGE_TOL", "").strip()
    if not raw:
        return DEFAULT_CONVERGE_TOL
    tol = _parse_number("RAFT_CONVERGE_TOL", raw, float)
    if tol < 0:
        raise ValueError(f"RAFT_CONVERGE_TOL must be >= 0, got {tol}")
    return tol


class StreamSession:
    """One live stream's host-side state.  Mutated only under the
    manager's lock; the held flow array itself is treated as immutable
    once deposited (requests read it, never write it)."""

    __slots__ = ("key", "tenant", "flow", "padded_shape", "frames",
                 "warm_frames", "created", "last_seen", "chip")

    def __init__(self, key: Tuple[str, str], now: float,
                 chip: Optional[int] = None):
        self.key = key
        self.tenant = key[0]
        self.flow: Optional[np.ndarray] = None   # (1, H/f, W/f, 1) fp32
        self.padded_shape: Optional[Tuple[int, int]] = None
        self.frames = 0
        self.warm_frames = 0
        self.created = now
        self.last_seen = now
        # graftpod chip affinity: on a live mesh the session is pinned
        # to one data-shard ordinal (round-robin at creation) so its
        # frames keep landing on the same chip's rows; None on a
        # single-device service.  Host-side placement hint only — the
        # held flow is host memory, so a migrate (chip quarantined)
        # keeps the stream warm.
        self.chip = chip


class StreamManager:
    """Bounded (LRU + TTL + per-tenant caps) session table + the request
    stamping/deposit protocol the service drives.

    Protocol (all on the request dict, so bounces/retries carry it for
    free):

    - :meth:`admit` (service admission, both entry points): resolves the
      session for ``request["stream"]``, stamps ``request["_stream"]``
      (the table key) and — when the held flow matches this frame's
      padded bucket — ``request["_flow_init"]`` + ``_converge_tol``;
    - the serving path (scheduler or :func:`stream_infer`) attaches the
      exiting row's low-res flow as ``request["_stream_flow"]`` /
      ``request["_stream_shape"]``;
    - :meth:`deposit` (response resolution, BEFORE the Future resolves,
      so a client that waits for frame N's response and then posts frame
      N+1 is guaranteed a warm join) stores it back into the session —
      or counts a drop when the session expired/evicted mid-flight.
    """

    def __init__(self, session, *, registry=None,
                 max_sessions: Optional[int] = None,
                 ttl_ms: Optional[float] = None,
                 converge_tol: Optional[float] = None,
                 per_tenant: Optional[int] = None):
        self.session = session
        self.registry = registry if registry is not None else \
            session.registry
        self.max_sessions = resolve_stream_sessions(max_sessions)
        self.ttl_s = resolve_stream_ttl_ms(ttl_ms) / 1e3
        self.converge_tol = resolve_converge_tol(converge_tol)
        # Per-tenant cap rides the tenant machinery: one hostile tenant
        # cannot occupy the whole table.  An eighth of the global cap
        # (>= 1) mirrors the quota stance — generous for a real rig,
        # bounding for an adversary.
        self.per_tenant = (int(per_tenant) if per_tenant is not None
                           else max(1, self.max_sessions // 8))
        self._lock = threading.Lock()
        self._table: "OrderedDict[Tuple[str, str], StreamSession]" = \
            OrderedDict()
        self._per_tenant: Dict[str, int] = {}
        # graftpod: round-robin cursor for chip-affinity placement of
        # new sessions (mutated under self._lock with the table).
        self._rr_chip = 0
        reg = self.registry
        self._g_sessions = reg.gauge(
            "raft_stream_sessions", "live stream sessions (LRU+TTL "
            "bounded table)")
        self._c_created = reg.counter(
            "raft_stream_sessions_created_total", "stream sessions created")
        self._c_evicted = reg.counter(
            "raft_stream_sessions_evicted_total",
            "stream sessions evicted (global or per-tenant cap)")
        self._c_expired = reg.counter(
            "raft_stream_sessions_expired_total",
            "stream sessions expired by TTL")
        self._c_dropped = reg.counter(
            "raft_stream_deposits_dropped_total",
            "flow deposits dropped because the session expired/evicted "
            "mid-flight")
        self._c_warm = reg.counter(
            "raft_stream_warm_joins_total",
            "frames that actually warm-started (prepare_warm ran)")
        self._c_converged = reg.counter(
            "raft_stream_converged_total",
            "rows that exited early through the convergence monitor")

    # -- table maintenance (caller holds self._lock) -----------------------

    def _drop_locked(self, key: Tuple[str, str]) -> None:
        sess = self._table.pop(key, None)
        if sess is None:
            return
        n = self._per_tenant.get(sess.tenant, 1) - 1
        if n <= 0:
            self._per_tenant.pop(sess.tenant, None)
        else:
            self._per_tenant[sess.tenant] = n

    def _sweep_locked(self, now: float) -> None:
        expired = [k for k, s in self._table.items()
                   if now - s.last_seen > self.ttl_s]
        for k in expired:
            self._drop_locked(k)
        if expired:
            self._c_expired.inc(len(expired))

    def _create_locked(self, key: Tuple[str, str], now: float
                       ) -> StreamSession:
        tenant = key[0]
        # Per-tenant cap first (a tenant at its own cap must not push
        # OTHER tenants' sessions out), then the global cap: both evict
        # the victim population's least-recently-used session.  Eviction
        # is bounded-loss by design — the stream just goes cold for one
        # frame — and counted, never silent.
        if self._per_tenant.get(tenant, 0) >= self.per_tenant:
            victim = next((k for k, s in self._table.items()
                           if s.tenant == tenant), None)
            if victim is not None:
                self._drop_locked(victim)
                self._c_evicted.inc()
        while len(self._table) >= self.max_sessions:
            victim = next(iter(self._table))
            self._drop_locked(victim)
            self._c_evicted.inc()
        # Chip-affinity placement (graftpod): spread new sessions round-
        # robin over the mesh's data shards; None off-mesh so the stamp
        # stays absent on a single-device service.
        chip = None
        n_chips = getattr(self.session, "mesh_chips", 1)
        if getattr(self.session, "mesh_active", False) and n_chips > 1:
            chip = self._rr_chip % n_chips
            self._rr_chip += 1
        sess = self._table[key] = StreamSession(key, now, chip=chip)
        self._per_tenant[tenant] = self._per_tenant.get(tenant, 0) + 1
        self._c_created.inc()
        return sess

    def _touch_locked(self, key: Tuple[str, str], now: float) -> StreamSession:
        # Caller holds self._lock (like every mutator here: the table
        # and the per-tenant counts are mutated ONLY in these lock-held
        # helpers).
        sess = self._table.get(key)
        if sess is None:
            return self._create_locked(key, now)
        self._table.move_to_end(key)
        return sess

    def _clear_locked(self) -> int:
        # Caller holds self._lock.
        n = len(self._table)
        self._table.clear()
        self._per_tenant.clear()
        return n

    def _migrate_locked(self, bad: set, n_chips: int) -> int:
        # Caller holds self._lock.  Reassign sessions pinned to a bad
        # chip — or to an ordinal past the shrunken mesh — round-robin
        # over the survivors (None when the mesh is 1-wide).
        migrated = 0
        for sess in self._table.values():
            if sess.chip is None:
                continue
            if sess.chip in bad or sess.chip >= max(1, n_chips):
                if n_chips > 1:
                    nxt = self._rr_chip % n_chips
                    self._rr_chip += 1
                    sess.chip = nxt
                else:
                    sess.chip = None
                migrated += 1
        return migrated

    def _repin_unplaced_locked(self, n_chips: int) -> int:
        # Caller holds self._lock.
        repinned = 0
        for sess in self._table.values():
            if sess.chip is not None:
                continue
            sess.chip = self._rr_chip % n_chips
            self._rr_chip += 1
            repinned += 1
        return repinned

    # -- the request protocol ----------------------------------------------

    def admit(self, request: Dict) -> None:
        """Stamp one validated request (arrays already canonical).  A
        request without ``stream`` passes through untouched except for
        normalizing an explicit ``converge_tol`` field — any request may
        opt into the convergence early exit without a session (the bench
        measures cold iterations-to-convergence this way)."""
        if request.get("_converge_tol") is None and \
                request.get("converge_tol") is not None:
            tol = float(request["converge_tol"])
            if not (tol >= 0) or not np.isfinite(tol):
                tol = 0.0
            request["_converge_tol"] = tol
        sid = request.get("stream")
        if sid is None:
            return
        key = (sanitize_tenant(request.get("tenant")),
               sanitize_tenant(str(sid)))
        now = self.session.clock.now()
        padded = self.session.padder_for(
            request["left"].shape).padded_shape
        with self._lock:
            self._sweep_locked(now)
            sess = self._touch_locked(key, now)
            sess.last_seen = now
            sess.frames += 1
            request["_stream"] = key
            if sess.chip is not None:
                # graftpod: the scheduler's join phase stable-sorts by
                # this stamp so same-chip stream rows pack together in
                # the pod-wide batch (adjacent rows share a data shard).
                request["_chip"] = sess.chip
            if sess.flow is not None and sess.padded_shape == padded:
                # Warm frame: hand out the held seed.  A shape change
                # (client reconfigured the camera) goes cold — the held
                # field is for a different compiled bucket.
                sess.warm_frames += 1
                request["_flow_init"] = sess.flow
                if request.get("_converge_tol") is None:
                    request["_converge_tol"] = self.converge_tol
            self._g_sessions.set(len(self._table))

    def deposit(self, request: Dict, resp: Dict) -> None:
        """Store a served frame's low-res flow back into its session.
        Runs on the response-resolution path for BOTH serving modes and
        must never raise."""
        key = request.get("_stream")
        flow = request.pop("_stream_flow", None)
        shape = request.pop("_stream_shape", None)
        if key is None or resp.get("status") != "ok" or flow is None:
            return
        now = self.session.clock.now()
        with self._lock:
            self._sweep_locked(now)
            # The sweep may have dropped sessions: refresh the gauge
            # here too, or it reads stale-high until the next admit.
            self._g_sessions.set(len(self._table))
            sess = self._table.get(key)
            if sess is None:
                # TTL expired (or the slot was evicted) while this frame
                # was in flight: the deposit is dropped, counted — the
                # next frame of that stream simply starts cold.
                self._c_dropped.inc()
                return
            sess.flow = np.asarray(flow, dtype=np.float32)
            sess.padded_shape = tuple(shape) if shape is not None else None
            sess.last_seen = now

    # -- serving-path accounting -------------------------------------------

    def note_warm_join(self, tenant_label: str) -> None:
        """One frame actually warm-started (its prepare_warm ran on the
        device).  Counted where it happens — scheduler join or the
        sequential runner — not at stamping (an upload failure between
        stamping and join must not inflate the count)."""
        self._c_warm.inc()
        self.session.usage.note_stream(tenant_label, warm_join=True)

    def note_converged(self, tenant_label: str) -> None:
        """One row exited early through the convergence monitor."""
        self._c_converged.inc()
        self.session.usage.note_stream(tenant_label, converged=True)

    def migrate_off_chips(self, quarantined, n_chips: int) -> int:
        """graftpod migrate-on-bounce: reassign every session pinned to a
        quarantined chip — or to an ordinal past the shrunken mesh — onto
        a surviving data shard (round-robin).  The held flow is HOST
        memory, so a migrated stream stays warm: its next frame rides
        prepare_warm on the new chip (pinned in tests/test_mesh_serve.py).
        Returns the number of sessions migrated."""
        with self._lock:
            migrated = self._migrate_locked(
                set(int(c) for c in quarantined), int(n_chips))
        if migrated:
            self.registry.counter(
                "raft_stream_migrations_total",
                "stream sessions migrated off quarantined chips"
            ).inc(migrated)
        return migrated

    def repin_unplaced(self, n_chips: int) -> int:
        """graftheal re-place-on-grow: the migration seam in reverse.  A
        mesh shrink to one chip parks sessions off-mesh (``chip=None``,
        ``_migrate_locked``); when a re-admitted chip re-grows the mesh,
        those parked sessions re-pin round-robin over the new extent so
        the scheduler's same-chip packing works again.  Their held seeds
        are HOST memory, so re-placed streams come back WARM — the next
        frame rides prepare_warm on its new chip.  Returns the number of
        sessions re-pinned (0 when the mesh is still 1-wide)."""
        n_chips = int(n_chips)
        if n_chips <= 1:
            return 0
        with self._lock:
            repinned = self._repin_unplaced_locked(n_chips)
        if repinned:
            self.registry.counter(
                "raft_stream_migrations_total",
                "stream sessions migrated off quarantined chips"
            ).inc(repinned)
        return repinned

    # -- lifecycle ---------------------------------------------------------

    def drop_all(self) -> int:
        """Service stop/drain: every session dies cleanly (held flow
        freed, gauge zeroed).  In-flight deposits after this land as
        counted drops."""
        with self._lock:
            n = self._clear_locked()
            self._g_sessions.set(0)
        return n

    # -- reporting ---------------------------------------------------------

    def status(self) -> Dict:
        """The /healthz ``stream`` block — bounded by construction."""
        with self._lock:
            per_tenant = dict(sorted(self._per_tenant.items()))
            n = len(self._table)
            by_chip: Dict[str, int] = {}
            for sess in self._table.values():
                if sess.chip is not None:
                    k = str(sess.chip)
                    by_chip[k] = by_chip.get(k, 0) + 1
        return {
            "sessions": n,
            # graftpod: live sessions per pinned data shard (empty on a
            # single-device service — no fabricated zeros).
            "by_chip": by_chip,
            "max_sessions": self.max_sessions,
            "per_tenant_cap": self.per_tenant,
            "per_tenant": per_tenant,
            "ttl_ms": self.ttl_s * 1e3,
            "converge_tol": self.converge_tol,
            "created": int(self._c_created.value),
            "evicted": int(self._c_evicted.value),
            "expired": int(self._c_expired.value),
            "deposits_dropped": int(self._c_dropped.value),
            "warm_joins": int(self._c_warm.value),
            "converged_exits": int(self._c_converged.value),
        }


# ---------------------------------------------------------------------------
# Sequential streaming inference: the worker-pool / demo twin of the
# scheduler's warm path, on the b=1 prepare[_warm]/advance/epilogue
# programs.  Composition is bit-identical to the "full" single-scan
# program when no early exit fires (the PR 3/5 segment-composition pins),
# which is what makes a stream's first frame byte-identical to the
# stateless serving path.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StreamOutcome:
    """One served stream frame + the seed for the next one."""

    result: InferenceResult
    flow_low: np.ndarray            # (1, H/f, W/f, 1) fp32, padded bucket
    padded_shape: Tuple[int, int]
    warm: bool = False              # the prepare_warm program ran


def _flow_matches(flow_init: Optional[np.ndarray], session,
                  ph: int, pw: int) -> bool:
    if flow_init is None:
        return False
    factor = session._run_cfg.downsample_factor
    return tuple(flow_init.shape) == (1, ph // factor, pw // factor, 1)


def _attempt(session, padder, left, right, *, flow_init, converge_tol,
             deadline, trace):
    """One ladder attempt of the segmented stream loop.  Returns
    ``(flow_up_padded, flow_low, quality, iters_done, warm)``."""
    clock = session.clock
    segments = session.cfg.segments
    m = session.cfg.valid_iters // segments
    ph, pw = padder.padded_shape
    lp, rp = padder.pad_np(left, right)

    warm = _flow_matches(flow_init, session, ph, pw)
    if warm:
        prep = session.get_program("prepare_warm", ph, pw, 0)
        (state,) = session.invoke(
            prep, lp, rp, np.asarray(flow_init, np.float32), trace=trace)
    else:
        prep = session.get_program("prepare", ph, pw, 0)
        (state,) = session.invoke(prep, lp, rp, trace=trace)
    adv = session.get_program("advance", ph, pw, m)

    done = 0
    converged = False
    reduced = False
    for _ in range(segments):
        if done:  # a best-so-far exists: deadline checks mirror degrade
            now = clock.now()
            est = session.estimate(adv.key)
            if deadline is not None and (
                    now >= deadline
                    or (est is not None
                        and now + est * SAFETY > deadline)):
                reduced = True
                trace.event("degrade", label=f"reduced_iters:{done}",
                            reason=("deadline_expired" if now >= deadline
                                    else "predicted_overshoot"))
                break
        state, _rowsum, dnorm = session.invoke(adv, state, trace=trace)
        done += m
        if converge_tol is not None and done < session.cfg.valid_iters \
                and float(dnorm[0]) < converge_tol:
            converged = True
            trace.event("converged", label=f"converged:{done}",
                        norm=float(dnorm[0]), tol=converge_tol)
            break
    epi = session.get_program("epilogue", ph, pw, 0)
    flow_up, flow_low = session.invoke(epi, state, trace=trace)
    if done >= session.cfg.valid_iters:
        quality = "full"
    elif converged:
        quality = f"converged:{done}"
    else:  # the only other early exit is the deadline path
        assert reduced, "early exit with neither converged nor reduced"
        quality = f"reduced_iters:{done}"
    return flow_up, flow_low, quality, done, warm


def stream_infer(session, left, right, *,
                 flow_init: Optional[np.ndarray] = None,
                 converge_tol: Optional[float] = None,
                 deadline: Optional[float] = None,
                 prevalidated: bool = False,
                 trace=NULL_TRACE) -> StreamOutcome:
    """Serve one stream frame sequentially (b=1 programs).

    Mirrors ``InferenceSession.infer``'s contract — breaker-ladder
    retries, output validation, honest quality labels, session counters —
    but runs the segmented prepare[_warm]/advance/epilogue composition so
    warm starts and convergence exits engage.  ``flow_init`` must be the
    padded-bucket low-res field a previous :class:`StreamOutcome` carried
    (shape mismatch = cold start, never an error).
    """
    from raft_stereo_tpu.serve.validate import validate_pair

    t_start = session.clock.now()
    try:
        if not prevalidated:
            left, right = validate_pair(left, right, session.cfg.admission)
        orig_h, orig_w = left.shape[1], left.shape[2]
        padder = session.padder_for(left.shape)

        last_exc: Optional[Exception] = None
        for _ in range(len(session.breaker.ladder) + 1):
            try:
                flow_up, flow_low, quality, done, warm = _attempt(
                    session, padder, left, right, flow_init=flow_init,
                    converge_tol=converge_tol, deadline=deadline,
                    trace=trace)
                break
            except Exception as e:  # noqa: BLE001 — filtered just below
                if isinstance(e, SessionError) or not is_kernel_failure(e):
                    raise
                last_exc = e
                session._breaker_retry(
                    e, getattr(e, "_raft_phase", "runtime_failure"),
                    traces=(trace,))
                padder = session.padder_for(left.shape)
                continue
        else:
            raise InferenceFailed(
                "ladder_exhausted",
                f"breaker retries exhausted: {last_exc}") from last_exc

        with trace.span("unpad"):
            disparity = session._finish(flow_up, padder, quality,
                                        orig_h, orig_w)
        elapsed = session.clock.now() - t_start
        session.count_request(ok=True, degraded=quality != "full")
        result = InferenceResult(
            disparity=disparity, quality=quality, iters=done,
            elapsed_s=elapsed, padded_shape=padder.padded_shape,
            deadline_missed=(deadline is not None
                             and session.clock.now() > deadline))
        return StreamOutcome(result=result,
                             flow_low=np.asarray(flow_low, np.float32),
                             padded_shape=padder.padded_shape, warm=warm)
    except Exception as e:
        session.count_request(
            ok=False,
            nonfinite=(isinstance(e, InferenceFailed)
                       and e.code == "nonfinite_output"))
        raise


class StreamRunner:
    """In-process stream driver over one :class:`InferenceSession` —
    what ``demo.py --video`` and ``scratch/bench_stream.py`` run.  Holds
    exactly one stream's state (the previous frame's low-res flow) and
    feeds each frame through :func:`stream_infer`.

    The first frame (no held flow) runs the cold segmented composition
    at full ``valid_iters`` with no convergence exit unless
    ``converge_cold`` opts in — byte-identical to the stateless
    single-pair path (pinned in tests/test_stream.py).
    """

    def __init__(self, session, *, converge_tol: Optional[float] = None,
                 converge_cold: bool = False):
        self.session = session
        self.converge_tol = resolve_converge_tol(converge_tol)
        self.converge_cold = converge_cold
        self._flow: Optional[np.ndarray] = None
        self._shape: Optional[Tuple[int, int]] = None
        self.frames = 0
        self.warm_frames = 0

    def reset(self) -> None:
        self._flow = None
        self._shape = None

    def infer(self, left, right, *, deadline=None,
              trace=NULL_TRACE) -> InferenceResult:
        padded = self.session.padder_for(
            np.asarray(left).shape).padded_shape
        flow = self._flow if self._shape == padded else None
        warm = flow is not None
        tol = (self.converge_tol
               if (warm or self.converge_cold) else None)
        out = stream_infer(self.session, left, right, flow_init=flow,
                           converge_tol=tol, deadline=deadline,
                           trace=trace)
        self._flow = out.flow_low
        self._shape = out.padded_shape
        self.frames += 1
        if warm:
            self.warm_frames += 1
        return out.result

"""Resilient inference subsystem (DESIGN.md "Serving & degradation").

Layers, bottom up:

- ``validate``  — admission control: structured rejection of malformed
                  requests before they touch a device;
- ``guard``     — kernel circuit breaker: risky fast paths declared with
                  their XLA fallbacks; trips degrade the session one rung
                  instead of killing the process;
- ``session``   — shape-bucketed compile cache + output validation +
                  breaker-driven rebuild/retry; the unit that owns params;
- ``degrade``   — deadline-aware anytime policy over the segmented
                  refinement scan (``models.raft_stereo_segment``);
- ``scheduler`` — iteration-level continuous batching: requests join a
                  running device batch at tick boundaries, exit at
                  segment boundaries (``SessionConfig.max_batch > 1``);
- ``stream``    — graftstream: long-lived video-stereo sessions — a
                  bounded (LRU + TTL + per-tenant caps) table of held
                  1/8-res disparities that warm-start consecutive
                  frames through the ``prepare_warm`` program, plus the
                  convergence early exit (``converged:k`` labels);
- ``cache``     — graftrecall: two-tier content-addressed response
                  cache — an exact tier (sha256 of the padded pair +
                  program fingerprint + tier + tenant → the stored
                  response, bit-identical, zero device seconds,
                  ``cache:exact``) and a near tier (block-mean
                  perceptual signature → warm-start seed through
                  ``prepare_warm``, ``warm:cache:k``), byte-bounded
                  with per-tenant sub-caps and TTL;
- ``service``   — bounded queue, backpressure, per-request deadlines,
                  /healthz status;
- ``supervise`` — graftguard: hang watchdogs over every device
                  invocation, tick-loop/uploader liveness, scheduler
                  generation bounces with bounded per-request retries,
                  and graceful drain (SIGTERM) semantics;
- ``wire``      — graftwire codec: hostile-input request parsing
                  (strict multipart / raw-pair framing), bomb-guarded
                  image decode, response-contract serialization and the
                  honest HTTP status mapping;
- ``http``      — graftwire frontend: stdlib HTTP/1.1 listener with
                  per-read socket timeouts, a hard content-length cap,
                  decode offload, per-tenant token-bucket quotas and
                  real /healthz + /metrics endpoints;
- ``fleet``     — graftfleet: the fleet supervisor — N serve_stereo
                  subprocess instances behind one router (headroom-
                  weighted placement, session affinity with drain
                  handoff, preemption-proof replacement, zero-downtime
                  rolling deploys, /fleet/healthz + /fleet/metrics).

Everything is CPU-testable with deterministic injected faults
(``raft_stereo_tpu.faults.ServeFaultPlan``).
"""

from raft_stereo_tpu.serve.fleet import (  # noqa: F401
    FleetConfig,
    FleetFrontend,
    FleetSupervisor,
)
from raft_stereo_tpu.serve.guard import (  # noqa: F401
    DEFAULT_LADDER,
    FastPath,
    KernelCircuitBreaker,
)
from raft_stereo_tpu.serve.cache import (  # noqa: F401
    CacheEntry,
    ResponseCache,
)
from raft_stereo_tpu.serve.scheduler import (  # noqa: F401
    BatchScheduler,
)
from raft_stereo_tpu.serve.service import (  # noqa: F401
    ServiceConfig,
    StereoService,
)
from raft_stereo_tpu.serve.supervise import (  # noqa: F401
    InvocationWatch,
    Supervisor,
    WatchdogTrip,
)
from raft_stereo_tpu.serve.session import (  # noqa: F401
    DeadlineExceeded,
    InferenceFailed,
    InferenceResult,
    InferenceSession,
    SessionConfig,
    SessionError,
)
from raft_stereo_tpu.serve.stream import (  # noqa: F401
    StreamManager,
    StreamOutcome,
    StreamRunner,
    stream_infer,
)
from raft_stereo_tpu.serve.validate import (  # noqa: F401
    AdmissionConfig,
    InputRejected,
)
from raft_stereo_tpu.serve.http import (  # noqa: F401
    HttpConfig,
    HttpFrontend,
)

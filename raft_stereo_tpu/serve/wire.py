"""Wire-level request/response codec for the HTTP ingress (graftwire).

The frontend (serve/http.py) owns sockets and threads; THIS module owns
bytes: parsing a hostile request body into a stereo pair, decoding image
bytes behind the decompression-bomb guard, and serializing the PR 3
response contract onto the wire unchanged. Everything here is pure
bytes-in/values-out — no sockets, no service state — so the whole codec
is unit-testable without a server and the hostile-input battery
(tests/test_http.py) can pin one stable code per malformation.

Two request encodings for ``POST /v1/stereo``:

- ``multipart/form-data`` with file parts ``left`` and ``right`` (PNG or
  JPEG bytes) plus optional text parts ``id`` / ``deadline_ms`` — the
  curl-friendly form. The parser is hand-rolled and STRICT (exact
  CRLF-delimited boundaries, closing terminator required): a truncated
  or boundary-less body is ``bad_multipart``, never a silently-partial
  parse (the stdlib ``email`` parser is lenient by design, which is the
  wrong property for hostile input);
- ``application/x-raft-stereo``: two raw image parts concatenated, with
  ``X-Raft-Left-Len`` / ``X-Raft-Right-Len`` declaring the split — the
  zero-framing-overhead form a programmatic client uses.

The response is JSON carrying EVERY key of the in-process response dict
(quality labels, structured errors, ``retries: k`` — test-pinned), with
the disparity array encoded as ``{dtype, shape, b64}`` (raw little-endian
float32 bytes, base64) so a client round-trips it bit-exactly
(:func:`decode_response`).

Status mapping (DESIGN.md r14): honest HTTP codes derived from the
structured response — backpressure and drain are 503 (with Retry-After),
quota is 429, expired deadlines are 504, admission rejects are 400, and
internal/serving errors are 500. Wire-level malformations carry their own
status on :class:`WireRejected`.
"""

from __future__ import annotations

import base64
import io
import json
import math
from typing import Dict, Optional, Tuple

import numpy as np

#: Structured rejection codes -> HTTP status, for codes the service (not
#: the wire layer) produces. Everything rejected and unlisted is a 400
#: (admission control: invalid_input:*), everything with status "error"
#: is a 500 — the ingress never invents a success code.
REJECT_STATUS: Dict[str, int] = {
    "queue_full": 503,
    "service_draining": 503,
    "service_stopped": 503,
    "not_running": 503,
    "quota_exceeded": 429,
    "deadline_exceeded": 504,
    "deadline_exceeded_in_queue": 504,
}

#: Codes whose response carries a Retry-After header (seconds): the
#: client is told to come back, not to give up — 503s are transient by
#: contract (bounded queue, drain in progress), 429 is a refill wait.
RETRY_AFTER_S: Dict[str, int] = {
    "queue_full": 1,
    "service_draining": 5,
    "service_stopped": 5,
    "not_running": 5,
    "quota_exceeded": 1,
}


class WireRejected(ValueError):
    """A request failed at the wire layer (framing, codec, decode) —
    before it could become a service submission. ``code`` is the stable
    machine-readable rejection class; ``http_status`` the honest HTTP
    mapping."""

    def __init__(self, code: str, message: str, http_status: int = 400):
        self.code = code
        self.http_status = http_status
        super().__init__(message)


def http_status_for(resp: Dict) -> int:
    """HTTP status for one structured service response."""
    status = resp.get("status")
    if status == "ok":
        return 200
    if status == "error":
        return 500
    return REJECT_STATUS.get(str(resp.get("code", "")), 400)


def retry_after_for(resp: Dict) -> Optional[int]:
    return RETRY_AFTER_S.get(str(resp.get("code", "")))


# ---------------------------------------------------------------------------
# Request parsing
# ---------------------------------------------------------------------------

#: The two request encodings POST /v1/stereo accepts. The frontend
#: checks the media type against this BEFORE reading the body (an
#: unsupported type must not cost a body_max-sized buffer);
#: parse_stereo_request re-checks so the codec stays correct standalone.
SUPPORTED_MEDIA = ("multipart/form-data", "application/x-raft-stereo")


def parse_content_type(raw: Optional[str]) -> Tuple[str, Dict[str, str]]:
    """``type/subtype; k=v; ...`` -> (lowercased media type, params).
    Tolerant of whitespace and quoted parameter values; never raises —
    an unparseable header is simply an unknown media type."""
    if not raw:
        return "", {}
    parts = raw.split(";")
    media = parts[0].strip().lower()
    params: Dict[str, str] = {}
    for p in parts[1:]:
        k, _, v = p.partition("=")
        k = k.strip().lower()
        v = v.strip()
        if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        if k:
            params[k] = v
    return media, params


def _part_name(head: bytes) -> Optional[str]:
    """``name="..."`` from a part's Content-Disposition header lines."""
    for line in head.split(b"\r\n"):
        k, _, v = line.partition(b":")
        if k.strip().lower() != b"content-disposition":
            continue
        _, params = parse_content_type("x/x;" + v.decode("latin-1"))
        return params.get("name")
    return None


def parse_multipart(body: bytes, boundary: str) -> Dict[str, bytes]:
    """Strict ``multipart/form-data`` split: parts keyed by their
    Content-Disposition ``name``.

    Strictness IS the defense: every violation — missing boundary
    parameter, body not opening with the dash-boundary, a part without
    the blank-line header separator, a missing closing ``--`` terminator
    (the truncated-upload case) — is one ``bad_multipart`` rejection.
    The body is already fully read and bounded by the frontend's
    content-length cap, so this parser never sees unbounded input.
    """
    if not boundary:
        raise WireRejected("bad_multipart",
                           "multipart content-type carries no boundary")
    delim = b"--" + boundary.encode("latin-1")
    if not body.startswith(delim):
        raise WireRejected(
            "bad_multipart",
            "body does not start with the declared boundary")
    parts: Dict[str, bytes] = {}
    rest = body[len(delim):]
    while True:
        if rest.startswith(b"--"):
            return parts  # closing terminator reached: parse complete
        if not rest.startswith(b"\r\n"):
            raise WireRejected("bad_multipart",
                               "malformed boundary delimiter (no CRLF)")
        rest = rest[2:]
        head, sep, tail = rest.partition(b"\r\n\r\n")
        if not sep:
            raise WireRejected(
                "bad_multipart",
                "part headers never terminate (truncated upload?)")
        idx = tail.find(b"\r\n" + delim)
        if idx < 0:
            raise WireRejected(
                "bad_multipart",
                "part content never reaches a closing boundary "
                "(truncated upload)")
        name = _part_name(head)
        if name:
            parts[name] = tail[:idx]
        rest = tail[idx + 2 + len(delim):]


def parse_stereo_request(content_type: Optional[str], headers,
                         body: bytes) -> Dict:
    """One POST /v1/stereo body -> ``{left, right, id, deadline_ms}``
    with ``left``/``right`` still ENCODED image bytes (the decode runs in
    the frontend's offload pool, not here, and not on the acceptor).

    ``headers`` is any mapping with ``.get`` (the stdlib message object);
    ``X-Raft-Id`` / ``X-Raft-Deadline-Ms`` override body-carried fields
    so the raw-pair encoding needs no side-channel parts.
    """
    if not body:
        raise WireRejected("empty_body", "request body is empty")
    media, params = parse_content_type(content_type)
    fields: Dict[str, Optional[str]] = {"id": None, "deadline_ms": None,
                                        "converge_tol": None}
    if media == "multipart/form-data":
        parts = parse_multipart(body, params.get("boundary", ""))
        for k in fields:
            if k in parts:
                fields[k] = parts[k].decode("utf-8", "replace")
        left = parts.get("left")
        right = parts.get("right")
        if left is None or right is None:
            missing = [k for k in ("left", "right") if k not in parts]
            raise WireRejected(
                "missing_part",
                f"multipart body lacks required part(s): {missing}")
    elif media == "application/x-raft-stereo":
        lens = []
        for h in ("X-Raft-Left-Len", "X-Raft-Right-Len"):
            raw = headers.get(h)
            if raw is None:
                raise WireRejected(
                    "missing_part",
                    f"raw-pair encoding requires the {h} header")
            try:
                n = int(raw)
            except ValueError:
                raise WireRejected(
                    "bad_part_lengths",
                    f"{h} must be an integer, got {raw!r}") from None
            if n < 0:
                raise WireRejected("bad_part_lengths",
                                   f"{h} must be non-negative, got {n}")
            lens.append(n)
        if lens[0] + lens[1] != len(body):
            raise WireRejected(
                "bad_part_lengths",
                f"declared part lengths {lens[0]}+{lens[1]} != body "
                f"length {len(body)} (truncated upload?)")
        left, right = body[:lens[0]], body[lens[0]:]
    else:
        raise WireRejected(
            "unsupported_media_type",
            f"content-type {media or '(none)'!r} is not one of "
            f"multipart/form-data, application/x-raft-stereo",
            http_status=415)
    for h, k in (("X-Raft-Id", "id"), ("X-Raft-Deadline-Ms", "deadline_ms"),
                 ("X-Raft-Converge-Tol", "converge_tol")):
        v = headers.get(h)
        if v is not None:
            fields[k] = v
    deadline_ms: Optional[float] = None
    if fields["deadline_ms"] is not None:
        try:
            deadline_ms = float(fields["deadline_ms"])
        except ValueError:
            raise WireRejected(
                "bad_deadline",
                f"deadline_ms must be a number, "
                f"got {fields['deadline_ms']!r}") from None
        if not math.isfinite(deadline_ms):
            # float() accepts "nan"/"inf"; a NaN deadline makes every
            # downstream now-vs-deadline comparison False, silently
            # disabling the deadline machinery for that request.
            raise WireRejected(
                "bad_deadline",
                f"deadline_ms must be finite, "
                f"got {fields['deadline_ms']!r}")
    converge_tol: Optional[float] = None
    if fields["converge_tol"] is not None:
        # Streaming convergence tolerance (graftstream): same hostile-
        # input stance as the deadline — a NaN would make the norm
        # comparison silently False, a negative is meaningless.
        try:
            converge_tol = float(fields["converge_tol"])
        except ValueError:
            raise WireRejected(
                "bad_converge_tol",
                f"converge_tol must be a number, "
                f"got {fields['converge_tol']!r}") from None
        if not math.isfinite(converge_tol) or converge_tol < 0:
            raise WireRejected(
                "bad_converge_tol",
                f"converge_tol must be finite and >= 0, "
                f"got {fields['converge_tol']!r}")
    return {"left": left, "right": right, "id": fields["id"],
            "deadline_ms": deadline_ms, "converge_tol": converge_tol}


# ---------------------------------------------------------------------------
# Image decode (runs in the frontend's offload pool)
# ---------------------------------------------------------------------------

def decode_image_rgb(data: bytes, name: str,
                     max_pixels: Optional[int] = None) -> np.ndarray:
    """Decode PNG/JPEG bytes -> (H, W, 3) uint8, behind the
    decompression-bomb guard: PIL's ``open`` parses only the header, the
    declared pixel count is checked against the decode cap, and only
    then does the array conversion run the actual decoder.
    ``image_too_large`` maps to 413; any other decode failure is one
    ``bad_image`` rejection (a garbage payload must cost a parse
    attempt, never a crash or an allocation).

    PIL and frame_utils import lazily (function scope): frame_utils
    drags in cv2 at module top, and `import raft_stereo_tpu.serve` must
    not hard-depend on the image stack a wire-less embedder never uses.
    """
    from PIL import Image

    from raft_stereo_tpu.data.frame_utils import (ImageTooLarge,
                                                  guard_decode_size)
    try:
        img = Image.open(io.BytesIO(data))
        guard_decode_size(img.size, source=name, max_pixels=max_pixels)
        arr = np.asarray(img.convert("RGB"), dtype=np.uint8)
    except ImageTooLarge as e:
        raise WireRejected("image_too_large", str(e), http_status=413) \
            from e
    except Image.DecompressionBombError as e:
        # PIL's own tripwire fires inside ``open`` for declarations ~5x
        # above our default cap — same defense, same stable code.
        raise WireRejected("image_too_large", f"{name}: {e}",
                           http_status=413) from e
    except WireRejected:
        raise
    except Exception as e:  # noqa: BLE001 — hostile-bytes boundary
        raise WireRejected(
            "bad_image",
            f"{name}: cannot decode image bytes ({type(e).__name__}: "
            f"{e})") from e
    if arr.ndim != 3 or arr.shape[-1] != 3:
        raise WireRejected("bad_image",
                           f"{name}: decoded to shape {arr.shape}, "
                           f"expected (H, W, 3)")
    return arr


def decode_canonical(data: bytes, name: str,
                     max_pixels: Optional[int] = None) -> np.ndarray:
    """One image -> the canonical float32 ``(1, H, W, 3)`` array (the
    exact form ``validate_pair`` returns, so admission skips nothing).
    The frontend submits the two images of a request as SEPARATE pool
    tasks — decode is ~33 ms/sample (BASELINE.md), and one combined
    task would serialize the pair even with an idle decode worker."""
    return decode_image_rgb(data, name, max_pixels).astype(
        np.float32)[None]


def decode_pair(left_bytes: bytes, right_bytes: bytes,
                max_pixels: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Both images of a request, sequentially — the in-thread
    convenience form (tests, single-worker callers)."""
    return (decode_canonical(left_bytes, "left", max_pixels),
            decode_canonical(right_bytes, "right", max_pixels))


# ---------------------------------------------------------------------------
# Response encoding
# ---------------------------------------------------------------------------

def encode_response(resp: Dict) -> bytes:
    """Serialize one structured service response to the wire.

    Every key passes through unchanged (the PR 3 response contract —
    quality labels, structured errors, ``retries: k`` — is test-pinned
    to survive serialization); the disparity ndarray becomes
    ``{dtype, shape, b64}`` with raw little-endian bytes so
    :func:`decode_response` restores it bit-exactly."""
    doc = dict(resp)
    disp = doc.pop("disparity", None)
    if disp is not None:
        arr = np.ascontiguousarray(disp, dtype="<f4")
        doc["disparity"] = {
            "dtype": "float32",
            "shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
        }
    return json.dumps(doc, default=str).encode("utf-8")


def decode_response(payload: bytes) -> Dict:
    """Client-side inverse of :func:`encode_response` (tests, bench, the
    chaos storm): the disparity comes back as the exact float32 array
    that was served."""
    doc = json.loads(payload.decode("utf-8"))
    disp = doc.get("disparity")
    if isinstance(disp, dict):
        doc["disparity"] = np.frombuffer(
            base64.b64decode(disp["b64"]), dtype="<f4").reshape(
                disp["shape"]).copy()
    return doc


# ---------------------------------------------------------------------------
# Client-side builders (tests / bench / chaos storm)
# ---------------------------------------------------------------------------

def encode_image_png(arr: np.ndarray) -> bytes:
    """uint8 (H, W, 3) -> PNG bytes (lossless: the server decodes back
    the identical array, which is what makes the loopback parity
    acceptance byte-exact)."""
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(np.asarray(arr, dtype=np.uint8)).save(buf, format="PNG")
    return buf.getvalue()


def build_multipart(parts: Dict[str, bytes],
                    boundary: str = "raftwire") -> Tuple[str, bytes]:
    """(content_type, body) for a multipart/form-data request — the
    canonical client encoding the parser above accepts."""
    chunks = []
    for name, data in parts.items():
        chunks.append(
            b"--" + boundary.encode() + b"\r\n"
            b'Content-Disposition: form-data; name="' + name.encode()
            + b'"\r\n\r\n' + data + b"\r\n")
    body = b"".join(chunks) + b"--" + boundary.encode() + b"--\r\n"
    return f"multipart/form-data; boundary={boundary}", body

"""Kernel circuit breaker: trip a risky fast path, fall back, keep serving.

The inference stack layers four hand-written fast paths over a plain-XLA
baseline (DESIGN.md r6): the streamed encoder tail, the co-scheduled
gru16+32 kernel, the packed Pallas correlation gather, and the Pallas corr
implementations themselves. Each already has a kill switch — an env var or
a config field — but as shipped those are operator knobs: a compile
failure, a ``RESOURCE_EXHAUSTED``, or a parity drift in any one of them
kills the process. This module turns the kill switches into a structured
**fallback ladder**: every risky path is declared once with its switch and
its XLA fallback; on a classified kernel failure the breaker trips one
rung, the session rebuilds one step closer to plain XLA, and the trip is
recorded in metrics. The process degrades; it does not die. The bottom of
the ladder is the pure-XLA program, which has no rung below it by
construction.

Trips are one-way only while the recovery plane is off (``RAFT_HEAL=0``
— a tripped kernel is then assumed broken until an operator resets).
With healing armed (the r22 default), a tripped rung enters **probation**:
after an exponential per-rung backoff on the session clock the rung goes
*half-open*, and the next heal sweep re-runs the parity canary — the
candidate projection (current trips minus this rung) against the
plain-XLA reference — BEFORE the rung re-engages for real traffic.  A
passing canary untrips the rung (re-keying programs exactly as tripping
keyed them; the session re-warms before routing); a failing canary
re-trips with doubled backoff (capped), so a persistently broken kernel
costs one canary per backoff period, never a serving-path flap.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, Mapping, Optional, Tuple, Union

from raft_stereo_tpu.analysis.knobs import ENV_KNOBS
from raft_stereo_tpu.faults import InjectedKernelError

logger = logging.getLogger(__name__)

# Parity canary drift band: the fast-path forward is compared against the
# plain-XLA program on one bucketed pair at session startup. The bench gate
# pins kernel checksums at rtol 5e-3 (BASELINE.md, r6); per-pixel disparity
# gets the same relative band plus an absolute floor of 0.05 px — below any
# metric threshold (the tightest eval threshold is D1 at 1 px).
CANARY_RTOL = 5e-3
CANARY_ATOL = 5e-2


@dataclasses.dataclass(frozen=True)
class FastPath:
    """One risky fast path: its kill switch and its fallback.

    env_var/env_off: existing env-var kill switch — tripping exports
        ``env_var=env_off`` for every subsequent trace.
    cfg_field/cfg_fallback: config-field switch — tripping rewrites the
        session's run config. A dict fallback maps current -> fallback
        value (e.g. ``reg_tpu -> reg``); a plain value replaces outright.
    matchers: lowercase substrings that attribute a failure message to
        THIS path (kept specific — a generic failure falls back to
        ladder order instead of guessing).
    """

    name: str
    description: str
    env_var: Optional[str] = None
    env_off: str = "0"
    cfg_field: Optional[str] = None
    cfg_fallback: Union[None, bool, Mapping[str, str]] = None
    matchers: Tuple[str, ...] = ()


# Ladder order = cheapest capability loss first. Tripping fuse_gru1632
# costs ~latency only; the last two rungs abandon the streaming encoder
# stems and every streaming scan-body kernel — with corr already mapped
# to its XLA twin, the bottom of the ladder is a genuinely kernel-free
# forward (this is also what the parity canary compares against).
DEFAULT_LADDER: Tuple[FastPath, ...] = (
    # r19 rungs lead the ladder: each costs latency/DMA only — tripping
    # fuse_iter reverts the resident mega-kernel to the serial fused
    # kernels, corr_pack8 reverts int8 containers to bf16 pair-packing
    # (a rung that only bites when the operator opted in), stream_batch
    # reverts BATCHED device calls to the XLA twins while B=1 keeps its
    # kernels.
    FastPath(
        name="fuse_iter",
        description="resident per-iteration mega-kernel — corr lookup + "
                    "motion encoder + gru08 + flow head in one stream "
                    "(ops/pallas_resident.py)",
        env_var="RAFT_FUSE_ITER",
        matchers=("fuse_iter", "resident"),
    ),
    FastPath(
        # Must precede corr_pack8: classify() walks the ladder in order
        # and corr_pack8's "pack8" matcher is a substring of every
        # "lane_pack8" failure message.
        name="lane_pack8",
        description="int8 quad-packed context containers for the "
                    "per-iteration feature/context lanes "
                    "(ops/pallas_stream.py RAFT_LANE_PACK8)",
        env_var="RAFT_LANE_PACK8",
        matchers=("lane_pack8", "lane8", "czrq8"),
    ),
    FastPath(
        name="corr_pack8",
        description="int8 quad-packed correlation containers "
                    "(corr/pallas_reg.py RAFT_CORR_PACK8)",
        env_var="RAFT_CORR_PACK8",
        matchers=("pack8", "packed8"),
    ),
    FastPath(
        name="stream_batch",
        description="B>1 engagement of the streamed scan-body kernels "
                    "(ops/pallas_stream.py RAFT_STREAM_BATCH)",
        env_var="RAFT_STREAM_BATCH",
        matchers=("stream_batch",),
    ),
    FastPath(
        name="fuse_gru1632",
        description="co-scheduled gru16+32 streaming kernel "
                    "(ops/pallas_stream.py fused_gru1632)",
        env_var="RAFT_FUSE_GRU1632",
        matchers=("gru1632", "gru16+32"),
    ),
    FastPath(
        name="stream_tail",
        description="streamed encoder tail — raw1/mid1/point2 passes "
                    "(ops/pallas_encoder.py)",
        env_var="RAFT_STREAM_TAIL",
        matchers=("stream_tail", "raw1", "point2"),
    ),
    FastPath(
        name="packed_l2",
        description="packed layer2 bit-layout in the encoder stems "
                    "(models/extractor.py RAFT_PACKED_L2)",
        env_var="RAFT_PACKED_L2",
        matchers=("packed_l2", "packed stem"),
    ),
    FastPath(
        name="corr_kernel",
        description="Pallas correlation gather, packed pyramid "
                    "(corr/pallas_reg.py / pallas_alt.py) -> XLA twin",
        cfg_field="corr_implementation",
        cfg_fallback={"reg_tpu": "reg", "alt_tpu": "alt",
                      "reg_cuda": "reg", "alt_cuda": "alt"},
        matchers=("pallas_reg", "pallas_alt", "gather_lerp",
                  "corr_kernel", "corr lookup"),
    ),
    FastPath(
        name="fused_encoders",
        description="one-pass-per-conv streaming encoder stems "
                    "(ops/pallas_encoder.py RAFT_FUSED_ENCODERS)",
        env_var="RAFT_FUSED_ENCODERS",
        matchers=("fused_encoder", "pallas_encoder", "encoder stem"),
    ),
    FastPath(
        name="fused_update",
        description="streaming scan-body kernels — fused ConvGRU / motion "
                    "encoder / flow head (cfg.fused_update) -> plain XLA",
        cfg_field="fused_update",
        cfg_fallback=False,
        matchers=("pallas_stream", "fused_motion", "fused_gru",
                  "flow_head"),
    ),
)

@dataclasses.dataclass(frozen=True)
class FailureMarker:
    """One failure-classifier marker: a lowercase substring whose presence
    in ``str(exc).lower()`` marks the exception as *kernel-failure*
    territory (breaker trips + rebuild-one-rung-down), with the failure
    category it attributes and why it is specific enough to trust."""

    substring: str
    category: str  # 'oom' | 'kernel_compiler' | 'xla_runtime'
    note: str


# The ONE table of kernel-failure markers (previously an anonymous tuple
# matched inline): a raised exception is a *kernel* failure — breaker
# territory — only if it is an injected kernel error, an XLA runtime
# error by type name, or its message carries one of these substrings.
# Anything else (a TypeError in our own code, a KeyboardInterrupt) must
# propagate. Kept deliberately specific: a marker loose enough to match
# an application error would convert crashes into silent rung walks.
KERNEL_FAILURE_MARKERS: Tuple[FailureMarker, ...] = (
    FailureMarker("resource_exhausted", "oom",
                  "XLA RESOURCE_EXHAUSTED status text (HBM/VMEM OOM)"),
    FailureMarker("out of memory", "oom",
                  "allocator message form of the same OOM class"),
    FailureMarker("mosaic", "kernel_compiler",
                  "Mosaic (TPU Pallas backend) compile/verify errors"),
    FailureMarker("pallas", "kernel_compiler",
                  "pallas_call lowering/interpret errors name the layer"),
    FailureMarker("internal: ", "xla_runtime",
                  "XLA INTERNAL status prefix (miscompiled/failed launch)"),
    FailureMarker("xla runtime error", "xla_runtime",
                  "generic XLA runtime failure text"),
)

#: Exception type NAMES (not classes — jaxlib import is optional here)
#: that are kernel failures regardless of message.
KERNEL_FAILURE_TYPE_NAMES = ("XlaRuntimeError", "JaxRuntimeError")


def match_failure_marker(exc: BaseException) -> Optional[FailureMarker]:
    """The first marker whose substring appears in the exception message,
    else None. Exposed (rather than inlined) so tests pin every entry and
    the classification table has one reviewable home."""
    msg = str(exc).lower()
    for marker in KERNEL_FAILURE_MARKERS:
        if marker.substring in msg:
            return marker
    return None


def is_kernel_failure(exc: BaseException) -> bool:
    if isinstance(exc, InjectedKernelError):
        return True
    if type(exc).__name__ in KERNEL_FAILURE_TYPE_NAMES:
        return True
    return match_failure_marker(exc) is not None


@dataclasses.dataclass
class TripRecord:
    path: str
    reason: str          # 'compile_failure' | 'runtime_failure' |
                         # 'canary_mismatch' | 'manual'
    error: str = ""
    count: int = 1
    at: float = dataclasses.field(default_factory=time.time)


class LadderExhausted(RuntimeError):
    """Every rung is tripped and the plain-XLA program still failed."""


class KernelCircuitBreaker:
    """Trip registry + fallback ladder for one serving process.

    Thread-safe; shared between an :class:`~raft_stereo_tpu.serve.session.
    InferenceSession` and its service wrapper so /healthz sees trips the
    moment they happen.
    """

    def __init__(self, ladder: Tuple[FastPath, ...] = DEFAULT_LADDER,
                 registry=None):
        # obs/metrics.py registry (optional): when bound, every trip also
        # increments raft_breaker_trips_total{rung,reason} so /metrics
        # carries the ladder walk without a second bookkeeping path.
        self._registry = registry
        self.ladder = tuple(ladder)
        self._by_name = {p.name: p for p in self.ladder}
        if len(self._by_name) != len(self.ladder):
            raise ValueError("duplicate fast-path names in ladder")
        # Fingerprint/trace contract (one registry: analysis/knobs.py): a
        # rung's env switch must be in ENV_KNOBS so UNTRIPPED programs key
        # on it too. resolve_env keeps unknown override keys — the trace
        # still sees the switch — so drift is a warning, not an error
        # (tests inject synthetic ladders).
        for p in self.ladder:
            if p.env_var is not None and p.env_var not in ENV_KNOBS:
                logger.warning(
                    "ladder rung %s uses env var %s not in ENV_KNOBS "
                    "(raft_stereo_tpu/analysis/knobs.py) — add it so "
                    "untripped programs key on it too", p.name, p.env_var)
        self._tripped: Dict[str, TripRecord] = {}
        self._lock = threading.Lock()
        # graftheal (r22) probation state. Disarmed until the owning
        # session calls configure_heal with its clock — an unconfigured
        # breaker keeps the historical one-way semantics bit-for-bit.
        self._heal_enabled = False
        self._heal_clock = None
        self._heal_backoff_s = 30.0
        self._heal_backoff_max_s = 480.0
        # rung -> {backoff_s, deadline, probes, retrips}; deadlines run
        # on the session clock (FakeClock in tests/storms).
        self._probation: Dict[str, Dict] = {}

    def bind_registry(self, registry) -> None:
        """Attach a metrics registry (first bind wins — a breaker shared
        between sessions keeps reporting into the store it started
        with)."""
        if self._registry is None:
            self._registry = registry

    def configure_heal(self, *, enabled: bool, clock,
                       backoff_s: float, backoff_max_s: float) -> None:
        """Arm (or disarm) half-open probation for this breaker.  Called
        by the owning session with ITS clock so every probation deadline
        rides the same FakeClock the tests/storms drive.  Existing trips
        (a breaker shared across a rebuild) are put on probation at one
        full backoff from now — never instantly eligible."""
        with self._lock:
            self._heal_enabled = bool(enabled)
            self._heal_clock = clock
            self._heal_backoff_s = float(backoff_s)
            self._heal_backoff_max_s = float(backoff_max_s)
            if not self._heal_enabled or clock is None:
                self._probation.clear()
                return
            now = clock.now()
            for name in self._tripped:
                if name not in self._probation:
                    self._probation[name] = {
                        "backoff_s": self._heal_backoff_s,
                        "deadline": now + self._heal_backoff_s,
                        "probes": 0, "retrips": 0}

    # -- state ------------------------------------------------------------

    @property
    def tripped_names(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._tripped)

    @property
    def trip_count(self) -> int:
        with self._lock:
            return sum(r.count for r in self._tripped.values())

    @property
    def exhausted(self) -> bool:
        """True when the session is already at plain XLA (no rung left)."""
        with self._lock:
            return len(self._tripped) == len(self.ladder)

    def fingerprint(self) -> Tuple[str, ...]:
        """Stable component of compile-cache keys: programs traced under a
        different trip set must never be served for this one."""
        with self._lock:
            return tuple(sorted(self._tripped))

    # -- classification ---------------------------------------------------

    def classify(self, exc: BaseException) -> Optional[FastPath]:
        """The rung to trip for this failure: the first *untripped* path
        whose matchers hit the message, else the first untripped path in
        ladder order (a generic OOM/compile failure walks the ladder top
        down), else None — the ladder is exhausted."""
        msg = str(exc).lower()
        with self._lock:
            untripped = [p for p in self.ladder if p.name not in self._tripped]
        for p in untripped:
            if any(m in msg for m in p.matchers):
                return p
        return untripped[0] if untripped else None

    # -- transitions ------------------------------------------------------

    def trip(self, name: str, reason: str,
             error: Optional[BaseException] = None) -> TripRecord:
        if name not in self._by_name:
            raise KeyError(f"unknown fast path {name!r}")
        if self._registry is not None:
            self._registry.counter(
                "raft_breaker_trips_total",
                "circuit-breaker trips by rung and reason",
                rung=name, reason=reason).inc()
        with self._lock:
            rec = self._tripped.get(name)
            if rec is None:
                rec = TripRecord(path=name, reason=reason,
                                 error=str(error) if error else "")
                self._tripped[name] = rec
            else:  # repeated failure attributed to an already-dark path
                rec.count += 1
            if self._heal_enabled and self._heal_clock is not None:
                now = self._heal_clock.now()
                st = self._probation.get(name)
                if st is None:
                    # First trip of this rung: probation at base backoff.
                    self._probation[name] = {
                        "backoff_s": self._heal_backoff_s,
                        "deadline": now + self._heal_backoff_s,
                        "probes": 0, "retrips": 0}
                else:
                    # Re-trip (incl. a failed half-open canary): backoff
                    # doubles, capped — a persistently broken kernel
                    # settles at one canary per max-backoff period.
                    st["backoff_s"] = min(st["backoff_s"] * 2.0,
                                          self._heal_backoff_max_s)
                    st["deadline"] = now + st["backoff_s"]
                    st["retrips"] += 1
            return rec

    # -- half-open probation (graftheal r22) -------------------------------

    def heal_candidate(self, now: Optional[float] = None) -> Optional[str]:
        """The ONE rung eligible for a half-open canary probe right now,
        or None.  Only the MOST recently tripped rung is ever a
        candidate (``_tripped`` is insertion-ordered, so re-engagement
        walks the ladder back in strict reverse trip order — re-arming a
        lower rung under a still-dark higher one would canary a
        configuration that was never served).  Handing out a candidate
        pushes its deadline one backoff out, so a sweep that dies
        mid-probe cannot hand the same rung to a concurrent sweep."""
        with self._lock:
            if not self._heal_enabled or self._heal_clock is None \
                    or not self._tripped:
                return None
            if now is None:
                now = self._heal_clock.now()
            name = next(reversed(self._tripped))
            st = self._probation.get(name)
            if st is None:  # tripped before heal was configured
                self._probation[name] = {
                    "backoff_s": self._heal_backoff_s,
                    "deadline": now + self._heal_backoff_s,
                    "probes": 0, "retrips": 0}
                return None
            if now < st["deadline"]:
                return None
            st["probes"] += 1
            st["deadline"] = now + st["backoff_s"]
            return name

    def untrip(self, name: str) -> bool:
        """Half-open canary passed: the rung re-engages.  Removes the
        trip record AND its probation state (a later re-trip starts back
        at the base backoff — the fault class that cleared is not the
        one that re-trips).  The caller owns re-keying: the trip set is
        in the program-cache projection, so it must rebuild its run
        config and RE-WARM before routing traffic (the PR 5
        mid-request-compile class)."""
        with self._lock:
            if name not in self._tripped:
                return False
            del self._tripped[name]
            self._probation.pop(name, None)
        if self._registry is not None:
            self._registry.counter(
                "raft_heal_untrips_total",
                "breaker rungs re-engaged after a passing half-open "
                "canary", rung=name).inc()
        return True

    def heal_status(self) -> Dict:
        """The /healthz ``breaker.heal`` block: probation state per
        still-tripped rung."""
        with self._lock:
            now = (self._heal_clock.now()
                   if self._heal_clock is not None else None)
            half_open = {}
            for name, st in self._probation.items():
                row = {"backoff_ms": st["backoff_s"] * 1e3,
                       "probes": st["probes"],
                       "retrips": st["retrips"]}
                if now is not None:
                    row["eligible_in_s"] = max(0.0, st["deadline"] - now)
                half_open[name] = row
            return {"enabled": self._heal_enabled,
                    "half_open": half_open}

    def reset(self) -> None:
        """Operator action: forget all trips (e.g. after a driver fix)."""
        with self._lock:
            self._tripped.clear()
            self._probation.clear()

    # -- application ------------------------------------------------------

    def apply(self, cfg, tripped: Optional[Tuple[str, ...]] = None):
        """Project a trip set (default: the current one) onto a run config
        + env overrides.

        Returns ``(run_cfg, env)`` where ``run_cfg`` is ``cfg`` with every
        tripped config-field switch rewritten and ``env`` maps each
        tripped env-var switch to its off value — to be exported around
        every trace of a serving program.
        """
        overrides = {}
        env: Dict[str, str] = {}
        if tripped is None:
            with self._lock:
                tripped = tuple(self._tripped)
        for name in tripped:
            p = self._by_name[name]
            if p.env_var is not None:
                env[p.env_var] = p.env_off
            if p.cfg_field is not None:
                if isinstance(p.cfg_fallback, Mapping):
                    cur = getattr(cfg, p.cfg_field)
                    new = p.cfg_fallback.get(cur, cur)
                else:
                    new = p.cfg_fallback
                overrides[p.cfg_field] = new
        run_cfg = (cfg if not overrides
                   else type(cfg)(**{**cfg.__dict__, **overrides}))
        return run_cfg, env

    def plain_xla_cfg(self, cfg):
        """``cfg`` with EVERY ladder switch at its fallback — the parity
        canary's reference program, independent of the current trip set."""
        return self.apply(cfg, tripped=tuple(p.name for p in self.ladder))

    # -- reporting --------------------------------------------------------

    def status(self) -> Dict:
        with self._lock:
            return {
                "ladder": [p.name for p in self.ladder],
                "tripped": {
                    name: {"reason": r.reason, "error": r.error,
                           "count": r.count, "at": r.at}
                    for name, r in self._tripped.items()},
                "trip_count": sum(r.count for r in self._tripped.values()),
                "exhausted": len(self._tripped) == len(self.ladder),
            }

    def status_with_heal(self) -> Dict:
        """``status()`` plus the r22 probation block (kept separate so
        pre-r22 status pins stay byte-stable)."""
        doc = self.status()
        doc["heal"] = self.heal_status()
        return doc

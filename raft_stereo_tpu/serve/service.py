"""Stdlib-only request service over an InferenceSession.

Queueing discipline for a latency-bound model server, with nothing but
``threading`` + ``queue``:

- **bounded depth + explicit backpressure**: a full queue rejects the
  request *immediately* (``queue_full``) instead of stretching every
  caller's latency without bound;
- **admission control at submit time**: malformed inputs (validate.py)
  never occupy a queue slot or a device;
- **per-request deadlines**: requests that expire while queued are
  rejected on dequeue without touching the device; live ones carry their
  absolute deadline into the session's degrade policy;
- **crash-proof workers**: a worker turns *any* session failure into a
  structured error response — one poisoned request cannot take the
  process down (fault-storm-pinned in tests/test_serve.py);
- **continuous batching** (``SessionConfig.max_batch > 1``): the worker
  pool is replaced by ONE scheduler thread driving
  :class:`~raft_stereo_tpu.serve.scheduler.BatchScheduler` — requests
  join a running device batch at tick boundaries and exit at segment
  boundaries. The queue, admission, backpressure (``queue_full``) and
  response contract are IDENTICAL to the sequential path;
- **/healthz**: ``status()`` folds session state (bucket cache, breaker
  trips, canary), queue depth, request counters by rejection/error code,
  degraded-request count, p50/p99 latency over a sliding window, and — in
  batched mode — the ``batching`` document (per-tick occupancy histogram,
  joins/exits per tick, pad-row waste, tick latency percentiles: the
  numbers that tune ``--max_batch`` in production).

Every response is a plain dict: ``{"status": "ok" | "rejected" |
"error", ...}`` — ``ok`` always carries a finite disparity and an honest
``quality`` label.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from concurrent.futures import Future
from typing import Dict, Optional

from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.serve.session import (DeadlineExceeded, InferenceSession,
                                           SessionError)
from raft_stereo_tpu.serve.validate import InputRejected, validate_pair


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    max_queue: int = 8
    workers: int = 1
    # Applied when a request carries no deadline_ms of its own; None means
    # undegraded full-iteration serving by default.
    default_deadline_ms: Optional[float] = None
    latency_window: int = 512
    # Scheduler idle poll (continuous batching only): how long the
    # scheduler thread blocks for new work when every bucket is empty.
    # None reads RAFT_SCHED_TICK_MS at start() (default 2 ms). Purely a
    # host-side latency/CPU trade — it never shapes a compiled program.
    tick_ms: Optional[float] = None
    # SLO flight recorder (obs/flight.py): a served request whose
    # end-to-end latency exceeds slo_ms * slo_factor — or any request
    # that tripped a breaker rung, missed its deadline or produced a
    # non-finite output — persists a bounded flight record to
    # RAFT_FLIGHT_DIR. slo_ms=None disables the latency criterion only;
    # the other breach classes always record when the recorder is armed.
    slo_ms: Optional[float] = None
    slo_factor: float = 1.0


def _reject(code: str, message: str) -> Dict:
    return {"status": "rejected", "code": code, "message": message}


def _error(code: str, message: str) -> Dict:
    return {"status": "error", "code": code, "message": message}


class StereoService:
    """Request queue + worker pool around one :class:`InferenceSession`."""

    def __init__(self, session: InferenceSession,
                 service_cfg: Optional[ServiceConfig] = None):
        self.session = session
        self.cfg = service_cfg or ServiceConfig()
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.cfg.max_queue)
        self._workers = []
        self._stop = threading.Event()
        # graftscope: request counters and the latency reservoir live in
        # the session's ONE registry (shared with the scheduler), so
        # /healthz is derivable from /metrics byte-for-byte — no service-
        # private Counter/deque to fold in.
        self.registry = session.registry
        self.tracer = session.tracer
        self.profiler = session.profiler
        self._latency = self.registry.histogram(
            "raft_request_latency_seconds",
            "end-to-end served-request latency (bounded reservoir)",
            reservoir=self.cfg.latency_window)
        self._lock = threading.Lock()
        self._started = False
        # Continuous batching engages when the SESSION was built for it
        # (max_batch shapes compiled programs and warmup, so it lives on
        # SessionConfig); the service then runs one scheduler thread
        # instead of the worker pool. The scheduler itself is built per
        # START generation (like the stop event): stop() permanently ends
        # its uploader thread and drains its state, so reusing one across
        # a restart would hang every post-restart request in the join
        # queue.
        self._batched = session.cfg.max_batch > 1
        self._scheduler = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "StereoService":
        with self._lock:
            if self._started:
                return self
            self._started = True
            # Fresh event per generation: a worker that outlives a timed-out
            # join (e.g. mid-compile on a cold bucket) still holds its OWN
            # generation's set event and exits when its request finishes —
            # it can never be revived as an untracked extra worker.
            self._stop = threading.Event()
            stop_event = self._stop
        if self._batched:
            import os
            from raft_stereo_tpu.serve.scheduler import BatchScheduler
            tick_ms = self.cfg.tick_ms
            if tick_ms is None:
                tick_ms = float(os.environ.get("RAFT_SCHED_TICK_MS", "2"))
            # Fresh scheduler per generation; a previous generation's
            # thread (possibly still ticking its own active rows out past
            # the join timeout) holds only its OWN scheduler and, once its
            # stop event is set, never touches the shared queue again —
            # two generations can never race on one batch state.
            self._scheduler = BatchScheduler(
                self.session, resolve=self._resolve_scheduled)
            t = threading.Thread(target=self._scheduler_loop,
                                 args=(self._scheduler, stop_event,
                                       tick_ms / 1e3),
                                 name="stereo-scheduler", daemon=True)
            t.start()
            self._workers.append(t)
            return self
        for i in range(self.cfg.workers):
            t = threading.Thread(target=self._worker_loop,
                                 args=(stop_event,),
                                 name=f"stereo-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        return self

    def stop(self) -> None:
        with self._lock:
            # Flip first, under the same lock submit() enqueues under: any
            # submit that saw started=True has already enqueued, so the
            # drain below provably sees it; later submits are rejected.
            self._started = False
            self._stop.set()
        for _ in self._workers:
            try:
                self._queue.put_nowait(None)  # wake sentinel
            except queue.Full:
                break  # queue backlog itself will wake the workers
        for t in self._workers:
            t.join(timeout=10)
        # Resolve every still-queued Future with a structured rejection —
        # an abandoned Future deadlocks any caller blocked on .result().
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            request, fut = item
            resp = _reject("service_stopped",
                           "service stopped before this request ran")
            if request.get("id") is not None:
                resp["id"] = request["id"]
            self._count("rejected:service_stopped")
            self._finish_trace(request, resp)
            try:
                fut.set_result(resp)
            except Exception:  # already resolved/cancelled
                pass
        self._workers = [t for t in self._workers if t.is_alive()]

    def __enter__(self) -> "StereoService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path -----------------------------------------------------

    def _count(self, outcome: str) -> None:
        """One request outcome into the registry (same keys /healthz has
        always reported: 'ok', 'rejected:<code>', 'error:<code>',
        'degraded')."""
        self.registry.counter(
            "raft_requests_total", "request outcomes by disposition",
            outcome=outcome).inc()

    @staticmethod
    def _finish_trace(request: Dict, resp: Dict) -> None:
        trace = request.get("_trace")
        if trace is not None:
            trace.finish(status=resp["status"], code=resp.get("code"),
                         quality=resp.get("quality"))

    # -- SLO flight recorder ----------------------------------------------

    def _breach_reasons(self, resp: Dict, spans) -> list:
        """Why this response is an SLO breach (empty = healthy). The
        latency criterion needs an explicit slo_ms; breaker trips,
        missed deadlines and non-finite outputs always count."""
        reasons = []
        if resp.get("code") == "nonfinite_output":
            reasons.append("nonfinite_output")
        if any(s.kind == "breaker_trip" for s in spans):
            reasons.append("breaker_trip")
        # Served-but-late (degrade sets deadline_missed) AND rejected-as-
        # expired (deadline_exceeded / deadline_exceeded_in_queue) both
        # count: the queue-backlog rejection is exactly the case whose
        # queue_wait timeline an operator needs most.
        if resp.get("deadline_missed") or \
                str(resp.get("code", "")).startswith("deadline_exceeded"):
            reasons.append("deadline_missed")
        elapsed = resp.get("elapsed_ms")
        if (self.cfg.slo_ms is not None and elapsed is not None
                and elapsed > self.cfg.slo_ms * self.cfg.slo_factor):
            reasons.append("latency_slo")
        return reasons

    def _maybe_flight(self, request: Dict, resp: Dict) -> None:
        """Persist a flight record when this (already trace-finished)
        response breached its SLO. Runs on the response-resolution path
        for BOTH serving modes, so it must never raise — the recorder is
        failure-isolated, and this wrapper only reads local state."""
        flight = self.session.flight
        if not flight.enabled:
            return
        trace = request.get("_trace")
        spans = getattr(trace, "spans", None) or []
        reasons = self._breach_reasons(resp, spans)
        if not reasons:
            return
        # Ledger rows of every program the request actually rode: spans
        # carry the program's ledger id (session.invoke / the scheduler
        # stamp them), and the ledger is bounded by the LRU cache size.
        ids = {s.attrs.get("program") for s in spans
               if s.attrs.get("program")}
        doc = {
            "schema": 1,
            "reasons": reasons,
            "slo_ms": self.cfg.slo_ms,
            "slo_factor": self.cfg.slo_factor,
            "response": {k: resp.get(k) for k in
                         ("id", "status", "code", "quality", "iters",
                          "elapsed_ms", "deadline_missed")},
            "trace": (trace.to_dict()
                      if trace is not None and trace is not NULL_TRACE
                      else None),
            "programs": self.session.ledger.rows_by_id(ids),
            "breaker": self.session.breaker.status(),
            "metrics": self.registry.snapshot(),
        }
        flight.record(doc, trace_id=getattr(trace, "trace_id", None))

    def _admit(self, request: Dict) -> Optional[Dict]:
        """Validation + deadline stamping; returns a rejection dict or
        None. Mutates ``request``: a trace is opened (trace id at
        admission), the absolute ``_deadline`` is stamped and left/right
        are replaced with their validated canonical form, so the session
        skips a second O(N) validation pass on dequeue."""
        trace = request.get("_trace")
        if trace is None:
            trace = self.tracer.start_request(request.get("id"))
            request["_trace"] = trace
        try:
            request["left"], request["right"] = validate_pair(
                request["left"], request["right"],
                self.session.cfg.admission)
        except InputRejected as e:
            trace.mark("admission", rejected=e.code)
            return _reject(f"invalid_input:{e.code}", str(e))
        except KeyError as e:
            trace.mark("admission", rejected="missing_field")
            return _reject("invalid_input:missing_field",
                           f"request missing {e}")
        deadline_ms = request.get("deadline_ms",
                                  self.cfg.default_deadline_ms)
        request["_deadline"] = (
            None if deadline_ms is None
            else self.session.clock.now() + deadline_ms / 1e3)
        trace.mark("admission", h=int(request["left"].shape[1]),
                   w=int(request["left"].shape[2]),
                   deadline_ms=deadline_ms)
        return None

    def _respond(self, request: Dict) -> Dict:
        """One request, synchronously, never raising."""
        rid = request.get("id")
        trace = request.get("_trace") or NULL_TRACE
        trace.mark("queue_wait")
        try:
            deadline = request.get("_deadline")
            if deadline is not None and self.session.clock.now() >= deadline:
                resp = _reject("deadline_exceeded_in_queue",
                               "deadline expired before the request "
                               "reached a device")
            else:
                t0 = self.session.clock.now()
                result = self.session.infer(
                    request["left"], request["right"], deadline=deadline,
                    allow_half_res=request.get("allow_half_res"),
                    prevalidated=True, trace=trace)
                self._latency.observe(self.session.clock.now() - t0)
                resp = {
                    "status": "ok",
                    "quality": result.quality,
                    "disparity": result.disparity,
                    "iters": result.iters,
                    "elapsed_ms": result.elapsed_s * 1e3,
                    "deadline_missed": result.deadline_missed,
                }
        except InputRejected as e:
            resp = _reject(f"invalid_input:{e.code}", str(e))
        except DeadlineExceeded as e:
            resp = _reject(e.code, str(e))
        except SessionError as e:
            resp = _error(e.code, str(e))
        except Exception as e:  # noqa: BLE001 — the crash-proofing boundary
            resp = _error("internal", f"{type(e).__name__}: {e}")
        if rid is not None:
            resp["id"] = rid
        key = resp["status"]
        if resp["status"] != "ok":
            key = f'{resp["status"]}:{resp["code"]}'
        elif resp.get("quality") != "full":
            self._count("degraded")
        self._count(key)
        self._finish_trace(request, resp)
        self._maybe_flight(request, resp)
        return resp

    def handle(self, request: Dict) -> Dict:
        """Synchronous path: admit, run, respond. The fault-storm battery
        drives this for deterministic ordering. In batched mode with the
        scheduler running, the request rides the batch like any other
        (submit + wait); otherwise it runs the sequential session path."""
        with self._lock:
            batched_live = self._batched and self._started
        if batched_live:
            return self.submit(request).result()
        rejection = self._admit(request)
        if rejection is not None:
            if request.get("id") is not None:
                rejection["id"] = request["id"]
            self._count(f'rejected:{rejection["code"]}')
            self._finish_trace(request, rejection)
            return rejection
        return self._respond(request)

    def submit(self, request: Dict) -> Future:
        """Async path: admission + bounded enqueue. The returned Future
        always resolves to a response dict (rejections included)."""
        fut: Future = Future()
        rejection = self._admit(request)
        if rejection is None:
            # started-check + enqueue under the lifecycle lock: stop()
            # flips _started under the same lock before draining, so a
            # request can never land in the queue after the drain.
            with self._lock:
                if not self._started:
                    rejection = _reject("not_running",
                                        "service is not started")
                else:
                    try:
                        self._queue.put_nowait((request, fut))
                    except queue.Full:
                        rejection = _reject(
                            "queue_full",
                            f"queue depth {self.cfg.max_queue} reached — "
                            "retry with backoff")
        if rejection is not None:
            if request.get("id") is not None:
                rejection["id"] = request["id"]
            self._count(f'rejected:{rejection["code"]}')
            self._finish_trace(request, rejection)
            fut.set_result(rejection)
        return fut

    # -- continuous batching ----------------------------------------------

    def _resolve_scheduled(self, request: Dict, resp: Dict) -> None:
        """Scheduler response sink: fold counters/latency exactly like the
        sequential ``_respond`` path, then resolve the caller's Future.
        (The scheduler already finished the request's trace.)"""
        key = resp["status"]
        if resp["status"] != "ok":
            key = f'{resp["status"]}:{resp["code"]}'
        else:
            self._latency.observe(resp["elapsed_ms"] / 1e3)
            if resp.get("quality") != "full":
                self._count("degraded")
        self._count(key)
        # Flight record BEFORE resolving the Future: a caller that wakes
        # on .result() and immediately lists RAFT_FLIGHT_DIR must see the
        # record its breach produced.
        self._maybe_flight(request, resp)
        fut = request.get("_future")
        if fut is not None:
            try:
                fut.set_result(resp)
            except Exception:  # already resolved/cancelled
                pass

    def _scheduler_loop(self, sched, stop_event: threading.Event,
                        tick_s: float) -> None:
        """One thread owns all batch state: drain the bounded queue into
        the scheduler, run ticks while there is work, block briefly when
        idle. On stop: the thread never touches the shared queue again
        (stop()'s own drain — or the next generation — owns it),
        un-admitted joiners are rejected (same structured
        ``service_stopped`` the sequential drain uses), and active rows
        tick to their segment-boundary exits — a row that already owns
        device state is finished, never abandoned."""
        while True:
            if stop_event.is_set():
                sched.drain_pending()
                if sched.active_rows == 0:
                    sched.shutdown()
                    return
                sched.run_tick()
                continue
            while True:  # drain everything currently queued
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is None:  # wake sentinel
                    continue
                request, fut = item
                request["_future"] = fut
                sched.submit(request)
            if stop_event.is_set():
                continue  # reject/finish via the stop branch above
            if not sched.run_tick():
                try:
                    item = self._queue.get(timeout=tick_s)
                except queue.Empty:
                    continue
                if item is not None:
                    request, fut = item
                    request["_future"] = fut
                    sched.submit(request)

    def _worker_loop(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            item = self._queue.get()
            if item is None:  # stop sentinel
                break
            request, fut = item
            try:
                fut.set_result(self._respond(request))
            except Exception as e:  # noqa: BLE001 — worker must survive
                try:
                    fut.set_result(_error("internal",
                                          f"{type(e).__name__}: {e}"))
                except Exception:  # future already resolved/cancelled
                    pass

    # -- health -----------------------------------------------------------

    def status(self) -> Dict:
        """The /healthz document — every number here is a registry read
        (the same counters /metrics exposes), no service-private state."""
        counts = {labels["outcome"]: int(v) for labels, v in
                  self.registry.series("raft_requests_total")}

        def pct(p: float) -> Optional[float]:
            v = self._latency.percentile(p)
            return None if v is None else v * 1e3

        return {
            "queue": {"depth": self._queue.qsize(),
                      "max": self.cfg.max_queue,
                      "workers": (1 if self._batched
                                  else self.cfg.workers)},
            "requests": counts,
            "latency_ms": {"p50": pct(0.50), "p99": pct(0.99),
                           "n": self._latency.n},
            "batching": (self._scheduler.status()
                         if self._scheduler is not None else None),
            "session": self.session.status(),
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the shared registry — the
        /metrics endpoint body (session + service + scheduler series,
        one scrape)."""
        return self.registry.render_prometheus()

"""Stdlib-only request service over an InferenceSession.

Queueing discipline for a latency-bound model server, with nothing but
``threading`` + ``queue``:

- **bounded depth + explicit backpressure**: a full queue rejects the
  request *immediately* (``queue_full``) instead of stretching every
  caller's latency without bound;
- **admission control at submit time**: malformed inputs (validate.py)
  never occupy a queue slot or a device;
- **per-request deadlines**: requests that expire while queued are
  rejected on dequeue without touching the device; live ones carry their
  absolute deadline into the session's degrade policy;
- **crash-proof workers**: a worker turns *any* session failure into a
  structured error response — one poisoned request cannot take the
  process down (fault-storm-pinned in tests/test_serve.py);
- **continuous batching** (``SessionConfig.max_batch > 1``): the worker
  pool is replaced by ONE scheduler thread driving
  :class:`~raft_stereo_tpu.serve.scheduler.BatchScheduler` — requests
  join a running device batch at tick boundaries and exit at segment
  boundaries. The queue, admission, backpressure (``queue_full``) and
  response contract are IDENTICAL to the sequential path;
- **/healthz**: ``status()`` folds session state (bucket cache, breaker
  trips, canary), queue depth, request counters by rejection/error code,
  degraded-request count, p50/p99 latency over a sliding window, and — in
  batched mode — the ``batching`` document (per-tick occupancy histogram,
  joins/exits per tick, pad-row waste, tick latency percentiles: the
  numbers that tune ``--max_batch`` in production).

Every response is a plain dict: ``{"status": "ok" | "rejected" |
"error", ...}`` — ``ok`` always carries a finite disparity and an honest
``quality`` label.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from concurrent.futures import Future
from typing import Dict, Optional

import logging

from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.obs.usage import sanitize_tenant
from raft_stereo_tpu.serve.session import (DeadlineExceeded, InferenceSession,
                                           SessionError)
from raft_stereo_tpu.serve.supervise import (Heartbeat, Supervisor,
                                             WatchdogTrip, drain_deadline,
                                             drain_expired,
                                             resolve_drain_grace_ms,
                                             resolve_retry_budget,
                                             resolve_watchdog_ms)
from raft_stereo_tpu.serve.validate import InputRejected, validate_pair

logger = logging.getLogger(__name__)

#: Response codes the retry budget may re-admit (graftguard, DESIGN.md
#: r13).  Everything else is deterministic — validation failures,
#: internal invariants — and fails fast.  ``nonfinite_output`` is
#: special-cased: retried at most once (the issue contract: a second
#: non-finite output is a deterministic failure, not noise).
TRANSIENT_CODES = frozenset({
    "upload_failed",         # uploader thread death (bounce brings a new one)
    "device_hang",           # watchdog-detected hung invocation
    "scheduler_restarted",   # generation bounce (tick crash/stall)
    "nonfinite_output",      # possibly-transient poisoned output (once)
})


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    max_queue: int = 8
    workers: int = 1
    # Applied when a request carries no deadline_ms of its own; None means
    # undegraded full-iteration serving by default.
    default_deadline_ms: Optional[float] = None
    latency_window: int = 512
    # Scheduler idle poll (continuous batching only): how long the
    # scheduler thread blocks for new work when every bucket is empty.
    # None reads RAFT_SCHED_TICK_MS at start() (default 2 ms). Purely a
    # host-side latency/CPU trade — it never shapes a compiled program.
    tick_ms: Optional[float] = None
    # SLO flight recorder (obs/flight.py): a served request whose
    # end-to-end latency exceeds slo_ms * slo_factor — or any request
    # that tripped a breaker rung, missed its deadline or produced a
    # non-finite output — persists a bounded flight record to
    # RAFT_FLIGHT_DIR. slo_ms=None disables the latency criterion only;
    # the other breach classes always record when the recorder is armed.
    slo_ms: Optional[float] = None
    slo_factor: float = 1.0
    # graftguard (serve/supervise.py). All three resolve at construction:
    # explicit value > env knob > default — host-side serving behavior
    # only, never part of any program fingerprint (analysis/knobs.py
    # SERVE_ENV_KNOBS).
    #
    # retry_budget: bounded re-admissions per request for TRANSIENT
    # failures (upload_failed / generation bounce / a first non-finite
    # output), original deadline honored, count surfaced as
    # ``retries: k`` on the response. None -> RAFT_RETRY_BUDGET -> 2.
    retry_budget: Optional[int] = None
    # watchdog_ms: hang-deadline floor for the invocation watchdog and
    # heartbeat staleness. None -> RAFT_WATCHDOG_MS -> 0 = supervision
    # disarmed (the library default; serve_stereo.py defaults it ON).
    watchdog_ms: Optional[float] = None
    # drain_grace_ms: graceful-drain hard deadline (real time) before
    # remaining Futures resolve service_stopped.
    # None -> RAFT_DRAIN_GRACE_MS -> 10 s.
    drain_grace_ms: Optional[float] = None
    # supervise: run the watchdog monitor thread (batched mode with a
    # positive watchdog floor only). check_now() remains drivable by
    # hand either way.
    supervise: bool = True
    # graftstream (serve/stream.py). All three resolve at construction:
    # explicit value > env knob > default — host-side session-table
    # sizing and a host-side norm comparison, never part of any program
    # fingerprint (analysis/knobs.py HOST_ENV_KNOBS).
    #
    # stream_sessions: global bound on live stream sessions (LRU).
    # None -> RAFT_STREAM_SESSIONS -> 128.
    stream_sessions: Optional[int] = None
    # stream_ttl_ms: idle-session expiry on the session clock.
    # None -> RAFT_STREAM_TTL_MS -> 60 s.
    stream_ttl_ms: Optional[float] = None
    # converge_tol: default convergence tolerance stamped on warm
    # frames (px/iter segment-mean |delta_x| at 1/8 res; 0 disables).
    # None -> RAFT_CONVERGE_TOL -> 0.01.
    converge_tol: Optional[float] = None
    # graftrecall (serve/cache.py). All four resolve at construction:
    # explicit value > env knob > default — a host-side response store,
    # never part of any program fingerprint (the fingerprint is folded
    # INTO every cache key instead; analysis/knobs.py HOST_ENV_KNOBS).
    #
    # cache_bytes: host-RAM budget for the two-tier response cache.
    # None -> RAFT_CACHE_BYTES -> 0 = disabled (the library default —
    # the watchdog stance: test rigs and embedders opt in,
    # serve_stereo.py defaults it ON at 256 MiB).
    cache_bytes: Optional[int] = None
    # cache_ttl_ms: entry expiry on the session clock.
    # None -> RAFT_CACHE_TTL_MS -> 10 min.
    cache_ttl_ms: Optional[float] = None
    # cache_near_tol: near-tier block-mean signature threshold (gray
    # levels; 0 = near tier off). None -> RAFT_CACHE_NEAR_TOL -> 0.
    cache_near_tol: Optional[float] = None
    # cache_dir: optional disk spill for evicted exact-tier entries.
    # None -> RAFT_CACHE_DIR -> RAM only.
    cache_dir: Optional[str] = None


def _reject(code: str, message: str) -> Dict:
    return {"status": "rejected", "code": code, "message": message}


def _error(code: str, message: str) -> Dict:
    return {"status": "error", "code": code, "message": message}


class StereoService:
    """Request queue + worker pool around one :class:`InferenceSession`."""

    def __init__(self, session: InferenceSession,
                 service_cfg: Optional[ServiceConfig] = None):
        self.session = session
        self.cfg = service_cfg or ServiceConfig()
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.cfg.max_queue)
        self._workers = []
        self._stop = threading.Event()
        # graftscope: request counters and the latency reservoir live in
        # the session's ONE registry (shared with the scheduler), so
        # /healthz is derivable from /metrics byte-for-byte — no service-
        # private Counter/deque to fold in.
        self.registry = session.registry
        self.tracer = session.tracer
        self.profiler = session.profiler
        self._latency = self.registry.histogram(
            "raft_request_latency_seconds",
            "end-to-end served-request latency (bounded reservoir)",
            reservoir=self.cfg.latency_window)
        self._gauge_depth = self.registry.gauge(
            "raft_queue_depth", "requests currently queued (bounded by "
            "max_queue)")
        self._lock = threading.Lock()
        self._started = False
        # graftfleet: /healthz carries generation identity + age at the
        # top level so a fleet router can detect deploy-generation
        # membership and restarts from the ONE endpoint it already
        # polls (fingerprint otherwise lives only on /debug/config).
        self._born = self.session.clock.now()
        # graftguard (serve/supervise.py): generation counter, drain
        # flag, retry budget, watchdog config. The scheduler generation
        # is bounced (fresh scheduler + thread, rows re-admitted) by the
        # supervisor on a watchdog trip; _zombies keeps retired threads
        # joinable at stop().
        self._draining = False
        self._generation = 0
        self._zombies: list = []
        self._heartbeat: Optional[Heartbeat] = None
        self._supervisor: Optional[Supervisor] = None
        self._inflight = 0   # sequential-mode requests between dequeue
        #                      and resolution (drain quiescence check)
        # Futures admitted by submit() and not yet resolved. Queue depth
        # + scheduler state alone cannot prove quiescence: a row lives in
        # NEITHER for the instant between a consumer's dequeue and its
        # adoption (sched.submit / the worker's _inflight increment), so
        # a _quiesced() poll in that window would report a clean drain
        # with work still in flight. This counter spans the whole life of
        # an admitted Future — incremented under the enqueue lock, decre-
        # mented exactly once at resolution (_claim guards the batched
        # paths; a worker-mode item has exactly one consumer).
        self._outstanding = 0
        self._retry_budget = resolve_retry_budget(self.cfg.retry_budget)
        self._watchdog_s = resolve_watchdog_ms(self.cfg.watchdog_ms) / 1e3
        self._drain_grace_s = resolve_drain_grace_ms(
            self.cfg.drain_grace_ms) / 1e3
        # Continuous batching engages when the SESSION was built for it
        # (max_batch shapes compiled programs and warmup, so it lives on
        # SessionConfig); the service then runs one scheduler thread
        # instead of the worker pool. The scheduler itself is built per
        # START generation (like the stop event): stop() permanently ends
        # its uploader thread and drains its state, so reusing one across
        # a restart would hang every post-restart request in the join
        # queue.
        self._batched = session.cfg.max_batch > 1
        self._scheduler = None
        # graftstream (serve/stream.py): the bounded session table +
        # warm-start/convergence stamping protocol.  Always constructed
        # (zero sessions when no client streams); serving paths count
        # warm joins / converged exits through it, and the response
        # hooks below deposit each served frame's low-res flow BEFORE
        # the Future resolves.
        from raft_stereo_tpu.serve.stream import StreamManager
        self.stream = StreamManager(
            session, max_sessions=self.cfg.stream_sessions,
            ttl_ms=self.cfg.stream_ttl_ms,
            converge_tol=self.cfg.converge_tol)
        # graftrecall (serve/cache.py): the two-tier content-addressed
        # response cache.  Always constructed (zero state when
        # disabled); admission consults it after validation, response
        # resolution deposits into it BEFORE the Future resolves.  The
        # stream's converge_tol is its warm-exit default so both
        # warm-start flavors (stream seed, near-tier seed) exit by one
        # rule.
        from raft_stereo_tpu.serve.cache import ResponseCache
        self.cache = ResponseCache(
            session, max_bytes=self.cfg.cache_bytes,
            ttl_ms=self.cfg.cache_ttl_ms,
            near_tol=self.cfg.cache_near_tol,
            cache_dir=self.cfg.cache_dir,
            default_converge_tol=self.stream.converge_tol)

    # -- lifecycle --------------------------------------------------------

    def _tick_s(self) -> float:
        import os
        tick_ms = self.cfg.tick_ms
        if tick_ms is None:
            tick_ms = float(os.environ.get("RAFT_SCHED_TICK_MS", "2"))
        return tick_ms / 1e3

    def _spawn_scheduler_thread(self, sched, stop_event, hb) -> threading.Thread:
        t = threading.Thread(target=self._scheduler_loop,
                             args=(sched, stop_event, self._tick_s(), hb),
                             name=f"stereo-scheduler-g{self._generation}",
                             daemon=True)
        t.start()
        return t

    def start(self) -> "StereoService":
        with self._lock:
            if self._started:
                return self
            self._started = True
            self._draining = False
            self._generation += 1
            # Fresh event per generation: a worker that outlives a timed-out
            # join (e.g. mid-compile on a cold bucket) still holds its OWN
            # generation's set event and exits when its request finishes —
            # it can never be revived as an untracked extra worker.
            self._stop = threading.Event()
            stop_event = self._stop
            if self._batched:
                from raft_stereo_tpu.serve.scheduler import BatchScheduler
                # Fresh scheduler per generation; a previous generation's
                # thread (possibly still ticking its own active rows out
                # past the join timeout) holds only its OWN scheduler and,
                # once its stop event is set, never touches the shared
                # queue again — two generations can never race on one
                # batch state.
                self._scheduler = BatchScheduler(
                    self.session, resolve=self._resolve_scheduled,
                    retry=self._retry_scheduled,
                    generation=self._generation, stream=self.stream,
                    cache=self.cache)
                self._heartbeat = Heartbeat("scheduler", self.session.clock)
                sched, hb = self._scheduler, self._heartbeat
                # Spawn + publish INSIDE the lock — the same invariant
                # _bounce() enforces: supervised_state() reads scheduler/
                # heartbeat/_stop/_workers under this lock, so a
                # concurrent check_now() can never observe started=True
                # with no live worker and stopping=False — a gap it
                # would misread as tick_crashed and bounce the brand-new
                # generation before its thread was even published.
                self._workers.append(
                    self._spawn_scheduler_thread(sched, stop_event, hb))
        if self._batched:
            # The watchdog monitor: armed only with a positive floor —
            # RAFT_WATCHDOG_MS=0 (the library default) leaves supervision
            # off so FakeClock test rigs never race a real-time poll.
            if self.cfg.supervise and self._watchdog_s > 0:
                if self._supervisor is None:
                    self._supervisor = Supervisor(
                        self, watchdog_s=self._watchdog_s)
                self._supervisor.start()
            return self
        for i in range(self.cfg.workers):
            t = threading.Thread(target=self._worker_loop,
                                 args=(stop_event,),
                                 name=f"stereo-worker-{i}", daemon=True)
            t.start()
            with self._lock:
                self._workers.append(t)
        return self

    def stop(self) -> None:
        sup = self._supervisor
        if sup is not None:
            sup.stop()
        with self._lock:
            # Flip first, under the same lock submit() enqueues under: any
            # submit that saw started=True has already enqueued, so the
            # drain below provably sees it; later submits are rejected.
            self._started = False
            self._stop.set()
        for _ in self._workers:
            try:
                self._queue.put_nowait(None)  # wake sentinel
            except queue.Full:
                break  # queue backlog itself will wake the workers
        for t in self._workers:
            t.join(timeout=10)
        # Resolve every still-queued Future with a structured rejection —
        # an abandoned Future deadlocks any caller blocked on .result().
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            request, fut = item
            self._force_resolve(request, fut, drain_event=True)
        self._gauge_depth.set(self._queue.qsize())
        # Stream sessions die with the service (graftstream lifecycle):
        # a restart serves cold first frames — a stale held flow must
        # never outlive the service generation that produced it.
        dropped = self.stream.drop_all()
        if dropped:
            logger.info("dropped %d stream session(s) on stop", dropped)
        # The response cache dies with the service too (graftrecall
        # lifecycle): a restart serves cold — RAM entries must never
        # outlive the generation that produced them (the optional
        # RAFT_CACHE_DIR spill persists deliberately; its entries are
        # fingerprint-keyed so a config-changed restart cannot read
        # them).
        dropped = self.cache.drop_all()
        if dropped:
            logger.info("dropped %d cached response(s) on stop", dropped)
        with self._lock:
            self._workers = [t for t in self._workers if t.is_alive()]
        # Zombie threads (generations retired by a bounce whose join
        # timed out — e.g. wedged in a real device hang) are joined LAST:
        # they never touch the shared queue once defunct, so waiting on
        # them before the drain above would only delay force-resolution
        # of every queued Future by the join timeout per leaked thread.
        # One SHARED deadline across all of them: k wedged generations
        # must not cost k x 5 s of shutdown latency.
        import time as _time
        deadline = _time.monotonic() + 5.0
        for t in self._zombies:
            t.join(timeout=max(0.0, deadline - _time.monotonic()))
        self._zombies = [t for t in self._zombies if t.is_alive()]

    # -- graceful drain (graftguard, DESIGN.md r13) ------------------------

    def begin_drain(self) -> None:
        """Flip into draining: new submits are rejected
        ``service_draining``; everything already admitted keeps running
        to its segment-boundary exit. Idempotent and non-blocking (a
        signal-driven caller flips this, then keeps consuming its
        in-flight Futures)."""
        with self._lock:
            if self._draining or not self._started:
                return
            self._draining = True
            sched = self._scheduler
        logger.info("service draining: new submits rejected "
                    "service_draining; admitted rows run to their exits")
        if sched is not None:
            for request in sched.inflight_requests():
                trace = request.get("_trace")
                if trace is not None:
                    trace.event("drain", action="run_to_exit")

    def drain(self, grace_s: Optional[float] = None) -> bool:
        """Graceful shutdown: reject new work, run everything admitted to
        its exit, then stop. Returns True when the service quiesced
        within the grace window (real time — drain is an operational
        action); on timeout, stop() force-resolves the remainder
        ``service_stopped`` so no Future is ever abandoned."""
        import time as _time
        self.begin_drain()
        deadline = drain_deadline(self._drain_grace_s
                                  if grace_s is None else grace_s)
        clean = False
        while not drain_expired(deadline):
            if self._quiesced():
                clean = True
                break
            _time.sleep(0.01)
        if not clean:
            logger.warning(
                "drain grace expired with work in flight — remaining "
                "Futures resolve service_stopped")
        self.stop()
        return clean

    def _quiesced(self) -> bool:
        with self._lock:
            # _outstanding spans an admitted Future's whole life, so the
            # dequeued-but-not-yet-adopted instant (invisible to both the
            # queue and the scheduler/_inflight) cannot read as quiesced.
            if self._outstanding or self._inflight:
                return False
            sched = self._scheduler if self._batched else None
        if not self._queue.empty():
            return False
        return sched is None or not (sched.active_rows > 0 or sched.has_work)

    def __enter__(self) -> "StereoService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- request path -----------------------------------------------------

    def _count(self, outcome: str) -> None:
        """One request outcome into the registry (same keys /healthz has
        always reported: 'ok', 'rejected:<code>', 'error:<code>',
        'degraded')."""
        self.registry.counter(
            "raft_requests_total", "request outcomes by disposition",
            outcome=outcome).inc()

    def _tenant_label(self, request: Dict) -> str:
        """Bounded usage label for one request's tenant (the ingress
        stamps ``request['tenant']`` with the sanitized header key;
        in-process callers default to 'default')."""
        return self.session.usage.label(
            sanitize_tenant(request.get("tenant")))

    def _count_outcome(self, request: Dict, outcome: str) -> None:
        """One request outcome into BOTH series: the service-wide
        ``raft_requests_total`` and the per-tenant usage account
        (obs/usage.py) — same outcome key, so the two reconcile."""
        self._count(outcome)
        self.session.usage.count_request(self._tenant_label(request),
                                         outcome)

    @staticmethod
    def _finish_trace(request: Dict, resp: Dict) -> None:
        trace = request.get("_trace")
        if trace is not None:
            trace.finish(status=resp["status"], code=resp.get("code"),
                         quality=resp.get("quality"))

    # -- SLO flight recorder ----------------------------------------------

    def _breach_reasons(self, resp: Dict, spans) -> list:
        """Why this response is an SLO breach (empty = healthy). The
        latency criterion needs an explicit slo_ms; breaker trips,
        missed deadlines and non-finite outputs always count."""
        reasons = []
        if resp.get("code") == "nonfinite_output":
            reasons.append("nonfinite_output")
        if any(s.kind == "breaker_trip" for s in spans):
            reasons.append("breaker_trip")
        # Served-but-late (degrade sets deadline_missed) AND rejected-as-
        # expired (deadline_exceeded / deadline_exceeded_in_queue) both
        # count: the queue-backlog rejection is exactly the case whose
        # queue_wait timeline an operator needs most.
        if resp.get("deadline_missed") or \
                str(resp.get("code", "")).startswith("deadline_exceeded"):
            reasons.append("deadline_missed")
        elapsed = resp.get("elapsed_ms")
        if (self.cfg.slo_ms is not None and elapsed is not None
                and elapsed > self.cfg.slo_ms * self.cfg.slo_factor):
            reasons.append("latency_slo")
        return reasons

    def _maybe_flight(self, request: Dict, resp: Dict) -> None:
        """Persist a flight record when this (already trace-finished)
        response breached its SLO. Runs on the response-resolution path
        for BOTH serving modes, so it must never raise — the recorder is
        failure-isolated, and this wrapper only reads local state."""
        flight = self.session.flight
        if not flight.enabled:
            return
        trace = request.get("_trace")
        spans = getattr(trace, "spans", None) or []
        reasons = self._breach_reasons(resp, spans)
        if not reasons:
            return
        # Ledger rows of every program the request actually rode: spans
        # carry the program's ledger id (session.invoke / the scheduler
        # stamp them), and the ledger is bounded by the LRU cache size.
        ids = {s.attrs.get("program") for s in spans
               if s.attrs.get("program")}
        # Tick-seq range (obs/deck.py): device spans carry the flight-
        # deck seq of the tick they rode, so the post-mortem names the
        # exact ticks to pull from GET /debug/ticks.
        tick_seqs = sorted({s.attrs.get("tick") for s in spans
                            if s.attrs.get("tick") is not None})
        doc = {
            "schema": 1,
            "ticks": ({"first": tick_seqs[0], "last": tick_seqs[-1],
                       "count": len(tick_seqs)} if tick_seqs else None),
            "reasons": reasons,
            "slo_ms": self.cfg.slo_ms,
            "slo_factor": self.cfg.slo_factor,
            "response": {k: resp.get(k) for k in
                         ("id", "status", "code", "quality", "iters",
                          "elapsed_ms", "deadline_missed")},
            "trace": (trace.to_dict()
                      if trace is not None and trace is not NULL_TRACE
                      else None),
            "programs": self.session.ledger.rows_by_id(ids),
            "breaker": self.session.breaker.status(),
            "metrics": self.registry.snapshot(),
        }
        flight.record(doc, trace_id=getattr(trace, "trace_id", None))

    def _admit(self, request: Dict) -> Optional[Dict]:
        """Validation + deadline stamping; returns a rejection dict or
        None. Mutates ``request``: a trace is opened (trace id at
        admission), the absolute ``_deadline`` is stamped and left/right
        are replaced with their validated canonical form, so the session
        skips a second O(N) validation pass on dequeue."""
        trace = request.get("_trace")
        if trace is None:
            trace = self.tracer.start_request(request.get("id"))
            request["_trace"] = trace
        try:
            request["left"], request["right"] = validate_pair(
                request["left"], request["right"],
                self.session.cfg.admission)
        except InputRejected as e:
            trace.mark("admission", rejected=e.code)
            return _reject(f"invalid_input:{e.code}", str(e))
        except KeyError as e:
            trace.mark("admission", rejected="missing_field")
            return _reject("invalid_input:missing_field",
                           f"request missing {e}")
        deadline_ms = request.get("deadline_ms",
                                  self.cfg.default_deadline_ms)
        request["_deadline"] = (
            None if deadline_ms is None
            else self.session.clock.now() + deadline_ms / 1e3)
        # graftstream: resolve the stream session (if any) and stamp the
        # warm-start seed + convergence tolerance onto the request.  On
        # the request dict deliberately — a generation bounce re-admits
        # harvested rows from these dicts, so a bounced stream frame
        # stays warm (chaos-pinned).
        self.stream.admit(request)
        trace.mark("admission", h=int(request["left"].shape[1]),
                   w=int(request["left"].shape[2]),
                   deadline_ms=deadline_ms,
                   warm=request.get("_flow_init") is not None)
        return None

    def _serve_cache_hit(self, request: Dict, resp: Dict) -> Dict:
        """Finalize one exact-tier cache hit (graftrecall): stream
        deposit first (the entry's held low-res flow keeps a stream
        session warm across a hit), then the normal resolution tail —
        id, counters, trace.  ZERO device seconds by construction: no
        invoke, no tick, no program counter, no usage nanosecond moves
        (the PR 12 three-way reconciliation delta is exactly 0 —
        test-pinned in tests/test_cache.py)."""
        flow = request.pop("_cache_stream_flow", None)
        if flow is not None:
            request["_stream_flow"] = flow
            request["_stream_shape"] = request.pop(
                "_cache_stream_shape", None)
        self.stream.deposit(request, resp)
        if request.get("id") is not None:
            resp["id"] = request["id"]
        # Label-not-full keeps counting in `degraded`; the label
        # distinguishes (the r17 converged:k stance — cache:exact IS the
        # full-quality answer, the counter key is just mechanical).
        if resp.get("quality") != "full":
            self._count("degraded")
        self._count_outcome(request, "ok")
        self._finish_trace(request, resp)
        return resp

    def _respond_once(self, request: Dict) -> Dict:
        """One serving attempt, synchronously, never raising — no
        counters, no trace finishing (the retry loop in ``_respond``
        owns those)."""
        trace = request.get("_trace") or NULL_TRACE
        trace.mark("queue_wait")
        try:
            deadline = request.get("_deadline")
            if deadline is not None and self.session.clock.now() >= deadline:
                resp = _reject("deadline_exceeded_in_queue",
                               "deadline expired before the request "
                               "reached a device")
            else:
                t0 = self.session.clock.now()
                # Sequential tenant attribution: this worker thread runs
                # exactly one request's device calls — bind its label so
                # invoke attributes the whole steady device time to it.
                label = self._tenant_label(request)
                # A stream member always takes the segmented path — a
                # COLD first frame must still deposit its low-res flow
                # or the stream never warms (bit-identical to the full
                # program by the composition pins, so routing cold
                # frames here costs nothing but program count).  Known
                # tradeoff (DESIGN.md r17): this path has no half-res
                # degrade rung — a held warm-start seed is keyed to the
                # full-res bucket, so halving would discard it; a
                # deadline that cannot absorb one full-res segment
                # resolves as reduced_iters with deadline_missed
                # reported honestly instead of the stateless path's
                # half_res route.  ROADMAP item 4's tier cascade is the
                # planned principled home for cross-resolution demotion.
                # graftrecall: with the near tier armed, EVERY
                # sequential request runs the segmented composition so
                # its 1/8-res flow is produced for the cache deposit
                # (bit-identical to the full program by the PR 3/5
                # composition pins; same no-half-res tradeoff as
                # streams — DESIGN.md r18).
                streaming = (request.get("_stream") is not None
                             or request.get("_flow_init") is not None
                             or request.get("_converge_tol") is not None
                             or self.cache.wants_flow)
                cache_warm = bool(request.get("_cache_warm"))
                with self.session.usage_riders([label]):
                    if streaming:
                        # graftstream sequential path: the segmented
                        # prepare[_warm]/advance/epilogue composition so
                        # warm starts and convergence exits engage
                        # (bit-identical to the full program when
                        # neither fires — the composition pins).
                        from raft_stereo_tpu.serve.stream import \
                            stream_infer
                        out = stream_infer(
                            self.session, request["left"],
                            request["right"],
                            flow_init=request.get("_flow_init"),
                            converge_tol=request.get("_converge_tol"),
                            deadline=deadline, prevalidated=True,
                            trace=trace)
                        result = out.result
                        if out.warm and not cache_warm:
                            # Counted where it happened (the warm
                            # prepare actually ran) — the scheduler's
                            # accounting stance, mirrored.  Cache-seeded
                            # rows were counted by ResponseCache.admit
                            # and must not inflate the stream series.
                            self.stream.note_warm_join(label)
                        if request.get("_stream") is not None:
                            request["_stream_flow"] = out.flow_low
                            request["_stream_shape"] = out.padded_shape
                        # Every computed response carries its low-res
                        # flow for the cache deposit (the near tier
                        # seeds future near-duplicates from it).
                        request["_cache_flow"] = out.flow_low
                        request["_cache_shape"] = out.padded_shape
                        if result.quality.startswith("converged:"):
                            if cache_warm:
                                # Honest near-tier label: the k is the
                                # iterations actually run, same
                                # contract as converged:k.
                                result.quality = \
                                    f"warm:cache:{result.iters}"
                            else:
                                self.stream.note_converged(label)
                    else:
                        result = self.session.infer(
                            request["left"], request["right"],
                            deadline=deadline,
                            allow_half_res=request.get("allow_half_res"),
                            prevalidated=True, trace=trace)
                self._latency.observe(self.session.clock.now() - t0)
                resp = {
                    "status": "ok",
                    "quality": result.quality,
                    "disparity": result.disparity,
                    "iters": result.iters,
                    "elapsed_ms": result.elapsed_s * 1e3,
                    "deadline_missed": result.deadline_missed,
                }
        except InputRejected as e:
            resp = _reject(f"invalid_input:{e.code}", str(e))
        except DeadlineExceeded as e:
            resp = _reject(e.code, str(e))
        except SessionError as e:
            resp = _error(e.code, str(e))
        except Exception as e:  # noqa: BLE001 — the crash-proofing boundary
            resp = _error("internal", f"{type(e).__name__}: {e}")
        return resp

    def _respond(self, request: Dict) -> Dict:
        """One request, synchronously, never raising; transient failures
        re-run inline under the retry budget (the sequential twin of the
        scheduler's re-admission path)."""
        while True:
            resp = self._respond_once(request)
            if resp["status"] == "ok" or \
                    not self._note_retry_if_transient(request, resp):
                break
        return self._finalize(request, resp)

    def _finalize(self, request: Dict, resp: Dict) -> Dict:
        """Count, stamp retries, finish the trace, flight-record — the
        single resolution tail every sequential response goes through."""
        # Deposit the served frame's warm-start seed FIRST: a client
        # that receives this response and immediately sends the next
        # frame must find the session warm.  Same ordering for the
        # response cache (graftrecall): a client that reads this
        # response and resubmits the identical frame is guaranteed an
        # exact hit.
        self.stream.deposit(request, resp)
        self.cache.deposit(request, resp)
        if request.get("id") is not None:
            resp["id"] = request["id"]
        retries = request.get("_retries", 0)
        if retries:
            resp["retries"] = retries
        key = resp["status"]
        if resp["status"] != "ok":
            key = f'{resp["status"]}:{resp["code"]}'
        elif resp.get("quality") != "full":
            self._count("degraded")
        self._count_outcome(request, key)
        self._finish_trace(request, resp)
        self._maybe_flight(request, resp)
        return resp

    # -- bounded per-request retries (graftguard, DESIGN.md r13) -----------

    def _note_retry_if_transient(self, request: Dict, resp: Dict) -> bool:
        """Decide + account one retry: True means the caller must re-run
        (sequential) or re-admit (batched/bounce) the request instead of
        resolving this response. Deterministic failures, exhausted
        budgets, expired deadlines and a second non-finite output all
        return False — fail fast, honestly."""
        code = resp.get("code")
        if resp.get("status") == "ok" or code not in TRANSIENT_CODES:
            return False
        if code == "nonfinite_output":
            n = request.get("_nonfinite", 0) + 1
            request["_nonfinite"] = n
            if n >= 2:  # twice non-finite = deterministic, not noise
                return False
        attempt = request.get("_retries", 0)
        if attempt >= self._retry_budget:
            return False
        deadline = request.get("_deadline")
        if deadline is not None and self.session.clock.now() >= deadline:
            return False  # the original deadline is honored, not reset
        request["_retries"] = attempt + 1
        self.registry.counter(
            "raft_request_retries_total",
            "transient-failure re-admissions under the retry budget").inc()
        trace = request.get("_trace")
        if trace is not None:
            trace.event("retry", code=code, attempt=attempt + 1)
        return True

    def _requeue(self, request: Dict, wait_s: float = 1.0) -> bool:
        """Put a retried/bounced request back on the queue for the live
        scheduler generation.  The started-check and the put are ONE
        critical section under the same lock ``stop()`` flips
        ``_started`` under before its one-shot queue drain — submit()'s
        proof, reused: any item enqueued while started was True is
        provably seen by that drain, so a stop racing a retry can never
        strand the Future (a check-then-blocking-put would).  Waits up
        to ``wait_s`` on a full queue rather than bouncing off the
        backpressure bound — this request was already admitted once.
        Callers ON the live scheduler thread must pass ``wait_s=0``:
        that thread is the queue's only consumer, so waiting there can
        never succeed — it would only stall the tick loop."""
        import time as _time
        fut = request.get("_future")
        deadline = _time.monotonic() + wait_s
        while True:
            with self._lock:
                if not self._started:
                    return False
                try:
                    self._queue.put_nowait((request, fut))
                except queue.Full:
                    pass
                else:
                    self._gauge_depth.set(self._queue.qsize())
                    return True
            if _time.monotonic() >= deadline:
                return False
            # Re-admitting stranded rows during a bounce polls a full
            # queue in 10 ms beats; the bounce already owns _check_lock
            # (one recovery at a time) and serving never waits on it.
            # graftlint: disable=GC203 (bounded requeue poll inside the one-bounce-at-a-time sweep)
            _time.sleep(0.01)

    def _force_resolve(self, request: Dict, fut=None, *,
                       drain_event: bool = False) -> None:
        """Structurally resolve a request that will never be served:
        claim, reject ``service_stopped``, count, finish the trace,
        resolve the Future — the one force-resolution tail ``stop()``'s
        queue drain and ``_unadopt`` share."""
        if not self._claim(request):
            return  # resolved by a retiring generation already
        self._mark_resolved()
        resp = _reject("service_stopped",
                       "service stopped before this request ran")
        if request.get("id") is not None:
            resp["id"] = request["id"]
        trace = request.get("_trace")
        if drain_event and trace is not None:
            # Drain decision on the timeline: this request was force-
            # resolved at the hard deadline, not served.
            trace.event("drain", action="force_resolved",
                        code="service_stopped")
        self._count_outcome(request, "rejected:service_stopped")
        self._finish_trace(request, resp)
        if fut is None:
            fut = request.get("_future")
        if fut is not None:
            try:
                fut.set_result(resp)
            except Exception:  # already resolved/cancelled
                pass

    def _unadopt(self, request: Dict) -> None:
        """A retiring generation dequeued this request but must not run
        it: push it back for the live generation; if the service is no
        longer running (a stopped service's one-shot queue drain may
        already have passed — a requeue would strand it) or the queue is
        wedged, resolve it structurally — never strand it."""
        if self._requeue(request):
            return
        self._force_resolve(request)

    def _retry_scheduled(self, request: Dict, resp: Dict) -> bool:
        """Scheduler retry hook: re-admit a transiently-failed batched
        request through the shared queue (whichever generation is live
        picks it up). Called on the scheduler thread."""
        with self._lock:
            live = self._started
        if not live:
            return False
        if not self._note_retry_if_transient(request, resp):
            return False
        # wait_s=0: this hook runs on the live scheduler thread — the
        # queue's only consumer — so waiting on a full queue could never
        # succeed and would stall every batchmate's tick for the wait.
        if self._requeue(request, wait_s=0):
            return True
        # Could not re-admit (queue full) — undo nothing: the retry
        # was recorded, the response resolves with its true retry count.
        return False

    @staticmethod
    def _draining_rejection() -> Dict:
        """The one service_draining rejection — handle(), submit()'s fast
        path and its locked re-check must never drift apart on the wire."""
        return _reject("service_draining",
                       "service is draining — not accepting new requests")

    def handle(self, request: Dict) -> Dict:
        """Synchronous path: admit, run, respond. The fault-storm battery
        drives this for deterministic ordering. In batched mode with the
        scheduler running, the request rides the batch like any other
        (submit + wait); otherwise it runs the sequential session path."""
        with self._lock:
            batched_live = self._batched and self._started
            draining = self._draining
        if batched_live:
            return self.submit(request).result()
        if draining:
            rejection = self._draining_rejection()
            if request.get("id") is not None:
                rejection["id"] = request["id"]
            self._count_outcome(request, f'rejected:{rejection["code"]}')
            self._finish_trace(request, rejection)
            return rejection
        rejection = self._admit(request)
        if rejection is not None:
            if request.get("id") is not None:
                rejection["id"] = request["id"]
            self._count_outcome(request, f'rejected:{rejection["code"]}')
            self._finish_trace(request, rejection)
            return rejection
        hit = self.cache.admit(request)
        if hit is not None:
            return self._serve_cache_hit(request, hit)
        return self._respond(request)

    def submit(self, request: Dict) -> Future:
        """Async path: admission + bounded enqueue. The returned Future
        always resolves to a response dict (rejections included)."""
        fut: Future = Future()
        # Draining fast path BEFORE validation (mirrors handle()): a
        # doomed submit must not pay the O(N) finite-scan, and both
        # entry points must reject with the same code during a drain.
        # The authoritative re-check below (under the lock begin_drain
        # flips under) still closes the race window.
        with self._lock:
            draining = self._draining
        if draining:
            rejection = self._draining_rejection()
        else:
            rejection = self._admit(request)
        if rejection is None:
            with self._lock:
                live = self._started
            # graftrecall: an exact cache hit resolves the Future RIGHT
            # HERE — it never occupies a queue slot, never joins a
            # batch, never counts toward _outstanding (nothing is in
            # flight; the response is already in hand at zero device
            # seconds).  Only while the service is RUNNING: submit()'s
            # lifecycle contract is not_running otherwise, and a
            # stopped service with a warm RAFT_CACHE_DIR must not keep
            # answering from the grave (the started re-check under the
            # enqueue lock below stays authoritative for the queue).
            hit = self.cache.admit(request) if live else None
            if hit is not None:
                fut.set_result(self._serve_cache_hit(request, hit))
                return fut
        if rejection is None:
            # started-check + enqueue under the lifecycle lock: stop()
            # flips _started under the same lock before draining, so a
            # request can never land in the queue after the drain.
            with self._lock:
                if self._draining:
                    rejection = self._draining_rejection()
                elif not self._started:
                    rejection = _reject("not_running",
                                        "service is not started")
                else:
                    try:
                        self._queue.put_nowait((request, fut))
                        self._outstanding += 1
                        self._gauge_depth.set(self._queue.qsize())
                    except queue.Full:
                        rejection = _reject(
                            "queue_full",
                            f"queue depth {self.cfg.max_queue} reached — "
                            "retry with backoff")
        if rejection is not None:
            if request.get("id") is not None:
                rejection["id"] = request["id"]
            self._count_outcome(request, f'rejected:{rejection["code"]}')
            self._finish_trace(request, rejection)
            fut.set_result(rejection)
        return fut

    # -- continuous batching ----------------------------------------------

    @staticmethod
    def _claim(request: Dict) -> bool:
        """Exactly-once resolution guard for requests that can be touched
        by two scheduler generations (a zombie waking from a hung device
        call races the bounce that re-admitted its rows). ``setdefault``
        is GIL-atomic: exactly one caller sees its own token and owns the
        counters + Future; everyone else discards. The Future itself
        already rejects double ``set_result`` — this guard keeps the
        COUNTERS honest too (the chaos-soak reconciliation invariant)."""
        token = object()
        return request.setdefault("_resolved", token) is token

    def _mark_resolved(self) -> None:
        """One admitted Future left flight — the _quiesced() side of the
        exactly-once contract. Batched callers invoke this right after a
        winning _claim; the worker loop after its Future resolves."""
        with self._lock:
            self._outstanding -= 1

    def _resolve_scheduled(self, request: Dict, resp: Dict) -> None:
        """Scheduler response sink: fold counters/latency exactly like the
        sequential ``_respond`` path, then resolve the caller's Future.
        (The scheduler already finished the request's trace.)"""
        if not self._claim(request):
            return  # another generation resolved this request first
        self._mark_resolved()
        # Deposit the warm-start seed BEFORE the Future resolves (same
        # ordering argument as the flight record below): a woken caller
        # posting its next frame must find the session warm — and a
        # woken caller resubmitting the identical frame must find the
        # response cache primed (graftrecall deposit-before-resolve).
        self.stream.deposit(request, resp)
        self.cache.deposit(request, resp)
        retries = request.get("_retries", 0)
        if retries and "retries" not in resp:
            resp["retries"] = retries
        key = resp["status"]
        if resp["status"] != "ok":
            key = f'{resp["status"]}:{resp["code"]}'
        else:
            self._latency.observe(resp["elapsed_ms"] / 1e3)
            if resp.get("quality") != "full":
                self._count("degraded")
        self._count_outcome(request, key)
        # Flight record BEFORE resolving the Future: a caller that wakes
        # on .result() and immediately lists RAFT_FLIGHT_DIR must see the
        # record its breach produced.
        self._maybe_flight(request, resp)
        fut = request.get("_future")
        if fut is not None:
            try:
                fut.set_result(resp)
            except Exception:  # already resolved/cancelled
                pass

    def _adopt(self, sched, item) -> bool:
        """Adopt one dequeued queue item into the scheduler generation.
        Returns False when the generation retired (defunct) while this
        thread held the just-dequeued item: a defunct scheduler must
        never adopt work (its harvest may already have run — a submit
        would strand the row), so the item is handed back for the live
        generation and the calling loop thread must exit."""
        request, fut = item
        request["_future"] = fut
        if sched.defunct:
            self._unadopt(request)
            return False
        sched.submit(request)
        return True

    def _scheduler_loop(self, sched, stop_event: threading.Event,
                        tick_s: float, hb) -> None:
        """One thread owns all batch state: drain the bounded queue into
        the scheduler, run ticks while there is work, block briefly when
        idle. On stop: the thread never touches the shared queue again
        (stop()'s own drain — or the next generation — owns it),
        un-admitted joiners are rejected (same structured
        ``service_stopped`` the sequential drain uses), and active rows
        tick to their segment-boundary exits — a row that already owns
        device state is finished, never abandoned.

        The outer try is the supervision boundary: the loop beats its
        heartbeat every iteration, and a crash (real bug or injected
        ``ChaosPlan.crash_ticks``) is recorded on the heartbeat so the
        watchdog detects a dead tick loop by state, then bounces the
        generation and re-admits the stranded rows."""
        try:
            while True:
                if sched.defunct:
                    # Generation bounced while this thread was parked (a
                    # released hang): harvest() owns every row — exiting
                    # without another tick is the only non-racy move.
                    return
                hb.beat()
                if stop_event.is_set():
                    sched.drain_pending()
                    if sched.active_rows == 0:
                        sched.shutdown()
                        return
                    sched.run_tick()
                    continue
                while True:  # drain everything currently queued
                    try:
                        item = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:  # wake sentinel
                        continue
                    if not self._adopt(sched, item):
                        return
                self._gauge_depth.set(self._queue.qsize())
                if stop_event.is_set():
                    continue  # reject/finish via the stop branch above
                if not sched.run_tick():
                    try:
                        item = self._queue.get(timeout=tick_s)
                    except queue.Empty:
                        continue
                    if item is not None and not self._adopt(sched, item):
                        return
                else:
                    # Work-tick ordinal for the injected tick-loop crash
                    # (deterministic: counts only ticks that did work).
                    self.session.faults.on_tick()
        except BaseException as e:  # noqa: BLE001 — supervision boundary
            hb.mark_dead(e)
            logger.exception(
                "scheduler generation thread died — the watchdog will "
                "bounce the generation and re-admit stranded rows")

    def _worker_loop(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            item = self._queue.get()
            if item is None:  # stop sentinel
                break
            request, fut = item
            self._gauge_depth.set(self._queue.qsize())
            with self._lock:
                self._inflight += 1
            try:
                fut.set_result(self._respond(request))
            except Exception as e:  # noqa: BLE001 — worker must survive
                try:
                    fut.set_result(_error("internal",
                                          f"{type(e).__name__}: {e}"))
                except Exception:  # future already resolved/cancelled
                    pass
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._outstanding -= 1

    # -- generation bounce (graftguard, DESIGN.md r13) ---------------------

    def supervised_state(self) -> Optional[Dict]:
        """The supervisor's view of the current generation (None when
        the service is not running batched): heartbeat, scheduler,
        whether the generation thread is alive, and whether a stop is
        already in progress (a stopping thread exiting is not a crash)."""
        with self._lock:
            if not self._batched or not self._started:
                return None
            return {
                "heartbeat": self._heartbeat,
                "scheduler": self._scheduler,
                "thread_alive": any(t.is_alive() for t in self._workers),
                "stopping": self._stop.is_set(),
            }

    def bounce(self, reason: str = "manual") -> bool:
        """Operator action: retire the scheduler generation by hand (the
        watchdog path with a human as the detector)."""
        return self._bounce([WatchdogTrip(reason, f"manual bounce "
                                          f"({reason})")])

    def _bounce(self, trips) -> bool:
        """Watchdog response: retire the current scheduler generation,
        start a fresh one, and re-admit every harvested in-flight row
        from its original (still-held) inputs under the retry budget.
        Budget-exhausted rows fail structured (``device_hang`` for a
        hang trip, ``scheduler_restarted`` otherwise) — never silently
        dropped, never abandoned."""
        kind = trips[0].kind
        with self._lock:
            if not (self._started and self._batched
                    and self._scheduler is not None):
                return False
            old_sched = self._scheduler
            old_stop = self._stop
            old_threads = list(self._workers)
            # Defunct-then-stop, both before any harvesting: a zombie
            # thread waking mid-tick discards results (defunct) and then
            # exits through its stop branch — it can never double-resolve
            # a re-admitted row nor touch the shared queue again.
            old_sched.defunct = True
            old_stop.set()
            self._generation += 1
            gen = self._generation
            self._stop = threading.Event()
            stop_event = self._stop
            from raft_stereo_tpu.serve.scheduler import BatchScheduler
            self._scheduler = BatchScheduler(
                self.session, resolve=self._resolve_scheduled,
                retry=self._retry_scheduled, generation=gen,
                stream=self.stream, cache=self.cache)
            self._heartbeat = Heartbeat("scheduler", self.session.clock)
            sched, hb = self._scheduler, self._heartbeat
            # Spawn + publish the new generation's thread INSIDE the
            # lock: supervised_state() reads scheduler/heartbeat/_stop/
            # _workers under this lock, so it can never observe the new
            # (unset) stop event paired with the old exiting thread — a
            # gap a concurrent sweep would misread as tick_crashed and
            # double-bounce the fresh, healthy generation.  The new
            # thread only briefly contends for this lock (_requeue), so
            # spawning here cannot deadlock.
            self._workers = [
                self._spawn_scheduler_thread(sched, stop_event, hb)]
        # Unpark any injected hang so the abandoned victim thread can run
        # to its (defunct, discarded) completion instead of leaking.
        self.session.faults.release_hangs()
        # Wait (bounded) for the retired thread to actually exit BEFORE
        # harvesting: a dead thread can neither steal a queue item for
        # the defunct scheduler nor race the harvest mid-tick, so the
        # harvest below is single-threaded truth.  A thread wedged in a
        # REAL device hang won't join — harvest anyway; its eventual
        # wake discards behind the scheduler's ``defunct`` checks.
        for t in old_threads:
            # Joining the dead generation's threads IS the bounce; it
            # runs under _check_lock because exactly one recovery may
            # touch generation state at a time, and the join is bounded.
            # graftlint: disable=GC203 (bounded generation join inside the serialized bounce)
            t.join(timeout=5.0)
        self._zombies.extend(t for t in old_threads if t.is_alive())
        # graftpod: a device_hang bounce on a live mesh probes every
        # chip and quarantines only the hung ones — the mesh shrinks to
        # the largest divisor of its base extent that fits the
        # survivors, the epoch bump re-keys the mesh programs, and the
        # OTHER chips keep serving.  Stream sessions pinned to a
        # quarantined chip migrate (their held seed is host-side, so
        # they stay warm — the bounce-warm pin extended to chips).
        quarantined: list = []
        if kind == "device_hang" and self.session.mesh_active:
            hung = self.session.probe_chips()
            for chip in hung:
                if self.session.quarantine_chip(chip):
                    quarantined.append(chip)
            if quarantined:
                migrated = self.stream.migrate_off_chips(
                    quarantined, self.session.mesh_chips)
                logger.warning(
                    "quarantined chip(s) %s after device_hang — mesh "
                    "now %d-wide, %d stream session(s) migrated",
                    quarantined, self.session.mesh_chips, migrated)
        self.registry.counter(
            "raft_sched_restarts_total",
            "scheduler generation bounces by watchdog reason",
            reason=kind).inc()
        logger.warning("scheduler generation bounced (%s) -> g%d",
                       kind, gen)
        harvested = old_sched.harvest()
        fail_code = ("device_hang" if kind == "device_hang"
                     else "scheduler_restarted")
        requeued = failed = 0
        for request in harvested:
            if request.get("_resolved") is not None:
                # The retiring generation resolved this request before it
                # went defunct (its exits landed mid-bounce): the caller
                # already has a legitimate response — nothing to re-admit.
                continue
            trace = request.get("_trace")
            if trace is not None:
                trace.event("generation_bounce", reason=kind,
                            generation=gen)
            resp = _error(fail_code,
                          f"request interrupted by a scheduler generation "
                          f"bounce ({kind}): {trips[0].reason}")
            if self._note_retry_if_transient(request, resp) and \
                    self._requeue(request):
                requeued += 1
                continue
            failed += 1
            if request.get("id") is not None:
                resp["id"] = request["id"]
            if trace is not None:
                trace.finish(status="error", code=fail_code)
            self._resolve_scheduled(request, resp)
        # Every watchdog action leaves a flight record naming its reason
        # (the chaos-soak invariant); the recorder is failure-isolated
        # and a no-op when unarmed.
        self.session.flight.record({
            "schema": 1,
            "reasons": [f"watchdog:{t.kind}" for t in trips],
            "watchdog": [{"kind": t.kind, "reason": t.reason,
                          "detail": t.detail} for t in trips],
            "generation": {"from": gen - 1, "to": gen},
            "requests": {"harvested": len(harvested),
                         "requeued": requeued, "failed": failed},
            "mesh": ({"quarantined": quarantined,
                      "n_data": self.session.mesh_chips}
                     if quarantined else None),
            "breaker": self.session.breaker.status(),
            "metrics": self.registry.snapshot(),
        }, trace_id=f"bounce-g{gen}")
        return True

    # -- recovery plane (graftheal, DESIGN.md r22) -------------------------

    def heal_sweep(self) -> Dict:
        """One recovery-plane sweep: at most one half-open breaker-rung
        canary (strict reverse trip order), then one probe pass over
        probation-eligible quarantined chips, then stream-session
        re-placement onto any re-grown mesh.

        Deliberately NOT wired into the Supervisor's monitor thread —
        detection (watchdog) and recovery (heal) run on different
        clocks and different triggers, and the chaos battery pins the
        detector's one-way monotonicity mid-storm.  Production drives
        this from the serve_stereo.py / fleet_stereo.py wait loops;
        tests and storms call it explicitly on the FakeClock.  With
        ``RAFT_HEAL=0`` every sub-step is a no-op and the PR 3..17
        one-way semantics hold bit-for-bit."""
        rung = self.session.heal_breaker()
        mesh = self.session.heal_mesh()
        repinned = 0
        if mesh["readmitted"]:
            # Sessions parked off-mesh (chip=None after a shrink to one
            # chip) re-pin round-robin onto the re-grown extent — their
            # held seeds are host-side, so they come back WARM (the
            # migration seam's bounce-warm pin, in reverse).
            repinned = self.stream.repin_unplaced(self.session.mesh_chips)
        return {"breaker": rung, "mesh": mesh,
                "stream_repinned": repinned}

    def supervision_status(self) -> Dict:
        """The /healthz ``supervision`` block: generation, drain state,
        heartbeat ages, watchdog/retry config, restart + trip counters."""
        now = self.session.clock.now()
        with self._lock:
            gen = self._generation
            draining = self._draining
            hb = self._heartbeat
            sched = self._scheduler
            alive = any(t.is_alive() for t in self._workers)
        uploader = sched.uploader if sched is not None else None
        return {
            "generation": gen,
            "draining": draining,
            "retry_budget": self._retry_budget,
            "drain_grace_ms": self._drain_grace_s * 1e3,
            "watchdog": (self._supervisor.status()
                         if self._supervisor is not None else
                         {"armed": False,
                          "floor_ms": self._watchdog_s * 1e3}),
            "heartbeats": {
                "scheduler_age_s": hb.age(now) if hb is not None else None,
                "scheduler_alive": alive,
                "scheduler_died": (str(hb.died)
                                   if hb is not None and hb.died is not None
                                   else None),
                "uploader_alive": (uploader.alive
                                   if uploader is not None else None),
                "uploader_dead": (str(uploader.dead)
                                  if uploader is not None
                                  and uploader.dead is not None else None),
            },
            "in_flight_invocations": self.session.watch.count,
            "restarts": {labels["reason"]: int(v) for labels, v in
                         self.registry.series("raft_sched_restarts_total")},
            "watchdog_trips": {labels["kind"]: int(v) for labels, v in
                               self.registry.series(
                                   "raft_watchdog_trips_total")},
            "retries": int(self.registry.value(
                "raft_request_retries_total")),
        }

    # -- health -----------------------------------------------------------

    def status(self) -> Dict:
        """The /healthz document — every number here is a registry read
        (the same counters /metrics exposes), no service-private state."""
        counts = {labels["outcome"]: int(v) for labels, v in
                  self.registry.series("raft_requests_total")}

        def pct(p: float) -> Optional[float]:
            v = self._latency.percentile(p)
            return None if v is None else v * 1e3

        return {
            # graftfleet: generation identity + age, top-level — the
            # fleet router keys rolling deploys on fingerprint_id and
            # detects silent restarts from uptime_s going backwards.
            "fingerprint_id": self.session.fingerprint_id(),
            "uptime_s": self.session.clock.now() - self._born,
            "queue": {"depth": self._queue.qsize(),
                      "max": self.cfg.max_queue,
                      "workers": (1 if self._batched
                                  else self.cfg.workers)},
            "requests": counts,
            "latency_ms": {"p50": pct(0.50), "p99": pct(0.99),
                           "n": self._latency.n},
            "batching": (self._scheduler.status()
                         if self._scheduler is not None else None),
            # graftstream: the bounded session table + warm/converged
            # counters (serve/stream.py).
            "stream": self.stream.status(),
            # graftrecall: the two-tier response cache — hit/miss/near
            # counters, byte accounting, tier config (serve/cache.py).
            "cache": self.cache.status(),
            "supervision": self.supervision_status(),
            # graftheal: the recovery plane — per-rung/per-chip
            # probation state, flap caps, MTTR (serve/heal.py knobs;
            # session.heal_status()).
            "heal": self.session.heal_status(),
            # The operator-plane capacity block (obs/capacity.py):
            # per-bucket theoretical requests/s from the warmed EMAs,
            # live saturation from the tick deck, headroom gauges
            # published as a side effect.
            "capacity": self.session.capacity_status(),
            "session": self.session.status(),
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the shared registry — the
        /metrics endpoint body (session + service + scheduler series,
        one scrape)."""
        return self.registry.render_prometheus()

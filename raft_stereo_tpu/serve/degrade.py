"""Deadline-aware anytime degradation for the refinement scan.

RAFT-Stereo's GRU refinement is an anytime algorithm: every iteration
yields a valid (progressively sharper) disparity field — the paper's
real-time mode simply runs fewer iterations. This module exploits that
for serving: a deadline-carrying request runs the scan as ``segments``
host-visible chunks (``raft_stereo_segment`` — the same compiled scan
body, bit-identical composition), checks the wall clock between chunks,
and returns the **best-so-far upsampled field with an honest quality
label** instead of timing out hard:

- ``full``              — every iteration ran within budget;
- ``reduced_iters:<k>`` — the budget expired mid-scan; k iterations'
                          refinement is what you got;
- ``half_res``          — the predicted cost of even one full-res segment
                          exceeded the remaining budget, so the pair ran
                          at half resolution (disparity scaled ×2 back to
                          the input geometry).

Segment-time predictions are per-program EMAs recorded by the session; a
segment always runs when no estimate exists yet (you cannot degrade on a
guess), so the very first request on a bucket may overshoot its deadline —
``deadline_missed`` reports that honestly. A response is never fabricated:
whatever field is returned came out of the real refinement.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.ops.padder import InputPadder

# Predicted-time inflation: stop one segment EARLY when the prediction is
# within 15% of the remaining budget rather than overshoot by a whole
# segment (EMAs smooth over compile-warm jitter, not eliminate it).
SAFETY = 1.15


@dataclasses.dataclass
class Outcome:
    """What a (possibly degraded) refinement produced, pre-unpad."""

    flow_padded: np.ndarray   # (1, H, W, 1); for half_res: already restored
    quality: str
    iters: int
    deadline_missed: bool


def _segment_plan(session) -> Tuple[int, int]:
    segments = session.cfg.segments
    return segments, session.cfg.valid_iters // segments


def warm_segmented(session, padder: InputPadder, zeros: np.ndarray) -> None:
    """Pre-compile the prepare/segment programs for one bucket — and, when
    half-res degradation is allowed, for its half bucket too (the policy
    only ever routes onto warm half-res programs; a cold one would trade a
    blown budget for a compile that dwarfs it)."""
    _, m = _segment_plan(session)
    ph, pw = padder.padded_shape
    lp, rp = padder.pad_np(zeros, zeros)
    prep = session.get_program("prepare", ph, pw, 0)
    (state,) = session.invoke(prep, lp, rp)
    seg = session.get_program("segment", ph, pw, m)
    session.invoke(seg, state)
    if session.cfg.allow_half_res and min(zeros.shape[1:3]) >= 2:
        half = _downscale_half(zeros)
        warm_segmented_half(session, half)


def warm_segmented_half(session, half_zeros: np.ndarray) -> None:
    _, m = _segment_plan(session)
    half_padder = session.padder_for(half_zeros.shape)
    hh, hw = half_padder.padded_shape
    lp, rp = half_padder.pad_np(half_zeros, half_zeros)
    prep = session.get_program("prepare", hh, hw, 0)
    (state,) = session.invoke(prep, lp, rp)
    seg = session.get_program("segment", hh, hw, m)
    session.invoke(seg, state)


def _run_segmented(session, padder: InputPadder, left: np.ndarray,
                   right: np.ndarray, deadline: float,
                   trace=NULL_TRACE) -> Outcome:
    """Full-resolution anytime loop: prepare, then segments until done or
    out of budget. The first segment always runs."""
    segments, m = _segment_plan(session)
    ph, pw = padder.padded_shape
    lp, rp = padder.pad_np(left, right)

    prep = session.get_program("prepare", ph, pw, 0)
    (state,) = session.invoke(prep, lp, rp, trace=trace)
    seg = session.get_program("segment", ph, pw, m)

    flow = None
    done = 0
    for i in range(segments):
        if flow is not None:  # best-so-far exists; is another chunk safe?
            est = session.estimate(seg.key)
            now = session.clock.now()
            if now >= deadline:
                trace.event("degrade", label=f"reduced_iters:{done}",
                            reason="deadline_expired")
                break
            if est is not None and now + est * SAFETY > deadline:
                trace.event("degrade", label=f"reduced_iters:{done}",
                            reason="predicted_overshoot")
                break
        state, flow, _checksum = session.invoke(seg, state, trace=trace)
        done += m
    missed = session.clock.now() > deadline
    quality = "full" if done == session.cfg.valid_iters \
        else f"reduced_iters:{done}"
    return Outcome(flow, quality, done, missed)


def _downscale_half(img: np.ndarray) -> np.ndarray:
    """(1, H, W, C) -> (1, ceil(H/2), ceil(W/2), C) by 2x2 box filter
    (edge-replicated to even dims first, matching the padder's pad mode)."""
    _, h, w, _ = img.shape
    if h % 2 or w % 2:
        img = np.pad(img, ((0, 0), (0, h % 2), (0, w % 2), (0, 0)),
                     mode="edge")
    return 0.25 * (img[:, 0::2, 0::2] + img[:, 1::2, 0::2]
                   + img[:, 0::2, 1::2] + img[:, 1::2, 1::2])


def _restore_half(flow_half: np.ndarray, orig_h: int,
                  orig_w: int) -> np.ndarray:
    """Half-res flow -> full-res: nearest 2x upsample, crop, values ×2
    (disparity is measured in pixels, and the pixels doubled)."""
    up = flow_half.repeat(2, axis=1).repeat(2, axis=2)
    return 2.0 * up[:, :orig_h, :orig_w, :]


def _half_res_viable(session, padder: InputPadder, deadline: float) -> bool:
    """Drop to half resolution only when the full-res cost is *known* to
    exceed the budget (both the prepare and segment EMAs exist and their
    sum overshoots) AND the half-res programs are already compiled (warm
    the half buckets via ``warmup_segmented``/``warm_segmented``). An
    unknown full-res cost runs at full res — degrading on a guess would
    silently halve quality on every cold bucket — and a cold half bucket
    would trade a blown budget for an XLA compile that dwarfs it."""
    segments, m = _segment_plan(session)
    ph, pw = padder.padded_shape
    prep_key = session.cache_key("prepare", ph, pw, 0)
    seg_key = session.cache_key("segment", ph, pw, m)
    est_prep = session.estimate(prep_key)
    est_seg = session.estimate(seg_key)
    if est_prep is None or est_seg is None:
        return False
    remaining = deadline - session.clock.now()
    if (est_prep + est_seg) * SAFETY <= remaining:
        return False
    # padder.ht/wd are the ORIGINAL image dims; the half route pads
    # ceil(dim/2) onto the session bucket.
    hh = -(-(padder.ht + padder.ht % 2) // 2)
    hw = -(-(padder.wd + padder.wd % 2) // 2)
    half_h, half_w = session.padder_for((hh, hw, 3)).padded_shape
    return (session.has_program("prepare", half_h, half_w, 0)
            and session.has_program("segment", half_h, half_w, m))


def run_with_deadline(session, padder: InputPadder, left: np.ndarray,
                      right: np.ndarray, deadline: float, *,
                      allow_half_res: bool = True,
                      trace=NULL_TRACE) -> Outcome:
    """The degrade policy: full-res segmented scan, or half-res when the
    budget provably cannot fit one full-res segment."""
    if allow_half_res and _half_res_viable(session, padder, deadline):
        trace.event("degrade", label="half_res",
                    reason="budget_below_one_full_res_segment")
        orig_h, orig_w = left.shape[1], left.shape[2]
        left_h = _downscale_half(left)
        right_h = _downscale_half(right)
        half_padder = session.padder_for(left_h.shape)
        out = _run_segmented(session, half_padder, left_h, right_h,
                             deadline, trace=trace)
        flow_half = half_padder.unpad_np(out.flow_padded)
        flow = _restore_half(flow_half, orig_h, orig_w)
        return Outcome(flow, "half_res", out.iters, out.deadline_missed)
    return _run_segmented(session, padder, left, right, deadline,
                          trace=trace)

"""Iteration-level continuous batching over the segmented refinement scan.

LLM serving schedulers batch at *decode-token* granularity: requests join
and leave a running device batch between token steps, so the accelerator
always runs one big program instead of many small ones. RAFT-Stereo's GRU
refinement has the same shape — the PR 3 segmented scan already advances an
explicit carry dict ``{net, inp, fmap1, fmap2, coords1}`` k iterations at a
time, bit-identical to the single scan — so this module batches at
*segment* granularity:

- each **tick** runs ONE compiled batched ``advance`` program (the segment
  scan body WITHOUT the mask-head epilogue) over every active request
  sharing a (padded shape, config) bucket, padded up to a power-of-two
  **batch bucket** (pad rows are dead carries — replicated live rows that
  are never read back);
- **joins** happen at tick boundaries: waiting requests' image pairs are
  uploaded by a background thread while the current segment executes (the
  ``device_prefetch`` pattern), then a batched ``prepare`` builds their
  carries, which are concatenated onto the running batch;
- **exits** happen at segment boundaries: rows that finished their
  iterations — or whose per-row deadline provably cannot absorb another
  batched segment (the EMA cost model is keyed per (program, batch
  bucket)) — leave the batch and pay the mask-head ``epilogue`` once, as
  one stacked device round trip;
- per-request outputs are **provably independent of batchmates**: every op
  in the scan body is batch-row independent (convs, the corr gather, the
  epipolar ``.at[..., 1]`` update), so row i of the batched run is the PR 3
  sequential path's bytes — correctness is inherited, not renegotiated
  (pinned in tests/test_batch_serve.py).

The scheduler is single-threaded by design: all batch state is owned by
the one thread calling :meth:`run_tick` (the service's scheduler thread,
or a test driving ticks deterministically). Only the aggregate metrics are
lock-shared with /healthz readers.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_stereo_tpu.obs.ledger import ledger_id
from raft_stereo_tpu.obs.tracing import NULL_TRACE
from raft_stereo_tpu.obs.usage import sanitize_tenant
from raft_stereo_tpu.serve.degrade import SAFETY
from raft_stereo_tpu.serve.guard import is_kernel_failure
from raft_stereo_tpu.serve.session import (InferenceFailed, InferenceSession,
                                           SessionError)

logger = logging.getLogger(__name__)


def _reject(code: str, message: str) -> Dict:
    return {"status": "rejected", "code": code, "message": message}


def _error(code: str, message: str) -> Dict:
    return {"status": "error", "code": code, "message": message}


class _Row:
    """Bookkeeping for one admitted request while it rides the batch."""

    __slots__ = ("request", "padder", "orig_h", "orig_w", "deadline",
                 "iters_done", "t_start", "dev_pair", "upload_error",
                 "uploaded", "tenant_label", "flow_init", "dev_flow",
                 "converge_tol", "converged", "cache_warm")

    def __init__(self, request, padder, deadline, t_start,
                 tenant_label: str = "default"):
        self.request = request
        self.padder = padder
        self.orig_h = request["left"].shape[1]
        self.orig_w = request["left"].shape[2]
        self.deadline = deadline
        self.iters_done = 0
        self.t_start = t_start
        self.dev_pair = None
        self.upload_error: Optional[Exception] = None
        self.uploaded = threading.Event()
        # Bounded usage label (obs/usage.py first-come discipline),
        # resolved once at admission: every device call this row rides
        # attributes its exact share of device seconds here.
        self.tenant_label = tenant_label
        # graftstream (serve/stream.py): a warm frame carries its
        # previous frame's padded low-res flow (the StreamManager
        # stamped it at admission — and it stays ON the request dict, so
        # a generation bounce re-admits the row still warm); the
        # convergence tolerance arms the early-exit monitor.
        self.flow_init = request.get("_flow_init")
        self.dev_flow = None
        self.converge_tol = request.get("_converge_tol")
        self.converged = False
        # graftrecall (serve/cache.py): a near-tier seed rides the SAME
        # warm-start machinery as a stream frame, but is labeled
        # ``warm:cache:k`` and must not count in the STREAM metrics.
        self.cache_warm = bool(request.get("_cache_warm"))

    @property
    def trace(self):
        """The request's span timeline (NULL when the request came in
        without one — tests driving the scheduler directly)."""
        return self.request.get("_trace") or NULL_TRACE


class _Bucket:
    """Active batch + FIFO of waiting joiners for one padded shape."""

    def __init__(self, key: Tuple[int, int]):
        self.key = key                      # (padded_h, padded_w)
        self.rows: List[_Row] = []          # row i of carry == rows[i]
        # Batched state dict; its leading dim may EXCEED len(rows) — live
        # rows are the prefix, the rest are dead pad rows. Keeping the
        # carry at batch-bucket width between ticks means a steady
        # occupancy that is not itself a bucket size (say 5 under
        # buckets 4/8) pays the pad/trim gathers only when the batch
        # composition changes, not on every segment.
        self.carry = None
        self.pending: "collections.deque[_Row]" = collections.deque()
        # The join group currently mid-prepare: rows popped from
        # ``pending`` but not yet merged into ``rows``. Without this,
        # a hung or terminally-failing batched prepare strands its
        # joiners in a local variable no harvest or bucket-failure path
        # can see — their Futures would never resolve.
        self.joining: List[_Row] = []

    @property
    def carry_width(self) -> int:
        return 0 if self.carry is None else int(
            self.carry["coords1"].shape[0])

    @property
    def has_work(self) -> bool:
        return bool(self.rows or self.pending)


class _Uploader:
    """Background host->device transfer: pads and uploads a joiner's image
    pair while the current segment executes on device, so a join costs the
    batch a carry concat, not a host round trip (train.py's
    ``device_prefetch`` pattern applied to serving). Each upload lands in
    the row's trace as a CONCURRENT span — visible in the timeline,
    excluded from the tiled latency partition (it overlaps a running
    segment by design).

    Crash-proofing (graftguard, DESIGN.md r13): a per-row transfer
    failure was always surfaced on that row, but a crash in the loop
    itself (trace plumbing, the injected ``ChaosPlan.crash_uploads``
    fault, any future bug outside the per-row try) used to kill the
    thread silently and leave every joiner's ``uploaded`` event — and
    therefore its Future — stranded forever.  Now a thread-killing crash
    records itself in ``dead``, resolves the current row AND everything
    still queued with that error (the scheduler turns it into a
    structured ``upload_failed``), and later ``push`` calls short-
    circuit the same way.  The watchdog bounces the generation onto a
    fresh uploader; this class only guarantees nothing is ever stranded.
    ``dead``/``busy_since`` are plain attributes written by one thread
    and read by the supervisor — monotonic one-way flags, no lock
    needed."""

    def __init__(self, clock, faults=None):
        self._clock = clock
        self._faults = faults
        self.dead: Optional[BaseException] = None
        self.busy_since: Optional[float] = None
        self._q: "queue.Queue[Optional[_Row]]" = queue.Queue()
        # stop() is the queue's None sentinel — the loop exits after
        # draining, and the generation watchdog owns replacement;
        # joining would park stop() behind a possibly-wedged device
        # upload, the exact hang the watchdog exists to break.
        # graftlint: disable=GC206 (sentinel stop; watchdog owns a wedged uploader)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stereo-uploader")
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _fail_row(self, row: _Row, exc: BaseException) -> None:
        row.upload_error = exc
        row.uploaded.set()

    def push(self, row: _Row) -> None:
        if self.dead is not None:
            self._fail_row(row, self.dead)
            return
        self._q.put(row)
        # Death raced the put: the dying loop's queue drain may already
        # have finished, so re-check — an unresolved ``uploaded`` event
        # strands the joiner's Future forever.
        if self.dead is not None and not row.uploaded.is_set():
            self._fail_row(row, self.dead)

    def stop(self) -> None:
        self._q.put(None)

    def _loop(self) -> None:
        import jax
        while True:
            row = self._q.get()
            if row is None:
                return
            try:
                self.busy_since = self._clock.now()
                if self._faults is not None:
                    self._faults.on_upload()
                t0 = self._clock.now()
                try:
                    lp, rp = row.padder.pad_np(row.request["left"],
                                               row.request["right"])
                    row.dev_pair = (jax.device_put(lp), jax.device_put(rp))
                    if row.flow_init is not None:
                        # Warm-start seed (already at the padded low-res
                        # bucket shape — the StreamManager only hands
                        # out a matching field).
                        row.dev_flow = jax.device_put(row.flow_init)
                except Exception as e:  # noqa: BLE001 — surfaced per-row
                    row.upload_error = e
                row.trace.add_span("upload", t0, self._clock.now(),
                                   concurrent=True)
                row.uploaded.set()
                self.busy_since = None
            except BaseException as e:  # noqa: BLE001 — thread-killing crash
                logger.exception(
                    "uploader thread died — current and queued joiners "
                    "fail upload_failed; the watchdog bounces the "
                    "generation onto a fresh uploader")
                self.dead = e
                self._fail_row(row, e)
                while True:
                    try:
                        later = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if later is not None:
                        self._fail_row(later, e)
                return


class BatchScheduler:
    """Continuous-batching engine over one :class:`InferenceSession`.

    ``resolve(row_request, response)`` is called exactly once per admitted
    request (the service wires its Future resolution + counters in; tests
    collect responses). All scheduling state is confined to the thread
    calling :meth:`submit` / :meth:`run_tick`.
    """

    def __init__(self, session: InferenceSession, *,
                 resolve: Optional[Callable[[Dict, Dict], None]] = None,
                 retry: Optional[Callable[[Dict, Dict], bool]] = None,
                 generation: int = 0, stream=None, cache=None):
        if session.cfg.max_batch < 2:
            raise ValueError("BatchScheduler needs SessionConfig.max_batch "
                             ">= 2; use the sequential worker path at 1")
        self.session = session
        # Stamped on every tick flight-deck record (obs/deck.py) so a
        # post-mortem can see which scheduler generation ran a tick —
        # the service passes its generation counter; tests driving the
        # scheduler directly default to 0.
        self.generation = generation
        self.resolve = resolve or self._default_resolve
        # Supervision hooks (serve/supervise.py): ``retry`` is consulted
        # before a failed response is finalized — True means the service
        # re-admitted the request under its retry budget and this
        # scheduler must neither finish the trace nor resolve the
        # Future.  ``defunct`` is flipped (once, by the service, before
        # harvest) when a generation bounce retires this scheduler: a
        # zombie thread waking from a hung device call then discards its
        # results instead of double-resolving rows the new generation
        # re-admitted.
        self.retry = retry
        self.defunct = False
        # graftstream accounting hooks (serve/stream.py StreamManager):
        # warm joins and convergence exits are counted where they happen
        # (this tick loop); tests driving the scheduler directly may
        # leave it None.
        self.stream = stream
        # graftrecall (serve/cache.py ResponseCache): exact hits never
        # reach this scheduler at all — the only couplings here are the
        # cumulative hit column stamped on each deck tick row and the
        # warm:cache labeling of near-seeded rows.
        self.cache = cache
        self.uploader = _Uploader(session.clock, faults=session.faults)
        self._buckets: Dict[Tuple[int, int], _Bucket] = {}
        self._rr: List[Tuple[int, int]] = []   # round-robin bucket order
        self._rr_next = 0
        # Guards the bucket map: /healthz readers iterate it from other
        # threads while submit() (scheduler thread) inserts new shape
        # buckets. Per-bucket rows/carries need no lock — they are touched
        # only by the scheduling thread. Aggregate metrics live in the
        # session's registry (self-locking instruments), so a restart's
        # fresh scheduler keeps accumulating into the same series.
        self._lock = threading.Lock()
        reg = session.registry
        self.registry = reg
        self._m_ticks = reg.counter("raft_sched_ticks_total",
                                    "scheduler ticks run")
        self._m_joins = reg.counter("raft_sched_joins_total",
                                    "requests joined into a device batch")
        self._m_exits = reg.counter("raft_sched_exits_total",
                                    "rows exited at a segment boundary")
        self._m_pad_rows = reg.counter(
            "raft_sched_pad_rows_total",
            "dead pad rows advanced (batch-bucket padding waste)")
        self._m_batch_rows = reg.counter(
            "raft_sched_batch_rows_total",
            "total rows advanced (live + pad)")
        self._tick_hist = reg.histogram(
            "raft_sched_tick_seconds",
            "wall time of one scheduler tick (bounded reservoir)",
            reservoir=512)

    # -- request intake ---------------------------------------------------

    @staticmethod
    def _default_resolve(request: Dict, resp: Dict) -> None:
        fut = request.get("_future")
        if fut is not None:
            try:
                fut.set_result(resp)
            except Exception:  # already resolved/cancelled
                pass

    def submit(self, request: Dict) -> None:
        """Admit one validated request (arrays already canonical, deadline
        already stamped as ``_deadline``) into its shape bucket's join
        queue and start its host->device upload immediately."""
        padder = self.session.padder_for(request["left"].shape)
        row = _Row(request, padder, request.get("_deadline"),
                   self.session.clock.now(),
                   tenant_label=self.session.usage.label(
                       sanitize_tenant(request.get("tenant"))))
        key = padder.padded_shape
        bucket = self._buckets.get(key)
        if bucket is None:
            with self._lock:
                bucket = self._buckets[key] = _Bucket(key)
            self._rr.append(key)
        bucket.pending.append(row)
        self.uploader.push(row)

    def _bucket_list(self) -> List[_Bucket]:
        with self._lock:
            return list(self._buckets.values())

    @property
    def has_work(self) -> bool:
        return any(b.has_work for b in self._bucket_list())

    @property
    def active_rows(self) -> int:
        return sum(len(b.rows) for b in self._bucket_list())

    # -- the tick ---------------------------------------------------------

    def run_tick(self) -> bool:
        """Run one scheduler tick on the next bucket with work (round
        robin). Returns False when every bucket is idle. Never raises: a
        terminal failure fails the affected bucket's requests with
        structured error responses and clears that bucket."""
        bucket = self._next_bucket()
        if bucket is None:
            return False
        # Tick flight-deck record (obs/deck.py): opened on THIS thread
        # before any device work, closed in the finally so a failed or
        # zombie-discarded tick still leaves its row. Queue depth is the
        # scheduler's own view — joiners waiting across all buckets at
        # tick start.
        deck = self.session.deck
        tick = deck.begin_tick(
            bucket=f"{bucket.key[0]}x{bucket.key[1]}",
            generation=self.generation,
            queue_depth=sum(len(b.pending) for b in self._bucket_list()))
        if self.cache is not None:
            # Cumulative hit count at tick start: diffing two deck rows
            # gives the hit rate over that window (obs/deck.py report).
            tick.cache_hits = self.cache.hits_cumulative
        t0 = time.perf_counter()
        try:
            self._tick_bucket(bucket, tick)
        except Exception as e:  # noqa: BLE001 — the crash-proof boundary
            logger.exception("tick failed for bucket %s", bucket.key)
            self._fail_bucket(bucket, e)
        finally:
            deck.end_tick(tick)
        self._m_ticks.inc()
        self._tick_hist.observe(time.perf_counter() - t0)
        return True

    def _next_bucket(self) -> Optional[_Bucket]:
        for _ in range(len(self._rr)):
            key = self._rr[self._rr_next % len(self._rr)]
            self._rr_next += 1
            b = self._buckets[key]
            # A bucket whose only work is still uploading counts as work
            # (has_work) but cannot tick yet — skip it this round.
            if b.rows or (b.pending and b.pending[0].uploaded.is_set()):
                return b
        return None

    def _tick_bucket(self, bucket: _Bucket, tick) -> None:
        from raft_stereo_tpu.models import (stack_refinement_states,
                                            take_refinement_rows)
        session = self.session
        clock = session.clock
        m_iters = session.cfg.valid_iters // session.cfg.segments
        ph, pw = bucket.key

        # 1. Joins: admit uploaded joiners (FIFO) up to capacity; one
        # batched prepare builds their carries. The group is published
        # on ``bucket.joining`` (the same list object — appends are
        # visible) for the whole window between leaving ``pending`` and
        # merging into ``rows``: a hang/crash inside the batched prepare
        # must leave these rows harvestable, never stranded.
        joiners: List[_Row] = []
        bucket.joining = joiners
        capacity = session.cfg.max_batch - len(bucket.rows)
        while capacity > 0 and bucket.pending and \
                bucket.pending[0].uploaded.is_set():
            row = bucket.pending.popleft()
            # Published on ``joining`` BEFORE any respond/admit decision:
            # a generation bounce landing while this row is only in a
            # local (its ``_respond`` below discards behind ``defunct``)
            # must still find it harvestable, never stranded.
            joiners.append(row)
            if row.upload_error is not None:
                self.session.count_request(ok=False)
                # Structured + transient: the retry budget re-admits it
                # (a bounced generation brings a fresh uploader).
                self._respond(row, _error(
                    "upload_failed",
                    f"host->device upload failed: {row.upload_error}"))
                if self.defunct:
                    return  # harvest() owns the joining rows now
                joiners.pop()  # resolved or re-admitted: leave the group
                continue
            now = clock.now()
            if row.deadline is not None and now >= row.deadline:
                self._respond(row, _reject(
                    "deadline_exceeded_in_queue",
                    "deadline expired before the request joined a batch"))
                if self.defunct:
                    return  # harvest() owns the joining rows now
                joiners.pop()  # resolved: leave the join group
                continue
            # Queue wait ends here: admission-to-join is the span.
            row.trace.mark("queue_wait")
            capacity -= 1
        if joiners:
            import jax.numpy as jnp
            # graftstream: warm joiners (a held flow_init rode in with
            # the request) seed their carries through the prepare_warm
            # program; cold joiners run the classic prepare.  Two device
            # calls at most — the resulting carries then share ONE
            # advance program (the x-only seed keeps the flow_y == 0
            # invariant, see serve/session.py build_program), so warm
            # and cold rows batch together from here on.
            cold = [r for r in joiners if r.dev_flow is None]
            warm = [r for r in joiners if r.dev_flow is not None]
            # graftpod chip affinity: within each group, stable-sort by
            # the stream session's assigned chip (StreamManager stamps
            # ``_chip`` at admission) so a stream's rows keep landing on
            # the same mesh shard tick after tick — the data sharding
            # splits the leading batch dim contiguously, so adjacent
            # rows share a chip.  Chip-less rows (-1) sort first; the
            # sort is stable, so FIFO order within a chip is preserved
            # and the single-device path (every key -1) is a no-op.
            def _chip_key(r: _Row) -> int:
                c = r.request.get("_chip")
                return c if isinstance(c, int) else -1
            cold.sort(key=_chip_key)
            warm.sort(key=_chip_key)
            # Reorder the published join group to match the carry concat
            # order below (same membership, so harvest coverage is
            # unchanged; appends already happened).
            joiners[:] = cold + warm
            states = []
            for kind, group in (("prepare", cold),
                                ("prepare_warm", warm)):
                if not group:
                    continue
                bb = session.batch_bucket(len(group))
                lefts = [r.dev_pair[0] for r in group]
                rights = [r.dev_pair[1] for r in group]
                pad = bb - len(group)
                lb = jnp.concatenate(lefts + [lefts[0]] * pad, axis=0)
                rb = jnp.concatenate(rights + [rights[0]] * pad, axis=0)
                args = (lb, rb)
                if kind == "prepare_warm":
                    flows = [r.dev_flow for r in group]
                    args = (lb, rb, jnp.concatenate(
                        flows + [flows[0]] * pad, axis=0))
                p0 = clock.now()
                # Rider binding (obs/usage.py): the joiners' tenant
                # labels ride this device call — invoke partitions its
                # steady device seconds exactly across them, zombie or
                # not (the binding lives on this thread, and accounting
                # happens at the same place the program counters
                # increment).  prepare_warm is its own kind, so the PR
                # 12 three-way reconciliation extends to it unchanged.
                with session.usage_riders(
                        [r.tenant_label for r in group]):
                    (state_g,) = self._device_call(
                        kind, ph, pw, 0, bb, *args,
                        traces=[r.trace for r in group])
                if self.defunct:
                    return  # retired mid-prepare: harvest() took the
                    #         joining rows; this result is discarded.
                p1 = clock.now()
                # The program id joins this span to its ledger row
                # (flight records collect the rows of every program a
                # request rode); the tick seq links it to the
                # flight-deck record, so a post-mortem names the exact
                # ticks the request rode.
                prep_id = session.ledger_key_id(kind, ph, pw, 0, b=bb)
                for r in group:  # one device interval, fanned per rider
                    r.trace.add_span(kind, p0, p1, batch=len(group),
                                     program=prep_id, tick=tick.seq)
                if pad:
                    state_g = take_refinement_rows(state_g,
                                                   range(len(group)))
                states.append(state_g)
            if self.stream is not None:
                for r in warm:
                    # Cache-seeded rows ride the same prepare_warm
                    # device call but are NOT stream frames: their hit
                    # was counted by ResponseCache.admit — counting
                    # them here would inflate the stream metrics.
                    if not r.cache_warm:
                        self.stream.note_warm_join(r.tenant_label)
            state_j = (states[0] if len(states) == 1
                       else stack_refinement_states(states))
            if bucket.carry is None:
                bucket.carry = state_j
            else:
                live = (bucket.carry
                        if bucket.carry_width == len(bucket.rows) else
                        take_refinement_rows(bucket.carry,
                                             range(len(bucket.rows))))
                bucket.carry = stack_refinement_states([live, state_j])
            bucket.rows.extend(joiners)
            self._m_joins.inc(len(joiners))
            tick.joins = len(joiners)
            tick.warm_joins = len(warm)
        bucket.joining = []

        # Local binding for the rest of the tick: a concurrent generation
        # bounce REBINDS bucket.rows/carry (harvest), so re-reading the
        # attribute mid-tick would index a list someone else emptied. The
        # snapshot keeps this tick's view consistent; every result lands
        # behind a ``defunct`` check, so a retired tick discards instead
        # of racing the re-admitted rows.
        rows = bucket.rows
        n = len(rows)
        if n == 0:
            return

        # 2. One batched segment over the whole active set, padded up to
        # its batch bucket (pad rows replicate row 0 — dead carries). The
        # output stays at bucket width: a steady composition re-enters
        # here next tick with carry_width == bb and pays no gather.
        bb = session.batch_bucket(n)
        if bucket.carry_width != bb:
            bucket.carry = take_refinement_rows(
                bucket.carry, list(range(n)) + [0] * (bb - n))
        adv_key = session.cache_key("advance", ph, pw, m_iters, b=bb)
        a0 = clock.now()
        with session.usage_riders([r.tenant_label for r in rows]):
            state, _rowsum, dnorm = self._device_call(
                "advance", ph, pw, m_iters, bb, bucket.carry,
                traces=[r.trace for r in rows])
        if self.defunct:
            return  # retired mid-advance: harvest() owns these rows
        a1 = clock.now()
        bucket.carry = state
        adv_id = ledger_id(adv_key)
        tick.occupancy = n
        tick.batch = bb
        tick.pad_rows = bb - n
        tick.iters = m_iters
        tick.program = adv_id
        for row in rows:
            row.iters_done += m_iters
            row.trace.add_span("advance", a0, a1, iters=m_iters,
                               occupancy=n, batch=bb, program=adv_id,
                               tick=tick.seq)
        self.registry.counter(
            "raft_sched_occupancy_total",
            "ticks by live-row occupancy", rows=str(n)).inc()
        self._m_batch_rows.inc(bb)
        self._m_pad_rows.inc(bb - n)

        # 3. Exits: finished rows, rows whose convergence monitor fell
        # below their tolerance (graftstream early exit — the per-row
        # delta-flow norm rode the advance fetch, so the check is free
        # and evaluated exactly at segment boundaries), plus rows whose
        # deadline cannot absorb another batched segment (per-row
        # anytime degradation — the first segment always runs because
        # this check only happens after one).
        now = clock.now()
        est = session.estimate(adv_key)
        exits: List[int] = []
        n_converged = 0
        for i, row in enumerate(rows):
            if row.iters_done >= session.cfg.valid_iters:
                exits.append(i)
            elif row.converge_tol is not None and \
                    float(dnorm[i]) < row.converge_tol:
                # Honest label: converged:k — or warm:cache:k for a
                # near-tier cache seed (graftrecall) — with k ==
                # iterations this row ACTUALLY ran (stamped by _finish
                # off iters_done).
                row.converged = True
                row.trace.event(
                    "converged",
                    label=(f"warm:cache:{row.iters_done}"
                           if row.cache_warm
                           else f"converged:{row.iters_done}"),
                    norm=float(dnorm[i]), tol=row.converge_tol)
                exits.append(i)
                n_converged += 1
                if self.stream is not None and not row.cache_warm:
                    self.stream.note_converged(row.tenant_label)
            elif row.deadline is not None and (
                    now >= row.deadline
                    or (est is not None
                        and now + est * SAFETY > row.deadline)):
                row.trace.event(
                    "degrade", label=f"reduced_iters:{row.iters_done}",
                    reason=("deadline_expired" if now >= row.deadline
                            else "predicted_overshoot"))
                exits.append(i)
        if not exits:
            return
        eb = session.batch_bucket(len(exits))
        ex_state = take_refinement_rows(
            bucket.carry, exits + [exits[0]] * (eb - len(exits)))
        e0 = clock.now()
        with session.usage_riders([rows[i].tenant_label for i in exits]):
            flow_up, flow_low = self._device_call(
                "epilogue", ph, pw, 0, eb, ex_state,
                traces=[rows[i].trace for i in exits])
        if self.defunct:
            return  # retired mid-epilogue: harvest() owns these rows
        e1 = clock.now()
        epi_id = session.ledger_key_id("epilogue", ph, pw, 0, b=eb)
        for i in exits:
            rows[i].trace.add_span("epilogue", e0, e1,
                                   batch=len(exits),
                                   program=epi_id, tick=tick.seq)
        now = clock.now()
        for j, i in enumerate(exits):
            if rows[i].request.get("_stream") is not None:
                # The exiting row's 1/8-res flow is the next frame's
                # warm-start seed: ride it on the request dict so the
                # service's response hook deposits it into the stream
                # session BEFORE the caller's Future resolves.
                rows[i].request["_stream_flow"] = \
                    np.array(flow_low[j:j + 1], dtype=np.float32)
                rows[i].request["_stream_shape"] = bucket.key
            # graftrecall: with the NEAR tier armed, every exit also
            # carries its low-res flow for the response cache's deposit
            # (future near-duplicates seed from it).  Already fetched
            # by the batched epilogue, but the row copy is skipped
            # entirely when no tier would consume it — a disabled
            # cache stays zero-cost on this path.
            if self.cache is not None and self.cache.wants_flow:
                rows[i].request["_cache_flow"] = \
                    np.array(flow_low[j:j + 1], dtype=np.float32)
                rows[i].request["_cache_shape"] = bucket.key
            self._finish(rows[i], flow_up[j:j + 1], now)
        self._m_exits.inc(len(exits))
        tick.exits = len(exits)
        tick.converged = n_converged
        if self.defunct:
            return  # never write stale rows back over a harvested bucket
        survivors = [i for i in range(n) if i not in set(exits)]
        bucket.rows = [rows[i] for i in survivors]
        bucket.carry = (take_refinement_rows(bucket.carry, survivors)
                        if survivors else None)

    # -- device calls with breaker retry ----------------------------------

    def _device_call(self, kind: str, ph: int, pw: int, iters: int,
                     b: int, *args, traces=()):
        """get_program + invoke, walking the breaker ladder on classified
        kernel failures exactly like the sequential path (the carry is
        plain data — it composes with a rebuilt rung's programs).
        ``traces``: timelines of every request riding this call — a trip
        becomes a decision event on each (the span itself is fanned out by
        the caller, which knows the per-phase interval)."""
        session = self.session
        last: Optional[Exception] = None
        for _ in range(len(session.breaker.ladder) + 1):
            try:
                prog = session.get_program(kind, ph, pw, iters, b=b)
                return session.invoke(prog, *args)
            except Exception as e:  # noqa: BLE001 — filtered just below
                if isinstance(e, SessionError) or not is_kernel_failure(e):
                    raise
                last = e
                session._breaker_retry(
                    e, getattr(e, "_raft_phase", "runtime_failure"),
                    traces=traces)
        raise InferenceFailed(
            "ladder_exhausted", f"breaker retries exhausted: {last}")

    # -- responses --------------------------------------------------------

    def _respond(self, row: _Row, resp: Dict) -> None:
        if self.defunct:
            # A retired generation (bounce) never resolves: the new
            # generation owns these requests now — resolving here would
            # race the re-admitted run for the same Future.
            return
        if row.request.get("id") is not None:
            resp.setdefault("id", row.request["id"])
        if resp["status"] != "ok" and self.retry is not None and \
                self.retry(row.request, resp):
            # Re-admitted under the retry budget: the trace stays open
            # (the retry attempt appends to the same timeline) and the
            # Future resolves with the retried attempt's response.
            return
        row.trace.finish(status=resp["status"], code=resp.get("code"),
                         quality=resp.get("quality"))
        self.resolve(row.request, resp)

    def _finish(self, row: _Row, flow_padded: np.ndarray, now: float) -> None:
        if self.defunct:
            return  # retired generation: don't even count the attempt
        session = self.session
        with row.trace.span("unpad"):
            flow = row.padder.unpad_np(flow_padded)[0, ..., 0]
        if row.iters_done >= session.cfg.valid_iters:
            quality = "full"
        elif row.converged:
            # Near-tier cache seeds label their convergence honestly as
            # warm:cache:k (graftrecall) — k is still the iterations
            # this row actually ran, same contract as converged:k.
            quality = (f"warm:cache:{row.iters_done}" if row.cache_warm
                       else f"converged:{row.iters_done}")
        else:
            quality = f"reduced_iters:{row.iters_done}"
        if flow.shape != (row.orig_h, row.orig_w):
            session.count_request(ok=False)
            self._respond(row, _error(
                "internal", f"output shape {flow.shape} != input "
                f"({row.orig_h}, {row.orig_w})"))
            return
        if not np.isfinite(flow).all():
            session.count_request(ok=False, nonfinite=True)
            self._respond(row, _error(
                "nonfinite_output",
                "disparity contains NaN/Inf — refusing to serve it"))
            return
        session.count_request(ok=True, degraded=quality != "full")
        self._respond(row, {
            "status": "ok",
            "quality": quality,
            "disparity": -flow,
            "iters": row.iters_done,
            "elapsed_ms": (now - row.t_start) * 1e3,
            "deadline_missed": (row.deadline is not None
                                and now > row.deadline),
        })

    @staticmethod
    def _bucket_rows(bucket: _Bucket) -> List[_Row]:
        """Every row the bucket currently owns — active, mid-prepare
        (``joining``), and still-pending — deduped by identity (a row is
        in both ``rows`` and ``joining`` for the instants between the
        join merge and the ``joining`` reset)."""
        seen = set()
        out: List[_Row] = []
        for row in (list(bucket.rows) + list(bucket.joining)
                    + list(bucket.pending)):
            if id(row) not in seen:
                seen.add(id(row))
                out.append(row)
        return out

    def _fail_bucket(self, bucket: _Bucket, exc: Exception) -> None:
        """Terminal tick failure: every request in the bucket gets a
        structured error (never an abandoned Future), the bucket resets."""
        if self.defunct:
            return  # harvest() owns these rows; a zombie's failure is moot
        code = exc.code if isinstance(exc, SessionError) else "internal"
        for row in self._bucket_rows(bucket):
            # Mirror the sequential path's accounting (infer() increments
            # requests_failed on every exception): /healthz session
            # counters stay one truth across serving modes.
            self.session.count_request(ok=False)
            self._respond(row, _error(
                code, f"batched tick failed: {exc}"))
        bucket.rows = []
        bucket.joining = []
        bucket.carry = None
        bucket.pending.clear()

    def drain_pending(self, code: str = "service_stopped",
                      message: str = "service stopped before this request "
                                     "ran") -> None:
        """Reject joiners that never made it into a batch (shutdown path:
        active rows keep ticking to their segment-boundary exits — they
        already own device state — while un-admitted work is returned with
        the same structured rejection the sequential stop() uses)."""
        for bucket in self._bucket_list():
            while bucket.pending:
                self._respond(bucket.pending.popleft(),
                              _reject(code, message))

    def drain(self, code: str = "service_stopped",
              message: str = "service stopped before this request ran"
              ) -> None:
        """Reject everything still waiting or mid-flight (hard shutdown)."""
        self.drain_pending(code, message)
        for bucket in self._bucket_list():
            for row in list(bucket.rows) + list(bucket.joining):
                self._respond(row, _reject(code, message))
            bucket.rows = []
            bucket.joining = []
            bucket.carry = None
        self.shutdown()

    def shutdown(self) -> None:
        self.uploader.stop()

    # -- supervision (serve/supervise.py) ----------------------------------

    def inflight_requests(self) -> List[Dict]:
        """Request dicts of every row currently riding this scheduler
        (active + pending joiners), read-only — the drain path stamps
        decision events on their timelines."""
        return [row.request for bucket in self._bucket_list()
                for row in self._bucket_rows(bucket)]

    def harvest(self) -> List[Dict]:
        """Generation bounce: strip every admitted request (active rows
        + pending joiners) out of the batch state and return their
        request dicts for re-admission — original host inputs are still
        held on each dict, so nothing is silently dropped.

        Call ONLY after ``defunct`` is set and this generation's stop
        event fired: a zombie thread waking from a hung device call
        checks ``defunct`` behind every device call (discarding its
        results), its loop exits immediately, and its ``_respond``
        discards instead of double-resolving.  The ``joining`` group —
        rows mid-batched-prepare, already popped from ``pending`` — is
        harvested too: a hung prepare must strand nothing.  Device-side
        carries are abandoned with the generation; re-admitted rows
        re-upload from host."""
        out: List[Dict] = []
        for bucket in self._bucket_list():
            rows = self._bucket_rows(bucket)
            bucket.rows = []
            bucket.joining = []
            bucket.pending.clear()
            bucket.carry = None
            out.extend(row.request for row in rows)
        self.shutdown()
        return out

    # -- reporting --------------------------------------------------------

    def status(self) -> Dict:
        """The /healthz "batching" document — every aggregate is a
        registry read (same series /metrics exposes)."""
        ticks = int(self._m_ticks.value)
        joins = int(self._m_joins.value)
        exits = int(self._m_exits.value)
        pad_rows = int(self._m_pad_rows.value)
        batch_rows = int(self._m_batch_rows.value)
        occ = {labels["rows"]: int(v) for labels, v in self.registry.series(
            "raft_sched_occupancy_total")}
        occ = {k: occ[k] for k in sorted(occ, key=int)}

        def pct(p: float) -> Optional[float]:
            v = self._tick_hist.percentile(p)
            return None if v is None else v * 1e3

        denom = max(1, ticks)
        return {
            "max_batch": self.session.cfg.max_batch,
            "batch_buckets": list(self.session.batch_buckets),
            "active": self.active_rows,
            "pending": sum(len(b.pending) for b in self._bucket_list()),
            "ticks": ticks,
            "joins": joins,
            "exits": exits,
            "joins_per_tick": joins / denom,
            "exits_per_tick": exits / denom,
            "occupancy_hist": occ,
            "pad_waste": (pad_rows / batch_rows if batch_rows else 0.0),
            "tick_latency_ms": {"p50": pct(0.50), "p99": pct(0.99),
                                "n": self._tick_hist.n},
        }

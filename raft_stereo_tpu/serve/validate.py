"""Admission control: reject malformed requests before they touch a device.

One bad frame must never cost a compile, an XLA crash, or a garbage
disparity served as truth (DESIGN.md "Serving & degradation"). Every check
here is a cheap host-side numpy predicate; each failure carries a stable
``code`` so callers (and the fault-storm test battery driven by
``faults.malformed_pairs``) can assert on the exact rejection class.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


class InputRejected(ValueError):
    """A request failed admission control; ``code`` is machine-readable."""

    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(message)


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Bounds a request must satisfy to be admitted.

    max_pixels: per-image area cap — the largest shape the operator is
        willing to compile/run (Middlebury-F is ~5.7 MP; the default
        admits it with headroom). Protects against a single huge frame
        triggering an OOM or a multi-minute compile for one request.
    require_finite: scan for NaN/Inf pixels on admission. O(N) on host —
        microseconds per megapixel against a multi-ms forward — and the
        alternative is NaN propagating through 32 GRU iterations into a
        disparity field that fails output validation anyway.
    """

    max_pixels: int = 8 << 20
    require_finite: bool = True


def _as_batched(name: str, img) -> np.ndarray:
    if not isinstance(img, np.ndarray):
        try:
            img = np.asarray(img)
        except Exception as e:  # ragged nested sequences etc.
            raise InputRejected(
                "not_an_array", f"{name}: cannot convert to ndarray: {e}")
    if img.dtype == object or not np.issubdtype(img.dtype, np.number):
        raise InputRejected(
            "bad_dtype", f"{name}: non-numeric dtype {img.dtype}")
    if img.ndim == 3:
        img = img[None]
    if img.ndim != 4:
        raise InputRejected(
            "wrong_rank",
            f"{name}: expected (H, W, 3) or (1, H, W, 3), got {img.shape}")
    if img.shape[0] != 1:
        raise InputRejected(
            "bad_batch", f"{name}: serving is single-pair, got batch "
            f"{img.shape[0]}")
    return img


def validate_pair(left, right,
                  admission: AdmissionConfig) -> Tuple[np.ndarray, np.ndarray]:
    """Validate one stereo pair; returns float32 ``(1, H, W, 3)`` arrays.

    Raises :class:`InputRejected` with a stable code on any violation:
    ``not_an_array`` / ``bad_dtype`` / ``wrong_rank`` / ``bad_batch`` /
    ``bad_channels`` / ``zero_area`` / ``too_large`` / ``shape_mismatch`` /
    ``nonfinite_input``.
    """
    left = _as_batched("left", left)
    right = _as_batched("right", right)
    for name, img in (("left", left), ("right", right)):
        if img.shape[-1] != 3:
            raise InputRejected(
                "bad_channels",
                f"{name}: expected 3 channels, got {img.shape[-1]}")
        h, w = img.shape[1], img.shape[2]
        if h <= 0 or w <= 0:
            raise InputRejected(
                "zero_area", f"{name}: zero-area image {img.shape}")
        if h * w > admission.max_pixels:
            raise InputRejected(
                "too_large", f"{name}: {h}x{w} = {h * w} px exceeds the "
                f"admission cap of {admission.max_pixels} px")
    if left.shape != right.shape:
        raise InputRejected(
            "shape_mismatch",
            f"left {left.shape} vs right {right.shape}")
    left = np.ascontiguousarray(left, dtype=np.float32)
    right = np.ascontiguousarray(right, dtype=np.float32)
    if admission.require_finite:
        for name, img in (("left", left), ("right", right)):
            if not np.isfinite(img).all():
                raise InputRejected(
                    "nonfinite_input", f"{name}: contains NaN/Inf pixels")
    return left, right

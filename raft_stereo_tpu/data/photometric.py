"""Numpy photometric augmentation with torchvision-equivalent semantics.

The reference composes ``torchvision.transforms.ColorJitter`` with a gamma
adjustment (``core/utils/augmentor.py:78,200,47-58``). This module reproduces
those semantics on uint8 numpy arrays with an explicit ``np.random.Generator``:

- brightness/contrast/saturation factors ~ U[max(0, 1-a), 1+a] (or a given
  [lo, hi] range), hue shift ~ U[-h, h] in "turns";
- the four jitter ops are applied in a uniformly random order (torchvision
  shuffles the op order per call);
- blends follow torchvision: brightness scales, contrast blends with the mean
  gray value, saturation blends with per-pixel grayscale (ITU-R 601 weights),
  hue rotates the HSV hue channel;
- gamma: ``out = 255 * gain * (in/255)**gamma``.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import numpy as np

import cv2

from raft_stereo_tpu import native

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)

_GRAY_WEIGHTS = np.asarray([0.299, 0.587, 0.114], np.float32)  # ITU-R 601

Range = Union[float, Tuple[float, float], Sequence[float]]


def _as_range(value: Range, center: float = 1.0) -> Tuple[float, float]:
    if np.isscalar(value):
        lo, hi = center - float(value), center + float(value)
        return max(0.0, lo), hi
    lo, hi = value
    return float(lo), float(hi)


def _blend(img: np.ndarray, other: np.ndarray, factor: float) -> np.ndarray:
    out = factor * img + (1.0 - factor) * other
    return np.clip(out, 0.0, 255.0)


def adjust_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    return _blend(img, np.zeros_like(img), factor)


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    mean_gray = (img @ _GRAY_WEIGHTS).mean()
    return _blend(img, np.full_like(img, mean_gray), factor)


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    gray = (img @ _GRAY_WEIGHTS)[..., None]
    return _blend(img, np.broadcast_to(gray, img.shape), factor)


def adjust_hue(img: np.ndarray, shift_turns: float) -> np.ndarray:
    """Rotate hue by ``shift_turns`` of a full color wheel (torchvision units)."""
    hsv = cv2.cvtColor(img.astype(np.uint8), cv2.COLOR_RGB2HSV)
    h = hsv[..., 0].astype(np.int32)  # OpenCV hue is [0, 180)
    hsv[..., 0] = ((h + int(round(shift_turns * 180.0))) % 180).astype(np.uint8)
    return cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB).astype(np.float32)


def adjust_gamma(img: np.ndarray, gamma: float, gain: float = 1.0) -> np.ndarray:
    out = 255.0 * gain * np.power(img / 255.0, gamma)
    return np.clip(out, 0.0, 255.0)


class ColorJitter:
    """uint8 (H, W, 3) -> uint8, torchvision ``ColorJitter`` + ``AdjustGamma``."""

    def __init__(self, brightness: Range = 0.0, contrast: Range = 0.0,
                 saturation: Range = 0.0, hue: float = 0.0,
                 gamma: Sequence[float] = (1.0, 1.0, 1.0, 1.0)):
        self.brightness = _as_range(brightness)
        self.contrast = _as_range(contrast)
        self.saturation = _as_range(saturation)
        self.hue = float(hue)
        # gamma bounds are (gamma_min, gamma_max, gain_min, gain_max), with the
        # gain pair defaulting to 1 (reference AdjustGamma, augmentor.py:47-58).
        g = tuple(gamma) + (1.0, 1.0)[:max(0, 4 - len(tuple(gamma)))]
        self.gamma_range, self.gain_range = (g[0], g[1]), (g[2], g[3])

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        out = img.astype(np.float32)
        ops = list(rng.permutation(4))
        b = rng.uniform(*self.brightness)
        c = rng.uniform(*self.contrast)
        s = rng.uniform(*self.saturation)
        h = rng.uniform(-self.hue, self.hue)
        gamma_gain_draw = (rng.uniform(*self.gamma_range),
                           rng.uniform(*self.gain_range))
        # Native and numpy paths may differ by ±1 uint8 count (tested,
        # bounded): bit-exact reproduction across machines additionally
        # requires the same RAFT_NATIVE setting (README notes this).
        if native.available():
            self._apply_native(out, ops, b, c, s, h, *gamma_gain_draw)
            return out.astype(np.uint8)
        for op in ops:
            if op == 0:
                out = adjust_brightness(out, b)
            elif op == 1:
                out = adjust_contrast(out, c)
            elif op == 2:
                out = adjust_saturation(out, s)
            elif op == 3 and self.hue > 0:
                out = adjust_hue(out, h)
        gamma, gain = gamma_gain_draw
        if gamma != 1.0 or gain != 1.0:
            out = adjust_gamma(out, gamma, gain)
        return out.astype(np.uint8)

    def _apply_native(self, out: np.ndarray, ops, b: float, c: float,
                      s: float, h: float, gamma: float, gain: float) -> None:
        """In-place jitter via the C++ kernels (``native/photometric.cpp``).

        Same op order and per-pixel float32 maths as the numpy path; runs of
        hue-free ops go through one ``native.jitter_ops`` call, the hue op
        (cv2 uint8 HSV fixed-point — already native) splits the sequence. The
        foreign calls release the GIL, so loader worker threads overlap.
        """
        pending: list = []
        for op in ops:
            if op == 3:
                if self.hue > 0:
                    native.jitter_ops(out, pending, b, c, s)
                    pending = []
                    out[...] = adjust_hue(out, h)
            else:
                pending.append(int(op))
        native.jitter_ops(out, pending, b, c, s)
        if gamma != 1.0 or gain != 1.0:
            native.gamma(out, gamma, gain)

"""Image / disparity format IO (reference ``core/utils/frame_utils.py``).

Numpy-native readers for every format the reference supports:

- PFM (SceneFlow / Middlebury / ETH3D disparities) — big/little endian,
  bottom-up row order (reference :34-81);
- Middlebury ``.flo`` optical flow (reference :13-32, 85-114);
- KITTI 16-bit PNG disparity, ``disp = png/256``, ``valid = disp > 0`` (:124-127);
- Sintel RGB-packed disparity + occlusion mask (:130-136);
- FallingThings depth PNG -> disparity via ``fx * 6cm * 100 / depth`` using the
  per-scene ``_camera_settings.json`` (:139-146);
- TartanAir ``.npy`` depth -> ``disp = 80 / depth`` (:149-153);
- Middlebury ``disp0GT.pfm`` + ``mask0nocc.png`` non-occlusion mask (:156-164);
- generic ``read_gen`` extension dispatch (:173-187).

OpenCV threading is disabled at import: loader worker threads fork-safely
share the process (reference :7-9).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple, Union

import numpy as np
from PIL import Image

import cv2

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)

FLO_MAGIC = 202021.25


# ---------------------------------------------------------------------------
# Decompression-bomb guard (graftwire, DESIGN.md r14)
# ---------------------------------------------------------------------------

#: Default cap on header-declared decoded pixel count (~33.5 MP): four
#: times the serving admission cap (8 MP), so a legitimately oversized
#: frame is still rejected by admission with its own ``too_large`` code,
#: while a crafted 100 MP PNG header (whose RGB decode would allocate
#: ~300 MB from a few hundred file bytes) never reaches the decoder at
#: all. Override with ``RAFT_DECODE_MAX_PIXELS`` (registered in
#: analysis/knobs.py HOST_ENV_KNOBS) or per call.
DEFAULT_DECODE_MAX_PIXELS = 32 << 20


class ImageTooLarge(ValueError):
    """Header-declared pixel count exceeds the decode cap.

    Raised BEFORE any full decode happens — PIL parses image headers
    lazily, so the only bytes touched are the header. ``code`` is the
    stable serving rejection code (the HTTP ingress maps it to 413).
    """

    code = "image_too_large"


def resolve_decode_max_pixels(value: Optional[int] = None) -> int:
    """Effective decode pixel cap: explicit value wins, else
    ``RAFT_DECODE_MAX_PIXELS``, else the default. A malformed env value
    raises a ValueError NAMING the variable (the SLURM_CPUS_PER_TASK
    convention) instead of a bare ``int()`` traceback."""
    if value is not None:
        return int(value)
    raw = os.environ.get("RAFT_DECODE_MAX_PIXELS", "").strip()
    if not raw:
        return DEFAULT_DECODE_MAX_PIXELS
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"RAFT_DECODE_MAX_PIXELS must be an integer pixel count, "
            f"got {raw!r}") from None


def guard_decode_size(size, source: str = "image",
                      max_pixels: Optional[int] = None) -> None:
    """Reject a decode whose header declares more pixels than the cap.

    ``size`` is PIL's ``(width, height)``. Called between the lazy header
    parse and the array conversion that triggers the actual pixel decode
    — the whole point is that a decompression bomb costs a header read,
    never an allocation proportional to its declared area."""
    w, h = int(size[0]), int(size[1])
    cap = resolve_decode_max_pixels(max_pixels)
    if w * h > cap:
        raise ImageTooLarge(
            f"{source}: header declares {w}x{h} = {w * h} px, above the "
            f"decode cap of {cap} px (RAFT_DECODE_MAX_PIXELS)")


# ---------------------------------------------------------------------------
# PFM (portable float map)
# ---------------------------------------------------------------------------

def read_pfm(path: Union[str, os.PathLike]) -> np.ndarray:
    """Read a PFM file -> (H, W) or (H, W, 3) float array, top-down rows."""
    with open(path, "rb") as f:
        kind = f.readline().strip()
        if kind == b"PF":
            channels = 3
        elif kind == b"Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {kind!r})")
        dims = f.readline().split()
        if len(dims) != 2:
            raise ValueError(f"{path}: malformed PFM dimensions {dims!r}")
        width, height = int(dims[0]), int(dims[1])
        scale = float(f.readline().strip())
        dtype = "<f4" if scale < 0 else ">f4"
        data = np.fromfile(f, dtype, count=width * height * channels)
    shape = (height, width, 3) if channels == 3 else (height, width)
    # PFM stores rows bottom-up; flip to conventional top-down.
    return np.flipud(data.reshape(shape)).copy()


def write_pfm(path: Union[str, os.PathLike], array: np.ndarray) -> None:
    """Write a single-channel float PFM (little-endian, bottom-up rows)."""
    if array.ndim != 2:
        raise ValueError(f"write_pfm expects (H, W), got {array.shape}")
    h, w = array.shape
    with open(path, "wb") as f:
        f.write(b"Pf\n%d %d\n-1\n" % (w, h))
        f.write(np.flipud(array).astype("<f4").tobytes())


# ---------------------------------------------------------------------------
# Middlebury .flo optical flow
# ---------------------------------------------------------------------------

def read_flow(path: Union[str, os.PathLike]) -> Optional[np.ndarray]:
    """Read a .flo file -> (H, W, 2) float32, or None on a bad magic."""
    with open(path, "rb") as f:
        magic = np.fromfile(f, np.float32, count=1)
        if magic.size == 0 or magic[0] != np.float32(FLO_MAGIC):
            return None
        w = int(np.fromfile(f, np.int32, count=1)[0])
        h = int(np.fromfile(f, np.int32, count=1)[0])
        data = np.fromfile(f, np.float32, count=2 * w * h)
    return data.reshape(h, w, 2)


def write_flow(path: Union[str, os.PathLike], flow: np.ndarray) -> None:
    """Write (H, W, 2) float32 optical flow as .flo."""
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"write_flow expects (H, W, 2), got {flow.shape}")
    h, w = flow.shape[:2]
    with open(path, "wb") as f:
        np.asarray([FLO_MAGIC], np.float32).tofile(f)
        np.asarray([w, h], np.int32).tofile(f)
        flow.astype(np.float32).tofile(f)


# ---------------------------------------------------------------------------
# KITTI 16-bit PNG encodings
# ---------------------------------------------------------------------------

def read_disp_kitti(path) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI disparity PNG: uint16 / 256; zero marks invalid."""
    disp = cv2.imread(str(path), cv2.IMREAD_ANYDEPTH) / 256.0
    return disp, disp > 0.0


def read_flow_kitti(path) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI flow PNG: uint16 channels ``(u, v, valid)``, ``(x - 2^15)/64``."""
    raw = cv2.imread(str(path), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    raw = raw[:, :, ::-1].astype(np.float32)  # BGR -> RGB channel order
    flow = (raw[:, :, :2] - 2 ** 15) / 64.0
    return flow, raw[:, :, 2]


def write_flow_kitti(path, flow: np.ndarray) -> None:
    enc = 64.0 * flow + 2 ** 15
    valid = np.ones((*flow.shape[:2], 1), flow.dtype)
    enc = np.concatenate([enc, valid], axis=-1).astype(np.uint16)
    cv2.imwrite(str(path), enc[:, :, ::-1])


# ---------------------------------------------------------------------------
# Per-dataset disparity readers (each -> (disp, valid))
# ---------------------------------------------------------------------------

def read_disp_sintel(path) -> Tuple[np.ndarray, np.ndarray]:
    """Sintel packs disparity into RGB: ``r*4 + g/64 + b/16384``; the paired
    ``occlusions/`` PNG marks occluded pixels (nonzero).

    Deviation from the reference (:130-136): the decode is done in float64.
    The reference multiplies the uint8 R channel by 4 before promotion, which
    wraps mod 256 for disparities >= 256 px under value-based casting — a
    latent overflow this implementation fixes.
    """
    rgb = np.asarray(Image.open(path), dtype=np.float64)
    disp = rgb[..., 0] * 4 + rgb[..., 1] / 2 ** 6 + rgb[..., 2] / 2 ** 14
    occ = np.asarray(Image.open(str(path).replace("disparities", "occlusions")))
    return disp, (occ == 0) & (disp > 0)


def read_disp_falling_things(path) -> Tuple[np.ndarray, np.ndarray]:
    """FallingThings depth PNG -> disparity with the 6 cm baseline:
    ``disp = fx * 6.0 * 100 / depth`` (fx from _camera_settings.json)."""
    depth = np.asarray(Image.open(path)).astype(np.float32)
    settings = os.path.join(os.path.dirname(str(path)), "_camera_settings.json")
    with open(settings) as f:
        fx = json.load(f)["camera_settings"][0]["intrinsic_settings"]["fx"]
    disp = (fx * 6.0 * 100) / depth
    return disp, disp > 0


def read_disp_tartan_air(path) -> Tuple[np.ndarray, np.ndarray]:
    """TartanAir depth .npy -> ``disp = 80 / depth``."""
    disp = 80.0 / np.load(path)
    return disp, disp > 0


def read_disp_middlebury(path) -> Tuple[np.ndarray, np.ndarray]:
    """Middlebury ``disp0GT.pfm`` with its ``mask0nocc.png`` (255 = nocc)."""
    path = str(path)
    if os.path.basename(path) != "disp0GT.pfm":
        raise ValueError(f"expected a disp0GT.pfm path, got {path}")
    disp = read_pfm(path).astype(np.float32)
    if disp.ndim != 2:
        raise ValueError(f"{path}: disparity PFM must be single-channel")
    mask_path = path.replace("disp0GT.pfm", "mask0nocc.png")
    nocc = np.asarray(Image.open(mask_path)) == 255
    if not nocc.any():
        raise ValueError(f"{mask_path}: empty non-occlusion mask")
    return disp, nocc


# ---------------------------------------------------------------------------
# Generic dispatch
# ---------------------------------------------------------------------------

def read_gen(path, pil: bool = False):
    """Extension-dispatched reader (reference ``read_gen``, :173-187).

    Images return PIL Images; ``.pfm`` returns float arrays with the alpha-like
    last channel dropped for 3-channel maps; ``.flo`` returns (H, W, 2).
    """
    ext = os.path.splitext(str(path))[-1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm"):
        return Image.open(path)
    if ext in (".bin", ".raw"):
        return np.load(path)
    if ext == ".flo":
        return read_flow(path).astype(np.float32)
    if ext == ".pfm":
        data = read_pfm(path).astype(np.float32)
        return data if data.ndim == 2 else data[:, :, :-1]
    return []


def read_image_rgb(path) -> np.ndarray:
    """Read an image as (H, W, 3) uint8, tiling grayscale to 3 channels.

    Guarded against decompression bombs: PIL's ``open`` only parses the
    header, so the declared pixel count is checked against
    ``RAFT_DECODE_MAX_PIXELS`` *before* ``np.asarray`` triggers the full
    decode (:class:`ImageTooLarge` on violation — same stable
    ``image_too_large`` code the HTTP ingress serves as 413). PIL's own
    bomb tripwire (``MAX_IMAGE_PIXELS``, which fires inside ``open`` for
    declarations ~5x above our default cap) is folded into the SAME
    stable code — which guard fires first is a threshold detail, not two
    error contracts."""
    try:
        img = read_gen(path)
    except Image.DecompressionBombError as e:
        raise ImageTooLarge(f"{path}: {e}") from e
    if isinstance(img, Image.Image):
        guard_decode_size(img.size, source=str(path))
    img = np.asarray(img).astype(np.uint8)
    if img.ndim == 2:
        return np.tile(img[..., None], (1, 1, 3))
    return img[..., :3]


# Reference-named aliases so existing user code ports one-to-one.
readPFM = read_pfm
writePFM = write_pfm
readFlow = read_flow
writeFlow = write_flow
readDispKITTI = read_disp_kitti
readFlowKITTI = read_flow_kitti
writeFlowKITTI = write_flow_kitti
readDispSintelStereo = read_disp_sintel
readDispFallingThings = read_disp_falling_things
readDispTartanAir = read_disp_tartan_air
readDispMiddlebury = read_disp_middlebury

"""Prefetching batch loader (reference: torch ``DataLoader``, :311-312).

The reference leans on torch's fork-based DataLoader; here the loader is a
thread pool over the numpy-native dataset with deterministic per-sample RNG:

- sample ``i`` of epoch ``e`` is loaded with ``default_rng([seed, e, i])`` —
  reproducible regardless of worker count or scheduling (the reference's
  per-worker global reseeding makes runs depend on worker assignment);
- bounded in-flight futures give prefetch with backpressure;
- ``device_prefetch`` overlaps host->device transfer of batch N+1 with the
  TPU step on batch N (double buffering), placing arrays with the mesh's
  batch sharding so each chip receives only its shard.

cv2/PIL decode and numpy augmentation release the GIL for their hot parts, so
threads keep an 8-chip slice fed without fork complexity; ``num_workers``
matches the reference's ``SLURM_CPUS_PER_TASK - 2`` sizing by default.

Fault tolerance (DESIGN.md "Failure recovery"): IO/decode errors retry with
bounded backoff; persistently-bad samples are quarantined for the run and
their batch slots filled by deterministic substitutes keyed off the same
``[seed, epoch, i]`` slot RNG, so one corrupt PNG/PFM costs one sample — not
the run — and multi-host batches stay identical.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional

import numpy as np

from raft_stereo_tpu.data.datasets import StereoDataset, fetch_dataset

logger = logging.getLogger(__name__)

ARRAY_KEYS = ("image1", "image2", "flow", "valid")

# Errors worth retrying/quarantining: filesystem hiccups (OSError covers
# PIL's UnidentifiedImageError and truncated-read IOErrors), decode failures
# (ValueError from the PFM/flow parsers), and cv2.imread's None-return
# arithmetic (TypeError). Anything else — shape bugs, OOM, KeyboardInterrupt
# — propagates: retrying a programming error hides it.
IO_RETRY_ERRORS = (OSError, ValueError, TypeError)

# Key-salt separating the substitute-candidate stream from the per-sample
# augmentation stream (both are keyed off [seed, epoch, position]).
_SUBSTITUTE_SALT = 0x5B5
# Distinct substitute candidates probed before giving up on a batch slot.
_SUBSTITUTE_TRIES = 32
# Quarantine is for ISOLATED corruption. IO_RETRY_ERRORS is deliberately
# broad (ValueError/TypeError also cover decode bugs reached through real
# files), so a systematic failure — an augmentation bug for a whole
# dataset, a dead mount — would otherwise be silently substituted away
# sample by sample. Cap the quarantine at this fraction of the dataset
# (floored at an absolute count so tiny datasets aren't over-strict) and
# abort loudly beyond it.
_MAX_QUARANTINE_FRAC = 0.01
_MAX_QUARANTINE_MIN = 16


def collate(samples, return_paths: bool = False) -> Dict[str, np.ndarray]:
    """Stack sample dicts into one batch dict of arrays.

    Paths are excluded by default so the batch is a pure JAX pytree — it can
    go straight into ``shard_batch`` / a jitted step without stripping keys.
    """
    batch = {k: np.stack([s[k] for s in samples]) for k in ARRAY_KEYS
             if k in samples[0]}
    if return_paths:
        batch["paths"] = [s["paths"] for s in samples]
    return batch


class StereoLoader:
    """Iterable over shuffled, augmented, batched samples."""

    def __init__(self, dataset: StereoDataset, batch_size: int,
                 shuffle: bool = True, num_workers: int = 4,
                 drop_last: bool = True, seed: int = 0, prefetch: int = 2,
                 return_paths: bool = False,
                 local_rows: Optional[slice] = None,
                 retries: int = 2, retry_backoff: float = 0.05):
        self.dataset = dataset
        self.batch_size = batch_size
        # Fault tolerance (DESIGN.md "Failure recovery"): per-sample load
        # errors retry `retries` times with bounded exponential backoff;
        # a sample still failing after that is quarantined for the run and
        # its batch slot filled by a deterministic substitute.
        self.retries = max(0, retries)
        self.retry_backoff = retry_backoff
        self.quarantined: Dict[int, str] = {}
        # Worker threads quarantine concurrently; the lock keeps the
        # check-then-insert atomic and quarantine_report()'s copy safe
        # against a late in-flight _load mutating the dict mid-iteration.
        self._quarantine_lock = threading.Lock()
        # Multi-host: decode only this process's rows of each (globally
        # deterministic) batch. Epoch order and per-sample RNG stay keyed
        # by GLOBAL position, so the pod-wide batch is identical to the
        # single-host one — only the decode work is partitioned.
        self.local_rows = local_rows
        self.shuffle = shuffle
        self.num_workers = max(1, num_workers)
        self.drop_last = drop_last
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self.return_paths = return_paths
        self.epoch = 0
        if drop_last and len(dataset) < batch_size:
            # A zero-batch loader would make train() spin forever in its
            # while-loop without ever advancing total_steps — fail fast.
            raise ValueError(
                f"drop_last=True leaves zero batches: dataset has "
                f"{len(dataset)} samples < batch_size {batch_size}")

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.default_rng([self.seed, epoch]).shuffle(order)
        return order

    def _load_once(self, index: int, epoch: int, position: int):
        """One sample with bounded retry; re-raises after retries exhaust.

        The RNG is re-derived from ``[seed, epoch, position]`` on every
        attempt, so a retried sample draws the *identical* augmentation
        stream — a transient IO fault that recovers within the retry budget
        yields a run bit-for-bit equal to the fault-free one.
        """
        last_err = None
        for attempt in range(self.retries + 1):
            rng = np.random.default_rng([self.seed, epoch, position])
            try:
                return self.dataset.__getitem__(int(index), rng=rng)
            except IO_RETRY_ERRORS as e:
                last_err = e
                if attempt < self.retries:
                    time.sleep(min(self.retry_backoff * (2 ** attempt), 2.0))
        raise last_err

    def _quarantine(self, index: int, err: BaseException) -> None:
        with self._quarantine_lock:
            if int(index) not in self.quarantined:
                self.quarantined[int(index)] = repr(err)
                logger.warning(
                    "quarantined sample %d after %d failed attempts (%r); "
                    "%d sample(s) quarantined so far",
                    index, self.retries + 1, err, len(self.quarantined))
            n = len(self.quarantined)
        limit = max(_MAX_QUARANTINE_MIN,
                    int(_MAX_QUARANTINE_FRAC * len(self.dataset)))
        if n > limit:
            # Last-resort abort, HOST-LOCAL by design: on a pod each process
            # decodes only its own rows, so a dead local mount trips the cap
            # here only — survivors exit via the distributed-runtime barrier
            # timeout at their next collective. Coordinating this abort at a
            # step boundary is impossible (the dying host never reaches one);
            # see DESIGN.md "Failure recovery".
            raise RuntimeError(
                f"{n} samples quarantined (limit "
                f"{limit}): this is a systematic data-pipeline failure "
                f"(bad mount, decode/augmentation bug), not isolated "
                f"corruption; last error: {err!r}")

    def quarantine_report(self) -> Dict[int, str]:
        """Quarantined dataset indices -> last error repr (copy)."""
        with self._quarantine_lock:
            return dict(self.quarantined)

    def _load(self, index: int, epoch: int, position: int):
        """Load batch slot ``position``: the scheduled sample, or — when it
        is persistently bad — a deterministic substitute.

        Substitutes are drawn from an RNG keyed off the same
        ``[seed, epoch, position]`` slot (plus a salt separating it from the
        augmentation stream), and a candidate is accepted or rejected by
        *content* (it must itself load within the retry budget). The local
        ``quarantined`` set is only a fast path past candidates already
        proven persistently bad — it never changes which candidate wins, so
        every run and every pod process fills the slot identically and
        multi-host batches stay batch-identical. (The retry budget is the
        transient/persistent boundary: a fault that exceeds it on one host
        but not another is by definition not transient.)
        """
        index = int(index)
        if index not in self.quarantined:
            try:
                return self._load_once(index, epoch, position)
            except IO_RETRY_ERRORS as e:
                self._quarantine(index, e)
        # Candidates are a seeded PERMUTATION (no duplicate or self draws
        # wasting tries), and a known-quarantined candidate consumes a try
        # just like probing-and-failing it would — so every host walks the
        # identical candidate sequence with the identical try budget whether
        # it learned a candidate was bad locally or not.
        order = np.random.default_rng(
            [self.seed, epoch, position, _SUBSTITUTE_SALT]).permutation(
                len(self.dataset))
        last_err: Optional[BaseException] = None
        tried = 0
        for j in order:
            j = int(j)
            if j == index:
                continue
            if tried >= _SUBSTITUTE_TRIES:
                break
            tried += 1
            if j in self.quarantined:
                continue  # counted: a content probe would fail identically
            try:
                return self._load_once(j, epoch, position)
            except IO_RETRY_ERRORS as e:
                self._quarantine(j, e)
                last_err = e
        raise RuntimeError(
            f"sample {index} is quarantined and none of {tried} "
            f"deterministic substitute candidates loaded") from last_err

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        # Claim the epoch number up front: a partially-consumed iterator
        # (step-bounded training loop breaking early) must not replay the
        # identical shuffle + augmentations on the next pass.
        epoch = self.epoch
        self.epoch += 1
        order = self._epoch_order(epoch)
        n_batches = len(self)
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        try:
            # Keep `prefetch` batches of futures in flight, in order.
            pending = []
            submitted = 0

            def submit_batch(b):
                lo = b * self.batch_size
                idxs = order[lo:lo + self.batch_size]
                # len(idxs) < batch_size on the final batch when
                # drop_last=False; the local-rows window clamps to it.
                rows = (range(len(idxs)) if self.local_rows is None
                        else range(*self.local_rows.indices(len(idxs))))
                return [pool.submit(self._load, idxs[k], epoch, lo + k)
                        for k in rows]

            while submitted < n_batches and len(pending) < self.prefetch:
                pending.append(submit_batch(submitted))
                submitted += 1
            while pending:
                futures = pending.pop(0)
                if submitted < n_batches:
                    pending.append(submit_batch(submitted))
                    submitted += 1
                yield collate([f.result() for f in futures],
                              return_paths=self.return_paths)
        finally:
            # No blocking join: an abandoned iterator (early break, interpreter
            # exit) must not hang or raise during generator finalization —
            # at interpreter teardown even module globals may be gone.
            try:
                pool.shutdown(wait=False, cancel_futures=True)
            except Exception:
                pass


def fetch_dataloader(train_cfg, root: Optional[str] = None,
                     local_rows: Optional[slice] = None) -> StereoLoader:
    """Build the training-mix loader (reference ``fetch_dataloader``).

    ``local_rows``: on a multi-host pod, the global-batch row range this
    process's devices own (``parallel.mesh.local_batch_rows``) — only
    those samples are decoded here (the reference's per-process
    DataLoader equivalent)."""
    dataset = fetch_dataset(train_cfg, root=root)
    num_workers = getattr(train_cfg, "num_workers", None)
    if num_workers is None:
        raw = os.environ.get("SLURM_CPUS_PER_TASK", "6")
        try:
            cpus = int(raw)
        except ValueError:
            raise ValueError(
                f"SLURM_CPUS_PER_TASK must be an integer, got {raw!r} — "
                "fix the allocation or pass num_workers explicitly"
            ) from None
        # A 1-2 CPU allocation must still get ONE worker, not 0/-1
        # (StereoLoader clamps too, but clamp at the read so the derived
        # value is never nonsensical in logs/configs).
        num_workers = max(1, cpus - 2)
    return StereoLoader(dataset, batch_size=train_cfg.batch_size, shuffle=True,
                        num_workers=num_workers, drop_last=True,
                        seed=getattr(train_cfg, "seed", 0),
                        local_rows=local_rows,
                        retries=getattr(train_cfg, "data_retries", 2),
                        retry_backoff=getattr(train_cfg, "data_retry_backoff",
                                              0.05))


def device_prefetch(loader, mesh=None, size: int = 2, image_dtype=None,
                    global_batch: Optional[int] = None):
    """Double-buffer batches onto device (sharded over the mesh's data axis).

    The host->device transfer of batch N+1 runs on a background thread while
    the training loop blocks on batch N's metrics fetch: ``jax.device_put``
    of a large numpy array is synchronous host-side, so putting from the
    consumer thread would serialize upload and compute — measured 3+ s/step
    of un-overlapped transfer at the reference crop through a tunneled chip.

    ``image_dtype`` (e.g. ``jnp.bfloat16`` under mixed precision) downcasts
    the image arrays BEFORE transfer, halving upload bytes; the model's
    first op casts images to the compute dtype anyway, so the values the
    network consumes are the same to one rounding step. (Precisely: the
    normalization ``2*(x/255)-1`` then runs on bf16-quantized uint8 values
    at train time while eval feeds fp32 — a train/eval asymmetry of at
    most one bf16 ulp per pixel, dwarfed by train-time photometric
    augmentation. Set ``image_dtype=None`` for bit-identical transport.)

    Multi-host: when ``global_batch`` exceeds the rows present in the
    yielded batches, each process is holding ONLY its shard of the global
    batch (a row-local loader via ``fetch_dataloader(local_rows=...)``,
    the reference's one-DataLoader-per-process equivalent) and the global
    array is assembled with ``jax.make_array_from_process_local_data`` —
    no host decodes work for another host's devices.
    """
    import jax

    if mesh is not None:
        # Same sharding rule as make_train_step/make_eval_step, so jit does
        # not insert a reshard that defeats the double-buffering overlap.
        from raft_stereo_tpu.parallel.mesh import data_sharding
        sharding = data_sharding(mesh)

        def placed(v):
            if global_batch is not None and v.shape[0] != global_batch:
                return jax.make_array_from_process_local_data(
                    sharding, v, (global_batch,) + v.shape[1:])
            return jax.device_put(v, sharding)
    else:
        placed = lambda v: jax.device_put(v)

    def put(b):
        out = {}
        for k, v in b.items():
            if isinstance(v, np.ndarray):
                if image_dtype is not None and k in ("image1", "image2"):
                    v = v.astype(image_dtype)
                v = placed(v)
            out[k] = v
        return out

    buf = []
    ex = ThreadPoolExecutor(max_workers=1)
    try:
        for batch in loader:
            buf.append(ex.submit(put, batch))
            if len(buf) >= size:
                yield buf.pop(0).result()
        while buf:
            yield buf.pop(0).result()
    finally:
        # No blocking join (mirrors StereoLoader above): train loops abandon
        # this generator at num_steps/preemption, and waiting here would
        # stall on a multi-second upload of a batch nobody will use — on
        # the preemption path that wait eats SIGTERM grace time. (Note
        # concurrent.futures still registers an atexit join of the worker
        # thread, so an in-flight device_put can delay interpreter EXIT;
        # the preempt checkpoint is already on disk by then, so only exit
        # latency — not safety — is affected.)
        try:
            ex.shutdown(wait=False, cancel_futures=True)
        except Exception:
            pass

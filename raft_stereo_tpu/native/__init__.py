"""Native (C++) data-pipeline kernels, loaded via ctypes.

The reference's only native component is its CUDA correlation sampler; its
TPU analog here is the Pallas kernel layer (``corr/pallas_*.py``). This
package is the native piece of the *host* runtime: photometric augmentation
kernels that the loader's worker threads call with the GIL released (ctypes
drops the GIL for the duration of a foreign call), keeping the input
pipeline fast enough to feed multi-chip training (scratch/bench_loader.py).

Build model: compiled lazily with the system C++ compiler into a per-user
cache keyed by source hash — no build step at install time, no binary in the
tree, works from a read-only site-packages. Everything degrades gracefully:
if no compiler exists or the build fails, ``lib()`` returns None and callers
fall back to the numpy implementation (``data/photometric.py``).

Set ``RAFT_NATIVE=0`` to force the numpy path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

_SRC = Path(__file__).with_name("photometric.cpp")
_lib: Optional[ctypes.CDLL] = None
_tried = False
_lock = threading.Lock()


def _cache_dir() -> Path:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    d = Path(base) / "raft_stereo_tpu"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _build() -> Optional[Path]:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"photometric-{tag}.so"
    if out.exists():
        return out
    last_err = "no C++ compiler found"
    for cxx in (os.environ.get("CXX"), "g++", "c++", "clang++"):
        if not cxx:
            continue
        # Compile to a temp path and rename: atomic vs concurrent builders.
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(out.parent))
        os.close(fd)
        cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
               str(_SRC), "-o", tmp]
        try:
            # Serializing the one-time native build is this module
            # lock's entire job: concurrent importers must wait for one
            # .so, not race multiple compilers over the same cache path.
            # graftlint: disable=GC203 (build serialization is the lock's purpose)
            proc = subprocess.run(cmd, capture_output=True, timeout=120)
            if proc.returncode == 0:
                # graftlint: disable=GC204 (atomic .so publish under the build lock)
                os.replace(tmp, out)
                return out
            last_err = proc.stderr.decode(errors="replace")[-500:]
        except (OSError, subprocess.TimeoutExpired) as e:
            last_err = str(e)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    # A silent failure here would silently cost ~30% loader throughput
    # (BASELINE.md); make the numpy fallback diagnosable.
    import logging
    logging.getLogger(__name__).warning(
        "native photometric build failed, falling back to numpy: %s", last_err)
    return None


_F32P = ctypes.POINTER(ctypes.c_float)
_I32P = ctypes.POINTER(ctypes.c_int32)


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if unavailable.

    Serialized by a lock: loader worker threads all hit the first call
    together, and the probe (which may spend seconds in the compiler) must
    run once — latecomers block and then share the result rather than
    spawning duplicate builds or silently taking the numpy path mid-run.
    """
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        if os.environ.get("RAFT_NATIVE", "1").strip().lower() in (
                "0", "false", "no"):
            _tried = True
            return None
        try:
            path = _build()
            if path is not None:
                cdll = ctypes.CDLL(str(path))
                cdll.rst_jitter_ops.argtypes = [_F32P, ctypes.c_int64, _I32P,
                                                ctypes.c_int32, ctypes.c_float,
                                                ctypes.c_float, ctypes.c_float]
                cdll.rst_gamma.argtypes = [_F32P, ctypes.c_int64,
                                           ctypes.c_float, ctypes.c_float]
                for name in ("rst_brightness", "rst_contrast",
                             "rst_saturation"):
                    getattr(cdll, name).argtypes = [_F32P, ctypes.c_int64,
                                                    ctypes.c_float]
                _lib = cdll
        except OSError:
            _lib = None
        _tried = True
        return _lib


def available() -> bool:
    return lib() is not None


def _buf(img: np.ndarray):
    # Raise (not assert — must survive ``python -O``): the C kernels trust
    # this layout and read/write H*W*3 floats with no further checks.
    if (img.dtype != np.float32 or not img.flags.c_contiguous
            or img.ndim != 3 or img.shape[2] != 3):
        raise ValueError(
            f"native kernels need a C-contiguous float32 (H, W, 3) array, "
            f"got {img.dtype} {img.shape}")
    return img.ctypes.data_as(_F32P)


def jitter_ops(img: np.ndarray, ops: Sequence[int], brightness: float,
               contrast: float, saturation: float) -> bool:
    """Apply a hue-free run of jitter ops in place on a float32 RGB image.

    Returns False (leaving ``img`` untouched) when the native library is
    unavailable — callers keep their numpy fallback.
    """
    cdll = lib()
    if cdll is None:
        return False
    if len(ops):
        arr = np.asarray(ops, np.int32)
        cdll.rst_jitter_ops(_buf(img), img.shape[0] * img.shape[1],
                            arr.ctypes.data_as(_I32P), len(arr),
                            brightness, contrast, saturation)
    return True


def gamma(img: np.ndarray, gamma_: float, gain: float) -> bool:
    """In-place gamma adjustment; False if the native library is unavailable."""
    cdll = lib()
    if cdll is None:
        return False
    cdll.rst_gamma(_buf(img), img.shape[0] * img.shape[1], gamma_, gain)
    return True

"""Training and evaluation engines."""

from raft_stereo_tpu.engine.loss import sequence_loss  # noqa: F401
from raft_stereo_tpu.engine.optimizer import (  # noqa: F401
    make_optimizer, onecycle_linear_schedule)

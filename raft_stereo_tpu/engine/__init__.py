"""Training and evaluation engines."""

from raft_stereo_tpu.engine.checkpoint import (  # noqa: F401
    CheckpointError, check_run_name, find_latest_checkpoint, load_checkpoint,
    load_params, prune_checkpoints, save_checkpoint, validate_checkpoint)
from raft_stereo_tpu.engine.logger import Logger  # noqa: F401
from raft_stereo_tpu.engine.loss import sequence_loss  # noqa: F401
from raft_stereo_tpu.engine.optimizer import (  # noqa: F401
    make_optimizer, onecycle_linear_schedule)
from raft_stereo_tpu.engine.steps import (  # noqa: F401
    make_eval_step, make_train_step)

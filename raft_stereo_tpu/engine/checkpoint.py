"""Full-state checkpointing: params + optimizer + step in one bundle.

The reference saves model weights only (``torch.save(model.state_dict())``,
``train_stereo.py:184``), so a resumed run restarts the OneCycle schedule from
zero — flagged in SURVEY §5 as a deliberate improvement target. Here a
checkpoint is the complete training state, serialized with flax msgpack
(pytree-structure-preserving, works for optax named-tuple states), written
atomically (tmp file + rename) so preemption mid-save never corrupts the
latest checkpoint.

Integrity (DESIGN.md "Failure recovery"): atomic rename protects against
*this* process dying mid-save, but not against a copy truncated in transit,
a partial NFS flush, or on-disk corruption. Every bundle therefore carries a
content hash — an 8-byte magic + sha256(payload) header ahead of the msgpack
payload — and loads verify it; headerless files load as legacy bundles (no
integrity information, best effort). ``find_latest_checkpoint`` turns that
into auto-resume: newest *valid* numbered bundle in a directory, falling
back past truncated/corrupt ones, and ``prune_checkpoints`` bounds disk use
with keep-last-K retention of the periodic saves.

``load_params`` additionally accepts the reference's ``.pth`` checkpoints via
the transplant shim, so all published RAFT-Stereo weights load anywhere our
checkpoints do.
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
from flax import serialization

logger = logging.getLogger(__name__)

CKPT_SUFFIX = ".msgpack"

# Hash-framed bundle: MAGIC + sha256(payload) + payload. The magic cannot
# collide with a legacy bundle — flax msgpack of the state dict starts with
# a msgpack fixmap byte (0x8N), never 'R'.
_MAGIC = b"RSCKPT1\n"
_DIGEST_LEN = hashlib.sha256().digest_size
_HEADER_LEN = len(_MAGIC) + _DIGEST_LEN

# Numbered resume candidates: "{step}_{name}", "{step}_preempt_{name}",
# "{step}_epoch_{name}". The final "{name}" bundle has no step prefix and
# means "finished" — never a resume candidate.
_STEP_RE = re.compile(r"^(\d+)_(?:preempt_|epoch_)?(.+)$")


class CheckpointError(ValueError):
    """A checkpoint file failed integrity validation (truncated/corrupt)."""


def check_run_name(name: str) -> str:
    """Reject run names that collide with the bundle-filename grammar.

    A name starting with ``<digits>_`` makes that run's FINAL bundle
    (``{name}.msgpack``) parse as another run's periodic bundle — so
    keep-last-K pruning for the other run could delete it; a name starting
    with ``preempt_``/``epoch_`` makes this run's periodic bundles parse as
    another run's marker bundles. Both are silent cross-run interference,
    so fail fast at train start instead.
    """
    if re.match(r"^\d+_", name) or name.startswith(("preempt_", "epoch_")):
        raise ValueError(
            f"run name {name!r} collides with the checkpoint filename "
            "grammar ('<step>_[preempt_|epoch_]<name>.msgpack'); it must "
            "not start with digits-underscore, 'preempt_' or 'epoch_'")
    return name


def save_checkpoint(path: str, params, opt_state=None, step: int = 0) -> str:
    """Serialize (params, opt_state, step) atomically; returns the path."""
    state = {"params": jax.device_get(params),
             "opt_state": (jax.device_get(opt_state)
                           if opt_state is not None else None),
             "step": step}
    blob = serialization.to_bytes(state)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(hashlib.sha256(blob).digest())
        f.write(blob)
    os.replace(tmp, path)
    return path


def _read_payload(path: str) -> Tuple[bytes, bool]:
    """Read a bundle's msgpack payload, verifying the content hash.

    Returns ``(payload, hash_verified)``. Raises :class:`CheckpointError`
    when a hash-framed bundle is truncated or its digest mismatches.
    Headerless (legacy) files pass through unverified
    (``hash_verified=False``) — corruption there surfaces as a msgpack
    parse error.
    """
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        return blob, False  # legacy bundle: no integrity header to check
    if len(blob) < _HEADER_LEN:
        raise CheckpointError(f"{path}: truncated checkpoint header")
    digest = blob[len(_MAGIC):_HEADER_LEN]
    payload = blob[_HEADER_LEN:]
    if hashlib.sha256(payload).digest() != digest:
        raise CheckpointError(
            f"{path}: checkpoint content hash mismatch (truncated or "
            "corrupt bundle)")
    return payload, True


def bundle_step(path: str) -> int:
    """Training step of a bundle: free from the filename for numbered
    bundles; a full payload parse (verifying the hash frame, so this raises
    :class:`CheckpointError` on corruption) only for unnumbered/final ones —
    multi-GB bundles must not be deserialized just to compare steps."""
    stem = os.path.basename(path)
    if stem.endswith(CKPT_SUFFIX):
        stem = stem[:-len(CKPT_SUFFIX)]
    m = _STEP_RE.match(stem)
    if m is not None:
        return int(m.group(1))
    payload, _ = _read_payload(path)
    return int(serialization.msgpack_restore(payload)["step"])


def validate_checkpoint(path: str) -> bool:
    """True iff ``path`` holds a structurally-sound checkpoint bundle."""
    try:
        payload, hash_verified = _read_payload(path)
        # A matching sha256 already proves the payload intact — the msgpack
        # parse (a full deserialization, expensive for multi-GB bundles) is
        # only the fallback check for legacy headerless files.
        if not hash_verified:
            serialization.msgpack_restore(payload)
        return True
    except Exception:  # noqa: BLE001 - any failure means "not loadable"
        return False


def load_checkpoint(path: str, params_template, opt_state_template=None
                    ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state, step); templates define the pytree shape.

    Migration shim, both directions across the ``optax.apply_if_finite``
    wrapper (the ``max_bad_steps`` skip-if-nonfinite optimizer): a wrapped
    template accepts bundles saved WITHOUT the wrapper (pre-wrapper runs,
    ``--max_bad_steps 0``) by re-wrapping the inner optimizer state with
    fresh (zero) failure counters, and an unwrapped template accepts
    wrapped bundles by restoring just their ``inner_state``. Without this,
    changing ``max_bad_steps`` across 0 would strand every checkpoint on
    the other side.
    """
    blob, _ = _read_payload(path)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": 0}
    try:
        state = serialization.from_bytes(template, blob)
    except (ValueError, KeyError):
        import optax

        # flax serializes the wrapper NamedTuple by field names and a plain
        # chain tuple as {"0": ..., "1": ...} — distinguishable in the raw
        # state dict.
        raw = serialization.msgpack_restore(blob)
        raw_opt = raw.get("opt_state") if isinstance(raw, dict) else None
        raw_wrapped = (isinstance(raw_opt, dict)
                       and set(raw_opt) >= {"inner_state", "notfinite_count"})
        tmpl_wrapped = isinstance(opt_state_template, optax.ApplyIfFiniteState)
        if tmpl_wrapped and not raw_wrapped:
            inner = serialization.from_state_dict(
                opt_state_template.inner_state, raw_opt)
            opt_state = opt_state_template._replace(inner_state=inner)
            logger.info("%s holds an unwrapped opt_state: re-wrapped with "
                        "fresh apply_if_finite counters", path)
        elif raw_wrapped and not tmpl_wrapped:
            opt_state = serialization.from_state_dict(
                opt_state_template, raw_opt["inner_state"])
            logger.info("%s holds an apply_if_finite opt_state: restored "
                        "its inner state into the unwrapped optimizer", path)
        else:
            raise
        params = serialization.from_state_dict(params_template, raw["params"])
        return params, opt_state, int(raw["step"])
    return state["params"], state["opt_state"], int(state["step"])


def load_params(path: str, cfg, params_template=None):
    """Load model params from either a native bundle or a reference ``.pth``."""
    if path.endswith(".pth"):
        from raft_stereo_tpu.transplant import load_pth
        return load_pth(path, cfg)
    params, _, _ = load_checkpoint(path, params_template)
    return params


def _numbered_bundles(ckpt_dir: str, name: Optional[str] = None
                      ) -> List[Tuple[int, str]]:
    """(step, path) for every numbered bundle under ``ckpt_dir``, optionally
    filtered to run ``name``; unsorted.

    The name filter anchors the regex on the literal name (rather than
    parsing generically and comparing), so prune/find operate strictly
    per-run. Names that collide with the bundle grammar (leading digits or
    marker words — ``check_run_name`` rejects e.g. ``epoch_v2``) are refused
    at train() start; callers using save/prune as library API directly must
    run :func:`check_run_name` themselves.
    """
    pat = _STEP_RE if name is None else re.compile(
        rf"^(\d+)_(?:preempt_|epoch_)?({re.escape(name)})$")
    out = []
    for fname in os.listdir(ckpt_dir):
        if not fname.endswith(CKPT_SUFFIX):
            continue
        m = pat.match(fname[:-len(CKPT_SUFFIX)])
        if m is None:
            continue
        out.append((int(m.group(1)), os.path.join(ckpt_dir, fname)))
    return out


def find_latest_checkpoint(ckpt_dir: str, name: Optional[str] = None,
                           include_final: bool = False) -> Optional[str]:
    """Newest *valid* bundle in ``ckpt_dir``, or None.

    Candidates are walked newest-step-first and validated (hash check for
    hash-framed bundles, msgpack parse for legacy ones); a truncated or
    corrupt newest bundle is logged and skipped so resume falls back to the
    previous good state instead of dying on it.

    ``include_final`` (needs ``name``) also considers the unnumbered FINAL
    ``{name}`` bundle, preferring it whenever it holds a step >= the newest
    valid numbered one: a finished run's final state can be AHEAD of its
    last periodic save (num_steps not a multiple of ckpt_every), and
    resuming from the periodic one would silently retrain the schedule
    tail. Off by default — the final bundle means "finished", and plain
    mid-run fallback must never pick it over newer numbered state it ties
    with arbitrarily.
    """
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    for step, path in sorted(_numbered_bundles(ckpt_dir, name), reverse=True):
        if validate_checkpoint(path):
            best = (step, path)
            break
        logger.warning("skipping invalid checkpoint %s (truncated/corrupt); "
                       "falling back to the previous bundle", path)
    if include_final and name is not None:
        final = os.path.join(ckpt_dir, name + CKPT_SUFFIX)
        if os.path.exists(final):
            try:
                # Parsing the step doubles as validation (hash frame or
                # msgpack parse) — no separate validate pass/read.
                fstep = bundle_step(final)
            except Exception:  # noqa: BLE001 - corrupt final: not a candidate
                logger.warning("ignoring corrupt final bundle %s", final)
            else:
                if best is None or fstep >= best[0]:
                    return final
    return best[1] if best is not None else None


def prune_checkpoints(ckpt_dir: str, name: str, keep: int) -> List[str]:
    """Keep-last-``keep``-*valid* retention over the *periodic*
    ``{step}_{name}`` bundles; preempt/epoch/final bundles are never pruned
    (each marks a distinct recovery point). Returns the removed paths.

    Only bundles that validate count toward ``keep`` — a run whose newest
    bundles were corrupted on disk (the exact failure the hash frame
    detects) must not have its only loadable fallback deleted out from
    under ``find_latest_checkpoint``. Corrupt bundles inside the retention
    window are left in place (deleting data on a failing filesystem helps
    nobody); corrupt or not, anything older than ``keep`` valid bundles is
    removed. Validation hashes each retained bundle per prune — a few
    sequential file reads every ``ckpt_every`` steps, noise next to the
    save itself.
    """
    if keep <= 0 or not os.path.isdir(ckpt_dir):
        return []
    periodic = re.compile(rf"^(\d+)_{re.escape(name)}{re.escape(CKPT_SUFFIX)}$")
    numbered = []
    for fname in os.listdir(ckpt_dir):
        m = periodic.match(fname)
        if m is not None:
            numbered.append((int(m.group(1)), os.path.join(ckpt_dir, fname)))
    removed = []
    kept_valid = 0
    for _, path in sorted(numbered, reverse=True):
        if kept_valid < keep:
            if validate_checkpoint(path):
                kept_valid += 1
            continue
        try:
            os.remove(path)
            removed.append(path)
        except OSError:  # already gone (concurrent cleanup): retention met
            pass
    return removed

"""Validation suite: ETH3D / KITTI / FlyingThings / Middlebury.

Reference ``evaluate_stereo.py:18-189``, metric quirks preserved exactly:

- ETH3D: outlier threshold 1px, **per-image** averaging (:40-53);
- KITTI: threshold 3px ("D1"), **per-pixel** aggregation via concatenation
  (:91-103), FPS protocol timing frames 52+ (``val_id > 50``, :77-81);
- FlyingThings (finalpass TEST, seed-1000 400-image subset): threshold 1px,
  extra validity filter ``|flow_gt| < 192``, per-pixel aggregation (:133-143);
- Middlebury: threshold 2px, per-image averaging, validity
  ``(valid >= -0.5) & (flow_gt > -1000)`` — the first clause is vacuously true
  for the 0/1 nocc mask, so the nocc mask is effectively ignored; replicated
  faithfully and flagged here (:172-186).

TPU adaptations: each distinct padded shape is jit-compiled once and cached;
an optional ``bucket`` rounds shapes up so a whole dataset shares a handful of
compilations. Timing uses a scalar host fetch as the completion barrier
(device ``block_until_ready`` is unreliable through the remote tunnel).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.data import datasets
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.ops.padder import InputPadder

logger = logging.getLogger(__name__)


def count_parameters(params) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def make_eval_forward(params, cfg: RAFTStereoConfig, iters: int,
                      mixed_prec: bool = False, mesh=None,
                      segments: int = 1):
    """Per-shape-cached jitted forward: (1,H,W,3)x2 -> (disparity map, checksum).

    ``mixed_prec`` mirrors the reference's autocast flag: bf16 compute for the
    whole network. The checksum is fetched first as the timing barrier.

    ``mesh``: an optional ``(data, space)`` device mesh. With ``n_space > 1``
    the image height — and with it the correlation volume, the memory hog —
    is sharded across chips (SURVEY §5 long-context; XLA inserts the conv
    halo exchanges), letting full-resolution frames that exceed one chip's
    HBM evaluate across the pod.

    ``segments``: run the refinement scan as this many chained segments
    (``raft_stereo_inference``) instead of one — the eval-scale A/B for the
    serving layer's anytime property (metrics must not move; the segmented
    composition is bit-identical, test-pinned). Unsharded eval only.
    """
    from raft_stereo_tpu.parallel.mesh import mesh_safe_cfg
    if segments > 1 and mesh is not None:
        raise ValueError("segments > 1 is not supported with --spatial_shard")
    extra = ({} if cfg.mixed_precision == mixed_prec else
             {"mixed_precision": mixed_prec})
    if mesh is not None:
        from raft_stereo_tpu.parallel.mesh import (
            data_sharding, replicated, shard_batch)
        in_sh, repl = data_sharding(mesh), replicated(mesh)
        # Replicate params onto the mesh ONCE — passing host-resident params
        # per call would reshard the whole pytree every frame, inside the
        # timed region.
        params = jax.device_put(params, repl)
    run_cfg = mesh_safe_cfg(cfg, mesh, **extra)
    from raft_stereo_tpu.parallel.mesh import space_mesh_of
    space_mesh = space_mesh_of(mesh)

    @functools.lru_cache(maxsize=None)
    def compiled(h: int, w: int):
        def fwd(p, image1, image2):
            if segments > 1:
                from raft_stereo_tpu.models import raft_stereo_inference
                _, flow_up = raft_stereo_inference(p, run_cfg, image1, image2,
                                                   iters=iters,
                                                   segments=segments)
            else:
                _, flow_up = raft_stereo_forward(p, run_cfg, image1, image2,
                                                 iters=iters, test_mode=True,
                                                 space_mesh=space_mesh)
            return flow_up, jnp.sum(flow_up.astype(jnp.float32))
        if mesh is None:
            return jax.jit(fwd)
        return jax.jit(fwd, in_shardings=(repl, in_sh, in_sh),
                       out_shardings=(in_sh, repl))

    def forward(image1: np.ndarray, image2: np.ndarray):
        """Returns (flow_up (1,H,W,1) np, seconds) for one padded pair."""
        _, h, w, _ = image1.shape  # pair always matches; read one shape only
        fwd = compiled(h, w)
        if mesh is not None:
            d1, d2 = shard_batch([jnp.asarray(image1), jnp.asarray(image2)],
                                 mesh)
        else:
            d1 = jax.device_put(jnp.asarray(image1))
            d2 = jax.device_put(jnp.asarray(image2))
        float(jnp.sum(d1)) , float(jnp.sum(d2))  # H2D barrier, outside timing
        t0 = time.perf_counter()
        flow_up, checksum = fwd(params, d1, d2)
        float(checksum)  # completion barrier
        elapsed = time.perf_counter() - t0
        return np.asarray(flow_up), elapsed

    return forward


def _epe_map(flow_pr: np.ndarray, flow_gt: np.ndarray) -> np.ndarray:
    """End-point error per pixel; flow is single-channel (disparity)."""
    if flow_pr.shape != flow_gt.shape:
        raise AssertionError((flow_pr.shape, flow_gt.shape))
    return np.sqrt(np.sum((flow_pr - flow_gt) ** 2, axis=-1))


def prefetch_samples(dataset):
    """Yield ``dataset[i]`` for all i, decoding sample i+1 on a background
    thread while the caller runs the forward — image decode (PIL/cv2, GIL
    released) overlaps device compute, so eval wall-clock approaches
    max(decode, forward) per frame instead of their sum. Yield order and
    contents are identical to direct indexing (eval datasets are
    augmentation-free, so loading is deterministic). ``dataset`` is anything
    indexable with a ``len`` (the demo CLI wraps its image-pair list)."""
    from concurrent.futures import ThreadPoolExecutor
    if len(dataset) == 0:
        return
    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(dataset.__getitem__, 0)
        for i in range(1, len(dataset) + 1):
            sample = fut.result()
            if i < len(dataset):
                fut = ex.submit(dataset.__getitem__, i)
            yield sample


def _run_pair(forward, sample, bucket: Optional[int]):
    image1 = sample["image1"][None]
    image2 = sample["image2"][None]
    padder = InputPadder(image1.shape, divis_by=32, bucket=bucket)
    image1, image2 = padder.pad_np(image1, image2)
    flow_pr, elapsed = forward(image1, image2)
    flow_pr = padder.unpad_np(np.asarray(flow_pr))[0]
    return flow_pr, elapsed


def validate_eth3d(params, cfg, iters: int = 32, mixed_prec: bool = False,
                   root: Optional[str] = None, mesh=None,
                   bucket: Optional[int] = None,
                   segments: int = 1) -> Dict[str, float]:
    """ETH3D train split: EPE + D1(>1px), per-image averaging.

    ``root`` is the datasets/ tree root for every validator (the per-class
    subdirectory — ETH3D/, KITTI/, Middlebury/ — is appended here).
    """
    kw = {"root": f"{root}/ETH3D"} if root else {}
    val_dataset = datasets.ETH3D(aug_params=None, **kw)
    forward = make_eval_forward(params, cfg, iters, mixed_prec, mesh=mesh,
                                segments=segments)

    out_list, epe_list = [], []
    for val_id, sample in enumerate(prefetch_samples(val_dataset)):
        flow_pr, _ = _run_pair(forward, sample, bucket)
        epe = _epe_map(flow_pr, sample["flow"]).flatten()
        val = sample["valid"].flatten() >= 0.5
        image_out = (epe > 1.0)[val].mean()
        image_epe = epe[val].mean()
        logger.info("ETH3D %d out of %d. EPE %.4f D1 %.4f", val_id + 1,
                    len(val_dataset), image_epe, image_out)
        epe_list.append(image_epe)
        out_list.append(image_out)

    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(out_list))
    print("Validation ETH3D: EPE %f, D1 %f" % (epe, d1))
    return {"eth3d-epe": epe, "eth3d-d1": d1}


def validate_kitti(params, cfg, iters: int = 32, mixed_prec: bool = False,
                   root: Optional[str] = None, mesh=None,
                   bucket: Optional[int] = None,
                   segments: int = 1) -> Dict[str, float]:
    """KITTI-2015 train split: EPE + D1(>3px, per-pixel), FPS protocol.

    The default is the reference-exact protocol (per-shape /32 padding,
    ``evaluate_stereo.py:77-81``) so a published FPS is apples-to-apples.
    KITTI frames come in a handful of near-identical sizes and the
    protocol only warms up the first shape, so a few timed frames pay a
    one-off compile on this backend; pass ``bucket=64`` to round shapes
    up so every timed frame runs an already-compiled program (any
    published number must state which protocol it used).
    """
    kw = {"root": f"{root}/KITTI"} if root else {}
    val_dataset = datasets.KITTI(aug_params=None, image_set="training", **kw)
    forward = make_eval_forward(params, cfg, iters, mixed_prec, mesh=mesh,
                                segments=segments)

    out_list, epe_list, elapsed_list = [], [], []
    # No decode prefetch here, unlike the other validators: the KITTI FPS
    # number is the published timing protocol, and a background decoder
    # holding the GIL during the timed forward would add contention jitter
    # to 'elapsed'. Decode stays serial, outside the timed region.
    for val_id in range(len(val_dataset)):
        sample = val_dataset.__getitem__(val_id)
        flow_pr, elapsed = _run_pair(forward, sample, bucket)
        if val_id > 50:  # warmup discard (reference :81)
            elapsed_list.append(elapsed)
        epe = _epe_map(flow_pr, sample["flow"]).flatten()
        val = sample["valid"].flatten() >= 0.5
        out = epe > 3.0
        image_epe = epe[val].mean()
        # Every frame, like the reference (evaluate_stereo.py:95-103).
        logger.info(
            "KITTI Iter %d out of %d. EPE %.4f D1 %.4f. Runtime: %.3fs "
            "(%.2f-FPS)", val_id + 1, len(val_dataset), image_epe,
            out[val].mean(), elapsed, 1 / elapsed)
        epe_list.append(image_epe)
        out_list.append(out[val])  # per-pixel aggregation (:97-100)

    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(np.concatenate(out_list)))
    avg_runtime = float(np.mean(elapsed_list)) if elapsed_list else float("nan")
    print(f"Validation KITTI: EPE {epe}, D1 {d1}, "
          f"{1 / avg_runtime:.2f}-FPS ({avg_runtime:.3f}s)")
    return {"kitti-epe": epe, "kitti-d1": d1, "kitti-fps": 1 / avg_runtime}


def validate_things(params, cfg, iters: int = 32, mixed_prec: bool = False,
                    root: Optional[str] = None, mesh=None,
                    bucket: Optional[int] = None,
                    segments: int = 1) -> Dict[str, float]:
    """FlyingThings3D finalpass TEST subset: EPE + D1(>1px, |gt|<192)."""
    kw = {"root": root} if root else {}
    val_dataset = datasets.SceneFlowDatasets(
        aug_params=None, dstype="frames_finalpass", things_test=True, **kw)
    forward = make_eval_forward(params, cfg, iters, mixed_prec, mesh=mesh,
                                segments=segments)

    out_list, epe_list = [], []
    for val_id, sample in enumerate(prefetch_samples(val_dataset)):
        flow_pr, _ = _run_pair(forward, sample, bucket)
        epe = _epe_map(flow_pr, sample["flow"]).flatten()
        val = ((sample["valid"].flatten() >= 0.5)
               & (np.abs(sample["flow"]).max(axis=-1).flatten() < 192))
        out = epe > 1.0
        epe_list.append(epe[val].mean())
        out_list.append(out[val])

    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(np.concatenate(out_list)))
    print("Validation FlyingThings: %f, %f" % (epe, d1))
    return {"things-epe": epe, "things-d1": d1}


def validate_middlebury(params, cfg, iters: int = 32, split: str = "F",
                        mixed_prec: bool = False, root: Optional[str] = None,
                        mesh=None,
                        bucket: Optional[int] = None,
                        segments: int = 1) -> Dict[str, float]:
    """Middlebury V3: EPE + D1(>2px), per-image averaging."""
    kw = {"root": f"{root}/Middlebury"} if root else {}
    val_dataset = datasets.Middlebury(aug_params=None, split=split, **kw)
    forward = make_eval_forward(params, cfg, iters, mixed_prec, mesh=mesh,
                                segments=segments)

    out_list, epe_list = [], []
    for val_id, sample in enumerate(prefetch_samples(val_dataset)):
        flow_pr, _ = _run_pair(forward, sample, bucket)
        epe = _epe_map(flow_pr, sample["flow"]).flatten()
        # Faithful to the reference: valid>=-0.5 is vacuously true for the 0/1
        # nocc mask, so only the -1000 sentinel filter bites (:173).
        val = ((sample["valid"].reshape(-1) >= -0.5)
               & (sample["flow"][..., 0].reshape(-1) > -1000))
        image_out = (epe > 2.0)[val].mean()
        image_epe = epe[val].mean()
        logger.info("Middlebury Iter %d out of %d. EPE %.4f D1 %.4f",
                    val_id + 1, len(val_dataset), image_epe, image_out)
        epe_list.append(image_epe)
        out_list.append(image_out)

    epe = float(np.mean(epe_list))
    d1 = 100 * float(np.mean(out_list))
    print(f"Validation Middlebury{split}: EPE {epe}, D1 {d1}")
    return {f"middlebury{split}-epe": epe, f"middlebury{split}-d1": d1}


VALIDATORS: Dict[str, Callable] = {
    "eth3d": validate_eth3d,
    "kitti": validate_kitti,
    "things": validate_things,
    "middlebury": validate_middlebury,
}

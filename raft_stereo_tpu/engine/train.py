"""Training engine (reference ``train_stereo.py:132-211``).

One compiled train step (forward scan -> sequence loss -> clipped AdamW+
OneCycle update) driven by the prefetching loader. Differences from the
reference, all deliberate:

- data parallelism is a sharding annotation (batch over the mesh ``data``
  axis) instead of ``nn.DataParallel`` replica scatter/gather;
- checkpoints carry params + optimizer + step, so resume continues the
  OneCycle schedule (the reference restarts it, SURVEY §5);
- no GradScaler: params/grads are fp32, bf16 appears only in activations.

Cadence preserved: validate + checkpoint every ``ckpt_every`` (10k) steps
on FlyingThings, final save to ``checkpoints/<name>``.

Fault tolerance (DESIGN.md "Failure recovery"): non-finite steps are skipped
(params/opt_state untouched via ``optax.apply_if_finite``) with a bounded
consecutive-failure abort; ``restore_ckpt`` may name a checkpoint directory
for auto-resume from the newest valid bundle; periodic checkpoints keep the
last K. All recovery paths are exercised by ``tests/test_faults.py`` through
the deterministic injection hooks (``faults=`` parameter).
"""

from __future__ import annotations

import logging
import os
import signal
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data.loader import device_prefetch, fetch_dataloader
from raft_stereo_tpu.engine import checkpoint as ckpt
from raft_stereo_tpu.engine.evaluate import count_parameters, validate_things
from raft_stereo_tpu.engine.logger import Logger
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_train_step
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.parallel.mesh import (make_mesh, maybe_distributed_init,
                                           validate_spatial_shard)

logger = logging.getLogger(__name__)


def choose_mesh(batch_size: int, spatial_shard: int, devices,
                process_count: int, local_device_count=None):
    """Pick the training mesh from the device/process topology.

    ``spatial_shard`` > 1 reserves a ``space`` axis (each sample's height
    split across chips — the big-crop enabler); the rest of ``devices`` form
    the ``data`` axis. Multi-host pods must place EVERY process's devices in
    the mesh (a process whose chips are excluded would deadlock at the first
    collective), so there the batch has to divide the data extent exactly.
    Returns None when a single device (no axis > 1) is the right answer.
    """
    # Group each process's devices contiguously before slicing the mesh:
    # jax.devices() order is not guaranteed per-process-contiguous on every
    # topology, and a ``space`` row spanning hosts would put the spatial
    # halo collectives on DCN instead of ICI (perf, not correctness).
    devices = sorted(devices, key=lambda d: (getattr(d, "process_index", 0),
                                             getattr(d, "id", 0)))
    n_devices = len(devices)
    n_space = max(1, spatial_shard)
    validate_spatial_shard(n_space, n_devices, local_device_count)
    avail = n_devices // n_space
    if process_count > 1:
        n_data = avail
        if batch_size % n_data:
            raise ValueError(
                f"batch_size {batch_size} must divide evenly over the "
                f"pod's data extent {n_data} ({n_devices} devices / "
                f"{n_space} spatial shards)")
    else:
        n_data = max(d for d in range(1, avail + 1) if batch_size % d == 0)
    if n_data * n_space == 1:
        return None
    return make_mesh(n_data=n_data, n_space=n_space,
                     devices=devices[:n_data * n_space])


class PreemptGuard:
    """Preemption-safe shutdown: SIGTERM requests a checkpoint-and-exit.

    TPU-pod maintenance/preemption delivers SIGTERM with a grace window; the
    reference's loop would lose up to 10k steps (SURVEY §5 failure-recovery
    row). The handler only sets a flag — the training loop polls it at step
    boundaries, where params/opt_state are consistent, saves, and returns.

    On a multi-host pod every process polls ``stop()`` which ORs the local
    flags across processes, so all processes leave the collective region at
    the SAME step — a host-local check would deadlock the survivors at the
    next psum. The allgather + host sync is NOT free over DCN-connected
    pods, so it runs every ``poll_every`` steps (all processes agree on the
    step counter, hence on when to poll — the cadence must be step-based,
    not wall-clock, or processes would desynchronize). Default 2: at the
    slowest observed step rate (~4.3 s/step through the tunnel) that bounds
    the agreement delay at ~9 s, inside a typical ~30 s SIGTERM grace
    window; 8 risked exceeding it.
    """

    def __init__(self, poll_every: int = 2):
        self.requested = False
        self.poll_every = max(1, poll_every)
        self._prev = None
        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_signal)
        except ValueError:  # not the main thread: polling still works
            pass

    def _on_signal(self, signum, frame):
        self.requested = True
        logger.warning("SIGTERM received: checkpointing at next step boundary")

    def stop(self, step: int = 0) -> bool:
        if jax.process_count() == 1:
            return self.requested
        if step % self.poll_every:
            return False
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray([self.requested]))
        return bool(np.any(flags))

    def restore(self) -> None:
        if self._prev is not None:
            signal.signal(signal.SIGTERM, self._prev)


class _NullLogger:
    """Logger stand-in for non-lead pod processes: accepts every call,
    writes nothing (TensorBoard/JSONL output comes from the lead only)."""

    total_steps = 0

    def push(self, *args, **kwargs):
        pass

    def write_scalar(self, *args, **kwargs):
        pass

    def write_dict(self, *args, **kwargs):
        pass

    def close(self):
        pass


def train(cfg: RAFTStereoConfig, tcfg: TrainConfig,
          mesh=None, data_root: Optional[str] = None,
          validate: bool = True, faults=None) -> Dict[str, float]:
    """Run the full training loop.

    Returns the last validation results plus the run's reliability
    counters (``skipped_steps``, ``quarantined_samples``).

    ``faults``: optional :class:`raft_stereo_tpu.faults.FaultPlan` — the
    deterministic fault-injection harness used by ``tests/test_faults.py``
    to exercise every recovery path; None in production.
    """
    # Multi-host launch (COORDINATOR_ADDRESS set): initialize the JAX
    # distributed runtime BEFORE any device query, so jax.devices() sees
    # the whole pod and the data mesh spans hosts over DCN. No-op otherwise.
    maybe_distributed_init()
    is_lead = jax.process_index() == 0
    if mesh is None:
        mesh = choose_mesh(tcfg.batch_size, tcfg.spatial_shard,
                           jax.devices(), jax.process_count(),
                           jax.local_device_count())

    # Fail fast on run names that collide with the checkpoint filename
    # grammar (cross-run prune/resume interference otherwise).
    ckpt.check_run_name(tcfg.name)

    key = jax.random.PRNGKey(tcfg.seed)
    params = jax.jit(lambda k: init_raft_stereo(k, cfg))(key)
    # max_bad_steps > 0 engages the skip-if-nonfinite policy: a NaN/Inf step
    # leaves params/opt_state untouched inside the compiled step and the
    # loop below aborts only after that many consecutive failures.
    tx, schedule = make_optimizer(tcfg.lr, tcfg.num_steps, tcfg.wdecay,
                                  skip_nonfinite=tcfg.max_bad_steps)
    opt_state = jax.jit(tx.init)(params)
    start_step = 0

    restore = tcfg.restore_ckpt
    ckpt_dir = "checkpoints"
    if restore is not None and os.path.isdir(restore):
        # Auto-resume: newest VALID bundle for THIS run name in the
        # directory; a truncated or corrupt newest checkpoint is skipped in
        # favor of the previous good one (ckpt.find_latest_checkpoint), and
        # an empty/fresh directory starts from scratch — the restart command
        # of a preemptible job is the same on its first and its N-th launch.
        # The name filter keeps a shared checkpoints/ directory from
        # silently resuming another experiment's state; to continue a
        # DIFFERENT run's checkpoint, pass its file path explicitly.
        # New checkpoints (periodic/preempt/final) and pruning follow the
        # SAME directory, so relaunch-with-identical-flags actually makes
        # forward progress when the directory isn't ./checkpoints.
        ckpt_dir = restore
        # include_final: a finished run's relaunch must restore the final
        # bundle (and train zero steps), not retrain the schedule tail from
        # the last periodic save on a fresh epoch ordering. (When the final
        # bundle wins, load_checkpoint parses it a second time — accepted:
        # that relaunch trains nothing anyway.)
        found = ckpt.find_latest_checkpoint(restore, name=tcfg.name,
                                            include_final=True)
        if found is None:
            logger.warning("no valid checkpoint under %s: starting fresh",
                           restore)
        restore = found
    if restore is not None:
        if restore.endswith(".pth"):
            params = ckpt.load_params(restore, cfg)
            opt_state = jax.jit(tx.init)(params)
            logger.info("Transplanted reference weights from %s", restore)
        else:
            params, opt_state, start_step = ckpt.load_checkpoint(
                restore, params, opt_state)
            logger.info("Restored full state from %s at step %d",
                        restore, start_step)

    logger.info("Parameter Count: %d", count_parameters(params))
    # Multi-host: each process decodes only the global-batch rows its
    # devices own (the reference runs one DataLoader per process,
    # core/stereo_datasets.py:311-312); device_prefetch reassembles the
    # global array from the process-local shards.
    local_rows = None
    if mesh is not None and jax.process_count() > 1:
        from raft_stereo_tpu.parallel.mesh import local_batch_rows
        local_rows = local_batch_rows(mesh, tcfg.batch_size)
    train_loader = fetch_dataloader(tcfg, root=data_root,
                                    local_rows=local_rows)
    # graftscope-device: the lead records the train step's compiler
    # cost/memory account (flops, peak HBM — the donation contract's
    # observable) and dumps it next to the TensorBoard events at close.
    # Non-lead processes keep plain jit dispatch: the ledger is telemetry,
    # and one writer per run is enough.
    ledger = None
    if is_lead:
        from raft_stereo_tpu.obs.ledger import ProgramLedger
        ledger = ProgramLedger()
    train_step = make_train_step(cfg, tx, tcfg.train_iters, mesh=mesh,
                                 ledger=ledger)
    if is_lead:
        from raft_stereo_tpu.obs.metrics import MetricsRegistry
        log = Logger(scheduler=schedule, registry=MetricsRegistry())
    else:
        log = _NullLogger()
    log.total_steps = start_step

    if faults is not None:
        from raft_stereo_tpu.faults import FaultyDataset
        train_loader.dataset = FaultyDataset(train_loader.dataset, faults)

    os.makedirs(ckpt_dir, exist_ok=True)
    total_steps = start_step
    # A resumed bundle at/past the horizon (auto-resume of a finished run)
    # must not execute extra steps beyond the OneCycle schedule: the
    # in-loop bound only triggers AFTER a step runs.
    should_keep_training = start_step < tcfg.num_steps
    if not should_keep_training:
        logger.warning("restored step %d >= num_steps %d: nothing to train",
                       start_step, tcfg.num_steps)
    preempted = False
    last_results: Dict[str, float] = {}
    skipped_total = 0
    consecutive_bad = 0
    quarantine_seen = 0
    guard = PreemptGuard()

    def run_step(params, opt_state, batch):
        params, opt_state, metrics = train_step(params, opt_state, batch)
        # ONE host fetch for all metrics (stacked): each fetch is a full
        # host<->device round trip — per-scalar float() costs n_metrics
        # round trips per step, which dominates step wall time on remote
        # (tunneled) chips. The fetch doubles as the completion barrier
        # (required for the profiler trace below to cover the device work).
        names = sorted(metrics)
        vals = np.asarray(jnp.stack([metrics[k] for k in names]))
        host = {k: float(v) for k, v in zip(names, vals)}
        return params, opt_state, host

    # bf16 image transport under mixed precision: halves H2D bytes; the
    # model's first op casts to the compute dtype anyway.
    image_dtype = jnp.bfloat16 if cfg.mixed_precision else None
    try:
        while should_keep_training:
            epoch_batches = train_loader
            if faults is not None:
                from raft_stereo_tpu.faults import poisoned_batches
                epoch_batches = poisoned_batches(train_loader, faults,
                                                 start_step=total_steps)
            for batch in device_prefetch(
                    epoch_batches, mesh=mesh, image_dtype=image_dtype,
                    global_batch=(tcfg.batch_size if local_rows is not None
                                  else None)):
                if (tcfg.trace_dir is not None and is_lead
                        and total_steps == start_step + 2):  # post-compile
                    with jax.profiler.trace(tcfg.trace_dir):
                        params, opt_state, host = run_step(params, opt_state,
                                                           batch)
                else:
                    params, opt_state, host = run_step(params, opt_state,
                                                       batch)
                bad = (host.get("finite", 1.0) < 1.0
                       or host.get("skipped", 0.0) > 0.0
                       or not np.isfinite(host.get("loss", 0.0)))
                # Keep the console LR on the schedule position the optimizer
                # actually reached (applied updates, not raw steps).
                log.schedule_offset = int(host.get("total_notfinite", 0))
                if bad:
                    # Reference invariant (train_stereo.py:48-56): NaN/Inf in
                    # the predictions or loss must never corrupt the
                    # parameters. With max_bad_steps > 0 the optimizer's
                    # apply_if_finite wrapper rejects any update whose
                    # gradients are non-finite (params/opt_state untouched),
                    # so such a step is counted and skipped; the abort fires
                    # only after max_bad_steps CONSECUTIVE failures — a
                    # systematic divergence, not a one-off bad batch. The
                    # decision inputs (loss, the wrapper's notfinite_count)
                    # are replicated values, so every pod process counts —
                    # and aborts — identically.
                    if not host.get("skipped", 0.0) > 0.0:
                        # No wrapper (max_bad_steps <= 0), or non-finite
                        # loss/predictions with FINITE gradients (fp32
                        # overflow in the loss reduction, NaN predictions
                        # masked out of the loss): the update was APPLIED, so
                        # the parameters are already suspect — skipping
                        # cannot undo it. Abort immediately, exactly like the
                        # reference.
                        raise FloatingPointError(
                            f"non-finite loss/predictions at step "
                            f"{total_steps} with the update applied "
                            f"(loss={host.get('loss')})")
                    skipped_total += 1
                    consecutive_bad = int(host["notfinite_count"])
                    log.write_scalar("skipped_steps", skipped_total,
                                     total_steps)
                    # Keep the Logger's step counter in lockstep with the
                    # loop (skipped steps contribute no metrics but do
                    # occupy a step), so running-mean x-axes and the console
                    # status line never drift from total_steps.
                    log.push({})
                    logger.warning(
                        "non-finite step %d skipped (%d consecutive, "
                        "%d total, loss=%s)", total_steps, consecutive_bad,
                        skipped_total, host.get("loss"))
                    if consecutive_bad >= tcfg.max_bad_steps:
                        raise FloatingPointError(
                            f"non-finite loss/predictions at step "
                            f"{total_steps} ({consecutive_bad} consecutive; "
                            f"loss={host.get('loss')})")
                else:
                    consecutive_bad = 0
                    log.push({k: host[k] for k in
                              ("epe", "1px", "3px", "5px", "loss")
                              if k in host})
                    log.write_scalar("live_loss", host["loss"], total_steps)
                    # Schedule position = APPLIED-update count: skipped
                    # steps leave the inner Adam count untouched, so the LR
                    # actually used sits total_notfinite steps behind
                    # total_steps (exact across checkpoint round trips —
                    # the counter lives in opt_state).
                    applied = total_steps - int(host.get("total_notfinite", 0))
                    log.write_scalar("learning_rate",
                                     float(schedule(applied)), total_steps)
                nq = len(getattr(train_loader, "quarantined", ()))
                if nq != quarantine_seen:
                    quarantine_seen = nq
                    log.write_scalar("quarantined_samples", nq, total_steps)
                total_steps += 1
                if faults is not None:
                    from raft_stereo_tpu.faults import fire_step_faults
                    fire_step_faults(faults, total_steps)

                # Writes (checkpoints, validation, TensorBoard) happen on the
                # lead process only: on a pod, every process executes the loop
                # and holds the same replicated state, and concurrent writers
                # to a shared filesystem would corrupt the checkpoint.
                if total_steps % tcfg.ckpt_every == 0 and is_lead:
                    save_path = os.path.join(
                        ckpt_dir, f"{total_steps}_{tcfg.name}"
                        f"{ckpt.CKPT_SUFFIX}")
                    ckpt.save_checkpoint(save_path, params, opt_state,
                                         total_steps)
                    logger.info("Saved %s", save_path)
                    if tcfg.keep_ckpts > 0:
                        # Keep-last-K retention over the periodic bundles
                        # (preempt/epoch/final saves are never pruned).
                        ckpt.prune_checkpoints(ckpt_dir, tcfg.name,
                                               keep=tcfg.keep_ckpts)
                    if validate:
                        # Pull params to host first: a lead-only jit on
                        # arrays still committed to the pod-wide sharding
                        # would be a multi-controller computation the other
                        # processes never join (deadlock). From host numpy
                        # the eval jit is process-local on the lead's devices.
                        eval_params = (jax.device_get(params)
                                       if jax.process_count() > 1 else params)
                        last_results = validate_things(
                            eval_params, cfg, iters=tcfg.valid_iters,
                            root=data_root)
                        log.write_dict(last_results)

                if total_steps >= tcfg.num_steps:
                    should_keep_training = False
                    break
                if guard.stop(total_steps):
                    preempted = True
                    if is_lead:
                        save_path = os.path.join(
                            ckpt_dir, f"{total_steps}_preempt_"
                            f"{tcfg.name}{ckpt.CKPT_SUFFIX}")
                        ckpt.save_checkpoint(save_path, params, opt_state,
                                             total_steps)
                        logger.warning(
                            "Preempted: saved %s; resume with "
                            "--restore_ckpt to continue the schedule",
                            save_path)
                    should_keep_training = False
                    break

            if len(train_loader) >= 10000 and is_lead:
                save_path = os.path.join(
                    ckpt_dir, f"{total_steps}_epoch_{tcfg.name}"
                    f"{ckpt.CKPT_SUFFIX}")
                ckpt.save_checkpoint(save_path, params, opt_state, total_steps)
                logger.info("Saved epoch checkpoint %s", save_path)

        # A preempted run must NOT write the final checkpoint: that name
        # means "finished training" to downstream eval/demo, and the preempt
        # file above already holds the resumable state.
        if is_lead and not preempted:
            final = os.path.join(ckpt_dir, f"{tcfg.name}{ckpt.CKPT_SUFFIX}")
            ckpt.save_checkpoint(final, params, opt_state, total_steps)
            logger.info("Saved final checkpoint %s", final)
    finally:
        log.close()
        guard.restore()
        if ledger is not None and len(ledger):
            # Device-ledger artifact next to the training telemetry
            # (metrics.prom/TensorBoard): what the compiled step cost.
            from raft_stereo_tpu.obs.ledger import save_doc
            try:
                os.makedirs(log.log_dir, exist_ok=True)
                save_doc(ledger.to_doc(backend=jax.default_backend()),
                         os.path.join(log.log_dir, "ledger.json"))
            except OSError:
                logger.exception("could not write ledger.json "
                                 "(training result is unaffected)")
    quarantined = getattr(train_loader, "quarantine_report", dict)()
    if quarantined:
        logger.warning("quarantine report: %d sample(s) substituted: %s",
                       len(quarantined), quarantined)
    return dict(last_results, skipped_steps=float(skipped_total),
                quarantined_samples=float(len(quarantined)))

"""Jitted train / eval steps with data-parallel shardings.

The train step is the reference's inner loop (``train_stereo.py:162-179``) as
one compiled program: forward (scan over GRU iterations) -> sequence loss ->
grads -> global-norm clip -> AdamW+OneCycle update. Under a ``Mesh`` the batch
is sharded over the ``data`` axis and params are replicated; XLA inserts the
gradient all-reduce (the DataParallel equivalent). Donation of (params,
opt_state) keeps HBM flat.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.loss import sequence_loss
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.parallel.mesh import (data_sharding, mesh_safe_cfg,
                                           replicated)


# Donation contract for the train step: (params, opt_state) buffers are
# donated so the update runs HBM-flat. ONE constant shared by both jit
# call sites below and by graftverify's GV105 checker
# (analysis/trace/checkers/gv105_donation.py), which proves the LOWERED
# program's input-output aliasing actually honors it — an aliasing change
# that silently drops donation doubles peak optimizer-state memory
# without failing any numeric test.
TRAIN_STEP_DONATE: Tuple[int, ...] = (0, 1)


def make_train_step(cfg: RAFTStereoConfig, tx: optax.GradientTransformation,
                    train_iters: int, mesh: Optional[Mesh] = None,
                    ledger=None):
    """Returns ``train_step(params, opt_state, batch) -> (params, opt_state, metrics)``.

    batch: dict with ``image1``, ``image2`` (B,H,W,3), ``flow`` (B,H,W,1),
    ``valid`` (B,H,W).

    ledger: an optional :class:`~raft_stereo_tpu.obs.ledger.ProgramLedger`
    (graftscope-device). When given, the step is compiled ahead of time on
    first call (same one compile, donation preserved) and its compiler
    cost/memory account lands in the ledger under the ``train_step`` key —
    in particular ``temp_bytes``/``peak_hbm_bytes``, the number the
    donation contract (``TRAIN_STEP_DONATE``, GV105) exists to keep flat.
    ``scan_scale`` is ``None``: the step mixes scan and non-scan stages,
    so no per-invocation flop estimate is honest (see DESIGN.md r12).
    """
    cfg = mesh_safe_cfg(cfg, mesh)
    from raft_stereo_tpu.parallel.mesh import space_mesh_of
    space_mesh = space_mesh_of(mesh)

    def loss_fn(params, batch):
        preds = raft_stereo_forward(params, cfg, batch["image1"], batch["image2"],
                                    iters=train_iters, space_mesh=space_mesh)
        return sequence_loss(preds, batch["flow"], batch["valid"])

    def step(params, opt_state, batch):
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics, loss=loss,
                       grad_norm=optax.global_norm(grads))
        if isinstance(opt_state, optax.ApplyIfFiniteState):
            # Skip-if-nonfinite optimizer (make_optimizer(skip_nonfinite=N)):
            # surface the wrapper's replicated skip decision so the host loop
            # counts skipped steps and bounds consecutive failures without a
            # second finiteness reduction.
            metrics["skipped"] = 1.0 - opt_state.last_finite.astype(jnp.float32)
            metrics["notfinite_count"] = opt_state.notfinite_count.astype(
                jnp.float32)
            # Lifetime skip total (survives checkpoint round trips inside
            # opt_state): step - total_notfinite is the APPLIED-update count,
            # i.e. the true schedule position for learning-rate logging.
            metrics["total_notfinite"] = opt_state.total_notfinite.astype(
                jnp.float32)
        return params, opt_state, metrics

    if mesh is None:
        jitted = jax.jit(step, donate_argnums=TRAIN_STEP_DONATE)
    else:
        repl, bsh = replicated(mesh), data_sharding(mesh)
        jitted = jax.jit(
            step,
            in_shardings=(repl, repl, bsh),
            out_shardings=(repl, repl, repl),
            donate_argnums=TRAIN_STEP_DONATE)
    if ledger is None:
        return jitted
    from raft_stereo_tpu.obs.ledger import AotLedgerFn
    return AotLedgerFn(jitted, ledger, ("train_step", train_iters),
                       kind="train_step", iters=train_iters)


def make_eval_step(cfg: RAFTStereoConfig, valid_iters: int,
                   mesh: Optional[Mesh] = None):
    """Returns ``eval_step(params, image1, image2) -> (flow_lr, flow_up)``."""
    cfg = mesh_safe_cfg(cfg, mesh)
    from raft_stereo_tpu.parallel.mesh import space_mesh_of
    space_mesh = space_mesh_of(mesh)

    def step(params, image1, image2):
        return raft_stereo_forward(params, cfg, image1, image2,
                                   iters=valid_iters, test_mode=True,
                                   space_mesh=space_mesh)

    if mesh is None:
        return jax.jit(step)
    repl, bsh = replicated(mesh), data_sharding(mesh)
    return jax.jit(step, in_shardings=(repl, bsh, bsh),
                   out_shardings=(bsh, bsh))

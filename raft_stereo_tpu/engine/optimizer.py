"""Optimizer: AdamW + OneCycle(linear) + global-norm clipping.

Reference ``train_stereo.py:72-79``: ``AdamW(lr, wdecay, eps=1e-8)`` with
``OneCycleLR(lr, num_steps+100, pct_start=0.01, cycle_momentum=False,
anneal_strategy='linear')`` and ``clip_grad_norm_(1.0)`` (:176). The schedule
below reproduces torch's OneCycleLR milestones exactly (two-phase linear with
``div_factor=25`` and ``final_div_factor=1e4`` defaults), verified against
torch in tests.

bf16 note: there is no GradScaler equivalent — gradients are computed in fp32
(params are fp32; bf16 appears only in activations), so the reference's
amp-scaler machinery (``train_stereo.py:18-32,155``) has no TPU counterpart by
design.
"""

from __future__ import annotations

import optax


def onecycle_linear_schedule(max_lr: float, total_steps: int,
                             pct_start: float = 0.01,
                             div_factor: float = 25.0,
                             final_div_factor: float = 1e4):
    """torch.optim.lr_scheduler.OneCycleLR, anneal_strategy='linear'.

    Phase milestones follow torch: warmup ends at ``pct_start*total_steps - 1``
    steps, anneal ends at ``total_steps - 1``.
    """
    initial_lr = max_lr / div_factor
    min_lr = initial_lr / final_div_factor
    up = float(pct_start * total_steps) - 1.0
    down = float(total_steps) - 1.0 - up

    def schedule(step):
        import jax.numpy as jnp
        step = jnp.asarray(step, jnp.float32)
        warm_pct = jnp.clip(step / jnp.maximum(up, 1e-8), 0.0, 1.0)
        lr_up = initial_lr + warm_pct * (max_lr - initial_lr)
        ann_pct = jnp.clip((step - up) / jnp.maximum(down, 1e-8), 0.0, 1.0)
        lr_down = max_lr + ann_pct * (min_lr - max_lr)
        return jnp.where(step <= up, lr_up, lr_down)

    return schedule


def make_optimizer(lr: float, num_steps: int, wdecay: float = 1e-5,
                   eps: float = 1e-8, clip_norm: float = 1.0,
                   skip_nonfinite: int = 0):
    """The reference's full optimizer stack as one optax transform.

    ``num_steps + 100`` mirrors the reference's scheduler horizon
    (``train_stereo.py:77``).

    ``skip_nonfinite`` > 0 wraps the stack in ``optax.apply_if_finite``: a
    step whose gradients contain NaN/Inf leaves params and the inner
    optimizer state untouched *inside the compiled step* (zero updates, no
    Adam-moment or schedule-count advance) instead of poisoning the
    parameters. The finiteness decision is made on the post-all-reduce
    gradients — replicated values — so every pod process skips the same
    steps. Note optax's wrapper gives up and APPLIES the non-finite update
    once more than ``skip_nonfinite`` consecutive steps were skipped; the
    train loop aborts at exactly ``skip_nonfinite`` consecutive bad steps
    (engine/train.py), strictly before that can happen.
    """
    schedule = onecycle_linear_schedule(lr, num_steps + 100)
    tx = optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=0.9, b2=0.999, eps=eps, weight_decay=wdecay),
    )
    if skip_nonfinite > 0:
        tx = optax.apply_if_finite(tx, max_consecutive_errors=skip_nonfinite)
    return tx, schedule

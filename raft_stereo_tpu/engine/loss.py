"""Sequence loss over the GRU iteration predictions.

Reference ``train_stereo.py:35-69``: exponentially-weighted L1 with the weight
exponent *adjusted for iteration count* — ``gamma_adj = gamma**(15/(N-1))`` —
so supervision strength is invariant to ``train_iters`` (:53-54). The valid
mask combines the dataset mask with a max-flow magnitude cutoff (:46). Metrics
(epe/1px/3px/5px) come from the final prediction (:59-67).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def _masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(x * mask) / denom


def sequence_loss(flow_preds: jax.Array, flow_gt: jax.Array, valid: jax.Array,
                  loss_gamma: float = 0.9, max_flow: float = 700.0,
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """L1 sequence loss.

    flow_preds: (N, B, H, W, 1) per-iteration full-res predictions.
    flow_gt:    (B, H, W, 1) ground-truth flow (negative disparity in x).
    valid:      (B, H, W) or (B, H, W, 1) validity mask.
    """
    n_predictions = flow_preds.shape[0]
    if valid.ndim == 4:
        valid = valid[..., 0]
    # Magnitude cutoff; flow is 1-channel so the L2 norm is |flow| (:45-46).
    mag = jnp.abs(flow_gt[..., 0])
    mask = ((valid >= 0.5) & (mag < max_flow)).astype(jnp.float32)[..., None]

    # Iteration-count-invariant decay (:53-54). N==1 degenerates to weight 1.
    adjusted_gamma = loss_gamma ** (15.0 / max(n_predictions - 1, 1))
    i_weights = adjusted_gamma ** jnp.arange(n_predictions - 1, -1, -1,
                                             dtype=jnp.float32)

    abs_err = jnp.abs(flow_preds - flow_gt[None])          # (N,B,H,W,1)
    per_iter = jnp.sum(abs_err * mask[None], axis=(1, 2, 3, 4))
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    flow_loss = jnp.sum(i_weights * per_iter / denom)

    epe = jnp.abs(flow_preds[-1][..., 0] - flow_gt[..., 0])
    m = mask[..., 0]
    metrics = {
        "epe": _masked_mean(epe, m),
        "1px": _masked_mean((epe < 1.0).astype(jnp.float32), m),
        "3px": _masked_mean((epe < 3.0).astype(jnp.float32), m),
        "5px": _masked_mean((epe < 5.0).astype(jnp.float32), m),
        # The reference asserts no NaN/Inf in predictions and loss
        # (train_stereo.py:48-56); under jit the invariant surfaces as a
        # metric the train loop raises on (engine/train.py).
        "finite": (jnp.isfinite(flow_loss)
                   & jnp.all(jnp.isfinite(flow_preds))).astype(jnp.float32),
    }
    return flow_loss, metrics

"""Training logger: running means -> TensorBoard + logging (reference
``train_stereo.py:82-129``).

Same observable behavior: scalars flushed every ``SUM_FREQ=100`` steps from
running means, per-batch ``live_loss`` and ``learning_rate`` entries, and
``write_dict`` for validation results. One deliberate refinement over the
reference: means divide by the number of pushes that actually carried the
key (the reference divides by the fixed window size) — skipped non-finite
steps push no metrics, and a fixed divisor would deflate exactly the
windows where divergence is being diagnosed. The writer is tensorboardX (pure
python), lazily constructed so headless / test runs pay nothing; when
tensorboardX is unavailable the scalars land in ``<log_dir>/scalars.jsonl``
(one ``{"step", "tag", "value"}`` object per line) so training telemetry is
never silently dropped.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, Optional

SUM_FREQ = 100

logger = logging.getLogger(__name__)


class _JsonlWriter:
    """SummaryWriter-shaped fallback: newline-delimited JSON scalars."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        # Line-buffered: scalars survive crash/SIGKILL paths that never
        # reach close() (the "never silently dropped" promise above).
        self._f = open(os.path.join(log_dir, "scalars.jsonl"), "a",
                       buffering=1)

    def add_scalar(self, tag: str, value, step) -> None:
        self._f.write(json.dumps(
            {"step": int(step), "tag": tag, "value": float(value)}) + "\n")

    def close(self) -> None:
        self._f.close()


class Logger:
    def __init__(self, log_dir: str = "runs", scheduler=None,
                 registry=None):
        self.log_dir = log_dir
        self.scheduler = scheduler
        # graftscope (obs/metrics.py): when a MetricsRegistry is attached,
        # every push also lands in raft_train_* series — a steps counter,
        # a bounded step-wall-time histogram (the steps/s behind the
        # trajectory gate's training metric) and a last-value gauge per
        # scalar — and close() writes the Prometheus snapshot next to the
        # TensorBoard events (<log_dir>/metrics.prom), so a training run's
        # telemetry has the same shape as the serving stack's /metrics.
        self.registry = registry
        self._last_push_t = None
        if registry is not None:
            self._m_steps = registry.counter(
                "raft_train_steps_total", "train-loop steps pushed")
            self._step_hist = registry.histogram(
                "raft_train_step_seconds",
                "wall time between metric pushes (bounded reservoir)",
                reservoir=512)
        self.total_steps = 0
        # Steps whose update was skipped (non-finite grads) don't advance
        # the optimizer's schedule position; the train loop keeps this at
        # the wrapper's total_notfinite so the console LR reads the
        # schedule where the optimizer actually is.
        self.schedule_offset = 0
        self.running_loss: Dict[str, float] = {}
        self.running_count: Dict[str, int] = {}
        self.writer = None

    def _ensure_writer(self):
        if self.writer is None:
            try:
                from tensorboardX import SummaryWriter
                self.writer = SummaryWriter(log_dir=self.log_dir)
            except ImportError:
                self.writer = _JsonlWriter(self.log_dir)
        return self.writer

    def _print_training_status(self):
        metrics_data = [self.running_loss[k] / self.running_count[k]
                        for k in sorted(self.running_loss.keys())]
        lr = (float(self.scheduler(self.total_steps - self.schedule_offset))
              if self.scheduler is not None else float("nan"))
        metrics_str = ("{:10.4f}, " * len(metrics_data)).format(*metrics_data)
        logger.info("[%6d, %10.7f] %s", self.total_steps + 1, lr, metrics_str)

        writer = self._ensure_writer()
        for k in self.running_loss:
            writer.add_scalar(k, self.running_loss[k] / self.running_count[k],
                              self.total_steps)
            self.running_loss[k] = 0.0

    def push(self, metrics: Dict[str, float]):
        self.total_steps += 1
        if self.registry is not None:
            import time
            now = time.monotonic()
            self._m_steps.inc()
            if self._last_push_t is not None:
                self._step_hist.observe(now - self._last_push_t)
            self._last_push_t = now
            for key, value in metrics.items():
                self.registry.gauge(
                    "raft_train_metric", "last pushed train scalar",
                    key=key).set(float(value))
        for key, value in metrics.items():
            self.running_loss[key] = self.running_loss.get(key, 0.0) + float(value)
            self.running_count[key] = self.running_count.get(key, 0) + 1
        if self.total_steps % SUM_FREQ == SUM_FREQ - 1:
            self._print_training_status()
            self.running_loss = {}
            self.running_count = {}

    def write_scalar(self, name: str, value: float, step: Optional[int] = None):
        self._ensure_writer().add_scalar(
            name, value, self.total_steps if step is None else step)

    def write_dict(self, results: Dict[str, float]):
        writer = self._ensure_writer()
        for key, value in results.items():
            writer.add_scalar(key, value, self.total_steps)

    def close(self):
        if self.registry is not None and self.total_steps:
            try:
                os.makedirs(self.log_dir, exist_ok=True)
                with open(os.path.join(self.log_dir, "metrics.prom"),
                          "w") as f:
                    f.write(self.registry.render_prometheus())
            except OSError:  # telemetry must never kill a finished run
                logger.exception("could not write metrics.prom")
        if self.writer is not None:
            self.writer.close()

"""Headline benchmark: Middlebury-F-resolution disparity fps/chip, 32 GRU iters.

Protocol mirrors the reference's KITTI FPS harness (``evaluate_stereo.py:77-81``
— warmup frames discarded, mean over timed frames) applied to the BASELINE.json
north-star config: full-resolution Middlebury-F-shaped input, 32 refinement
iterations, single chip. Prints ONE JSON line.

The reference publishes no CUDA fps number (BASELINE.md); ``vs_baseline`` is
the ratio against ``BASELINE.json``'s ``published.fps`` when present, else null.

Env overrides: RAFT_BENCH_H / RAFT_BENCH_W / RAFT_BENCH_ITERS /
RAFT_BENCH_FRAMES / RAFT_BENCH_CORR (reg|alt|reg_tpu|alt_tpu) /
RAFT_BENCH_BATCH (frames per dispatch — throughput mode for KITTI-size
shapes; the Middlebury-F default stays batch 1, which is all that fits) /
RAFT_BENCH_TRACE (directory: wrap one timed frame in ``jax.profiler.trace``
for op-level attribution — the SURVEY §5 tracing hook).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# Band width for newly-(re)pinned statistics: 10x the largest legitimate
# drift class ever observed (kernel-reassociation re-pins moved <=0.05%,
# r5 BASELINE.md) and 4x tighter than the old 2% band — a localized
# numerics regression that survives the signed sum's cancellation has to
# hide under BOTH statistics inside 0.5% to slip through.
_PIN_RTOL = 0.005


def _check_checksum_pin(key: str, checksum: float, sum_abs: float,
                        here: str) -> None:
    """Gate the disparity checksum against a recorded reference band.

    Finiteness alone proved too weak (a wrong-but-finite kernel sails
    through); each benched config pins TWO statistics in
    ``bench_checksum_ref.json`` — the signed disparity sum and the
    cancellation-proof magnitude sum |d| (a localized regression that
    preserves the mean cannot preserve both) — and numerics changes must
    consciously re-baseline via ``RAFT_BENCH_REBASELINE=1``.

    Pin lifecycle: an EXISTING statistic is always enforced and only
    ``RAFT_BENCH_REBASELINE=1`` may move it (never silently). A MISSING
    entry — a config benched for the first time, or a pre-existing entry
    from before the sum|d| statistic — is recorded (printed loudly) ONLY
    under the explicit ``RAFT_BENCH_AUTOPIN=1`` opt-in, which
    ``scripts/release_gate.sh`` passes for its pinned-config steps; a
    bare bench run warns and never mutates the ref file. Recording is
    the only way a statistic can be born, and it never overwrites."""
    path = os.path.join(here, "bench_checksum_ref.json")
    refs = {}
    if os.path.exists(path):
        # A present-but-unparseable pin file must fail loudly: silently
        # resetting it would drop every other config's pin on rebaseline
        # and disable the numerics gate for them.
        with open(path) as f:
            refs = json.load(f)

    def write(msg):
        with open(path, "w") as f:
            json.dump(refs, f, indent=1, sort_keys=True)
        print(msg, file=sys.stderr)

    if os.environ.get("RAFT_BENCH_REBASELINE"):
        # The absolute floor exists ONLY to absorb bf16 jitter when the
        # pinned checksum is legitimately near zero (signed disparities
        # canceling) — the rtol term covers every other magnitude.
        refs[key] = {"checksum": checksum, "sum_abs": sum_abs,
                     "rtol": _PIN_RTOL, "atol": 1.0}
        write(f"bench: re-baselined checksum for {key}: {checksum:.2f} "
              f"(sum|d| {sum_abs:.2f})")
        return
    ref = refs.get(key)
    # Auto-pin (RAFT_BENCH_AUTOPIN=1) records a MISSING entry/statistic —
    # it can never overwrite one — and is OPT-IN even on the chip: a bare
    # bench run must never mutate the tracked ref file (a wrong-but-finite
    # kernel benched first would become the blessed reference). The
    # explicit opt-in lives in scripts/release_gate.sh, where the
    # first-pin ceremony is a visible gate step; everything else warns.
    autopin = os.environ.get("RAFT_BENCH_AUTOPIN", "0").strip().lower() \
        not in ("0", "false", "no", "off")
    if ref is None:
        if autopin:
            refs[key] = {"checksum": checksum, "sum_abs": sum_abs,
                         "rtol": _PIN_RTOL, "atol": 1.0}
            write(f"bench: PINNED (new config) {key}: checksum "
                  f"{checksum:.2f}, sum|d| {sum_abs:.2f} — now enforced")
        else:
            print(f"bench: no pinned checksum for {key}; "
                  "RAFT_BENCH_REBASELINE=1 records one", file=sys.stderr)
        return
    # The absolute floor keeps a legitimately-near-zero pinned checksum
    # (signed disparities canceling) from rejecting ordinary bf16 jitter;
    # re-baselined pins write a tight 1.0 floor, pre-existing pins keep
    # their recorded (looser) one.
    for name, got, pinned in (("checksum", checksum, ref.get("checksum")),
                              ("sum_abs", sum_abs, ref.get("sum_abs"))):
        if pinned is None:
            if autopin:  # statistic added after this entry was pinned
                refs[key][name] = got
                write(f"bench: PINNED (new statistic) {key}.{name} = "
                      f"{got:.2f} — now enforced")
            continue
        tol = max(abs(pinned) * ref.get("rtol", 0.02),
                  ref.get("atol", 100.0))
        if abs(got - pinned) > tol:
            raise AssertionError(
                f"disparity {name} {got:.2f} outside the pinned band "
                f"{pinned:.2f} ±{tol:.2f} for {key}; if the numerics "
                "change is intentional, re-baseline with "
                "RAFT_BENCH_REBASELINE=1")


def _trace_device_seconds(trace_dir: str):
    """Sum device-stream op durations from the newest profiler trace."""
    import glob
    import gzip

    files = glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    if not files:
        return None
    ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in ev
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    total = sum(e["dur"] for e in ev
                if e.get("ph") == "X" and "dur" in e
                and "TPU" in pids.get(e.get("pid"), "")
                and not str(e.get("name", "")).startswith(("jit_", "while")))
    return total / 1e6 if total else None


# The chip peak table (MFU denominator) lives in obs/ledger.py now —
# graftscope-device's roofline tables and this bench share one source.


def main() -> None:
    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

    # Middlebury V3 full-res frames are ~2000x2960; the padder rounds UP to the
    # next /32 multiple (reference evaluate_stereo.py:162), giving 2016x2976.
    h = int(os.environ.get("RAFT_BENCH_H", 2016))
    w = int(os.environ.get("RAFT_BENCH_W", 2976))
    iters = int(os.environ.get("RAFT_BENCH_ITERS", 32))
    # The reference's KITTI protocol times ~150 frames; 8 here keeps the
    # driver run short while amortizing the residual per-batch host
    # overhead (measured: 5 frames -> 0.719 fps, 10 -> 0.729).
    n_frames = int(os.environ.get("RAFT_BENCH_FRAMES", 8))
    batch = int(os.environ.get("RAFT_BENCH_BATCH", 1))
    # Default to the Pallas lookup kernel — the north-star config and the
    # fastest measured path (BASELINE.md measured table).
    corr = os.environ.get("RAFT_BENCH_CORR", "reg_tpu")

    def env_flag(name: str, default: str) -> bool:
        return os.environ.get(name, default).strip().lower() not in (
            "0", "false", "no", "off")

    mixed = env_flag("RAFT_BENCH_MP", "1")

    # Architecture overrides, e.g. the reference's realtime configuration
    # (README.md:96-104): RAFT_BENCH_SHARED=1 RAFT_BENCH_DOWNSAMPLE=3
    # RAFT_BENCH_GRU_LAYERS=2 RAFT_BENCH_SLOW_FAST=1 RAFT_BENCH_ITERS=7.
    cfg = RAFTStereoConfig(
        corr_implementation=corr, mixed_precision=mixed,
        shared_backbone=env_flag("RAFT_BENCH_SHARED", "0"),
        n_downsample=int(os.environ.get("RAFT_BENCH_DOWNSAMPLE", "2")),
        n_gru_layers=int(os.environ.get("RAFT_BENCH_GRU_LAYERS", "3")),
        slow_fast_gru=env_flag("RAFT_BENCH_SLOW_FAST", "0"))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)

    @jax.jit
    def forward(params, image1, image2):
        _, flow_up = raft_stereo_forward(params, cfg, image1, image2,
                                         iters=iters, test_mode=True)
        # Scalar checksums alongside the full map: fetching bytes forces the
        # whole computation without timing a ~20MB host transfer. (Under the
        # axon tunnel, block_until_ready returns before execution finishes, so
        # a host fetch is the only reliable completion barrier.) Two pinned
        # statistics: the signed sum and the cancellation-proof sum |d|.
        return flow_up, jnp.sum(flow_up), jnp.sum(jnp.abs(flow_up))

    # graftscope-device: the headline program's cost/memory account comes
    # from the ledger, not hand-rolled tables — AOT lower+compile (the
    # same one compile the first jit call would pay) keeps the Compiled
    # handle so cost_analysis/memory_analysis feed a ledger row, exactly
    # like InferenceSession does for serving programs.
    from raft_stereo_tpu.obs.ledger import (ProgramLedger, analyze_compiled,
                                            chip_peaks)
    ledger = ProgramLedger()
    ledger_key = ("bench_full", batch, h, w, iters, corr)
    device_kind = jax.devices()[0].device_kind

    rng = np.random.default_rng(0)

    def frame():
        img1 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
        img2 = jnp.asarray(rng.uniform(0, 255, (batch, h, w, 3)), jnp.float32)
        return img1, img2

    def fetch_and_check(checksum, sum_abs):
        checksum = float(checksum)  # host fetch = completion barrier
        sum_abs = float(sum_abs)
        # A kernel that returns garbage fast must not produce a good fps
        # number: the disparity-sum checksums have to be finite.
        if not (np.isfinite(checksum) and np.isfinite(sum_abs)):
            raise AssertionError(
                f"non-finite disparity checksum {checksum} / {sum_abs}")
        return checksum, sum_abs

    # Warmup: compile + one steady-state frame (reference discards frames 1-50;
    # under AOT/jit a single post-compile frame reaches steady state). The
    # ledger row is recorded at compile time; if the AOT API is
    # unavailable the row carries no compiler numbers (graceful absence)
    # and plain jit dispatch serves the bench unchanged.
    img1, img2 = frame()
    try:
        fwd = forward.lower(params, img1, img2).compile()
        analysis = analyze_compiled(fwd)
    except (TypeError, AttributeError, NotImplementedError):
        fwd, analysis = forward, {}
    row = ledger.record(ledger_key, kind="full", b=batch, h=h, w=w,
                        iters=iters, analysis=analysis,
                        backend=jax.default_backend(),
                        device_kind=device_kind)

    def run(img1, img2):
        return fetch_and_check(*fwd(params, img1, img2)[1:])

    run(img1, img2)
    run(img1, img2)

    # Device-side op time from a one-frame profiler trace: wall fps through
    # the tunnel includes ~100 ms host overhead per barrier, and the
    # flops-derived MFU needs the on-device time to be honest. Failure to
    # trace (or parse) degrades to null rather than failing the bench.
    trace_dir = os.environ.get("RAFT_BENCH_TRACE") or "/tmp/raft_bench_trace"
    device_s = None
    try:
        import shutil
        if not os.environ.get("RAFT_BENCH_TRACE"):
            shutil.rmtree(trace_dir, ignore_errors=True)
        with jax.profiler.trace(trace_dir):
            run(img1, img2)
        device_s = _trace_device_seconds(trace_dir)
    except Exception:  # noqa: BLE001 - diagnostics only
        pass

    # The ledger row's raw compiler flops count the refinement scan body
    # ONCE (the r6 finding, re-verified for compiled programs in r12), so
    # the headline row — a "full" program mixing encoders, scan and
    # epilogue — carries no per-invocation estimate until this bench
    # ANNOTATES it from the unrolled-slope measurement below. MFU is then
    # read back off the ledger row, never hand-assembled.
    try:
        # Algorithmic flops from the XLA-twin program (fused_update off,
        # XLA corr): the production path's Pallas custom calls are
        # invisible to XLA cost analysis, which would understate MFU.
        # Computed by a CPU-platform subprocess — lowered (uncompiled)
        # HLO analysis takes ~2 s there, while the axon platform's
        # lowering path measured minutes.
        import subprocess
        # XLA counts a while-loop BODY once regardless of trip count
        # (measured: scan flops identical at 4 vs 8 iters), which
        # understated MFU ~5x at 32 iters. Two UNROLLED lowerings at 1
        # and 2 iterations give the per-iteration slope; extrapolate.
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "from raft_stereo_tpu.config import RAFTStereoConfig\n"
            "from raft_stereo_tpu.models import init_raft_stereo, "
            "raft_stereo_forward\n"
            f"cfg = RAFTStereoConfig(corr_implementation='reg', "
            f"mixed_precision={mixed}, fused_update=False, "
            f"shared_backbone={cfg.shared_backbone}, "
            f"n_downsample={cfg.n_downsample}, "
            f"n_gru_layers={cfg.n_gru_layers}, "
            f"slow_fast_gru={cfg.slow_fast_gru})\n"
            "params = init_raft_stereo(jax.random.PRNGKey(0), cfg)\n"
            f"img = jnp.zeros(({batch}, {h}, {w}, 3), jnp.float32)\n"
            "def f(n):\n"
            "    def fwd(p, a, b):\n"
            "        _, up = raft_stereo_forward(p, cfg, a, b, iters=n, "
            "test_mode=True, unroll=True)\n"
            "        return up\n"
            "    ca = jax.jit(fwd).lower(params, img, img).cost_analysis()\n"
            "    return (ca or {}).get('flops', 0.0)\n"
            "f1, f2 = f(1), f(2)\n"
            f"print('FLOPS', f1 + (f2 - f1) * ({iters} - 1))\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             cwd=os.path.dirname(os.path.abspath(__file__)),
                             capture_output=True, text=True, timeout=300)
        for line in out.stdout.splitlines():
            if line.startswith("FLOPS "):
                slope = float(line.split()[1]) or None
                if slope:
                    ledger.annotate(ledger_key, flops_est=slope)
    except Exception:  # noqa: BLE001 - diagnostics only
        pass

    # One device-resident pair, dispatched n_frames times (the reference
    # also times only the forward: its timer starts after load + pad +
    # .cuda(), evaluate_stereo.py:74-79). Runtime is content-independent —
    # fixed iteration count, no data-dependent control flow — and keeping
    # one pair makes bench memory O(1) in RAFT_BENCH_FRAMES instead of
    # pinning ~144 MB per frame.
    img1, img2 = frame()
    float(img1[0, 0, 0, 0]); float(img2[0, 0, 0, 0])

    # Dispatch all timed frames, then one completion barrier: device
    # execution is in-order, so fetching every checksum after the loop
    # costs a single tunnel round-trip (~100 ms) amortized over the batch
    # instead of per frame. The reference's own timing never synchronizes
    # per frame at all (the loop's only sync is the metric .cpu() fetch).
    t0 = time.perf_counter()
    pending = [fwd(params, img1, img2)[1:] for _ in range(n_frames)]
    checksum = sum_abs = None
    for c in pending:
        checksum, sum_abs = fetch_and_check(*c)
    elapsed = time.perf_counter() - t0

    fps = n_frames * batch / elapsed

    pin_key = (f"{h}x{w}_i{iters}_{corr}_{'bf16' if mixed else 'fp32'}"
               f"_b{batch}_sh{int(cfg.shared_backbone)}_d{cfg.n_downsample}"
               f"_g{cfg.n_gru_layers}_sf{int(cfg.slow_fast_gru)}")
    if jax.default_backend() != "tpu":
        # Off-chip runs produce different bf16 roundings (interpret-mode
        # kernels, CPU conv orders): namespace their pins so a laptop
        # experiment can never poison — or trivially satisfy — a chip pin.
        pin_key = f"{jax.default_backend()}:{pin_key}"
    _check_checksum_pin(pin_key, checksum, sum_abs,
                        os.path.dirname(os.path.abspath(__file__)))

    # Baseline preference: a published reference fps (none exists — the repo
    # publishes no numbers, BASELINE.md), else our measured torch-reference
    # datum at the same shape/protocol (CPU-labeled; no GPU in this image).
    # Baselines were measured single-frame; a batched run's throughput is a
    # different protocol, so the ratio is only reported at batch 1.
    baseline = None
    here = os.path.dirname(__file__)
    if batch == 1:
        try:
            with open(os.path.join(here, "BASELINE.json")) as f:
                baseline = json.load(f).get("published", {}).get("fps")
        except (OSError, ValueError):
            pass
        if baseline is None:
            try:
                with open(os.path.join(here, "baseline_measured.json")) as f:
                    baseline = json.load(f).get(
                        f"torch_cpu_fps_{h}x{w}_{iters}iters")
            except (OSError, ValueError):
                pass

    # MFU read off the ledger row (annotated flops_est over the device
    # time of one dispatch, against the shared chip peak table; falls
    # back to wall time when no profiler trace parsed). Absent inputs =>
    # absent MFU — the ledger contract, never a fabricated ratio.
    peaks = chip_peaks(device_kind)
    dispatch_s = device_s if device_s else elapsed / n_frames
    flops = row.flops_est
    mfu = (flops / dispatch_s / peaks[0]) if (flops and peaks) else None

    # r19 correlation-DMA accounting: the per-iteration pyramid bytes the
    # lookup's BlockSpecs declare, per packing mode — EXACT arithmetic
    # over the pack plans (corr/pallas_reg.plan_dma_bytes), computable at
    # any geometry without a compile. The int8/bf16 ratio is the
    # acceptance number (<= 0.6 at headline); the driver's on-chip run
    # corroborates it with the advance rows' compiler bytes_est.
    def corr_dma(hh, ww):
        from raft_stereo_tpu.corr.pallas_reg import (level_widths,
                                                     plan_dma_bytes)
        factor = cfg.downsample_factor
        widths = level_widths(ww // factor, cfg.corr_levels)
        npx = (hh // factor) * (ww // factor)
        bf16_px = plan_dma_bytes(widths, True, False)
        int8_px = plan_dma_bytes(widths, True, True)
        return {"h": hh, "w": ww,
                "bf16_bytes_per_iter": bf16_px * npx,
                "int8_bytes_per_iter": int8_px * npx,
                "int8_over_bf16": round(int8_px / bf16_px, 4)}

    corr_dma_doc = {"bench": corr_dma(h, w),
                    "headline": corr_dma(2016, 2976)}

    # r24 context-lane accounting, same ledger discipline: the
    # per-iteration czrq bytes the GRU scan-body BlockSpecs declare, bf16
    # vs the width-group int8 containers (exact arithmetic — grid revisit
    # factors cancel in the ratio; <= 0.6 is the acceptance bound,
    # asserted at headline AND serve geometry by check_engagement.py).
    def lane_dma(hh, ww):
        from raft_stereo_tpu.ops.pallas_stream import plan_lane_dma_bytes
        bf16_b = plan_lane_dma_bytes(hh, ww, pack8=False)
        int8_b = plan_lane_dma_bytes(hh, ww, pack8=True)
        return {"h": hh, "w": ww,
                "bf16_bytes_per_iter": bf16_b,
                "int8_bytes_per_iter": int8_b,
                "int8_over_bf16": round(int8_b / bf16_b, 4)}

    lane_dma_doc = {"bench": lane_dma(h, w),
                    "headline": lane_dma(2016, 2976)}

    doc = {
        "metric": (f"middlebury_F_disparity_fps_per_chip_{iters}iters_"
                   f"{h}x{w}_{corr}_{'bf16' if mixed else 'fp32'}"
                   + (f"_batch{batch}" if batch > 1 else "")),
        "value": round(fps, 4),
        "unit": "frames/s",
        "vs_baseline": round(fps / baseline, 4) if baseline else None,
        "checksum": round(checksum, 2),
        "sum_abs": round(sum_abs, 2),
        "device_s": round(device_s, 4) if device_s else None,
        "flops": flops,
        "mfu": round(mfu, 4) if mfu else None,
        "peak_hbm_bytes": row.peak_hbm_bytes,
        "roofline": row.roofline(peaks),
        "bytes": row.bytes_accessed,
        "corr_dma": corr_dma_doc,
        "lane_dma": lane_dma_doc,
    }
    print(json.dumps(doc))

    # Perf-trajectory gate (DESIGN.md r11): when RAFT_TRAJECTORY is
    # exported (the release gate does), the headline fps lands in the
    # consolidated TRAJECTORY.json next to requests/s and steps/s, where
    # the per-metric pinned bands catch a regression in ANY of them. The
    # ledger extras (flops/bytes/mfu) ride along as UNPINNED diagnostics:
    # autopin copies them into the band so an out-of-band failure can say
    # "program changed" vs "machine drifted" — the value pin and the
    # checksum pins above are untouched by any of this.
    from raft_stereo_tpu.obs.trajectory import emit
    emit(doc["metric"], fps, "frames/s",
         backend=jax.default_backend(), source="bench.py",
         extra={"mfu": doc["mfu"], "device_s": doc["device_s"],
                "flops": flops, "bytes": row.bytes_accessed,
                "roofline": doc["roofline"],
                "corr_dma": corr_dma_doc,
                "lane_dma": lane_dma_doc})


if __name__ == "__main__":
    main()

#!/bin/bash
# Fetch the evaluation datasets the validators expect, laid out under
# datasets/ exactly as raft_stereo_tpu.data.datasets globs them.
# Port of the reference fetcher (/root/reference/download_datasets.sh:1-23);
# same upstream URLs, plus fail-fast flags and idempotent unzips.
#
# Training sets (SceneFlow, Sintel, FallingThings, TartanAir, KITTI) are
# license-gated uploads; see README for their layout.
set -euo pipefail

mkdir -p datasets/Middlebury
(
  cd datasets/Middlebury
  wget -nc https://www.dropbox.com/s/fn8siy5muak3of3/official_train.txt -P MiddEval3/
  for split in Q H F; do
    wget -nc "https://vision.middlebury.edu/stereo/submit3/zip/MiddEval3-data-${split}.zip"
    unzip -n "MiddEval3-data-${split}.zip"
    wget -nc "https://vision.middlebury.edu/stereo/submit3/zip/MiddEval3-GT0-${split}.zip"
    unzip -n "MiddEval3-GT0-${split}.zip"
  done
  rm -f ./*.zip
)

mkdir -p datasets/ETH3D/two_view_testing
(
  cd datasets/ETH3D/two_view_testing
  wget -nc https://www.eth3d.net/data/two_view_test.7z
  7za x -aos two_view_test.7z
)

"""Train-step throughput + overfit smoke on the real chip.

Reference config of record (README.md:106-110): batch 8 on 2 GPUs -> batch
4/GPU; here batch 6 single chip (train_stereo.py default), 320x720 crops,
train_iters 22, bf16 compute. Prints steps/s and the loss trajectory on a
fixed synthetic batch (loss must drop = grads flow through scan + Pallas
custom_vjp + optimizer on hardware), then ONE JSON line (bench.py's
contract) and — when RAFT_TRAJECTORY is exported — a steps/s entry in the
consolidated perf-trajectory artifact (DESIGN.md r11), so the release
gate's pinned bands cover training throughput alongside fps/chip and
requests/s.

TRAIN_BENCH_TINY=1 is the CPU gate smoke: a 32-dim 1-GRU model at 64x96,
fp32, XLA corr — it proves the wiring (step compiles, loss finite, JSON +
trajectory emitted) on a machine where the real config would take hours;
the throughput bar and the overfit assertion apply to the on-chip run.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np
import jax, jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_train_step
from raft_stereo_tpu.models import init_raft_stereo

tiny = os.environ.get("TRAIN_BENCH_TINY", "0") not in ("0", "false")
corr = os.environ.get("TRAIN_BENCH_CORR", "reg" if tiny else "reg_tpu")
b = int(os.environ.get("TRAIN_BENCH_B", 2 if tiny else 6))
h = int(os.environ.get("TRAIN_BENCH_H", 64 if tiny else 320))
w = int(os.environ.get("TRAIN_BENCH_W", 96 if tiny else 720))
iters = int(os.environ.get("TRAIN_BENCH_ITERS", 2 if tiny else 22))
n_steps = int(os.environ.get("TRAIN_BENCH_STEPS", 6 if tiny else 12))
fused = os.environ.get("TRAIN_BENCH_FUSED",
                       "0" if tiny else "1") not in ("0", "false")
# TRAIN_BENCH_FUSED_TRAIN=1 engages the streaming kernels in the train
# step itself (with the save_only_these_names remat policy).
fused_train = os.environ.get("TRAIN_BENCH_FUSED_TRAIN", "0") not in (
    "0", "false")
arch = (dict(n_gru_layers=1, hidden_dims=(32, 32, 32), corr_levels=2,
             corr_radius=2) if tiny else {})
cfg = RAFTStereoConfig(corr_implementation=corr, mixed_precision=not tiny,
                       fused_update=fused, fused_train=fused_train, **arch)
params = jax.jit(lambda k: init_raft_stereo(k, cfg))(jax.random.PRNGKey(0))
tx, _ = make_optimizer(lr=2e-4, num_steps=1000)
opt_state = jax.jit(tx.init)(params)
step = make_train_step(cfg, tx, train_iters=iters)

rng = np.random.default_rng(0)
base = rng.uniform(0, 255, (b, h, w + 16, 3)).astype(np.float32)
batch = {
    # image1[x] = base[x], image2[x] = base[x+16]: the right-image match
    # for left pixel x sits at x-16 — 16 px to the LEFT, disparity +16,
    # flow-x = -16 (flow = -disp convention, data/datasets.py:85). The
    # smoke only asserts the loss drops on a FIXED batch (grads flow).
    "image1": jnp.asarray(base[:, :, :-16, :]),
    "image2": jnp.asarray(base[:, :, 16:, :]),
    "flow": jnp.full((b, h, w, 1), -16.0, jnp.float32),
    "valid": jnp.ones((b, h, w), jnp.float32),
}
losses = []
t0 = None
for i in range(n_steps):
    params, opt_state, m = step(params, opt_state, batch)
    losses.append(float(m["loss"]))  # host fetch = barrier
    if i == 1:
        t0 = time.perf_counter()  # skip 2 warmup/compile steps
t1 = time.perf_counter()
timed = n_steps - 2
steps_per_s = timed / (t1 - t0)
print(f"corr={corr} batch={b} {h}x{w} iters={iters}: "
      f"{steps_per_s:.3f} steps/s ({(t1-t0)/timed:.2f} s/step)")
print("loss trajectory:", " ".join(f"{l:.3f}" for l in losses))
assert all(np.isfinite(losses)), "non-finite loss"
if not tiny:
    assert losses[-1] < losses[1] * 0.9, "loss did not decrease"
    print("overfit smoke OK")
else:
    # 4 timed steps on random init prove wiring, not convergence — the
    # overfit bar stays an on-chip (full-config) assertion.
    print("tiny wiring smoke OK (overfit bar applies to the full config)")

doc = {
    "metric": (f"train_steps_per_s_{h}x{w}_b{b}_i{iters}_{corr}"
               f"{'_tiny' if tiny else ''}"),
    "value": round(steps_per_s, 4),
    "unit": "steps/s",
    "n_steps_timed": timed,
    "final_loss": round(losses[-1], 4),
    "backend": jax.default_backend(),
}
print(json.dumps(doc))
from raft_stereo_tpu.obs.trajectory import emit
emit(doc["metric"], steps_per_s, "steps/s",
     backend=jax.default_backend(), source="scratch/bench_train.py",
     extra={"final_loss": doc["final_loss"]})

"""Train-step throughput + overfit smoke on the real chip.

Reference config of record (README.md:106-110): batch 8 on 2 GPUs -> batch
4/GPU; here batch 6 single chip (train_stereo.py default), 320x720 crops,
train_iters 22, bf16 compute. Prints steps/s and the loss trajectory on a
fixed synthetic batch (loss must drop = grads flow through scan + Pallas
custom_vjp + optimizer on hardware).
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_train_step
from raft_stereo_tpu.models import init_raft_stereo

corr = os.environ.get("TRAIN_BENCH_CORR", "reg_tpu")
b = int(os.environ.get("TRAIN_BENCH_B", 6))
h = int(os.environ.get("TRAIN_BENCH_H", 320))
w = int(os.environ.get("TRAIN_BENCH_W", 720))
iters = int(os.environ.get("TRAIN_BENCH_ITERS", 22))
fused = os.environ.get("TRAIN_BENCH_FUSED", "1") not in ("0", "false")
# TRAIN_BENCH_FUSED_TRAIN=1 engages the streaming kernels in the train
# step itself (with the save_only_these_names remat policy).
fused_train = os.environ.get("TRAIN_BENCH_FUSED_TRAIN", "0") not in (
    "0", "false")
cfg = RAFTStereoConfig(corr_implementation=corr, mixed_precision=True,
                       fused_update=fused, fused_train=fused_train)
params = jax.jit(lambda k: init_raft_stereo(k, cfg))(jax.random.PRNGKey(0))
tx, _ = make_optimizer(lr=2e-4, num_steps=1000)
opt_state = jax.jit(tx.init)(params)
step = make_train_step(cfg, tx, train_iters=iters)

rng = np.random.default_rng(0)
base = rng.uniform(0, 255, (b, h, w + 16, 3)).astype(np.float32)
batch = {
    # image1[x] = base[x], image2[x] = base[x+16]: the right-image match
    # for left pixel x sits at x-16 — 16 px to the LEFT, disparity +16,
    # flow-x = -16 (flow = -disp convention, data/datasets.py:85). The
    # smoke only asserts the loss drops on a FIXED batch (grads flow).
    "image1": jnp.asarray(base[:, :, :-16, :]),
    "image2": jnp.asarray(base[:, :, 16:, :]),
    "flow": jnp.full((b, h, w, 1), -16.0, jnp.float32),
    "valid": jnp.ones((b, h, w), jnp.float32),
}
losses = []
t0 = None
for i in range(12):
    params, opt_state, m = step(params, opt_state, batch)
    losses.append(float(m["loss"]))  # host fetch = barrier
    if i == 1:
        t0 = time.perf_counter()  # skip 2 warmup/compile steps
t1 = time.perf_counter()
print(f"corr={corr} batch={b} {h}x{w} iters={iters}: "
      f"{10 / (t1 - t0):.3f} steps/s ({(t1-t0)/10:.2f} s/step)")
print("loss trajectory:", " ".join(f"{l:.3f}" for l in losses))
assert losses[-1] < losses[1] * 0.9, "loss did not decrease"
print("overfit smoke OK")

"""B=1 realtime wall-fps dispatch-path breakdown through the axon tunnel.

VERDICT r3/r4 asked: reach >=35 fps wall at the reference realtime config
(shared backbone, ds3, 2 GRU levels, slow_fast, 7 iters, 384x1248 — 7.3 ms
device) or prove the link's floor. This measures each stage of the
dispatch path separately, then the end-to-end loop in three modes:

- rtt:        dispatch+fetch of a scalar identity — the tunnel's floor for
              any synchronous frame loop (nothing can be faster).
- upload:     device_put of one bf16 frame pair (the realtime H2D payload).
- sync:       upload -> forward -> fetch checksum, one frame at a time
              (strict realtime latency semantics).
- pipelined:  frame n+1's upload+dispatch issued before frame n's fetch
              (depth-2 software pipeline; latency unchanged, throughput
              overlaps transfer with compute — legal for a realtime sink
              that tolerates one frame of latency).
- device:     device-side op time from the profiler (the hardware number a
              locally-attached chip would approach).
"""
import json
import sys
import time

sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

H, W, ITERS, N = 384, 1248, 7, 30
cfg = RAFTStereoConfig(corr_implementation="reg_tpu", mixed_precision=True,
                       shared_backbone=True, n_downsample=3, n_gru_layers=2,
                       slow_fast_gru=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)


@jax.jit
def forward(params, image1, image2):
    _, up = raft_stereo_forward(params, cfg, image1, image2, iters=ITERS,
                                test_mode=True)
    return up, jnp.sum(up)


@jax.jit
def ident(x):
    return x + 1.0


def fetch(x):
    return float(x)


rng = np.random.default_rng(0)
frames = [(rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32),
           rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32))
          for _ in range(4)]

# Warm compile + steady state.
d1 = jax.device_put(jnp.asarray(frames[0][0]))
d2 = jax.device_put(jnp.asarray(frames[0][1]))
fetch(forward(params, d1, d2)[1])
fetch(ident(jnp.float32(0.0)))

out = {}

# 1) RTT floor: scalar dispatch + fetch.
t0 = time.perf_counter()
for _ in range(N):
    fetch(ident(jnp.float32(1.0)))
out["rtt_ms"] = (time.perf_counter() - t0) / N * 1000

# 2) Upload: one fp32 frame pair + fetch (isolates H2D from compute).
t0 = time.perf_counter()
for i in range(N):
    a, b = frames[i % 4]
    d1 = jax.device_put(a)
    d2 = jax.device_put(b)
    # completion barrier for the upload itself
    fetch(jnp.sum(d1[0, 0, 0]) + jnp.sum(d2[0, 0, 0]))
out["upload_plus_rtt_ms"] = (time.perf_counter() - t0) / N * 1000

# 3) Strict sync realtime loop.
t0 = time.perf_counter()
for i in range(N):
    a, b = frames[i % 4]
    _, c = forward(params, jax.device_put(a), jax.device_put(b))
    fetch(c)
sync_ms = (time.perf_counter() - t0) / N * 1000
out["sync_ms_per_frame"] = sync_ms
out["sync_fps"] = 1000 / sync_ms

# 4) Depth-2 pipeline: dispatch n+1 before fetching n.
t0 = time.perf_counter()
pending = None
for i in range(N):
    a, b = frames[i % 4]
    nxt = forward(params, jax.device_put(a), jax.device_put(b))[1]
    if pending is not None:
        fetch(pending)
    pending = nxt
fetch(pending)
pipe_ms = (time.perf_counter() - t0) / N * 1000
out["pipelined_ms_per_frame"] = pipe_ms
out["pipelined_fps"] = 1000 / pipe_ms

# 5) Device-side op time.
try:
    import glob
    import gzip
    import shutil
    tdir = "/tmp/rt_dispatch_trace"
    shutil.rmtree(tdir, ignore_errors=True)
    with jax.profiler.trace(tdir):
        _, c = forward(params, d1, d2)
        fetch(c)
    files = sorted(glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True))
    ev = json.load(gzip.open(files[-1]))["traceEvents"]
    pids = {e["pid"]: e["args"]["name"] for e in ev
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev = sum(e["dur"] for e in ev
              if e.get("ph") == "X" and "dur" in e
              and "TPU" in pids.get(e.get("pid"), "")
              and not str(e.get("name", "")).startswith(("jit_", "while")))
    out["device_ms"] = dev / 1000
    out["device_fps"] = 1e6 / dev if dev else None
except Exception:
    pass

print(json.dumps({k: round(v, 2) for k, v in out.items()}))

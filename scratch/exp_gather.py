import sys, os; sys.path.insert(0, "/root/repo")
"""Experiment: which dynamic-gather forms compile in Mosaic on this TPU.

Candidates for the corr-lookup kernel's inner gather:
 A) jnp.take_along_axis(vol, idx, axis=-1)  — per-row dynamic gather along lanes
 B) vol row one-hot reduce (the XLA fallback, known to work)
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

P, W2, K = 128, 256, 16


def kernel_taa(vol_ref, idx_ref, out_ref):
    out_ref[:] = jnp.take_along_axis(vol_ref[:], idx_ref[:], axis=-1)


def run_taa():
    vol = jnp.asarray(np.random.default_rng(0).standard_normal((P, W2)), jnp.float32)
    idx = jnp.asarray(np.random.default_rng(1).integers(0, W2, (P, K)), jnp.int32)
    out = pl.pallas_call(
        kernel_taa,
        out_shape=jax.ShapeDtypeStruct((P, K), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(vol, idx)
    ref = np.take_along_axis(np.asarray(vol), np.asarray(idx), axis=-1)
    np.testing.assert_allclose(np.asarray(out), ref)
    print("A) take_along_axis lanes: OK")


def kernel_taa_sub(vol_ref, idx_ref, out_ref):
    # gather along sublanes (axis 0): out[k, w] = vol[idx[k, w], w]
    out_ref[:] = jnp.take_along_axis(vol_ref[:], idx_ref[:], axis=0)


def run_taa_sub():
    vol = jnp.asarray(np.random.default_rng(0).standard_normal((P, W2)), jnp.float32)
    idx = jnp.asarray(np.random.default_rng(1).integers(0, P, (K, W2)), jnp.int32)
    out = pl.pallas_call(
        kernel_taa_sub,
        out_shape=jax.ShapeDtypeStruct((K, W2), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )(vol, idx)
    ref = np.take_along_axis(np.asarray(vol), np.asarray(idx), axis=0)
    np.testing.assert_allclose(np.asarray(out), ref)
    print("B) take_along_axis sublanes: OK")


if __name__ == "__main__":
    print(jax.devices())
    for name, fn in [("A", run_taa), ("B", run_taa_sub)]:
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            print(f"{name}) FAILED: {type(e).__name__}: {str(e)[:500]}")

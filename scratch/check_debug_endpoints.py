"""Release-gate drive of the operator-plane /debug endpoints (graftdeck,
DESIGN.md r15) against the LIVE CLI service.

Stands up ``serve_stereo.py --http_port 0`` (tiny random-weight model —
wiring, not quality), pushes one real stereo request through the wire so
the deck/usage/capacity surfaces have content, then GETs every operator
endpoint and validates its JSON schema AND its boundedness (byte caps
asserted — a debug endpoint that can grow without bound is a self-DoS
surface).  Finishes with a SIGTERM drain and requires exit 0.

One JSON line on stdout (bench.py's contract), exit 0/1.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Hard byte caps per endpoint body: "bounded" is asserted, not assumed.
BODY_CAPS = {
    "/healthz": 1 << 20,
    "/debug/ticks": 4 << 20,
    "/debug/usage": 1 << 20,
    "/debug/stacks": 1 << 20,
    "/debug/config": 1 << 20,
}

H, W = 40, 60


def _get(base: str, path: str) -> bytes:
    from urllib.request import urlopen
    with urlopen(base + path, timeout=30) as resp:
        assert resp.status == 200, (path, resp.status)
        return resp.read()


def main() -> int:
    import numpy as np

    from raft_stereo_tpu.serve import wire

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "serve_stereo.py",
         "--http_port", "0", "--no_canary",
         "--max_batch", "2", "--valid_iters", "2", "--segments", "2",
         "--n_gru_layers", "1", "--hidden_dims", "32", "32", "32",
         "--corr_levels", "2", "--corr_radius", "2",
         "--corr_implementation", "reg"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    endpoint = None
    try:
        # A hard kill-timer, not a per-line deadline check: a child
        # wedged BEFORE printing anything would block the pipe read
        # forever and hang the gate — the timer turns that into an EOF
        # and a clean assertion instead.
        import threading
        startup_timer = threading.Timer(600.0, proc.kill)
        startup_timer.start()
        try:
            for line in proc.stdout:
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if doc.get("event") == "listening":
                    endpoint = doc["endpoint"]
                    break
        finally:
            startup_timer.cancel()
        assert endpoint, ("no listening event from serve_stereo.py "
                          "(wedged startup killed at 600 s?)")

        # Four real requests so ticks/usage/capacity have content — the
        # first three riding ONE X-Raft-Session so the graftstream
        # surfaces (warm joins, converged exits) light up through the
        # live wire: frame 1 cold, frames 2-3 PERTURBED (distinct bytes
        # — the CLI arms the response cache by default, so identical
        # bodies would exact-hit and never warm-join) with frame 3
        # carrying a loose convergence tolerance so it exits
        # converged:k.  Frame 4 then REPEATS frame 1's exact bytes and
        # must be answered cache:exact at zero device seconds
        # (graftrecall, DESIGN.md r18).
        rng = np.random.default_rng(0)
        left = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
        right = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)

        def frame_body(fid, l_arr):
            return wire.build_multipart(
                {"left": wire.encode_image_png(l_arr),
                 "right": wire.encode_image_png(right),
                 "id": fid.encode()})

        def perturbed(seed):
            noise = np.random.default_rng(seed).integers(
                -2, 3, left.shape)
            return np.clip(left.astype(np.int16) + noise,
                           0, 255).astype(np.uint8)

        from urllib.request import Request, urlopen

        def post(ct, body, extra_headers):
            req = Request(
                endpoint + "/v1/stereo", data=body, method="POST",
                headers={"Content-Type": ct,
                         "X-Raft-Tenant": "gate-tenant",
                         "X-Raft-Session": "gate-cam",
                         **extra_headers})
            with urlopen(req, timeout=300) as resp:
                return wire.decode_response(resp.read())

        ct, body1 = frame_body("gate-debug-0", left)
        served = post(ct, body1, {})
        assert served["status"] == "ok", served
        ct2, body2 = frame_body("gate-debug-1", perturbed(1))
        warm = post(ct2, body2, {})
        assert warm["status"] == "ok", warm
        ct3, body3 = frame_body("gate-debug-2", perturbed(2))
        conv = post(ct3, body3, {"X-Raft-Converge-Tol": "1e9"})
        assert conv["status"] == "ok", conv
        assert str(conv["quality"]).startswith("converged:"), conv
        assert int(str(conv["quality"]).split(":")[1]) == conv["iters"]
        # graftrecall exact tier through the live wire: identical bytes
        # -> cache:exact, byte-identical disparity.
        hit = post(ct, body1, {})
        assert hit["status"] == "ok", hit
        assert hit["quality"] == "cache:exact", hit["quality"]
        assert hit["disparity"].tobytes() == \
            served["disparity"].tobytes(), (
            "cache:exact response is not byte-identical to the cold "
            "serve")

        sizes = {}
        docs = {}
        for path, cap in BODY_CAPS.items():
            raw = _get(endpoint, path)
            assert len(raw) <= cap, (
                f"{path} body is {len(raw)} bytes > its {cap} bound")
            sizes[path] = len(raw)
            docs[path] = json.loads(raw)

        # /debug/ticks: the flight-deck ring, schema'd and ring-bounded.
        ticks = docs["/debug/ticks"]
        assert ticks["schema"] == 1 and isinstance(ticks["ticks"], list)
        assert len(ticks["ticks"]) <= ticks["ring"]
        assert ticks["recorded"] >= 1, "no deck records after a request"
        for t in ticks["ticks"]:
            for k in ("seq", "kind", "t_start", "device_s", "warm_s"):
                assert k in t, (k, t)
        # ?n= bounds it further
        one = json.loads(_get(endpoint, "/debug/ticks?n=1"))
        assert len(one["ticks"]) == 1

        # /debug/usage: the tenant rollup, integer-exact — now also the
        # graftstream per-tenant warm-join/converged counts (ISSUE 13:
        # the usage surface must expose them through the live CLI).
        usage = docs["/debug/usage"]
        assert usage["schema"] == 1
        assert "gate-tenant" in usage["by_tenant"], usage["by_tenant"]
        assert usage["by_tenant"]["gate-tenant"]["bytes_in"] > 0
        assert sum(t["device_ns"] for t in usage["by_tenant"].values()) \
            == usage["device_ns_total"]
        gate_stream = usage["by_tenant"]["gate-tenant"]["stream"]
        assert gate_stream["warm_joins"] >= 2, gate_stream
        assert gate_stream["converged_exits"] >= 1, gate_stream

        # /debug/ticks rows expose warm-join and converged-exit counts
        # (the deck surface half of the same ISSUE 13 requirement).  The
        # response Future resolves INSIDE the tick, so the final tick
        # record may publish an instant later — re-fetch briefly.
        ticks = docs["/debug/ticks"]
        for t in ticks["ticks"]:
            assert "warm_joins" in t and "converged" in t, t
        for _ in range(100):
            if sum(t["warm_joins"] for t in ticks["ticks"]) >= 2 and \
                    sum(t["converged"] for t in ticks["ticks"]) >= 1:
                break
            time.sleep(0.05)
            ticks = json.loads(_get(endpoint, "/debug/ticks"))
        assert sum(t["warm_joins"] for t in ticks["ticks"]) >= 2
        assert sum(t["converged"] for t in ticks["ticks"]) >= 1

        # /healthz stream block: the bounded session table is live.
        health = docs["/healthz"]
        assert health["stream"]["sessions"] >= 1, health["stream"]
        assert health["stream"]["warm_joins"] >= 2

        # /healthz cache block + /debug/usage cache columns
        # (graftrecall, DESIGN.md r18): the CLI arms the cache by
        # default, and the exact repeat above must be visible as a hit
        # on BOTH surfaces through the live wire.
        cache_block = health["cache"]
        assert cache_block["enabled"] is True, cache_block
        assert cache_block["hits"] >= 1, cache_block
        assert cache_block["entries"] >= 1, cache_block
        assert cache_block["bytes"] > 0, cache_block
        gate_cache = usage["by_tenant"]["gate-tenant"]["cache"]
        assert gate_cache["hits"] >= 1, gate_cache
        assert gate_cache["misses"] >= 1, gate_cache

        # /debug/stacks: bounded all-thread dump naming real threads.
        stacks = docs["/debug/stacks"]
        assert stacks["schema"] == 1 and stacks["threads"]
        names = {t["name"] for t in stacks["threads"]}
        assert any(n and "http-listener" in n for n in names), names
        for t in stacks["threads"]:
            assert len(t["frames"]) <= 32

        # /debug/config: resolved knobs, fingerprint, cache contents.
        config = docs["/debug/config"]
        for k in ("fingerprint", "session_cfg", "service_cfg", "ingress",
                  "breaker", "batch_buckets", "programs", "env_knobs"):
            assert k in config, k
        assert config["session_cfg"]["max_batch"] == 2

        # /healthz carries the capacity block.
        health = docs["/healthz"]
        assert "capacity" in health and "by_bucket" in health["capacity"]

        # /healthz identity block (graftfleet, DESIGN.md r20): the fleet
        # supervisor routes on these two top-level fields, so they are
        # pinned at the live wire — fingerprint_id must be the same id
        # /debug/config reports (a rolling deploy is detected by this
        # value changing across instances) and uptime_s must be a fresh
        # nonnegative monotonic age.
        assert health["fingerprint_id"] == config["fingerprint"], health
        assert isinstance(health["fingerprint_id"], str)
        assert len(health["fingerprint_id"]) == 12
        assert isinstance(health["uptime_s"], float)
        assert health["uptime_s"] >= 0.0, health

        # /healthz heal block (graftheal, DESIGN.md r22): the recovery
        # plane's probation state rides health — pacing knobs, the
        # per-rung half-open table, per-chip probation rows, MTTR.  On
        # a healthy default-ON instance the tables are empty and MTTR
        # has recorded nothing — absence, never a fabricated zero.
        heal = health["heal"]
        assert heal["enabled"] is True, heal
        assert heal["backoff_ms"] > 0, heal
        assert heal["backoff_max_ms"] >= heal["backoff_ms"], heal
        assert heal["flap_cap"] >= 1 and heal["window_ms"] > 0, heal
        assert heal["breaker"]["enabled"] is True, heal["breaker"]
        assert heal["breaker"]["half_open"] == {}, heal["breaker"]
        assert heal["chips"] == {}, heal["chips"]
        assert heal["mttr"] == {"last_s": None, "events": 0}, heal

        proc.send_signal(signal.SIGTERM)
        # communicate(), not wait(): the CLI prints its final /healthz
        # status document on drain, and an unread pipe could wedge it.
        proc.communicate(timeout=120)
        assert proc.returncode == 0, (
            f"CLI exited {proc.returncode} after SIGTERM drain")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # /fleet/healthz (graftheal satellite): every slot row carries its
    # live restart-budget position — ``restarts_spent`` and
    # ``budget_remaining`` — alongside the fleet-level ``heal`` block.
    # A 1-instance fleet over the same tiny recipe pins the schema
    # through the live fleet ingress.
    import tempfile

    from raft_stereo_tpu.serve.fleet import (FleetConfig, FleetFrontend,
                                             FleetSupervisor)
    fcfg = FleetConfig(
        instances=1, restart_budget=3, probe_ms=200.0,
        warmup_timeout_ms=600_000.0, drain_grace_ms=60_000.0,
        instance_args=("--no_canary", "--max_batch", "2",
                       "--valid_iters", "2", "--segments", "2",
                       "--n_gru_layers", "1",
                       "--hidden_dims", "32", "32", "32",
                       "--corr_levels", "2", "--corr_radius", "2",
                       "--corr_implementation", "reg"),
        instance_env={"JAX_PLATFORMS": "cpu"},
        cache_dir=tempfile.mkdtemp(prefix="gate-fleet-cache-"))
    with FleetSupervisor(fcfg) as fsup, FleetFrontend(fsup) as ffe:
        fraw = _get(f"http://{ffe.host}:{ffe.port}", "/fleet/healthz")
        assert len(fraw) <= 1 << 20, (
            f"/fleet/healthz body is {len(fraw)} bytes > its 1 MiB "
            f"bound")
        fdoc = json.loads(fraw)
        assert fdoc["restart_budget"] == 3, fdoc
        fheal = fdoc["heal"]
        assert fheal["enabled"] is True, fheal
        assert fheal["refill_ms"] > 0, fheal
        assert fheal["slot_relaunches_total"] == 0, fheal
        frows = fdoc["by_instance"]
        assert len(frows) == 1, frows
        row = frows[0]
        assert row["slot"] == 0, row
        # The first launch of a generation is free (budget charges
        # cover retries and replacements), so a fresh slot shows a full
        # budget.
        assert row["restarts_spent"] == 0, row
        assert row["budget_remaining"] == 3, row
        # The fleet rollup's recovery columns are present even before
        # any recovery happened — absence as None/0, never fabricated.
        assert fdoc["mttr_last_s"] is None, fdoc["mttr_last_s"]
        assert fdoc["heal_events"] == 0, fdoc["heal_events"]

    print(json.dumps({
        "metric": "debug_endpoints",
        "pass": True,
        "endpoint_bytes": sizes,
        "deck_recorded": ticks["recorded"],
        "tenants": list(usage["by_tenant"]),
        "stream": {"warm_joins": gate_stream["warm_joins"],
                   "converged_exits": gate_stream["converged_exits"]},
        "cache": {"hits": cache_block["hits"],
                  "entries": cache_block["entries"],
                  "tenant_hits": gate_cache["hits"]},
        "fleet": {"restart_budget": fdoc["restart_budget"],
                  "slot0_budget_remaining": row["budget_remaining"],
                  "heal_enabled": fheal["enabled"]},
    }))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(json.dumps({"metric": "debug_endpoints", "pass": False,
                          "error": str(e)}))
        raise SystemExit(1)

#!/usr/bin/env python
"""graftstream bench: cold-vs-warm iterations-to-convergence and fps on a
synthetic panning stereo sequence (ISSUE 13 acceptance; ROADMAP item 2).

Drives the REAL streaming stack — ``serve.StreamRunner`` over an
``InferenceSession``'s prepare/prepare_warm/advance/epilogue programs —
twice over the same sequence:

- **cold**: every frame served independently (the session reset between
  frames), convergence monitor armed — the per-frame
  iterations-to-convergence baseline;
- **warm**: one stream session across the sequence — each frame's
  1/8-res disparity seeds the next through ``prepare_warm``.

The gate asserts ``warm_iters_mean * 2 <= cold_iters_mean`` at the SAME
convergence tolerance (equal output-quality bar).  Iteration counts are
backend-independent, so this bar gates on CPU; wall-clock fps
(``warm_fps``/``cold_fps``) becomes meaningful on the driver's on-chip
run, where both land in TRAJECTORY.json via ``obs.trajectory``.

Why constructed params: a random-init update block is not a contraction
— its per-iteration delta-flow norm plateaus (measured ~1.4 px/iter
forever), so NO honest convergence measurement can distinguish warm from
cold on random weights.  :func:`tracker_params` surgically rewires the
update block into a genuine closed-loop matcher (delta_x = step *
tanh(gain * corr-centroid), a damped correction toward the correlation
peak) while leaving the architecture, the compiled programs and the
whole serving stack untouched — the dynamics are then real: cold frames
descend the full disparity, warm frames start near the fixed point and
converge in a fraction of the iterations.  This is the same stance as
``faults.py``'s injected faults: deterministic, synthetic, exercising
the REAL machinery.

One JSON line on stdout (bench.py's contract), exit 0/1.

Env:
  RAFT_STREAM_BENCH_FRAMES   sequence length (default 6)
  RAFT_STREAM_BENCH_TOL      convergence tolerance (default 0.08)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

H, W = 64, 96
DISP = 20          # true disparity, px full-res (2.5 px at 1/8 res)
PAN = 1            # horizontal pan, px full-res per frame
VALID_ITERS = 32
SEGMENTS = 8       # convergence checked every 4 iterations


def tracker_params(params, cfg, *, gain: float = 0.15, step: float = 0.3,
                   zbias: float = 30.0):
    """Rewire a random init into a correlation-centroid tracker.

    Path (n_gru_layers=1): convc1 computes ±(sum over level-l taps of
    ``2^l * offset * corr``) — the centroid of the correlation mass
    around the current coords, split into relu(+)/relu(-) channels;
    convc2/conv pass them through untouched; the flow path (convf1/2) is
    zeroed; the GRU is made memoryless (context cz bias -> z ~= 1,
    convq reads ``gain * centroid`` into hidden channel 0); the flow
    head emits ``delta_x = step * h0``.  Net per-iteration update:
    ``delta_x = step * tanh(gain * centroid)`` — a damped step toward
    the correlation peak, so the refinement genuinely contracts and the
    delta-flow norm genuinely decays.  The mask head keeps its random
    init (any mask is a valid convex-upsample after softmax)."""
    import jax.numpy as jnp
    import numpy as np

    def z(a):
        return jnp.zeros_like(a)

    r = cfg.corr_radius
    nt = 2 * r + 1
    hid = cfg.hidden_dims[2]
    ub = dict(params["update_block"])

    enc = {k: dict(v) for k, v in ub["encoder"].items()}
    w = np.zeros(np.asarray(enc["convc1"]["w"]).shape, np.float32)
    for lvl in range(cfg.corr_levels):
        for j in range(nt):
            w[0, 0, lvl * nt + j, 0] = (2 ** lvl) * (j - r)
            w[0, 0, lvl * nt + j, 1] = -(2 ** lvl) * (j - r)
    enc["convc1"] = {"w": jnp.asarray(w), "b": z(enc["convc1"]["b"])}
    w = np.zeros(np.asarray(enc["convc2"]["w"]).shape, np.float32)
    w[1, 1, 0, 0] = 1.0
    w[1, 1, 1, 1] = 1.0
    enc["convc2"] = {"w": jnp.asarray(w), "b": z(enc["convc2"]["b"])}
    enc["convf1"] = {"w": z(enc["convf1"]["w"]), "b": z(enc["convf1"]["b"])}
    enc["convf2"] = {"w": z(enc["convf2"]["w"]), "b": z(enc["convf2"]["b"])}
    w = np.zeros(np.asarray(enc["conv"]["w"]).shape, np.float32)
    w[1, 1, 0, 0] = 1.0
    w[1, 1, 1, 1] = 1.0
    enc["conv"] = {"w": jnp.asarray(w), "b": z(enc["conv"]["b"])}
    ub["encoder"] = enc

    g08 = {k: {"w": z(v["w"]), "b": z(v["b"])}
           for k, v in ub["gru08"].items()}
    wq = np.zeros(np.asarray(ub["gru08"]["convq"]["w"]).shape, np.float32)
    wq[1, 1, hid + 0, 0] = gain   # motion ch0 = relu(+centroid)
    wq[1, 1, hid + 1, 0] = -gain  # motion ch1 = relu(-centroid)
    g08["convq"]["w"] = jnp.asarray(wq)
    ub["gru08"] = g08

    fh = {}
    w = np.zeros(np.asarray(ub["flow_head"]["conv1"]["w"]).shape,
                 np.float32)
    w[1, 1, 0, 0] = 1.0
    w[1, 1, 0, 1] = -1.0
    fh["conv1"] = {"w": jnp.asarray(w),
                   "b": z(ub["flow_head"]["conv1"]["b"])}
    w = np.zeros(np.asarray(ub["flow_head"]["conv2"]["w"]).shape,
                 np.float32)
    w[1, 1, 0, 0] = step
    w[1, 1, 1, 0] = -step
    fh["conv2"] = {"w": jnp.asarray(w),
                   "b": z(ub["flow_head"]["conv2"]["b"])}
    ub["flow_head"] = fh

    out = dict(params)
    out["update_block"] = ub
    czr = []
    for conv in params["context_zqr_convs"]:
        b = np.zeros(np.asarray(conv["b"]).shape, np.float32)
        b[:hid] = zbias   # z = sigmoid(~30) ~= 1: memoryless GRU
        czr.append({"w": z(conv["w"]), "b": jnp.asarray(b)})
    out["context_zqr_convs"] = czr
    return out


def pan_sequence(n_frames: int, rng):
    """Piecewise-constant (8x8 blocks) random scene, true disparity DISP,
    panning PAN px/frame — smooth enough that the correlation landscape
    shifts gently between frames (the 'slowly-moving' workload)."""
    import numpy as np
    base = rng.uniform(
        0, 255, ((H + 16) // 8 + 2,
                 (W + DISP + PAN * n_frames + 16) // 8 + 2, 3))
    big = np.kron(base.astype(np.float32), np.ones((8, 8, 1), np.float32))
    frames = []
    for i in range(n_frames):
        s = i * PAN
        left = big[:H, s:s + W]
        right = big[:H, s + DISP:s + DISP + W]
        frames.append((left[None].copy(), right[None].copy()))
    return frames


def main() -> int:
    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.serve import (InferenceSession, SessionConfig,
                                       StreamRunner)

    n_frames = int(os.environ.get("RAFT_STREAM_BENCH_FRAMES", "6"))
    tol = float(os.environ.get("RAFT_STREAM_BENCH_TOL", "0.08"))

    cfg = RAFTStereoConfig(n_gru_layers=1, hidden_dims=(32, 32, 32),
                           corr_levels=2, corr_radius=4)
    params = tracker_params(
        init_raft_stereo(jax.random.PRNGKey(0), cfg), cfg)
    session = InferenceSession(params, cfg, SessionConfig(
        valid_iters=VALID_ITERS, segments=SEGMENTS, canary=False))

    rng = np.random.default_rng(3)
    frames = pan_sequence(n_frames, rng)

    # Warm the b=1 programs once so neither measured pass pays compiles.
    runner = StreamRunner(session, converge_tol=tol, converge_cold=True)
    runner.infer(*frames[0])
    runner.infer(*frames[1])
    runner.reset()

    # Cold pass: every frame independent, convergence armed (the equal
    # output-quality bar: same tolerance as the warm pass).
    cold_iters = []
    t0 = time.perf_counter()
    for left, right in frames:
        runner.reset()
        res = runner.infer(left, right)
        cold_iters.append(res.iters)
    cold_s = time.perf_counter() - t0

    # Warm pass: one stream session across the sequence; frames 2..N
    # warm-start from the held 1/8-res disparity.
    runner.reset()
    warm_iters = []
    qualities = []
    t0 = time.perf_counter()
    for left, right in frames:
        res = runner.infer(left, right)
        warm_iters.append(res.iters)
        qualities.append(res.quality)
    warm_s = time.perf_counter() - t0

    cold_mean = float(np.mean(cold_iters))
    warm_tail = warm_iters[1:]  # frame 1 is cold by definition
    warm_mean = float(np.mean(warm_tail))
    cold_fps = n_frames / cold_s
    warm_fps = n_frames / warm_s
    speedup = cold_mean / warm_mean if warm_mean else float("inf")
    ok = warm_mean * 2 <= cold_mean

    # graftrecall near tier (ISSUE 14 acceptance): near-duplicate warm
    # hits through the REAL ResponseCache — prime a corpus of cold
    # full-quality entries, then serve PERTURBED duplicates through the
    # real service admission path (near-tier signature match ->
    # prepare_warm seed -> convergence exit, labeled warm:cache:k) and
    # compare iterations-to-convergence against the same perturbed
    # frames served cold at the SAME tolerance.  Iteration counts are
    # backend-independent, so the >=2x bar gates on CPU.
    from raft_stereo_tpu.serve import ServiceConfig, StereoService
    from raft_stereo_tpu.serve.stream import stream_infer
    from raft_stereo_tpu.serve.validate import validate_pair

    NEAR_TOL = 6.0
    svc = StereoService(session, ServiceConfig(
        cache_bytes=64 << 20, cache_near_tol=NEAR_TOL,
        converge_tol=tol))
    cache = svc.cache
    for left, right in frames:
        lq, rq = validate_pair(left, right, session.cfg.admission)
        req = {"left": lq, "right": rq}
        cache.admit(req)
        # Strip any near seed the admit stamped: the corpus must be
        # COLD full-quality entries (deposit refuses warm-seeded ones).
        req.pop("_flow_init", None)
        req.pop("_cache_warm", None)
        out = stream_infer(session, lq, rq, prevalidated=True)
        req["_cache_flow"] = out.flow_low
        req["_cache_shape"] = out.padded_shape
        cache.deposit(req, {
            "status": "ok", "quality": out.result.quality,
            "disparity": out.result.disparity,
            "iters": out.result.iters})
    assert cache.status()["entries"] == n_frames, cache.status()

    rng_q = np.random.default_rng(5)
    queries = [(np.clip(left + rng_q.normal(0, 2, left.shape),
                        0, 255).astype(np.float32), right)
               for left, right in frames]
    near_iters = []
    near_quals = []
    t0 = time.perf_counter()
    for ln, rn in queries:
        r = svc.handle({"left": ln, "right": rn, "converge_tol": tol})
        assert r["status"] == "ok", r
        near_iters.append(r["iters"])
        near_quals.append(r["quality"])
    near_s = time.perf_counter() - t0
    for q, it in zip(near_quals, near_iters):
        assert str(q).startswith("warm:cache:"), (
            f"near query did not warm-start from the cache: {q}")
        assert int(str(q).rsplit(":", 1)[1]) == it, (
            f"dishonest near-hit label {q} vs iters {it}")
    svc_off = StereoService(session, ServiceConfig(cache_bytes=0))
    cold_q_iters = [svc_off.handle({"left": ln, "right": rn,
                                    "converge_tol": tol})["iters"]
                    for ln, rn in queries]
    near_mean = float(np.mean(near_iters))
    cold_q_mean = float(np.mean(cold_q_iters))
    near_speedup = cold_q_mean / near_mean if near_mean else float("inf")
    near_ok = near_mean * 2 <= cold_q_mean
    ok = ok and near_ok

    doc = {
        "metric": "bench_stream",
        "pass": bool(ok),
        "frames": n_frames,
        "tol": tol,
        "disp_px": DISP,
        "pan_px": PAN,
        "cold_iters": cold_iters,
        "warm_iters": warm_iters,
        "qualities": qualities,
        "cold_iters_mean": round(cold_mean, 3),
        "warm_iters_mean": round(warm_mean, 3),
        "iters_speedup": round(speedup, 3),
        "cold_fps": round(cold_fps, 3),
        "warm_fps": round(warm_fps, 3),
        # graftrecall near tier (serve/cache.py): perturbed duplicates
        # warm-started from the response cache vs the same frames cold.
        "near_cache_iters": near_iters,
        "near_cache_qualities": near_quals,
        "near_cache_iters_mean": round(near_mean, 3),
        "near_cache_cold_iters_mean": round(cold_q_mean, 3),
        "near_cache_speedup": round(near_speedup, 3),
        "near_cache_fps": round(len(queries) / near_s, 3),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    backend = jax.default_backend()
    extra = {"tol": tol, "frames": n_frames,
             "iters_speedup": doc["iters_speedup"],
             "cold_iters_mean": doc["cold_iters_mean"]}
    emit("stream_warm_iters_mean", warm_mean, "iters", backend=backend,
         source="scratch/bench_stream.py", extra=extra)
    emit("stream_warm_fps", warm_fps, "fps", backend=backend,
         source="scratch/bench_stream.py", extra=extra)
    emit("stream_cold_fps", cold_fps, "fps", backend=backend,
         source="scratch/bench_stream.py", extra=extra)
    emit("cache_near_iters_mean", near_mean, "iters", backend=backend,
         source="scratch/bench_stream.py",
         extra={"tol": tol, "near_tol": NEAR_TOL,
                "cold_iters_mean": doc["near_cache_cold_iters_mean"],
                "near_speedup": doc["near_cache_speedup"]})

    if not ok:
        print(f"FAIL: warm frames averaged {warm_mean:.1f} iters vs "
              f"cold {cold_mean:.1f} (near-cache {near_mean:.1f} vs "
              f"{cold_q_mean:.1f}) — less than the 2x bar",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Bisect the realtime-B16 device-time regression (129 -> 6.6 fps wall).

Variants: current | resize-fp32 monkeypatch | corr=reg. Device time via
profiler per one 16-frame dispatch.
"""
import sys; sys.path.insert(0, "/root/repo")
import os, glob, gzip, json
import numpy as np, jax, jax.numpy as jnp

variant = sys.argv[1] if len(sys.argv) > 1 else "current"

import raft_stereo_tpu.ops.resize as rz
if variant == "fused_b16":
    import raft_stereo_tpu.ops.pallas_stream as ps
    ps._batch_worthwhile = lambda t: True
if variant == "resize_fp32":
    _orig = rz.interp_align_corners
    def interp32(x, size):
        return _orig(x.astype(jnp.float32), size).astype(x.dtype)
    rz.interp_align_corners = interp32
    import raft_stereo_tpu.models.update as upd
    upd.interp_align_corners = interp32

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

corr = "reg" if variant == "corr_reg" else "reg_tpu"
cfg = RAFTStereoConfig(corr_implementation=corr, mixed_precision=True,
                       shared_backbone=True, n_downsample=3,
                       n_gru_layers=2, slow_fast_gru=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
b, h, w = 16, 384, 1248
rng = np.random.default_rng(0)
i1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
i2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)

@jax.jit
def fwd(p, a, bb):
    _, up = raft_stereo_forward(p, cfg, a, bb, iters=7, test_mode=True)
    return jnp.sum(up)

float(fwd(params, i1, i2)); float(fwd(params, i1, i2))
tdir = f"/tmp/trace_rt_{variant}"
os.system(f"rm -rf {tdir}")
with jax.profiler.trace(tdir):
    float(fwd(params, i1, i2))
files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
pids = {e["pid"]: e["args"]["name"] for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"}
tot = sum(e["dur"] for e in ev if e.get("ph") == "X" and "dur" in e
          and "TPU" in pids.get(e.get("pid"), "")
          and not str(e.get("name", "")).startswith(("jit_", "while")))
print(f"{variant}: device {tot/1e3:.1f} ms per 16-frame dispatch")

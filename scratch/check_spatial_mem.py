import sys; sys.path.insert(0, "/root/repo")
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.engine.steps import make_eval_step
from raft_stereo_tpu.parallel.mesh import make_mesh, shard_batch

cfg = RAFTStereoConfig(n_gru_layers=2)
params = init_raft_stereo(jax.random.key(0), cfg)
rng = np.random.default_rng(0)
for (h, w) in [(64, 64), (256, 128)]:
    i1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
    mesh = make_mesh(n_data=1, n_space=8)
    step_sp = make_eval_step(cfg, valid_iters=2, mesh=mesh)
    args_sp = shard_batch([i1, i2], mesh, spatial=True)
    sharded = step_sp.lower(params, *args_sp).compile().memory_analysis().temp_size_in_bytes
    step_1 = make_eval_step(cfg, valid_iters=2)
    single = step_1.lower(params, i1, i2).compile().memory_analysis().temp_size_in_bytes
    print(f"{h}x{w}: sharded={sharded/1e6:.2f}MB single={single/1e6:.2f}MB ratio={sharded/single:.3f}", flush=True)

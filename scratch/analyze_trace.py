import sys, glob, gzip, json, collections, re
tdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/trace_full"
files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
pids = {}
for e in ev:
    if e.get("ph") == "M" and e.get("name") == "process_name":
        pids[e["pid"]] = e["args"]["name"]

cat = collections.Counter()
top = collections.Counter()
total = 0.0
for e in ev:
    if e.get("ph") != "X" or "dur" not in e:
        continue
    if "TPU" not in pids.get(e.get("pid"), ""):
        continue
    name = str(e.get("name", ""))
    if name.startswith(("jit_", "while")):  # module/control wrappers double-count
        continue
    base = re.sub(r"[.\d]+$", "", name)
    cat[base] += e["dur"]
    top[name] += e["dur"]
    total += e["dur"]

print(f"device op total: {total/1e3:.1f} ms")
for name, dur in cat.most_common(15):
    print(f"{dur/1e3:9.2f} ms  {100*dur/total:5.1f}%  {name}")
print("\ntop individual ops:")
for name, dur in top.most_common(15):
    print(f"{dur/1e3:9.2f} ms  {name[:80]}")

import sys; sys.path.insert(0, "/root/repo")
import os, re
import jax, jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

cfg = RAFTStereoConfig(corr_implementation="reg_tpu", mixed_precision=True)
params = jax.eval_shape(lambda k: init_raft_stereo(k, cfg), jax.random.PRNGKey(0))
params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
h, w = 2016, 2976
i1 = jnp.zeros((1, h, w, 3), jnp.float32)
def fwd(p, a, b):
    _, up = raft_stereo_forward(p, cfg, a, b, iters=32, test_mode=True)
    return up
txt = jax.jit(fwd).lower(params, i1, i1).compile().as_text()
open("/tmp/hlo_full.txt", "w").write(txt)
# big copies / pads / bitcast-converts outside fusions
for m in re.finditer(r"^\s*(\S+) = (\S+\[[^\]]*\][^ ]*) (copy|pad|transpose|convert)\((.*?)\)", txt, re.M):
    name, shp, op, args = m.groups()
    nums = re.findall(r"\d+", shp.split("{")[0])
    import math
    n = math.prod(int(x) for x in nums) if nums else 0
    bytes_ = n * (2 if shp.startswith("bf16") else 4)
    if bytes_ > 50e6:
        print(f"{bytes_/1e6:8.0f} MB  {op:9s} {name:14s} {shp[:60]}")

import sys; sys.path.insert(0, "/root/repo")
import os
import time
import numpy as np, jax, jax.numpy as jnp
from raft_stereo_tpu.corr import make_corr_fn

B, H, W, D, iters = 1, 504, 744, 256, 16
rng = np.random.default_rng(0)
f1 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.bfloat16)
f2 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.bfloat16)
c0 = jnp.asarray(rng.uniform(0, W - 1, size=(B, H, W)), jnp.float32)

for tile in (128, 256, 512, 1024):
    # pallas_reg reads RAFT_CORR_TILE when each corr fn is built (trace
    # time) and keys its lookup cache by the tile, so same-process sweeps
    # just set the env var before make_corr_fn.
    os.environ["RAFT_CORR_TILE"] = str(tile)
    @jax.jit
    def run(c):
        fn = make_corr_fn("reg_tpu", f1, f2, num_levels=4, radius=4)
        def step(c, _):
            return c + 0.07, jnp.mean(fn(c))
        _, ys = jax.lax.scan(step, c, None, length=iters)
        return jnp.sum(ys)
    float(run(c0))
    t0 = time.perf_counter()
    float(run(c0))
    dt = time.perf_counter() - t0
    print(f"TILE={tile}: {dt*1000/iters:.2f} ms/lookup (wall, incl ~6ms tunnel/16)", flush=True)

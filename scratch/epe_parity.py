"""EPE parity evidence: torch reference vs JAX port, identical weights.

Published checkpoints are not fetchable in this image (zero egress), so the
protocol is: build the torch reference with its own random init (seed 1234),
transplant that exact state_dict through the shim, run BOTH frameworks on the
same synthetic pairs at 32 iters / validate-style resolution, and report the
disparity agreement. |EPE_ref - EPE_port| on any dataset is bounded by the
mean |d_ref - d_port| reported here.

Runs on CPU (torch side) + CPU JAX (same arithmetic class) to isolate
implementation parity from MXU precision.
"""
import sys
sys.path.insert(0, "/root/repo")
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import argparse, time
import numpy as np
import torch

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

sys.path.insert(0, "/root/reference")
from core.raft_stereo import RAFTStereo

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.transplant import transplant_state_dict

torch.set_num_threads(1)
ITERS = int(os.environ.get("EPE_ITERS", 32))
H, W = int(os.environ.get("EPE_H", 256)), int(os.environ.get("EPE_W", 512))
N_PAIRS = int(os.environ.get("EPE_PAIRS", 3))

defaults = dict(corr_implementation="reg", shared_backbone=False,
                corr_levels=4, corr_radius=4, n_downsample=2,
                slow_fast_gru=False, n_gru_layers=3,
                hidden_dims=[128, 128, 128], mixed_precision=False)
torch.manual_seed(1234)
model = RAFTStereo(argparse.Namespace(**defaults))
model.eval()
cfg = RAFTStereoConfig()
params = transplant_state_dict(model.state_dict(), cfg)

rng = np.random.default_rng(7)
deltas = []
for i in range(N_PAIRS):
    # Shifted-noise stereo pair: right image is the left translated a few px,
    # so the network has real structure to converge on.
    base = rng.uniform(0, 255, (1, 3, H, W + 32)).astype(np.float32)
    shift = int(rng.integers(4, 24))
    img1 = base[:, :, :, 32:]
    img2 = base[:, :, :, 32 - shift:-shift] if shift else img1
    with torch.no_grad():
        _, t_flow = model(torch.from_numpy(img1), torch.from_numpy(img2),
                          iters=ITERS, test_mode=True)
    t_disp = t_flow[0, 0].numpy()

    j1 = jnp.asarray(img1.transpose(0, 2, 3, 1))
    j2 = jnp.asarray(img2.transpose(0, 2, 3, 1))
    _, j_flow = raft_stereo_forward(params, cfg, j1, j2, iters=ITERS,
                                    test_mode=True)
    j_disp = np.asarray(j_flow)[0, :, :, 0]

    d = np.abs(t_disp - j_disp)
    deltas.append(d)
    print(f"pair {i}: shift={shift:2d}  ref_mean_disp={t_disp.mean():8.3f}  "
          f"port_mean_disp={j_disp.mean():8.3f}  max|d|={d.max():.4f}  "
          f"mean|d|={d.mean():.5f}", flush=True)

d = np.stack(deltas)
print(f"\n{ITERS} iters @ {H}x{W}, {N_PAIRS} pairs: "
      f"max|ddisp|={d.max():.4f} px, mean|ddisp|={d.mean():.5f} px, "
      f"p99.9|ddisp|={np.quantile(d, 0.999):.4f} px", flush=True)

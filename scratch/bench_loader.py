"""Data-pipeline throughput: samples/s through decode + augment + collate.

Fabricates a SceneFlow-shaped tree at real FlyingThings resolution (540x960),
then times StereoLoader with the reference train config (crop 320x720,
batch 6). The number to beat: the train step consumes 4.0 samples/s/chip
(BASELINE.md), so an 8-chip pod needs ~32 samples/s from the host pipeline.
"""

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from raft_stereo_tpu.data.datasets import SceneFlowDatasets
from raft_stereo_tpu.data.frame_utils import write_pfm
from raft_stereo_tpu.data.loader import StereoLoader

try:
    import cv2
    cv2.setNumThreads(0)
except ImportError:
    cv2 = None
from PIL import Image


def make_tree(root, n=24, h=540, w=960):
    rng = np.random.default_rng(0)
    base = os.path.join(root, "FlyingThings3D", "frames_cleanpass", "TRAIN",
                        "A", "0000")
    dbase = os.path.join(root, "FlyingThings3D", "disparity", "TRAIN", "A",
                         "0000")
    for cam in ("left", "right"):
        os.makedirs(os.path.join(base, cam), exist_ok=True)
        os.makedirs(os.path.join(dbase, cam), exist_ok=True)
    for i in range(n):
        for cam in ("left", "right"):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            Image.fromarray(img).save(
                os.path.join(base, cam, f"{i:07d}.png"))
        disp = rng.uniform(1, 60, (h, w)).astype(np.float32)
        write_pfm(os.path.join(dbase, "left", f"{i:07d}.pfm"), disp)


def main():
    aug_params = {"crop_size": [320, 720], "min_scale": -0.2, "max_scale": 0.4,
                  "do_flip": False, "yjitter": True}
    batch = int(os.environ.get("LOADER_BATCH", 6))
    workers = int(os.environ.get("LOADER_WORKERS", 4))
    n = int(os.environ.get("LOADER_N", 48))
    with tempfile.TemporaryDirectory() as root:
        make_tree(root, n=n)
        ds = SceneFlowDatasets(aug_params, root=os.path.join(
            root, "FlyingThings3D", ".."), dstype="frames_cleanpass",
            things_test=False)
        print(f"dataset: {len(ds)} samples")
        loader = StereoLoader(ds, batch_size=batch, num_workers=workers,
                              shuffle=True, drop_last=True)
        # warm epoch (page cache, lazy imports)
        for b in loader:
            pass
        t0 = time.perf_counter()
        nb = 0
        for b in loader:
            nb += 1
        dt = time.perf_counter() - t0
        sps = nb * batch / dt
        print(f"{nb} batches of {batch} in {dt:.2f}s -> "
              f"{sps:.1f} samples/s ({workers} workers)")
        # single-sample decomposition: decode vs augment
        t0 = time.perf_counter()
        for i in range(8):
            ds[i]
        print(f"per-sample (decode+augment): {(time.perf_counter()-t0)/8*1e3:.1f} ms")
        ds_noaug = SceneFlowDatasets(None, root=os.path.join(
            root, "FlyingThings3D", ".."), dstype="frames_cleanpass",
            things_test=False)
        t0 = time.perf_counter()
        for i in range(8):
            ds_noaug[i]
        print(f"per-sample (decode only):    {(time.perf_counter()-t0)/8*1e3:.1f} ms")


if __name__ == "__main__":
    main()

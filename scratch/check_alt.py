import sys; sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from raft_stereo_tpu.corr import make_corr_fn

rng = np.random.default_rng(0)
for (B, H, W, D) in [(2, 6, 32, 16), (1, 4, 376, 32)]:
    f1 = jnp.asarray(rng.standard_normal((B, H, W, D), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((B, H, W, D), dtype=np.float32))
    coords = jnp.asarray(rng.uniform(-8, W + 6, size=(B, H, W)).astype(np.float32))
    reg = make_corr_fn("reg", f1, f2, num_levels=4, radius=4)(coords)
    alt = make_corr_fn("alt_tpu", f1, f2, num_levels=4, radius=4)(coords)
    print(f"W={W}:", np.abs(np.asarray(alt) - np.asarray(reg)).max(), flush=True)
    def loss(f1, f2, impl):
        fn = make_corr_fn(impl, f1, f2, num_levels=4, radius=4)
        return jnp.mean(fn(coords) ** 2)
    gr = jax.grad(loss, (0, 1))(f1, f2, "reg")
    gt = jax.grad(loss, (0, 1))(f1, f2, "alt_tpu")
    print("  grad diff:", max(float(jnp.abs(a - b).max()) for a, b in zip(gr, gt)), flush=True)

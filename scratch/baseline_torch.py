"""Time the torch reference forward on CPU (no GPU exists in this image).

Protocol: reference evaluate_stereo.py:77-81 — time model(image1, image2,
iters=32, test_mode=True); here warmup is one small-shape run (no jit/caching
effects on CPU beyond first-touch allocs). CPU-labeled datum for BASELINE.md.
"""
import sys, time, json
sys.path.insert(0, "/root/reference")
import argparse
import numpy as np
import torch

torch.set_num_threads(1)  # the image has 1 core

from core.raft_stereo import RAFTStereo

args = argparse.Namespace(corr_implementation="reg", shared_backbone=False,
                          corr_levels=4, corr_radius=4, n_downsample=2,
                          slow_fast_gru=False, n_gru_layers=3,
                          hidden_dims=[128, 128, 128], mixed_precision=False)
torch.manual_seed(1234)
model = RAFTStereo(args)
model.eval()

rng = np.random.default_rng(0)

def run(h, w, iters=32, frames=1, label=""):
    times = []
    for _ in range(frames):
        i1 = torch.from_numpy(rng.uniform(0, 255, (1, 3, h, w)).astype(np.float32))
        i2 = torch.from_numpy(rng.uniform(0, 255, (1, 3, h, w)).astype(np.float32))
        t0 = time.perf_counter()
        with torch.no_grad():
            out = model(i1, i2, iters=iters, test_mode=True)
        dt = time.perf_counter() - t0
        times.append(dt)
        print(f"{label} {h}x{w} iters={iters}: {dt:.1f}s "
              f"({1/dt:.4f} fps) checksum={float(out[1].sum()):.3f}", flush=True)
    return times

run(64, 96, iters=2, frames=1, label="warmup")
run(512, 736, frames=1, label="mid")
run(1024, 1504, frames=1, label="kittiish")
run(2016, 2976, frames=2, label="middlebury_F")

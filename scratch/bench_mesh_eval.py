"""Batched sharded eval WITH the Pallas kernels under a mesh, on the chip.

BASELINE.json config 4 is batched eval over a data mesh; r3 could not
measure it with the kernels (space/data meshes stripped them). r4's
partitioned kernels make it well-defined: this runs make_eval_step under
the chip's degenerate data=1 mesh (the same partitioned-kernel code path
the 8-way CPU-mesh equality tests pin) at KITTI-ish shape, batched.

  MESH_BATCH (default 8), MESH_H/W (384x1248), MESH_ITERS (32)
"""
import sys; sys.path.insert(0, "/root/repo")
import os, time
import numpy as np
import jax, jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.steps import make_eval_step
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.parallel.mesh import make_mesh, shard_batch

b = int(os.environ.get("MESH_BATCH", 8))
h = int(os.environ.get("MESH_H", 384))
w = int(os.environ.get("MESH_W", 1248))
iters = int(os.environ.get("MESH_ITERS", 32))

cfg = RAFTStereoConfig(corr_implementation="reg_tpu", mixed_precision=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
mesh = make_mesh(n_data=len(jax.devices()))
step = make_eval_step(cfg, valid_iters=iters, mesh=mesh)

rng = np.random.default_rng(0)
im1 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
im2 = jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)), jnp.float32)
im1, im2 = shard_batch([im1, im2], mesh)

def run():
    _, up = step(params, im1, im2)
    return float(jnp.sum(up.astype(jnp.float32)))

run(); run()  # compile + steady state
t0 = time.perf_counter()
n = 4
cs = [run() for _ in range(n)]
dt = (time.perf_counter() - t0) / n
print({"mesh": dict(mesh.shape), "batch": b, "shape": f"{h}x{w}",
       "iters": iters, "wall_fps_per_chip": round(b / dt / len(jax.devices()), 2),
       "checksum": round(cs[-1], 1)})

#!/usr/bin/env python
"""Lock-order witness gate (graftlock runtime half, DESIGN.md r23).

Re-runs the chaos serve soak (scratch/chaos_serve.py main(), the same
seeded fault storm the gate's soak step runs) with every
``threading.Lock``/``RLock`` minted inside raft_stereo_tpu/ wrapped by
the :class:`LockWitness`, then asserts every OBSERVED nested
acquisition maps to an edge of the static lock-order graph — the graph
``LOCK_ORDER.md`` is rendered from.  A violation here means the static
model missed a real runtime ordering (or the code acquires against the
manifest), which is exactly the gap a lock-order manifest must not
have.

The soak must itself pass: a witness run over a crashed battery proves
nothing.  One JSON verdict line on stdout; exit 0 iff the soak passed
AND no unexplained edges.

Knobs: RAFT_WITNESS_N (requests for this re-run, default 80 — smaller
than the soak step's 200; the lock topology saturates within the first
few dozen requests) plus chaos_serve.py's own RAFT_CHAOS_* envs.
"""

import io
import json
import os
import sys
from contextlib import redirect_stdout

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))


def main() -> int:
    os.environ.setdefault("RAFT_CHAOS_N",
                          os.environ.get("RAFT_WITNESS_N", "80"))
    from raft_stereo_tpu.analysis.concurrency.witness import (
        LockWitness, package_model, unexplained_edges)

    # The soak imports jax/serve lazily inside main(), so arming the
    # witness FIRST wraps every serving-plane lock.  jax-internal locks
    # minted under a repo frame resolve to no declaration and are
    # skipped at check time.
    import chaos_serve
    soak_out = io.StringIO()
    with LockWitness() as witness:
        try:
            with redirect_stdout(soak_out):
                soak_rc = chaos_serve.main()
        except BaseException as e:  # noqa: BLE001 - verdict must emit
            soak_rc = 98
            print(f"witness: soak raised {type(e).__name__}: {e}",
                  file=sys.stderr)

    model = package_model()
    violations = unexplained_edges(witness, model)
    mapped = sum(1 for s, d in witness.edges
                 if model.decl_at(*s) is not None
                 and model.decl_at(*d) is not None)
    verdict = {
        "witness_ok": soak_rc == 0 and not violations,
        "soak_rc": soak_rc,
        "observed_edges": len(witness.edges),
        "mapped_edges": mapped,
        "violations": violations,
    }
    try:
        verdict["soak"] = json.loads(
            soak_out.getvalue().strip().splitlines()[-1])
    except (IndexError, ValueError):
        verdict["soak"] = soak_out.getvalue()[-2000:]
    print(json.dumps(verdict, indent=2, sort_keys=True))
    return 0 if verdict["witness_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Release-gate fleet chaos storm (graftfleet, DESIGN.md r20) against
REAL ``serve_stereo.py`` subprocesses.

Stands up a 2-instance fleet (tiny random-weight model — wiring, not
quality) behind the in-process :class:`FleetSupervisor` +
:class:`FleetFrontend`, then pushes mixed traffic through the fleet
ingress while the two advertised failure drills fire mid-storm:

1. ``kill -9`` of the instance currently PINNED to the storm's stream
   session — the very next frames must be answered structurally (the
   router's one-peer retry), the session must resume WARM on the
   surviving instance, and the supervisor must replace the corpse under
   the restart budget;
2. a rolling deploy that changes ``--valid_iters`` (and therefore the
   run fingerprint) WHILE a background traffic thread keeps posting —
   generation must advance with zero unstructured responses and the
   stream session must hand off to the new generation warm;
3. (graftheal) a slot whose restart budget is EXHAUSTED is killed raw —
   it must degrade (no relaunch, budget pinned at zero on
   ``/fleet/healthz``), then the refill decay clock refunds a charge
   and the next ``poke()`` must run the probation relaunch: exactly one
   handshake-verified launch, the slot back to ready, and
   ``raft_heal_slot_relaunches_total`` booked.

The storm then quiesces and settles the books: for every live instance,
the fleet router's ``answered`` count for that uid must EXACTLY equal
the instance's own ``raft_requests_total`` disposition sum from its
``/healthz`` (the ``degraded`` key is excluded — service._count rides
it ALONGSIDE the disposition key for any non-"full" quality answer, so
summing it would double-count cache hits and warm frames).

One JSON line on stdout (bench.py's contract), exit 0/1.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

H, W = 40, 60

#: Tiny random-weight instance recipe (same as check_debug_endpoints).
INSTANCE_ARGS = (
    "--no_canary", "--max_batch", "2",
    "--valid_iters", "2", "--segments", "2",
    "--n_gru_layers", "1", "--hidden_dims", "32", "32", "32",
    "--corr_levels", "2", "--corr_radius", "2",
    "--corr_implementation", "reg",
)
#: The rolling-deploy recipe: --corr_radius 2 -> 1 is a MODEL-config
#: change, so it lands in config_fingerprint (valid_iters would not —
#: per-request iteration counts ride the cache key, not the
#: fingerprint) and the roll is visible as a fingerprint_id flip on
#: /healthz.
ROLLED_ARGS = tuple("1" if INSTANCE_ARGS[i - 1] == "--corr_radius"
                    else a for i, a in enumerate(INSTANCE_ARGS))

STREAM_SESSION = "storm-cam"


def main() -> int:
    import numpy as np

    from raft_stereo_tpu.serve import wire
    from raft_stereo_tpu.serve.fleet import (FleetConfig, FleetFrontend,
                                             FleetSupervisor)

    rng = np.random.default_rng(0)
    left = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
    right = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
    seq = iter(range(1, 1 << 20))

    def frame_body(fid, l_arr):
        return wire.build_multipart(
            {"left": wire.encode_image_png(l_arr),
             "right": wire.encode_image_png(right),
             "id": fid.encode()})

    def perturbed():
        # Every perturbed frame is DISTINCT bytes (instances share one
        # RAFT_CACHE_DIR — a repeated body would exact-hit instead of
        # exercising the warm-join path).
        noise = np.random.default_rng(1000 + next(seq)).integers(
            -2, 3, left.shape)
        return np.clip(left.astype(np.int16) + noise,
                       0, 255).astype(np.uint8)

    ledger = []
    ledger_lock = threading.Lock()

    cache_dir = tempfile.mkdtemp(prefix="chaos-fleet-cache-")
    cfg = FleetConfig(
        instances=2, restart_budget=3, probe_ms=200.0,
        warmup_timeout_ms=600_000.0, drain_grace_ms=120_000.0,
        instance_args=INSTANCE_ARGS,
        instance_env={"JAX_PLATFORMS": "cpu"},
        cache_dir=cache_dir)

    with FleetSupervisor(cfg) as sup, FleetFrontend(sup) as fe:
        endpoint = f"http://{fe.host}:{fe.port}"

        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        def post(kind, body_pair, session=None):
            """One storm request through the fleet ingress.  Returns the
            decoded response and appends a ledger row; an UNstructured
            response (non-JSON error body, dangling socket) is the storm
            failure the gate exists to catch."""
            ct, body = body_pair
            headers = {"Content-Type": ct, "X-Raft-Tenant": "storm"}
            if session:
                headers["X-Raft-Session"] = session
            req = Request(endpoint + "/v1/stereo", data=body,
                          method="POST", headers=headers)
            structured, doc, status = False, None, None
            try:
                try:
                    with urlopen(req, timeout=600) as resp:
                        status, raw = resp.status, resp.read()
                except HTTPError as e:
                    status, raw = e.code, e.read()
                if status == 200:
                    doc = wire.decode_response(raw)
                    structured = doc.get("status") == "ok"
                else:
                    doc = json.loads(raw.decode())
                    structured = (doc.get("status") in
                                  ("rejected", "error")
                                  and "code" in doc)
            except Exception as e:  # noqa: BLE001 — the ledger judges
                doc = {"transport_error": repr(e)}
            with ledger_lock:
                ledger.append({"kind": kind, "status": status,
                               "structured": structured,
                               "ok": bool(doc) and
                               doc.get("status") == "ok"})
            return doc

        def pinned_instance():
            # Internal read on purpose: the gate pins the HANDOFF
            # contract, which lives in the affinity table.
            uid = sup._affinity.get(STREAM_SESSION)
            for inst in sup._slots:
                if inst is not None and inst.uid == uid:
                    return inst
            return None

        def settle(want_ready, timeout_s=600.0):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                sup.poke()
                doc = sup.status()
                if (doc["states"].get("ready", 0) == want_ready
                        and doc["degraded_slots"] == 0):
                    return doc
                time.sleep(0.2)
            raise AssertionError(
                f"fleet did not settle to {want_ready} ready instances")

        # -- phase 1: mixed warm traffic ------------------------------
        cold0 = frame_body("storm-cold-0", perturbed())
        assert post("cold", cold0)["status"] == "ok"
        assert post("cold", frame_body("storm-cold-1",
                                       perturbed()))["status"] == "ok"
        for i in range(3):
            r = post("stream", frame_body(f"storm-s{i}", perturbed()),
                     session=STREAM_SESSION)
            assert r["status"] == "ok", r
        # dup: exact repeat of cold0's bytes (the graftrecall tier rides
        # the shared cache dir; the gate only requires a structured ok).
        assert post("dup", cold0)["status"] == "ok"

        doc = settle(want_ready=2)
        fp_before = doc["fingerprints"]
        assert len(fp_before) == 1, fp_before

        # -- phase 2: kill -9 the pinned instance ---------------------
        victim = pinned_instance()
        assert victim is not None, "stream session never pinned"
        # Raw SIGKILL on the child — NOT FleetInstance.kill(), which
        # would tidy the supervisor's own state first.  The router must
        # discover the corpse the hard way (connection refused).
        victim.proc.kill()
        victim.proc.wait(timeout=30)
        # The very next frames must be answered structurally through
        # the surviving peer (router retry), session re-pinned.
        for i in range(2):
            r = post("stream", frame_body(f"storm-k{i}", perturbed()),
                     session=STREAM_SESSION)
            assert r["status"] == "ok", r
        assert post("cold", frame_body("storm-cold-2",
                                       perturbed()))["status"] == "ok"
        survivor = pinned_instance()
        assert survivor is not None and survivor.uid != victim.uid
        # Zero dropped sessions: the session resumed WARM on the peer.
        sup.poke()
        sdoc = survivor.last_doc
        assert sdoc["stream"]["sessions"] >= 1, sdoc["stream"]
        assert sdoc["stream"]["warm_joins"] >= 1, sdoc["stream"]
        # The supervisor replaces the corpse under the restart budget.
        doc = settle(want_ready=2)
        assert doc["counters"]["restarts_total"] >= 1, doc["counters"]

        # -- phase 3: rolling deploy under live traffic ---------------
        stop = threading.Event()

        def background_traffic():
            i = 0
            while not stop.is_set():
                post("roll-cold", frame_body(f"storm-r{next(seq)}",
                                             perturbed()))
                if i % 2 == 0:
                    post("roll-stream",
                         frame_body(f"storm-rs{next(seq)}", perturbed()),
                         session=STREAM_SESSION)
                i += 1

        storm = threading.Thread(target=background_traffic,
                                 name="chaos-storm-traffic")
        storm.start()
        try:
            report = sup.deploy(instance_args=ROLLED_ARGS)
        finally:
            stop.set()
            storm.join(timeout=600)
        assert not storm.is_alive(), "storm traffic thread wedged"
        assert report["completed"], report
        assert report["generation"] == 2, report
        assert all(s["rolled"] for s in report["slots"]), report

        # Fingerprint flipped fleet-wide; old generation fully drained.
        doc = settle(want_ready=2)
        fp_after = doc["fingerprints"]
        assert len(fp_after) == 1, fp_after
        assert fp_after != fp_before, (fp_before, fp_after)
        assert doc["counters"]["draining_total"] >= 2, doc["counters"]

        # The stream session survived the roll too — warm on gen 2.
        for i in range(2):
            r = post("stream", frame_body(f"storm-g2-{i}", perturbed()),
                     session=STREAM_SESSION)
            assert r["status"] == "ok", r
        sup.poke()
        pinned = pinned_instance()
        assert pinned is not None, "session lost across the roll"
        pdoc = pinned.last_doc
        assert pdoc["fingerprint_id"] == fp_after[0], pdoc
        assert pdoc["stream"]["warm_joins"] >= 1, pdoc["stream"]

        # -- phase 4: quiesce and settle the books --------------------
        # Every storm response was read to completion above, so the
        # router's `answered` is final; one probe pass refreshes each
        # instance's own /healthz document.
        sup.poke()
        final = sup.status()
        books = final["books"]
        reconciliation = {}
        for row in final["by_instance"]:
            if row["state"] != "ready":
                continue
            reqs = row.get("requests", {})
            # `degraded` rides ALONGSIDE the disposition key (see
            # module docstring) — exclude it from the disposition sum.
            instance_total = sum(n for k, n in reqs.items()
                                 if k != "degraded")
            fleet_answered = books[row["uid"]]["answered"]
            reconciliation[row["uid"]] = {
                "instance_requests_total": instance_total,
                "fleet_answered": fleet_answered}
            assert instance_total == fleet_answered, (
                f"books for {row['uid']} do not reconcile: instance "
                f"counted {instance_total}, router answered "
                f"{fleet_answered}")
        assert reconciliation, "no live instances to reconcile"

        # -- phase 5: graftheal — the slot rung of the recovery plane --
        # Exhaust one slot's restart budget at the ledger (the charge
        # arithmetic itself is pinned in tests/test_fleet.py) and kill
        # its instance raw: with zero budget remaining the supervisor
        # must DEGRADE the slot, not relaunch it.
        heal_slot, veteran = next(
            (i, inst) for i, inst in enumerate(sup._slots)
            if inst is not None)
        with sup._lock:
            sup._spent[heal_slot] = sup.restart_budget
            sup._refill_last[heal_slot] = time.monotonic()
        veteran.proc.kill()
        veteran.proc.wait(timeout=30)
        deadline = time.monotonic() + 30.0
        while True:
            sup.poke()
            doc = sup.status()
            row = next(r for r in doc["by_instance"]
                       if r.get("slot") == heal_slot)
            if row.get("state") == "degraded":
                break
            assert time.monotonic() < deadline, (
                f"slot {heal_slot} never degraded on an exhausted "
                f"restart budget: {row}")
            time.sleep(0.1)
        assert doc["degraded_slots"] == 1, doc
        # Satellite pin: the per-slot budget ledger is visible on the
        # fleet health document, spent == budget, nothing remaining.
        assert row["restarts_spent"] == sup.restart_budget, row
        assert row["budget_remaining"] == 0, row
        assert doc["heal"]["enabled"] is True, doc["heal"]
        relaunches0 = doc["heal"]["slot_relaunches_total"]
        # Now let the decay clock refund one charge: shrink the refill
        # interval so a fraction of a second of real time covers one
        # whole interval, and the next poke() pass must spend it on a
        # single handshake-verified probation relaunch.
        sup.refill_s = 0.3
        time.sleep(0.4)
        doc = settle(want_ready=2)
        row = next(r for r in doc["by_instance"]
                   if r.get("slot") == heal_slot)
        assert row["state"] == "ready", row
        assert doc["heal"]["slot_relaunches_total"] >= relaunches0 + 1, (
            doc["heal"])
        # Serving resumed through the fleet ingress over the healed
        # fleet — a structured ok is the whole requirement.
        r = post("healed", frame_body(
            f"storm-heal-{next(seq)}", perturbed()))
        assert r["status"] == "ok", r
        heal_report = {
            "slot": heal_slot,
            "degraded_observed": True,
            "restarts_spent_at_degrade": sup.restart_budget,
            "slot_relaunches_total":
                doc["heal"]["slot_relaunches_total"],
            "refill_ms": doc["heal"]["refill_ms"],
        }

        with ledger_lock:
            total = len(ledger)
            unstructured = [r for r in ledger if not r["structured"]]
            ok = sum(1 for r in ledger if r["ok"])
        assert total >= 12, f"storm too small ({total} requests)"
        assert not unstructured, (
            f"{len(unstructured)}/{total} responses were not "
            f"structured: {unstructured[:5]}")

        # Re-read after phase 5 so restarts_total includes the
        # probation relaunch.
        counters = sup.status()["counters"]

    print(json.dumps({
        "metric": "chaos_fleet",
        "pass": True,
        "requests": {"total": total, "ok": ok,
                     "structured": total - len(unstructured)},
        "kill": {"victim": victim.uid,
                 "restarts_total": counters["restarts_total"]},
        "deploy": {"completed": True, "generation": 2,
                   "fingerprint_before": fp_before[0],
                   "fingerprint_after": fp_after[0]},
        "heal": heal_report,
        "reconciliation": reconciliation,
        "counters": counters,
    }))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(json.dumps({"metric": "chaos_fleet", "pass": False,
                          "error": str(e)}))
        raise SystemExit(1)

"""Probe Pallas TPU behaviors needed for streaming conv kernels:
1. index_map with jnp.minimum / python arithmetic
2. grid longer than the array's block count (flush step) — does the OOB
   input block index clamp?
3. scratch persistence across grid steps (ring buffers)
"""
import sys; sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TH, W, NB = 8, 128, 4
H = TH * NB


def kernel(x_ref, o_ref, ring):
    i = pl.program_id(0)
    # write out block i = ring (previous step's input); step 0 writes zeros
    @pl.when(i == 0)
    def _():
        ring[:] = jnp.zeros_like(ring)
    o_ref[:] = ring[:]
    ring[:] = x_ref[:]


x = jnp.arange(H * W, dtype=jnp.float32).reshape(H, W)

out = pl.pallas_call(
    kernel,
    grid=(NB + 1,),
    in_specs=[pl.BlockSpec((TH, W), lambda i: (jnp.minimum(i, NB - 1), 0),
                           memory_space=pltpu.VMEM)],
    out_specs=pl.BlockSpec((TH, W), lambda i: (i, 0),
                           memory_space=pltpu.VMEM),
    out_shape=jax.ShapeDtypeStruct((H + TH, W), jnp.float32),
    scratch_shapes=[pltpu.VMEM((TH, W), jnp.float32)],
)(x)
out = np.asarray(out)
print("jnp.minimum index_map ok; lag-write correct:",
      np.array_equal(out[TH:], np.asarray(x)))

# 2: OOB index without clamping
try:
    out2 = pl.pallas_call(
        kernel,
        grid=(NB + 1,),
        in_specs=[pl.BlockSpec((TH, W), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((TH, W), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((H + TH, W), jnp.float32),
        scratch_shapes=[pltpu.VMEM((TH, W), jnp.float32)],
    )(x)
    out2 = np.asarray(out2)
    print("OOB input index ran; lag-write correct:",
          np.array_equal(out2[TH:], np.asarray(x)))
except Exception as e:
    print("OOB input index failed:", type(e).__name__, str(e)[:120])

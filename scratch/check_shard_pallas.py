import sys; sys.path.insert(0, "/root/repo")
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.evaluate import make_eval_forward
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.parallel import make_mesh

rng = np.random.default_rng(0)
img1 = rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32)
img2 = rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32)
mesh = make_mesh(n_data=1, n_space=8)
for impl in ("reg_tpu", "alt_tpu"):
    cfg = RAFTStereoConfig(n_gru_layers=1, corr_implementation=impl)
    params = init_raft_stereo(jax.random.key(0), cfg)
    try:
        fwd = make_eval_forward(params, cfg, iters=2, mesh=mesh)
        out, _ = fwd(img1, img2)
        print(impl, "OK", out.shape, flush=True)
    except Exception as e:
        print(impl, "FAILED:", str(e)[:200].replace("\n", " "), flush=True)

"""Sweep the reg_tpu lookup kernel's TILE (pixels per grid cell) on chip.

Isolated lookup at the headline 1/4-res shape (504 x 744, D=256-channel
fmaps, bf16 pyramid, 4 levels r=4), 8 lookups in a scan; device time from
the profiler trace (wall clock is tunnel-dominated). Each TILE value runs
in a fresh subprocess for clean per-tile profiler traces; the kernel reads
RAFT_CORR_TILE when each corr fn is built (pallas_reg.corr_tile — the
lookup cache is keyed by the tile), so same-process sweeps would also work.
Results recorded in BASELINE.md.
"""
import json
import os
import subprocess
import sys

CHILD = r'''
import glob, gzip, json, os, sys
import numpy as np
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
from raft_stereo_tpu.corr import make_corr_fn

h, w, d = 504, 744, 256
rng = np.random.default_rng(0)
f1 = jnp.asarray(rng.standard_normal((1, h, w, d)), jnp.bfloat16)
f2 = jnp.asarray(rng.standard_normal((1, h, w, d)), jnp.bfloat16)
corr_fn = make_corr_fn("reg_tpu", f1, f2, num_levels=4, radius=4,
                       out_dtype=jnp.bfloat16)

@jax.jit
def run(c0):
    def step(c, _):
        out = corr_fn(c)
        return c + 0.25, jnp.sum(out.astype(jnp.float32))
    _, ys = jax.lax.scan(step, c0, None, length=8)
    return jnp.sum(ys)

c0 = jnp.asarray(rng.uniform(0, w, (1, h, w)), jnp.float32)
float(run(c0))  # compile + warm
trace_dir = "/tmp/sweep_tile_trace"
import shutil; shutil.rmtree(trace_dir, ignore_errors=True)
with jax.profiler.trace(trace_dir):
    float(run(c0))
files = sorted(glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True))
ev = json.load(gzip.open(files[-1]))["traceEvents"]
pids = {e["pid"]: e["args"]["name"] for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"}
total = lookup = 0.0
for e in ev:
    if e.get("ph") == "X" and "dur" in e and "TPU" in pids.get(e.get("pid"), ""):
        n = str(e.get("name", ""))
        if n.startswith(("jit_", "while")):
            continue
        total += e["dur"]
        if n.startswith("closed_call"):
            lookup += e["dur"]
print(json.dumps({"tile": int(os.environ["RAFT_CORR_TILE"]),
                  "lookup_ms_per_call": round(lookup / 8 / 1000, 3),
                  "total_ms": round(total / 1000, 2)}))
'''

results = []
for tile in (1024, 2048, 4096):
    env = dict(os.environ, RAFT_CORR_TILE=str(tile))
    try:
        out = subprocess.run([sys.executable, "-c", CHILD], env=env,
                             capture_output=True, text=True, timeout=1500)
    except subprocess.TimeoutExpired:
        print(f"tile {tile} TIMEOUT", flush=True)
        continue
    line = [l for l in out.stdout.splitlines() if l.startswith("{")]
    if line:
        results.append(json.loads(line[-1]))
        print(results[-1], flush=True)
    else:
        print(f"tile {tile} FAILED:", out.stderr[-500:], flush=True)
print(json.dumps(results))

import sys; sys.path.insert(0, "/root/repo")
import os, re
import numpy as np, jax, jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

h, w = int(os.environ.get("H", 2016)), int(os.environ.get("W", 2976))
corr = os.environ.get("CORR", "reg_tpu")
cfg = RAFTStereoConfig(corr_implementation=corr, mixed_precision=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)

def forward(params, image1, image2):
    _, flow_up = raft_stereo_forward(params, cfg, image1, image2,
                                     iters=32, test_mode=True)
    return flow_up, jnp.sum(flow_up)

img = jnp.zeros((1, h, w, 3), jnp.float32)
lowered = jax.jit(forward).lower(params, img, img)
txt = lowered.compile().as_text()
open("/tmp/hlo_full.txt", "w").write(txt)
print("bytes:", len(txt))
for name in sys.argv[1:]:
    for line in txt.splitlines():
        if f"%{name} " in line or f" {name} =" in line or line.strip().startswith(name + " ="):
            print(line.strip()[:300]); break

"""Quantify bf16 (mixed-precision, reg_tpu) vs fp32 (reg) disparity drift
on the real chip with transplanted reference weights — the precision cost
of the kernel/bf16 trades, measured end-to-end at 32 iters.
"""
import sys; sys.path.insert(0, "/root/repo")
import numpy as np
import torch, argparse
sys.path.insert(0, "/root/reference")
from core.raft_stereo import RAFTStereo
import jax.numpy as jnp
from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.transplant import transplant_state_dict

defaults = dict(corr_implementation="reg", shared_backbone=False,
                corr_levels=4, corr_radius=4, n_downsample=2,
                slow_fast_gru=False, n_gru_layers=3,
                hidden_dims=[128, 128, 128], mixed_precision=False)
torch.manual_seed(1234)
model = RAFTStereo(argparse.Namespace(**defaults))
params = transplant_state_dict(model.state_dict(), RAFTStereoConfig())

rng = np.random.default_rng(11)
H, W = 384, 512
deltas = []
for i in range(2):
    base = rng.uniform(0, 255, (1, H, W + 32, 3)).astype(np.float32)
    shift = int(rng.integers(4, 24))
    j1 = jnp.asarray(base[:, :, 32:, :])
    j2 = jnp.asarray(base[:, :, 32 - shift:-shift, :])
    outs = {}
    for label, cfg in (
            ("fp32_reg", RAFTStereoConfig()),
            ("bf16_reg_tpu", RAFTStereoConfig(corr_implementation="reg_tpu",
                                              mixed_precision=True)),
            ("bf16_alt_tpu", RAFTStereoConfig(corr_implementation="alt_tpu",
                                              mixed_precision=True))):
        _, up = raft_stereo_forward(params, cfg, j1, j2, iters=32,
                                    test_mode=True)
        outs[label] = np.asarray(up)[0, :, :, 0]
    for label in ("bf16_reg_tpu", "bf16_alt_tpu"):
        d = np.abs(outs[label] - outs["fp32_reg"])
        print(f"pair {i} shift={shift:2d} {label}: mean|dd|={d.mean():.4f} "
              f"p99|dd|={np.quantile(d, 0.99):.4f} max|dd|={d.max():.4f} "
              f"(mean disp {np.abs(outs['fp32_reg']).mean():.2f})", flush=True)

"""Spatially-sharded fused eval on the real chip (VERDICT r4 item 7).

The halo shard_map GRU variants were CPU-tested only; this runs one
spatially-sharded eval step on hardware (space=1 degenerate on the single
chip — same code path, shard_map + ppermute halos compiled by the real
Mosaic/XLA stack) and records COMPILE TIME, answering the r3 concern that
Mosaic kernels under meshes explode compile time.

  SPATIAL_N (default 1 — the chip), SPATIAL_H/W (384x1248), SPATIAL_ITERS.
"""
import sys; sys.path.insert(0, "/root/repo")
import os, time
import numpy as np
import jax, jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.steps import make_eval_step
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.parallel.mesh import make_mesh, shard_batch

ns = int(os.environ.get("SPATIAL_N", 1))
h = int(os.environ.get("SPATIAL_H", 384))
w = int(os.environ.get("SPATIAL_W", 1248))
iters = int(os.environ.get("SPATIAL_ITERS", 32))

cfg = RAFTStereoConfig(corr_implementation="reg_tpu", mixed_precision=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
mesh = make_mesh(n_space=ns)
step = make_eval_step(cfg, valid_iters=iters, mesh=mesh)

rng = np.random.default_rng(0)
im1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
im2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
im1, im2 = shard_batch([im1, im2], mesh)

t0 = time.perf_counter()
_, up = step(params, im1, im2)
c0 = float(jnp.sum(up.astype(jnp.float32)))
compile_s = time.perf_counter() - t0

t0 = time.perf_counter()
n = 4
for _ in range(n):
    _, up = step(params, im1, im2)
    c = float(jnp.sum(up.astype(jnp.float32)))
dt = (time.perf_counter() - t0) / n
print({"mesh": dict(mesh.shape), "shape": f"{h}x{w}", "iters": iters,
       "compile_s": round(compile_s, 1), "wall_s_per_frame": round(dt, 3),
       "checksum": round(c, 1), "matches_warmup": abs(c - c0) < 1e-3})

"""Join profiler trace durations with HLO metadata -> per-source-line ranking."""
import sys, glob, gzip, json, collections, re

tdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/trace_r3"
hlo = open(sys.argv[2] if len(sys.argv) > 2 else "/tmp/hlo_full.txt").read()

# name -> (shape, source, op_name)
meta = {}
for m in re.finditer(
        r"%(\S+?) = (\S+?) (?:fusion|copy|pad|convolution|custom-call|reduce-window|"
        r"transpose|reshape|slice|convert|bitcast-convert|dynamic-slice|dynamic-update-slice|"
        r"all-reduce|select-and-scatter|reduce)\(.*?metadata=\{([^}]*)\}", hlo):
    name, shp, md = m.groups()
    src = re.search(r'source_file="([^"]*)"', md)
    line = re.search(r"source_line=(\d+)", md)
    op = re.search(r'op_name="([^"]*)"', md)
    key = ""
    if src:
        key = src.group(1).split("/")[-1] + ":" + (line.group(1) if line else "?")
    meta[name] = (shp.split("{")[0], key, op.group(1)[-60:] if op else "")

files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
pids = {}
for e in ev:
    if e.get("ph") == "M" and e.get("name") == "process_name":
        pids[e["pid"]] = e["args"]["name"]

byline = collections.Counter()
byop = collections.defaultdict(float)
total = 0.0
for e in ev:
    if e.get("ph") != "X" or "dur" not in e:
        continue
    if "TPU" not in pids.get(e.get("pid"), ""):
        continue
    name = str(e.get("name", ""))
    if name.startswith(("jit_", "while")):
        continue
    shp, key, op = meta.get(name, ("?", "(unmapped)", ""))
    byline[key] += e["dur"]
    byop[(key, name, shp, op)] += e["dur"]
    total += e["dur"]

print(f"device op total: {total/1e3:.1f} ms")
for key, dur in byline.most_common(20):
    print(f"{dur/1e3:9.2f} ms  {100*dur/total:5.1f}%  {key}")
print("\ntop ops with shape:")
for (key, name, shp, op), dur in sorted(byop.items(), key=lambda kv: -kv[1])[:30]:
    print(f"{dur/1e3:8.2f} ms  {key:22s} {shp:38s} {name[:28]}")

import sys; sys.path.insert(0, "/root/repo")
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp
import numpy as np
from raft_stereo_tpu.models.update import (
    init_conv_gru, apply_conv_gru, init_motion_encoder, apply_motion_encoder,
    init_flow_head, apply_flow_head)
from raft_stereo_tpu.ops.pallas_stream import (
    fused_conv_gru_fwd_impl, prepare_gru_context, fused_motion_fwd_impl)
from raft_stereo_tpu.config import RAFTStereoConfig

key = jax.random.PRNGKey(0)
for (H, W, ch, parts_c, dtype) in [
        (16, 24, 128, (128, 128), jnp.float32),
        (8, 13, 64, (64,), jnp.float32),
        (24, 9, 32, (32, 32), jnp.float32),
        (16, 24, 128, (128, 128), jnp.bfloat16),
]:
    cin = sum(parts_c)
    p = init_conv_gru(key, ch, cin)
    hp = init_flow_head(jax.random.PRNGKey(9), ch, 64, 2)
    ks = jax.random.split(key, 8)
    h = jax.random.normal(ks[0], (1, H, W, ch), dtype) * 0.5
    xs = [jax.random.normal(k, (1, H, W, c), dtype)
          for k, c in zip(ks[1:1 + len(parts_c)], parts_c)]
    ctx = tuple(jax.random.normal(k, (1, H, W, ch), dtype) * 0.3
                for k in ks[5:8])
    czrq = prepare_gru_context(p, ctx, dtype)
    ref = apply_conv_gru(p, h, ctx, *xs)
    got, _ = fused_conv_gru_fwd_impl(p, h, czrq, *xs)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32))))
    tol = 1e-5 if dtype == jnp.float32 else 3e-2
    # with flow head chained (delta compared against the head applied to
    # the KERNEL's h', isolating the head from gru rounding amplification)
    got2, dx = fused_conv_gru_fwd_impl(p, h, czrq, *xs, head_p=hp)
    dref = apply_flow_head(hp, got2)[..., :1] - hp["conv2"]["b"][0]
    err2 = float(jnp.max(jnp.abs(got2.astype(jnp.float32) - ref.astype(jnp.float32))))
    err3 = float(jnp.max(jnp.abs(dx - dref.astype(jnp.float32))))
    print(f"H={H} W={W} ch={ch} parts={parts_c} {dtype.__name__}: "
          f"gru={err:.2e} gru+head={err2:.2e} dx={err3:.2e}")
    assert err < tol and err2 < tol and err3 < 3 * tol, "MISMATCH"

# motion encoder — integer-valued inputs are EXACT in fp32, so any tap /
# shift / boundary-mask bug shows as an integer-sized error while pure
# reassociation shows as 0. Float inputs then only check the rounding
# amplification envelope (seeds ~1e-6 growing through two more 576/1152-
# term conv stages).
cfg = RAFTStereoConfig()
rng = np.random.default_rng(0)
pm = init_motion_encoder(key, cfg)
pmi = jax.tree.map(lambda t: jnp.asarray(rng.integers(-2, 3, t.shape),
                                         jnp.float32), pm)
corr_i = jnp.asarray(rng.integers(-3, 4, (1, 16, 24, cfg.cor_planes)),
                     jnp.float32)
flow_i = jnp.asarray(rng.integers(-3, 4, (1, 16, 24, 2)), jnp.float32)
ref = apply_motion_encoder(pmi, flow_i, corr_i)
got = fused_motion_fwd_impl(pmi, flow_i, corr_i)
err = float(jnp.max(jnp.abs(got - ref)))
print(f"motion integer-exact: max|d|={err}")
assert err == 0.0, "MISMATCH"
for (H, W, dtype) in [(16, 24, jnp.float32), (16, 24, jnp.bfloat16)]:
    corr = jax.random.normal(key, (1, H, W, cfg.cor_planes), dtype)
    flow = jax.random.normal(key, (1, H, W, 2), dtype)
    ref = apply_motion_encoder(pm, flow, corr)
    got = fused_motion_fwd_impl(pm, flow, corr)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref.astype(jnp.float32))))
    print(f"motion H={H} W={W} {dtype.__name__}: max|d|={err:.2e}")
    assert err < 5e-2, "MISMATCH"
print("OK")

"""Isolate encoder (cnet+fnet) device time at Middlebury-F shape.

Variants via env:
  ENC_BATCHED=1  force the batch-concat fnet path (no lax.map)
  ENC_H/ENC_W    input shape (default 2016x2976)
"""
import sys; sys.path.insert(0, "/root/repo")
import os, time, glob, gzip, json, collections
import numpy as np
import jax, jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.models import raft_stereo as rs

if os.environ.get("ENC_BATCHED"):
    rs.FNET_SEQUENTIAL_MIN_PIXELS = 1 << 62

h = int(os.environ.get("ENC_H", 2016))
w = int(os.environ.get("ENC_W", 2976))
cfg = RAFTStereoConfig(corr_implementation="reg_tpu", mixed_precision=True)
params = init_raft_stereo(jax.random.PRNGKey(0), cfg)

@jax.jit
def encoders(params, img1, img2):
    net, inp, f1, f2 = rs._context_and_features(params, cfg, img1, img2,
                                                jnp.bfloat16)
    outs = [f1, f2] + [n for n in net] + [c for t in inp for c in t]
    return jnp.stack([jnp.sum(o.astype(jnp.float32)) for o in outs])

rng = np.random.default_rng(0)
img1 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)
img2 = jnp.asarray(rng.uniform(0, 255, (1, h, w, 3)), jnp.float32)

float(encoders(params, img1, img2)[0])  # compile+run
t0 = time.perf_counter()
pending = [encoders(params, img1, img2) for _ in range(4)]
for p in pending:
    float(p[0])
wall = (time.perf_counter() - t0) / 4

tdir = "/tmp/trace_enc"
os.system(f"rm -rf {tdir}")
with jax.profiler.trace(tdir):
    float(encoders(params, img1, img2)[0])

files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
pids = {e["pid"]: e["args"]["name"] for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"}
total = sum(e["dur"] for e in ev
            if e.get("ph") == "X" and "dur" in e
            and "TPU" in pids.get(e.get("pid"), "")
            and not str(e.get("name", "")).startswith(("jit_", "while")))
print(json.dumps({"wall_s": round(wall, 4),
                  "device_ms": round(total / 1e3, 1),
                  "batched": bool(os.environ.get("ENC_BATCHED"))}))

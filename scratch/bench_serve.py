"""Serving-throughput bench: requests/s through the real StereoService,
sequential (max_batch=1) vs continuous batching (max_batch=B).

Drives a synthetic closed-loop workload through the production stack —
admission, bounded queue, scheduler/worker threads, padding, program cache
— so the number includes every host-side cost a real deployment pays, not
just device fps. Prints ONE JSON line (bench.py's contract), with both
modes and the speedup, so the release-gate trajectory pins serve
throughput alongside the forward-pass fps.

BASELINE.md's itemized headroom makes batching the single largest
unexploited serving lever on chip (384x1248 full-quality: 12.6 fps/chip at
batch 8 vs ~1.6 at batch 1); the acceptance bar for this bench is >=2x
requests/s at batch >= 4 on a multi-request workload on chip. On CPU this
is a wiring smoke: it must run and print the line (conv throughput on CPU
is roughly linear in batch, so the CPU speedup is dispatch-overhead only).

Env overrides (RAFT_SERVE_BENCH_*):
  H / W          image shape               (default 384 x 1248)
  N              requests per mode         (default 24)
  ITERS          refinement iterations     (default 32)
  SEGMENTS       segments per request      (default 4)
  MAX_BATCH      batched mode's ceiling    (default 8)
  CORR           corr implementation       (default reg_tpu on TPU, reg off)
  TINY=1         32-dim 1-GRU model at 64x96 (the CPU gate smoke)
  LOOPBACK=1     also run the graftwire loopback-network mode (ROADMAP
                 item 4): the SAME closed-loop workload served over real
                 sockets through serve/http.py — requests/s vs the
                 in-process number quantifies the whole wire overhead
                 (HTTP parse, multipart, PNG decode in the offload pool,
                 JSON+b64 response). Folded into the one JSON line as
                 ``loopback_rps`` plus its own TRAJECTORY entry.
  MESH_SWEEP     graftpod ``--mesh`` sweep list  (default "1,2,4,8")

``--mesh`` (graftpod, DESIGN.md r21): instead of the seq/batched/repeat
battery, run the SAME closed-loop batched workload once per data-mesh
width n in MESH_SWEEP — one service per n, ``SessionConfig.mesh_data=n``
— and emit ``rps_per_chip`` + ``mesh_scaling_efficiency`` (rps(n) /
(n * rps(1))) per width into the JSON line and trajectory extras.  Off
chip this self-arms ``--xla_force_host_platform_device_count=8`` so the
sweep runs anywhere; the fake devices share one physical CPU, so the
off-chip efficiency is a WIRING number (the gate asserts emission, not
the >=0.75 linear-scaling bar — that lands with the on-chip run, like
every BASELINE.md device number).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(f"RAFT_SERVE_BENCH_{name}", default))


def main() -> None:
    mesh_mode = "--mesh" in sys.argv[1:]
    if mesh_mode:
        # Fake devices must exist BEFORE jax initializes: arm the flag
        # here (a no-op on a real pod — the host platform is not the
        # default backend there, and an operator-set XLA_FLAGS wins).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)

    tiny = os.environ.get("RAFT_SERVE_BENCH_TINY", "0").strip().lower() \
        not in ("0", "false", "no", "off")
    on_tpu = jax.default_backend() == "tpu"
    h = _env_int("H", 64 if tiny else 384)
    w = _env_int("W", 96 if tiny else 1248)
    # Enough requests to amortize the one-time tracing of the eager
    # stack/take helper ops (distinct gather shapes compile on first use)
    # — the first few ticks are NOT steady state, on any backend.
    n_requests = _env_int("N", 24 if tiny else 24)
    iters = _env_int("ITERS", 4 if tiny else 32)
    segments = _env_int("SEGMENTS", 2 if tiny else 4)
    max_batch = _env_int("MAX_BATCH", 4 if tiny else 8)
    corr = os.environ.get("RAFT_SERVE_BENCH_CORR",
                          "reg_tpu" if on_tpu else "reg")

    arch = (dict(n_gru_layers=1, hidden_dims=(32, 32, 32), corr_levels=2,
                 corr_radius=2) if tiny else {})
    cfg = with_eval_precision(
        RAFTStereoConfig(corr_implementation=corr, **arch))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)

    rng = np.random.default_rng(0)
    pairs = [
        (rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
         rng.uniform(0, 255, (h, w, 3)).astype(np.float32))
        for _ in range(min(n_requests, 4))  # cycle a few distinct frames
    ]

    def run_mode(mb: int, mesh: int = None) -> dict:
        session = InferenceSession(
            params, cfg,
            SessionConfig(valid_iters=iters, segments=segments,
                          max_batch=mb, mesh_data=mesh,
                          warmup_shapes=((h, w),),
                          warmup_segmented=True))
        # cache_bytes=0: this mode measures COMPUTED requests/s on a
        # cycled pair set — the graftrecall cache would (correctly)
        # short-circuit the repeats and measure the host's hash rate
        # instead.  The repeat-traffic mode below measures the cache.
        service = StereoService(session, ServiceConfig(
            max_queue=max(8, 2 * mb), workers=1, cache_bytes=0))
        # Closed-loop driver with an in-flight cap under the queue bound
        # (serve_stereo.py's drain-as-you-submit discipline): the bench
        # measures serving throughput, not the rejection rate of an
        # open-loop flood. Twice the batch ceiling keeps the join queue
        # non-empty while a full batch is mid-segment — a cap of exactly
        # max_batch lets ticks race the submitter and run partial batches.
        inflight_cap = max(2 * mb, 8)
        responses = []
        from collections import deque
        pending: deque = deque()
        with service:
            t0 = time.perf_counter()
            for i in range(n_requests):
                while len(pending) >= inflight_cap:
                    responses.append(pending.popleft().result(timeout=3600))
                pending.append(service.submit({
                    "id": i,
                    "left": pairs[i % len(pairs)][0],
                    "right": pairs[i % len(pairs)][1],
                }))
            while pending:
                responses.append(pending.popleft().result(timeout=3600))
            elapsed = time.perf_counter() - t0
        bad = [r for r in responses if r["status"] != "ok"]
        if bad:
            raise AssertionError(
                f"mode max_batch={mb}: {len(bad)} non-ok responses, "
                f"first: {bad[0]}")
        status = service.status()
        # graftscope-device acceptance (DESIGN.md r12): after the serve
        # battery, EVERY cached program must have a ledger row — enforced
        # in-process here, and again by the gate's `obs.ledger report`
        # step on the dumped artifact (RAFT_LEDGER).
        ledger_doc = session.ledger_doc()
        if not ledger_doc["complete"]:
            raise AssertionError(
                f"mode max_batch={mb}: cached programs with no ledger "
                f"row: {ledger_doc['missing']}")
        from raft_stereo_tpu.obs.ledger import dump_path, save_doc
        if dump_path():
            save_doc(ledger_doc, dump_path())
        out = {"rps": n_requests / elapsed, "elapsed_s": elapsed}
        # r19 "measured-first" record: the per-kind ledger attribution
        # (flops/bytes/MFU/roofline per program kind) rides the bench
        # JSON line + trajectory extras, so kernel-ordering decisions
        # are in the recorded trajectory rather than folklore. One row
        # per kind — the largest batch bucket is the serve-batch story.
        kind_rows = {}
        for r in ledger_doc["rows"]:
            k = r.get("kind")
            if k and (k not in kind_rows
                      or (r.get("b") or 0) > (kind_rows[k].get("b") or 0)):
                kind_rows[k] = {f: r.get(f) for f in (
                    "b", "h", "w", "iters", "flops_est", "bytes_est",
                    "intensity", "roofline")}
        out["ledger_kinds"] = kind_rows
        out["ledger_attribution"] = {
            k: {"mfu": v.get("mfu"), "roofline": v.get("roofline"),
                "device_s": v.get("device_s")}
            for k, v in (ledger_doc.get("attribution") or {}).items()}
        if status.get("batching"):
            b = status["batching"]
            out["occupancy_hist"] = b["occupancy_hist"]
            out["pad_waste"] = round(b["pad_waste"], 4)
            out["ticks"] = b["ticks"]
        # graftdeck (DESIGN.md r15): the batching-efficiency numbers the
        # ROADMAP quotes come off the tick flight-deck + capacity model
        # so they ride the recorded trajectory instead of log lines.
        deck_ticks = [t for t in session.deck.snapshot()
                      if t["kind"] == "tick" and t["batch"] > 0]
        if deck_ticks:
            adv_rows = sum(t["batch"] for t in deck_ticks)
            out["occupancy_mean"] = round(
                sum(t["occupancy"] for t in deck_ticks)
                / len(deck_ticks), 4)
            out["pad_waste_ratio"] = round(
                sum(t["pad_rows"] for t in deck_ticks) / adv_rows, 4)
        cap = status.get("capacity") or {}
        sat = cap.get("saturation")
        out["sat_ratio"] = (round(sat["ratio"], 4)
                            if sat is not None else None)
        out["predicted_rps"] = (round(cap["best_rps"], 4)
                                if cap.get("best_rps") else None)
        return out

    def run_repeat(mb: int, cache_bytes: int) -> dict:
        """graftrecall (DESIGN.md r18): the repeat-traffic third — a
        1/3 unique + 1/3 exact-duplicate + 1/3 near-duplicate mix
        through the real batched service, phases drained so deposits
        provably precede their repeats.  Run twice by the caller (cache
        off / cache on) over the IDENTICAL request stream: the rps
        ratio is the cache multiplier on repetitive traffic.  With the
        cache armed, exact repeats must be byte-identical cache:exact
        and near repeats must exit warm:cache:k with honest k."""
        session = InferenceSession(
            params, cfg,
            SessionConfig(valid_iters=iters, segments=segments,
                          max_batch=mb,
                          warmup_shapes=((h, w),),
                          warmup_segmented=True))
        service = StereoService(session, ServiceConfig(
            max_queue=max(8, 2 * mb), workers=1,
            cache_bytes=cache_bytes, cache_near_tol=6.0))
        n_uniq = max(2, n_requests // 3)
        rng2 = np.random.default_rng(7)
        uniq = [(rng2.uniform(0, 255, (h, w, 3)).astype(np.float32),
                 rng2.uniform(0, 255, (h, w, 3)).astype(np.float32))
                for _ in range(n_uniq)]
        near = [(np.clip(lq + rng2.normal(0, 2, lq.shape), 0, 255)
                 .astype(np.float32), rq) for lq, rq in uniq]
        from collections import deque
        inflight_cap = max(2 * mb, 8)
        with service:
            t0 = time.perf_counter()

            def phase(reqs):
                pending: deque = deque()
                out = []
                for req in reqs:
                    while len(pending) >= inflight_cap:
                        out.append(pending.popleft().result(timeout=3600))
                    pending.append(service.submit(req))
                while pending:
                    out.append(pending.popleft().result(timeout=3600))
                return out

            cold = phase([{"id": f"u{i}", "left": lq, "right": rq}
                          for i, (lq, rq) in enumerate(uniq)])
            dup = phase([{"id": f"d{i}", "left": lq, "right": rq}
                         for i, (lq, rq) in enumerate(uniq)])
            nearr = phase([{"id": f"n{i}", "left": lq, "right": rq,
                            "converge_tol": 1e9}
                           for i, (lq, rq) in enumerate(near)])
            elapsed = time.perf_counter() - t0
        responses = cold + dup + nearr
        bad = [r for r in responses if r["status"] != "ok"]
        if bad:
            raise AssertionError(
                f"repeat mode cache_bytes={cache_bytes}: {len(bad)} "
                f"non-ok responses, first: {bad[0]}")
        st = service.status()["cache"]
        if cache_bytes:
            cold_by_id = {r["id"]: r for r in cold}
            for i, r in enumerate(dup):
                assert r["quality"] == "cache:exact", (i, r["quality"])
                ref = cold_by_id[f"u{r['id'][1:]}"]
                assert r["disparity"].tobytes() == \
                    ref["disparity"].tobytes(), (
                    f"exact hit {r['id']} is not byte-identical to its "
                    f"cold compute")
            for r in nearr:
                q = str(r["quality"])
                assert q.startswith("warm:cache:"), (r["id"], q)
                assert int(q.rsplit(":", 1)[1]) == r["iters"], (
                    f"dishonest near-hit label {q} vs iters {r['iters']}")
        n_total = len(responses)
        return {"rps": n_total / elapsed, "elapsed_s": elapsed,
                "n": n_total,
                "hits": st["hits"], "near_hits": st["near_hits"],
                "hit_ratio": ((st["hits"] + st["near_hits"]) / n_total
                              if cache_bytes else 0.0)}

    def run_loopback(mb: int) -> dict:
        """The batched workload again, but over REAL loopback sockets
        through the graftwire frontend — closed-loop clients, request
        bodies pre-encoded outside the timed region so the number
        isolates what the SERVER pays for the wire: HTTP parse, strict
        multipart, offloaded PNG decode, JSON+b64 response."""
        import socket

        from raft_stereo_tpu.serve import HttpConfig, HttpFrontend
        from raft_stereo_tpu.serve import wire as wire_codec

        session = InferenceSession(
            params, cfg,
            SessionConfig(valid_iters=iters, segments=segments,
                          max_batch=mb,
                          warmup_shapes=((h, w),),
                          warmup_segmented=True))
        service = StereoService(session, ServiceConfig(
            max_queue=max(8, 2 * mb), workers=1))
        bodies = []
        for i in range(len(pairs)):
            left, right = pairs[i % len(pairs)]
            ct, body = wire_codec.build_multipart({
                "left": wire_codec.encode_image_png(
                    left.astype("uint8")),
                "right": wire_codec.encode_image_png(
                    right.astype("uint8"))})
            head = (f"POST /v1/stereo HTTP/1.1\r\nHost: bench\r\n"
                    f"Content-Type: {ct}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"Connection: close\r\n\r\n").encode("latin-1")
            bodies.append(head + body)

        def one(i: int) -> int:
            with socket.create_connection(
                    (fe.host, fe.port), timeout=3600) as s:
                s.sendall(bodies[i % len(bodies)])
                chunks = []
                while True:
                    b = s.recv(65536)
                    if not b:
                        break
                    chunks.append(b)
            raw = b"".join(chunks)
            return int(raw.split(b" ", 2)[1])

        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        inflight_cap = max(2 * mb, 8)
        statuses = []
        with service:
            with HttpFrontend(service, HttpConfig(port=0)) as fe:
                with ThreadPoolExecutor(
                        max_workers=inflight_cap,
                        thread_name_prefix="bench-client") as pool:
                    pending: deque = deque()
                    t0 = time.perf_counter()
                    for i in range(n_requests):
                        while len(pending) >= inflight_cap:
                            statuses.append(
                                pending.popleft().result(timeout=3600))
                        pending.append(pool.submit(one, i))
                    while pending:
                        statuses.append(
                            pending.popleft().result(timeout=3600))
                    elapsed = time.perf_counter() - t0
        bad = [s for s in statuses if s != 200]
        if bad:
            raise AssertionError(
                f"loopback mode: {len(bad)} non-200 responses, "
                f"first: {bad[0]}")
        return {"rps": n_requests / elapsed, "elapsed_s": elapsed}

    # -- graftpod mesh sweep (--mesh): one closed-loop batched run per
    # data-mesh width, separate sessions (the mesh extent re-keys every
    # batched program), scaling normalized against n_data=1. -----------
    if mesh_mode:
        sweep_raw = os.environ.get("RAFT_SERVE_BENCH_MESH_SWEEP",
                                   "1,2,4,8")
        sweep = sorted({int(s) for s in sweep_raw.split(",") if s.strip()})
        n_dev = len(jax.devices())
        skipped = [n for n in sweep if n > n_dev]
        sweep = [n for n in sweep if n <= n_dev]
        if skipped:
            # No silent caps: a 4-device host drops the 8-wide point
            # visibly, never pretends it ran.
            print(json.dumps({"event": "mesh_sweep_skipped",
                              "skipped": skipped, "devices": n_dev}),
                  file=sys.stderr)
        if 1 not in sweep:
            sweep.insert(0, 1)  # the scaling baseline is mandatory
        per_n = {}
        for n in sweep:
            r = run_mode(max_batch, mesh=n)
            per_n[n] = r
        base_rps = per_n[1]["rps"]
        mesh_doc = {}
        for n, r in per_n.items():
            mesh_doc[str(n)] = {
                "rps": round(r["rps"], 4),
                "rps_per_chip": round(r["rps"] / n, 4),
                "mesh_scaling_efficiency": (
                    round(r["rps"] / (n * base_rps), 4)
                    if base_rps else None),
                "pad_waste": r.get("pad_waste"),
                "occupancy_mean": r.get("occupancy_mean"),
            }
        top = max(per_n)
        doc = {
            "metric": (f"serve_mesh_scaling_{h}x{w}_i{iters}_{corr}"
                       f"_b{max_batch}{'_tiny' if tiny else ''}"),
            "value": mesh_doc[str(top)]["mesh_scaling_efficiency"],
            "unit": "x-linear",
            "n_data_max": top,
            "devices": n_dev,
            "rps_per_chip": mesh_doc[str(top)]["rps_per_chip"],
            "mesh_scaling_efficiency":
                mesh_doc[str(top)]["mesh_scaling_efficiency"],
            "by_n_data": mesh_doc,
            "n_requests": n_requests,
            "max_batch": max_batch,
            "backend": jax.default_backend(),
        }
        print(json.dumps(doc))
        from raft_stereo_tpu.obs.trajectory import emit
        emit(doc["metric"],
             doc["value"] if doc["value"] is not None else 0.0,
             "x-linear", backend=jax.default_backend(),
             source="scratch/bench_serve.py",
             extra={"by_n_data": mesh_doc,
                    "rps_per_chip": doc["rps_per_chip"],
                    "mesh_scaling_efficiency":
                        doc["mesh_scaling_efficiency"],
                    "devices": n_dev, "n_data_max": top})
        return

    # Sequential first (its warmup also proves the shape compiles), then
    # batched. Separate sessions: programs differ by batch bucket anyway,
    # and separate caches keep the two measurements independent.
    seq = run_mode(1)
    bat = run_mode(max_batch)
    speedup = bat["rps"] / seq["rps"] if seq["rps"] else None
    # graftrecall repeat-traffic third: the identical duplicate-heavy
    # stream with the cache off, then on — the ratio is the requests/s
    # multiplier repetitive traffic buys for zero device seconds.
    rep_off = run_repeat(max_batch, cache_bytes=0)
    rep_on = run_repeat(max_batch, cache_bytes=256 << 20)
    cache_mult = (rep_on["rps"] / rep_off["rps"]
                  if rep_off["rps"] else None)
    loopback = None
    if os.environ.get("RAFT_SERVE_BENCH_LOOPBACK", "0").strip().lower() \
            not in ("0", "false", "no", "off", ""):
        loopback = run_loopback(max_batch)

    # r24 context-lane ledger at the serve bucket (exact arithmetic, no
    # compile — the analytic half; check_engagement.py gates the <= 0.6
    # bound at this geometry and headline).
    from raft_stereo_tpu.ops.pallas_stream import plan_lane_dma_bytes
    lane_bf16 = plan_lane_dma_bytes(h, w, pack8=False)
    lane_int8 = plan_lane_dma_bytes(h, w, pack8=True)
    lane_dma_doc = {"h": h, "w": w,
                    "bf16_bytes_per_iter": lane_bf16,
                    "int8_bytes_per_iter": lane_int8,
                    "int8_over_bf16": round(lane_int8 / lane_bf16, 4)}

    doc = {
        "metric": (f"serve_requests_per_s_{h}x{w}_i{iters}_{corr}"
                   f"_b{max_batch}{'_tiny' if tiny else ''}"),
        "value": round(bat["rps"], 4),
        "unit": "requests/s",
        "sequential_rps": round(seq["rps"], 4),
        "speedup_vs_sequential": round(speedup, 4) if speedup else None,
        "n_requests": n_requests,
        "max_batch": max_batch,
        "occupancy_hist": bat.get("occupancy_hist"),
        "pad_waste": bat.get("pad_waste"),
        "occupancy_mean": bat.get("occupancy_mean"),
        "pad_waste_ratio": bat.get("pad_waste_ratio"),
        "sat_ratio": bat.get("sat_ratio"),
        "predicted_rps": bat.get("predicted_rps"),
        # graftrecall (DESIGN.md r18): the repeat-traffic mix.
        "cache_hit_ratio": round(rep_on["hit_ratio"], 4),
        "cache_rps_multiplier": (round(cache_mult, 4)
                                 if cache_mult else None),
        "cache_repeat_rps": round(rep_on["rps"], 4),
        "nocache_repeat_rps": round(rep_off["rps"], 4),
        # r19: per-kind compiler attribution off the batched session.
        "ledger_kinds": bat.get("ledger_kinds"),
        "ledger_attribution": bat.get("ledger_attribution"),
        # r24: per-iteration context-lane bytes at THIS serve geometry,
        # bf16 vs int8 containers (exact BlockSpec arithmetic —
        # ops/pallas_stream.plan_lane_dma_bytes; <= 0.6 gated by
        # check_engagement.py).
        "lane_dma": lane_dma_doc,
        "backend": jax.default_backend(),
    }
    if loopback is not None:
        doc["loopback_rps"] = round(loopback["rps"], 4)
        doc["wire_overhead_frac"] = (
            round(1.0 - loopback["rps"] / bat["rps"], 4)
            if bat["rps"] else None)
    print(json.dumps(doc))

    # Consolidated perf-trajectory artifact (DESIGN.md r11): serve
    # throughput rides TRAJECTORY.json alongside fps/chip and steps/s so
    # the release gate's pinned bands cover it too.
    from raft_stereo_tpu.obs.trajectory import emit
    emit(doc["metric"], bat["rps"], "requests/s",
         backend=jax.default_backend(), source="scratch/bench_serve.py",
         extra={"sequential_rps": doc["sequential_rps"],
                "speedup_vs_sequential": doc["speedup_vs_sequential"],
                # Operator-plane extras (graftdeck): gate runs pin
                # predicted-vs-measured requests/s side by side, plus
                # the batching-efficiency numbers off the tick deck.
                "predicted_rps": doc["predicted_rps"],
                "sat_ratio": doc["sat_ratio"],
                "occupancy_mean": doc["occupancy_mean"],
                "pad_waste_ratio": doc["pad_waste_ratio"],
                # graftrecall: the repeat-traffic cache numbers ride
                # the same trajectory entry.
                "cache_hit_ratio": doc["cache_hit_ratio"],
                "cache_rps_multiplier": doc["cache_rps_multiplier"],
                # graftresident (r19): per-kind flops/bytes/MFU rows, so
                # the measured-first ordering is in the trajectory.
                "ledger_kinds": doc["ledger_kinds"],
                "ledger_attribution": doc["ledger_attribution"],
                # graftlane (r24): context-lane bytes ride the same
                # trajectory entry as unpinned diagnostics.
                "lane_dma": lane_dma_doc})
    if loopback is not None:
        emit(doc["metric"].replace("serve_requests_per_s",
                                   "serve_loopback_requests_per_s"),
             loopback["rps"], "requests/s",
             backend=jax.default_backend(),
             source="scratch/bench_serve.py",
             extra={"inprocess_rps": doc["value"],
                    "wire_overhead_frac": doc["wire_overhead_frac"]})


if __name__ == "__main__":
    main()

import re, collections
txt = open("/tmp/hlo_full.txt").read()
sizes = collections.Counter()
where = {}
for m in re.finditer(r"%(copy[-.\w]*\d+) = (\w+)\[([\d,]*)\][^\n]*", txt):
    name, dt, dims = m.group(1), m.group(2), m.group(3)
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    b = n * (2 if dt == "bf16" else 4)
    key = (dt, dims)
    sizes[key] += b
    mm = re.search(r'source_file="([^"]+)" source_line=(\d+)', m.group(0))
    op = re.search(r'op_name="([^"]+)"', m.group(0))
    where[key] = ((mm.group(1).split("/")[-1] + ":" + mm.group(2)) if mm else "?",
                  op.group(1)[:60] if op else "?")
for key, b in sizes.most_common(12):
    dt, dims = key
    print(f"{b/1e6:8.1f} MB  {dt}[{dims}]  {where[key]}")

"""Probe the fused-encoder compile wall: compile+run one fnet trunk pass
chain and the cnet trunk at a given shape, timing compile vs run.

  ENC_H/ENC_W  shape (default 1024x1504)
  ENC_CACHE    set to a dir to enable the persistent compilation cache
"""
import sys; sys.path.insert(0, "/root/repo")
import os, time
os.environ["RAFT_FUSED_ENCODERS"] = "1"

import numpy as np
import jax, jax.numpy as jnp

cache = os.environ.get("ENC_CACHE")
if cache:
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

from raft_stereo_tpu.models.extractor import (init_basic_encoder,
                                              init_multi_basic_encoder)
from raft_stereo_tpu.ops.pallas_encoder import (fused_in_stem_layer1,
                                                fused_stem_layer1)

h = int(os.environ.get("ENC_H", 1024))
w = int(os.environ.get("ENC_W", 1504))
which = os.environ.get("ENC_WHICH", "both")

rng = np.random.default_rng(0)
x = jnp.asarray(rng.uniform(-1, 1, (1, h, w, 3)), jnp.bfloat16)

if which in ("both", "cnet"):
    pc = init_multi_basic_encoder(jax.random.PRNGKey(0), [[128] * 3] * 2,
                                  norm_fn="batch", downsample=2)
    f = jax.jit(lambda p, x: jnp.sum(fused_stem_layer1(p, x)
                                     .astype(jnp.float32)))
    t0 = time.perf_counter()
    out = f(pc, x)
    v = float(out)
    t1 = time.perf_counter()
    print(f"cnet trunk {h}x{w}: compile+first-run {t1-t0:.1f}s sum={v:.1f}",
          flush=True)
    t0 = time.perf_counter()
    float(f(pc, x))
    print(f"  steady run {time.perf_counter()-t0:.3f}s", flush=True)

if which in ("both", "fnet"):
    pf = init_basic_encoder(jax.random.PRNGKey(1), 256, "instance", 2)
    f = jax.jit(lambda p, x: jnp.sum(fused_in_stem_layer1(p, x)
                                     .astype(jnp.float32)))
    t0 = time.perf_counter()
    v = float(f(pf, x))
    t1 = time.perf_counter()
    print(f"fnet trunk {h}x{w}: compile+first-run {t1-t0:.1f}s sum={v:.1f}",
          flush=True)
    t0 = time.perf_counter()
    float(f(pf, x))
    print(f"  steady run {time.perf_counter()-t0:.3f}s", flush=True)

"""CLI-level cross-implementation consistency on hardware.

Builds reference-init weights (torch seed-1234 state_dict saved as .pth),
fabricates a small synthetic ETH3D tree with shifted-noise stereo pairs
(known constant disparity, so EPE is meaningful), and runs the REAL
``evaluate_stereo.py`` CLI once per corr implementation on the TPU chip.
All paths — XLA fp32 lookup, Pallas bf16-volume kernel, fused alt kernel —
must report the same benchmark metrics to bf16-drift tolerance. This pins
the full stack (CLI flag -> kernel dispatch -> validator metrics) on
hardware, not just the corr layer in isolation.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/reference")

import torch
from PIL import Image

from raft_stereo_tpu.data.frame_utils import write_pfm

torch.set_num_threads(1)


def main() -> None:
    root = tempfile.mkdtemp(prefix="cliconsist")
    from core.raft_stereo import RAFTStereo  # torch reference, weights only
    torch.manual_seed(1234)
    model = RAFTStereo(argparse.Namespace(
        corr_implementation="reg", shared_backbone=False, corr_levels=4,
        corr_radius=4, n_downsample=2, slow_fast_gru=False, n_gru_layers=3,
        hidden_dims=[128, 128, 128], mixed_precision=False))
    pth = os.path.join(root, "init.pth")
    torch.save({f"module.{k}": v for k, v in model.state_dict().items()}, pth)

    rng = np.random.default_rng(7)
    h, w, disp = 192, 320, 12
    for i in range(2):
        base = rng.integers(0, 255, (h, w + disp, 3), dtype=np.uint8)
        # Smooth the noise so bilinear structure survives bf16: block-upsample.
        base = np.kron(base[::4, ::4], np.ones((4, 4, 1))).astype(np.uint8)[
            :h, :w + disp]
        left = base[:, disp:]
        right = base[:, :-disp] if disp else base
        sc = os.path.join(root, "ETH3D", "two_view_training", f"scene_{i}")
        os.makedirs(sc, exist_ok=True)
        Image.fromarray(left).save(os.path.join(sc, "im0.png"))
        Image.fromarray(right).save(os.path.join(sc, "im1.png"))
        gt = os.path.join(root, "ETH3D", "two_view_training_gt", f"scene_{i}")
        os.makedirs(gt, exist_ok=True)
        write_pfm(os.path.join(gt, "disp0GT.pfm"),
                  np.full((h, w), float(disp), np.float32))

    results = {}
    for impl in ("reg", "reg_tpu", "alt_tpu"):
        cmd = [sys.executable, "evaluate_stereo.py", "--dataset", "eth3d",
               "--dataset_root", root, "--restore_ckpt", pth,
               "--valid_iters", "16", "--corr_implementation", impl]
        if impl != "reg":
            cmd.append("--mixed_precision")
        r = subprocess.run(cmd, cwd="/root/repo", capture_output=True,
                           text=True)
        assert r.returncode == 0, r.stderr[-500:]
        line = [l for l in r.stdout.splitlines()
                if l.startswith("Validation ETH3D")][0]
        epe = float(line.split("EPE ")[1].split(",")[0])
        d1 = float(line.split("D1 ")[1])
        results[impl] = (epe, d1)
        print(f"{impl:8s} (mixed={impl != 'reg'}): EPE {epe:.4f} D1 {d1:.3f}")

    ref_epe, _ = results["reg"]
    for impl, (epe, d1) in results.items():
        assert abs(epe - ref_epe) < 0.05, (impl, epe, ref_epe)
    print(json.dumps({"consistency": "OK",
                      "max_epe_delta": round(max(
                          abs(e - ref_epe) for e, _ in results.values()), 5)}))


if __name__ == "__main__":
    main()

import sys; sys.path.insert(0, "/root/repo")
import glob, gzip, json, time, collections
import numpy as np, jax, jax.numpy as jnp
from raft_stereo_tpu.corr import make_corr_fn

impl = sys.argv[1] if len(sys.argv) > 1 else "reg_tpu"
B, H, W, D, iters = 1, int(sys.argv[2]) if len(sys.argv)>2 else 64, int(sys.argv[3]) if len(sys.argv)>3 else 376, 256, 8
rng = np.random.default_rng(0)
f1 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.float32)
f2 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.float32)
c0 = jnp.asarray(rng.uniform(0, W - 1, size=(B, H, W)), jnp.float32)

@jax.jit
def run(c):
    fn = make_corr_fn(impl, f1, f2, num_levels=4, radius=4)
    def step(c, _):
        out = fn(c)
        return c + 0.07, jnp.mean(out)
    _, ys = jax.lax.scan(step, c, None, length=iters)
    return jnp.sum(ys)

float(run(c0))
tdir = f"/tmp/prof_{impl}"
with jax.profiler.trace(tdir):
    float(run(c0))

# Parse the perfetto trace: sum durations by op name on the DEVICE track only,
# excluding jit_/while module wrappers (they contain their children and would
# double-count) — same filter as analyze_trace.py.
files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
ev = json.load(gzip.open(sorted(files)[-1]))["traceEvents"]
pids = {e["pid"]: e["args"]["name"] for e in ev
        if e.get("ph") == "M" and e.get("name") == "process_name"}
tot = collections.Counter()
for e in ev:
    if e.get("ph") != "X" or "dur" not in e:
        continue
    if "TPU" not in pids.get(e.get("pid"), ""):
        continue
    name = str(e.get("name", ""))
    if name.startswith(("jit_", "while")):
        continue
    tot[name] += e["dur"]
for name, dur in tot.most_common(25):
    print(f"{dur/1e3:9.2f} ms  {name[:110]}")

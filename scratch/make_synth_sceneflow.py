"""Synthetic SceneFlow-shaped dataset tree for sustained-train runs.

FlyingThings3D layout at the real 540x960 resolution: TRAIN split for the
training mix, TEST split for validate_things. Left/right pairs are
consistent with the generated disparity (right = left warped), so the
loss has real structure to fit, at real decode cost.
"""
import os
import os.path as osp
import sys

import numpy as np

sys.path.insert(0, "/root/repo")
from raft_stereo_tpu.data import frame_utils  # noqa: E402
import cv2  # noqa: E402

ROOT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/synth_sceneflow"
N_TRAIN = int(sys.argv[2]) if len(sys.argv) > 2 else 96
N_TEST = int(sys.argv[3]) if len(sys.argv) > 3 else 8
H, W = 540, 960


def make_pair(rng):
    base = rng.uniform(0, 255, (H // 8, W // 8, 3)).astype(np.float32)
    left = cv2.resize(base, (W, H), interpolation=cv2.INTER_CUBIC)
    left = np.clip(left + rng.normal(0, 6, left.shape), 0, 255)
    dbase = rng.uniform(5, 60, (H // 32, W // 32)).astype(np.float32)
    disp = cv2.resize(dbase, (W, H), interpolation=cv2.INTER_CUBIC)
    xs = np.arange(W)[None, :] - disp
    right = np.stack([
        np.stack([np.interp(xs[y], np.arange(W), left[y, :, c])
                  for y in range(H)])
        for c in range(3)], axis=-1)
    return left.astype(np.uint8), right.astype(np.uint8), disp


def write(split, n, seed):
    rng = np.random.default_rng(seed)
    for i in range(n):
        scene = f"{i:04d}"
        for dstype in ("frames_cleanpass", "frames_finalpass"):
            base = osp.join(ROOT, "FlyingThings3D", dstype, split, "A", scene)
            left, right, disp = make_pair(np.random.default_rng([seed, i]))
            for side, img in (("left", left), ("right", right)):
                d = osp.join(base, side)
                os.makedirs(d, exist_ok=True)
                cv2.imwrite(osp.join(d, "0006.png"), img[..., ::-1])
        ddir = osp.join(ROOT, "FlyingThings3D", "disparity", split, "A",
                        scene, "left")
        os.makedirs(ddir, exist_ok=True)
        frame_utils.write_pfm(osp.join(ddir, "0006.pfm"),
                              disp.astype(np.float32))


write("TRAIN", N_TRAIN, 1)
write("TEST", N_TEST, 2)
print("tree at", ROOT, "train", N_TRAIN, "test", N_TEST)

import sys, os; sys.path.insert(0, "/root/repo")
import time, numpy as np, jax, jax.numpy as jnp
from raft_stereo_tpu.corr import make_corr_fn

def bench(impl, B, H, W, D=256, iters=32):
    rng = np.random.default_rng(0)
    f1 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.float32)
    f2 = jnp.asarray(rng.standard_normal((B, H, W, D)), jnp.float32)
    c0 = jnp.asarray(rng.uniform(0, W - 1, size=(B, H, W)), jnp.float32)
    @jax.jit
    def run(c):
        fn = make_corr_fn(impl, f1, f2, num_levels=4, radius=4)
        def step(c, _):
            out = fn(c)
            return c + 0.07, jnp.mean(out)
        _, ys = jax.lax.scan(step, c, None, length=iters)
        return jnp.sum(ys)
    float(run(c0))  # compile+warm
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter(); float(run(c0)); t1 = time.perf_counter()
        best = min(best, t1 - t0)
    print(f"{impl:8s} H={H} W={W}: {best*1e3:7.1f} ms for {iters} lookups "
          f"({best*1e3/iters:6.2f} ms/lookup)", flush=True)

for impl in ("reg", "reg_tpu"):
    bench(impl, 1, 256, 376)   # 1024x1504 quarter-res
for impl in ("reg", "reg_tpu"):
    bench(impl, 1, 504, 744)   # Middlebury-F quarter-res

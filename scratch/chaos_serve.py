"""Seeded chaos soak for the supervised serving stack (graftguard,
DESIGN.md r13) — the release-gate proof that self-healing actually heals.

Drives N seeded requests through the REAL ``StereoService`` (continuous
batching, retry budget, watchdog supervision armed) under a composite
:class:`~raft_stereo_tpu.faults.ChaosPlan` fault storm — injected device
hangs, a tick-loop crash, an uploader crash, a compile failure, poisoned
outputs, slow forwards on FakeClock, per-request deadlines — and asserts
the global supervision invariants in-process:

1. **bounded resolution** — every submitted Future resolves with a
   structured outcome (``ok`` / ``rejected:code`` / ``error:code``)
   inside a hard real-time bound; zero abandoned Futures, zero
   deadlocks;
2. **breaker monotone** — the trip count never decreases and the tripped
   set only grows (sampled throughout the storm, not just at the end);
3. **counters reconcile** — ``raft_requests_total`` by outcome equals
   the collected responses exactly (degraded count included), and
   ``raft_request_retries_total`` equals the sum of every response's
   ``retries`` field;
4. **watchdog actions leave evidence** — every scheduler generation
   bounce wrote a flight record whose reasons name the watchdog kind;
5. **drain contract** — after the storm, a draining service rejects a
   late submit ``service_draining`` and quiesces clean.

One JSON line on stdout (bench.py's contract), a pass/fail entry into
``TRAJECTORY.json`` via ``RAFT_TRAJECTORY``, exit 0/1.

Env:
  RAFT_CHAOS_N      requests (default 200)
  RAFT_CHAOS_SEED   storm seed (default 1234; the gate pins it)
  RAFT_CHAOS_SPEC   JSON overrides, e.g. '{"n": 50, "hangs": 1}'
                    (registered in analysis/knobs.py HOST_ENV_KNOBS)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Hard real-time bound on the whole soak (the ISSUE acceptance: CPU,
#: bounded <= 60 s). Tripping it means a deadlock or a stranded Future —
#: exactly what the harness exists to catch.
REAL_BOUND_S = 60.0

H, W = 40, 60          # deliberately unpadded: bucketing must engage
IN_FLIGHT_CAP = 12     # closed-loop cap under the queue bound


def load_spec() -> dict:
    spec = {
        "n": int(os.environ.get("RAFT_CHAOS_N", "200")),
        "seed": int(os.environ.get("RAFT_CHAOS_SEED", "1234")),
        "hangs": 2,
        "crash_tick": True,
        "crash_uploader": True,
        "compile_errors": 1,
        "poison": 3,
        "slow_frac": 0.05,
        "deadline_frac": 0.25,
        "watchdog_ms": 2000.0,
        "retry_budget": 3,
    }
    raw = os.environ.get("RAFT_CHAOS_SPEC", "").strip()
    if raw:
        spec.update(json.loads(raw))
    return spec


def build_plan(rng, spec: dict):
    """One seeded, fully deterministic fault storm. Ordinals are chosen
    past the warmup-heavy head of each ordinal space so injected hangs
    land on steady invocations (a warming hang is governed by the warm
    grace and would merely self-release at the cap)."""
    from raft_stereo_tpu.faults import ChaosPlan
    n = spec["n"]
    # ~1 device invoke per request lands in this storm (batched
    # prepare/advance/epilogue); [40, 40+n//2) keeps the hangs past the
    # warmup-heavy head yet provably inside the run's ordinal budget
    # (main() asserts hangs_entered >= 1 so a drifting ordinal budget
    # can't silently turn the hang path vacuous).
    hang_invokes = {int(o): 10.0 for o in sorted(
        rng.choice(range(40, 40 + n // 2), size=spec["hangs"],
                   replace=False))} if spec["hangs"] else {}
    slow = {int(o): float(rng.uniform(0.2, 1.0)) for o in
            rng.choice(range(5, 3 * n),
                       size=max(1, int(3 * n * spec["slow_frac"])),
                       replace=False)}
    # Keep slow forwards out of the hang set: one ordinal, one fault.
    slow = {o: v for o, v in slow.items() if o not in hang_invokes}
    poison = tuple(int(o) for o in rng.choice(
        range(10, 2 * n), size=spec["poison"], replace=False)
        if int(o) not in hang_invokes) if spec["poison"] else ()
    compile_errors = ({2: "mosaic"} if spec["compile_errors"] else {})
    return ChaosPlan(
        compile_errors=compile_errors,
        slow_forwards=slow,
        poison_outputs=poison,
        hang_invokes=hang_invokes,
        crash_uploads=(int(n // 4),) if spec["crash_uploader"] else (),
        crash_ticks=(12,) if spec["crash_tick"] else (),
        hang_cap_s=5.0,
    )


def main() -> int:
    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.faults import FakeClock
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.obs.flight import FlightRecorder
    from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)

    spec = load_spec()
    n = spec["n"]
    rng = np.random.default_rng(spec["seed"])
    plan = build_plan(rng, spec)

    cfg = with_eval_precision(RAFTStereoConfig(
        n_gru_layers=1, hidden_dims=(32, 32, 32),
        corr_levels=2, corr_radius=2))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    clock = FakeClock()
    flight_dir = tempfile.mkdtemp(prefix="chaos-flight-")
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False),
        fault_plan=plan, clock=clock,
        flight=FlightRecorder(flight_dir, limit=1000))
    svc = StereoService(session, ServiceConfig(
        max_queue=16,
        watchdog_ms=spec["watchdog_ms"],
        retry_budget=spec["retry_budget"],
        drain_grace_ms=10_000.0)).start()

    pairs = [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
              rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
             for _ in range(4)]
    deadlines = {i: float(rng.uniform(5_000.0, 40_000.0))
                 for i in range(n) if rng.uniform() < spec["deadline_frac"]}

    def make_request(i: int) -> dict:
        left, right = pairs[i % len(pairs)]
        req = {"id": i, "left": left[None], "right": right[None]}
        if i in deadlines:
            req["deadline_ms"] = deadlines[i]
        return req

    t_real0 = time.monotonic()
    deadline_real = t_real0 + REAL_BOUND_S
    results: dict = {}
    futs: dict = {}
    submitted = 0
    trips_prev = 0
    tripped_prev: set = set()
    while len(results) < n:
        assert time.monotonic() < deadline_real, (
            f"chaos soak exceeded its {REAL_BOUND_S}s real-time bound "
            f"with {n - len(results)} Futures unresolved — deadlock or "
            f"abandoned Future")
        while submitted < n and len(futs) < IN_FLIGHT_CAP:
            futs[submitted] = svc.submit(make_request(submitted))
            submitted += 1
        sup = svc._supervisor
        if sup is not None:
            sup.check_now()
        # Invariant 2: breaker trips are monotone, the tripped set grows.
        tc = session.breaker.trip_count
        assert tc >= trips_prev, f"breaker trip count fell {trips_prev}->{tc}"
        trips_prev = tc
        ts = set(session.breaker.tripped_names)
        assert ts >= tripped_prev, f"tripped set shrank {tripped_prev}->{ts}"
        tripped_prev = ts
        for rid in [r for r, f in futs.items() if f.done()]:
            results[rid] = futs.pop(rid).result(timeout=1)
        time.sleep(0.002)

    # Invariant 5: draining rejects late submits, then quiesces clean.
    svc.begin_drain()
    late = svc.submit(make_request(0)).result(timeout=10)
    assert late["status"] == "rejected" and \
        late["code"] == "service_draining", late
    clean = svc.drain()
    assert clean, "drain failed to quiesce an idle service"
    elapsed_real = time.monotonic() - t_real0

    # Invariant 1: every outcome is structured.
    responses = list(results.values()) + [late]
    assert len(results) == n
    for r in responses:
        assert r["status"] in ("ok", "rejected", "error"), r
        if r["status"] != "ok":
            assert r.get("code"), r
        else:
            assert np.isfinite(r["disparity"]).all()

    # Invariant 3: registry counters reconcile with collected outcomes.
    reg = svc.registry
    counts = {labels["outcome"]: int(v) for labels, v in
              reg.series("raft_requests_total")}
    expect: dict = {}
    for r in responses:
        key = (r["status"] if r["status"] == "ok"
               else f'{r["status"]}:{r["code"]}')
        expect[key] = expect.get(key, 0) + 1
    degraded = sum(1 for r in responses
                   if r["status"] == "ok" and r.get("quality") != "full")
    if degraded:
        expect["degraded"] = degraded
    assert counts == expect, f"counters {counts} != outcomes {expect}"
    retries_total = int(reg.value("raft_request_retries_total"))
    retries_seen = sum(r.get("retries", 0) for r in responses)
    assert retries_total == retries_seen, (retries_total, retries_seen)

    # Invariant 4: every watchdog bounce left a flight record naming it.
    restarts = {labels["reason"]: int(v) for labels, v in
                reg.series("raft_sched_restarts_total")}
    n_restarts = sum(restarts.values())
    assert n_restarts >= 1, (
        "the storm never exercised a generation bounce — the chaos plan "
        "is vacuous for supervision")
    if spec["hangs"]:
        assert session.faults.hangs_entered >= 1, (
            "hang ordinals never landed on a live invocation — the storm "
            "is vacuous for the device-hang path; retune build_plan()")
    bounce_records = 0
    for path in session.flight.records():
        with open(path) as f:
            doc = json.load(f)
        if any(str(reason).startswith("watchdog:")
               for reason in doc.get("reasons", [])):
            bounce_records += 1
    assert bounce_records == n_restarts, (
        f"{n_restarts} bounces but {bounce_records} watchdog flight "
        f"records — a watchdog action left no evidence")

    outcome_counts = dict(sorted(expect.items()))
    doc = {
        "metric": "chaos_soak",
        "pass": True,
        "n": n,
        "seed": spec["seed"],
        "outcomes": outcome_counts,
        "restarts": restarts,
        "watchdog_trips": {labels["kind"]: int(v) for labels, v in
                           reg.series("raft_watchdog_trips_total")},
        "retries": retries_total,
        "breaker_trips": session.breaker.trip_count,
        "flight_records": len(session.flight.records()),
        "fault_ordinals": {"invokes": session.faults.invokes,
                           "uploads": session.faults.uploads,
                           "ticks": session.faults.ticks,
                           "hangs_entered": session.faults.hangs_entered},
        "elapsed_real_s": round(elapsed_real, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    emit("chaos_soak_resolved_frac", 1.0, "frac",
         backend=jax.default_backend(), source="scratch/chaos_serve.py",
         extra={"n": n, "restarts": sum(restarts.values()),
                "retries": retries_total,
                "elapsed_real_s": doc["elapsed_real_s"]})
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except AssertionError as e:
        print(json.dumps({"metric": "chaos_soak", "pass": False,
                          "error": str(e)}))
        raise SystemExit(1)

"""Seeded chaos soak for the supervised serving stack (graftguard,
DESIGN.md r13) — the release-gate proof that self-healing actually heals.
``--wire`` switches to the graftwire network storm (DESIGN.md r14): the
same seeded-determinism stance, but the faults are HOSTILE CLIENTS over
real loopback sockets and the server side is unmodified production code.
``--mesh`` switches to the graftpod one-chip-hang scenario (DESIGN.md
r21): a 2-chip data mesh on fake CPU devices takes an injected device
hang whose post-bounce health probe parks on exactly ONE chip — the
bounce must quarantine that chip alone, shrink the mesh, migrate the
chip-pinned stream sessions (held seeds are host-side, so they stay
warm), keep serving on the survivors, and reconcile the books.
``--heal`` switches to the graftheal fault-CLEARS recovery storm
(DESIGN.md r22): the same 2-chip quarantine, but the injected fault is
TRANSIENT — its window clears, the probation probe re-admits the chip,
headroom returns to within 10% of pre-fault, a flapping chip is
re-admitted exactly flap-cap times then permanently quarantined, and a
poisoned breaker rung's half-open canary fails closed (doubled backoff,
never served) until the poison clears.

Drives N seeded requests through the REAL ``StereoService`` (continuous
batching, retry budget, watchdog supervision armed) under a composite
:class:`~raft_stereo_tpu.faults.ChaosPlan` fault storm — injected device
hangs, a tick-loop crash, an uploader crash, a compile failure, poisoned
outputs, slow forwards on FakeClock, per-request deadlines — and asserts
the global supervision invariants in-process:

1. **bounded resolution** — every submitted Future resolves with a
   structured outcome (``ok`` / ``rejected:code`` / ``error:code``)
   inside a hard real-time bound; zero abandoned Futures, zero
   deadlocks;
2. **breaker monotone** — the trip count never decreases and the tripped
   set only grows (sampled throughout the storm, not just at the end);
3. **counters reconcile** — ``raft_requests_total`` by outcome equals
   the collected responses exactly (degraded count included), and
   ``raft_request_retries_total`` equals the sum of every response's
   ``retries`` field;
4. **watchdog actions leave evidence** — every scheduler generation
   bounce wrote a flight record whose reasons name the watchdog kind;
5. **drain contract** — after the storm, a draining service rejects a
   late submit ``service_draining`` and quiesces clean.

One JSON line on stdout (bench.py's contract), a pass/fail entry into
``TRAJECTORY.json`` via ``RAFT_TRAJECTORY``, exit 0/1.

Env:
  RAFT_CHAOS_N      requests (default 200)
  RAFT_CHAOS_SEED   storm seed (default 1234; the gate pins it)
  RAFT_CHAOS_SPEC   JSON overrides, e.g. '{"n": 50, "hangs": 1}'
                    (registered in analysis/knobs.py HOST_ENV_KNOBS)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: Hard real-time bound on the whole soak (the ISSUE acceptance: CPU,
#: bounded <= 60 s). Tripping it means a deadlock or a stranded Future —
#: exactly what the harness exists to catch.
REAL_BOUND_S = 60.0

H, W = 40, 60          # deliberately unpadded: bucketing must engage
IN_FLIGHT_CAP = 12     # closed-loop cap under the queue bound


def load_spec() -> dict:
    spec = {
        "n": int(os.environ.get("RAFT_CHAOS_N", "200")),
        "seed": int(os.environ.get("RAFT_CHAOS_SEED", "1234")),
        "hangs": 2,
        "crash_tick": True,
        "crash_uploader": True,
        "compile_errors": 1,
        "poison": 3,
        "slow_frac": 0.05,
        "deadline_frac": 0.25,
        "watchdog_ms": 2000.0,
        "retry_budget": 3,
    }
    raw = os.environ.get("RAFT_CHAOS_SPEC", "").strip()
    if raw:
        spec.update(json.loads(raw))
    return spec


def build_plan(rng, spec: dict):
    """One seeded, fully deterministic fault storm. Ordinals are chosen
    past the warmup-heavy head of each ordinal space so injected hangs
    land on steady invocations (a warming hang is governed by the warm
    grace and would merely self-release at the cap)."""
    from raft_stereo_tpu.faults import ChaosPlan
    n = spec["n"]
    # ~1 device invoke per request lands in this storm (batched
    # prepare/advance/epilogue); [40, 40+n//2) keeps the hangs past the
    # warmup-heavy head yet provably inside the run's ordinal budget
    # (main() asserts hangs_entered >= 1 so a drifting ordinal budget
    # can't silently turn the hang path vacuous).
    hang_invokes = {int(o): 10.0 for o in sorted(
        rng.choice(range(40, 40 + n // 2), size=spec["hangs"],
                   replace=False))} if spec["hangs"] else {}
    slow = {int(o): float(rng.uniform(0.2, 1.0)) for o in
            rng.choice(range(5, 3 * n),
                       size=max(1, int(3 * n * spec["slow_frac"])),
                       replace=False)}
    # Keep slow forwards out of the hang set: one ordinal, one fault.
    slow = {o: v for o, v in slow.items() if o not in hang_invokes}
    poison = tuple(int(o) for o in rng.choice(
        range(10, 2 * n), size=spec["poison"], replace=False)
        if int(o) not in hang_invokes) if spec["poison"] else ()
    compile_errors = ({2: "mosaic"} if spec["compile_errors"] else {})
    return ChaosPlan(
        compile_errors=compile_errors,
        slow_forwards=slow,
        poison_outputs=poison,
        hang_invokes=hang_invokes,
        crash_uploads=(int(n // 4),) if spec["crash_uploader"] else (),
        crash_ticks=(12,) if spec["crash_tick"] else (),
        hang_cap_s=5.0,
    )


def main() -> int:
    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.faults import FakeClock
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.obs.flight import FlightRecorder
    from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)

    spec = load_spec()
    n = spec["n"]
    rng = np.random.default_rng(spec["seed"])
    plan = build_plan(rng, spec)

    cfg = with_eval_precision(RAFTStereoConfig(
        n_gru_layers=1, hidden_dims=(32, 32, 32),
        corr_levels=2, corr_radius=2))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    clock = FakeClock()
    flight_dir = tempfile.mkdtemp(prefix="chaos-flight-")
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False),
        fault_plan=plan, clock=clock,
        flight=FlightRecorder(flight_dir, limit=1000))
    svc = StereoService(session, ServiceConfig(
        max_queue=16,
        watchdog_ms=spec["watchdog_ms"],
        retry_budget=spec["retry_budget"],
        drain_grace_ms=10_000.0)).start()

    pairs = [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
              rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
             for _ in range(4)]
    deadlines = {i: float(rng.uniform(5_000.0, 40_000.0))
                 for i in range(n) if rng.uniform() < spec["deadline_frac"]}

    def make_request(i: int) -> dict:
        left, right = pairs[i % len(pairs)]
        # graftdeck (DESIGN.md r15): a rotating tenant population rides
        # the storm so the per-tenant device-seconds partition is
        # exercised under every fault class (bounces, retries, zombies).
        req = {"id": i, "left": left[None], "right": right[None],
               "tenant": f"tenant-{i % 5}"}
        if i in deadlines:
            req["deadline_ms"] = deadlines[i]
        # graftstream (DESIGN.md r17): a third of the storm rides stream
        # sessions, so warm joins, deposits, TTL sweeps and the session
        # table are exercised under every fault class — bounces must
        # re-admit warm rows with their held flow_init, expiry mid-storm
        # must drop deposits as counted no-ops, never crashes.
        if i % 3 == 0:
            req["stream"] = f"cam-{(i // 3) % 2}"
            if i % 9 == 0:
                # Loose tolerance: these frames exit converged:k when
                # they get the chance — the honest-label machinery under
                # the storm.
                req["converge_tol"] = 1e9
        return req

    t_real0 = time.monotonic()
    deadline_real = t_real0 + REAL_BOUND_S
    results: dict = {}
    futs: dict = {}
    submitted = 0
    trips_prev = 0
    tripped_prev: set = set()
    # /debug/stacks acceptance (graftdeck, DESIGN.md r15): while an
    # injected hang is parked, the all-thread stack dump must name the
    # parked invocation frame (the fault hook inside session.invoke's
    # watchdog bracket).  A dedicated capture thread wakes on the
    # hang-entry notification itself — deterministic, instead of racing
    # the supervisor sweep that will release the hang — and keeps
    # scanning until a parked frame is seen or the storm ends.
    import threading as _threading
    storm_done = _threading.Event()
    hang_capture = {"captured": False}

    def _capture_hang_stacks() -> None:
        from raft_stereo_tpu.obs.deck import thread_stacks
        seen = 0
        while not storm_done.is_set():
            # Wait for the NEXT hang entry (seen + 1), not merely >= 1:
            # after the first hang, a missed scan must park here for a
            # fresh hang instead of degrading into a ~1 kHz full-process
            # stack-scan busy loop that could starve the storm past its
            # real-time bound on a slow box.
            if not session.faults.wait_hang_entered(seen + 1, timeout=0.5):
                continue
            seen = session.faults.hangs_entered
            stacks = thread_stacks()
            for th in stacks["threads"]:
                if any(fr["function"] == "on_invoke"
                       and fr["file"].endswith("faults.py")
                       for fr in th["frames"]):
                    hang_capture["captured"] = True
                    return

    capture_thread = _threading.Thread(
        target=_capture_hang_stacks, name="chaos-stack-capture",
        daemon=True)
    if spec["hangs"]:
        capture_thread.start()

    while len(results) < n:
        assert time.monotonic() < deadline_real, (
            f"chaos soak exceeded its {REAL_BOUND_S}s real-time bound "
            f"with {n - len(results)} Futures unresolved — deadlock or "
            f"abandoned Future")
        while submitted < n and len(futs) < IN_FLIGHT_CAP:
            futs[submitted] = svc.submit(make_request(submitted))
            submitted += 1
        sup = svc._supervisor
        if sup is not None:
            sup.check_now()
        # Invariant 2: breaker trips are monotone, the tripped set grows.
        tc = session.breaker.trip_count
        assert tc >= trips_prev, f"breaker trip count fell {trips_prev}->{tc}"
        trips_prev = tc
        ts = set(session.breaker.tripped_names)
        assert ts >= tripped_prev, f"tripped set shrank {tripped_prev}->{ts}"
        tripped_prev = ts
        for rid in [r for r, f in futs.items() if f.done()]:
            results[rid] = futs.pop(rid).result(timeout=1)
        time.sleep(0.002)

    # graftstream deterministic scenarios (ISSUE 13 chaos pins), run
    # after the storm so their ordering is exact:
    # (a) mid-stream bounce: a warm frame submitted and immediately
    #     bounced must resolve ok, and its request dict must still hold
    #     the warm-start seed the re-admission rode (harvest preserves
    #     _flow_init — the scheduler-level twin is pinned in
    #     tests/test_stream.py).
    extra_responses = []
    sf1 = make_request(0)
    sf1["id"] = "stream-bounce-1"
    sf1["stream"] = "bounce-cam"
    sf1.pop("deadline_ms", None)
    extra_responses.append(svc.submit(sf1).result(timeout=30))
    assert extra_responses[-1]["status"] == "ok", extra_responses[-1]
    warm_before = int(svc.registry.value("raft_stream_warm_joins_total"))
    sf2 = make_request(0)
    sf2["id"] = "stream-bounce-2"
    sf2["stream"] = "bounce-cam"
    sf2.pop("deadline_ms", None)
    fut2 = svc.submit(sf2)
    assert svc.bounce("chaos_stream"), "manual mid-stream bounce refused"
    r2 = fut2.result(timeout=30)
    extra_responses.append(r2)
    assert r2["status"] == "ok", r2
    assert sf2.get("_flow_init") is not None, (
        "the bounced stream frame lost its held flow_init")
    assert int(svc.registry.value("raft_stream_warm_joins_total")) \
        >= warm_before + 1, "the warm frame never warm-joined"
    # (b) TTL expiry: the session clock jumping past the TTL while the
    #     stream is idle expires the session (counted); the next frame
    #     starts cold and still serves fine.
    expired_before = int(svc.registry.value(
        "raft_stream_sessions_expired_total"))
    clock.sleep(svc.stream.ttl_s * 2 + 1)
    sf3 = make_request(0)
    sf3["id"] = "stream-ttl"
    sf3["stream"] = "bounce-cam"
    sf3.pop("deadline_ms", None)
    extra_responses.append(svc.submit(sf3).result(timeout=30))
    assert extra_responses[-1]["status"] == "ok", extra_responses[-1]
    assert sf3.get("_flow_init") is None, (
        "an expired session handed out a stale warm-start seed")
    assert int(svc.registry.value(
        "raft_stream_sessions_expired_total")) >= expired_before + 1

    stream_status = svc.stream.status()
    assert stream_status["sessions"] <= stream_status["max_sessions"]

    # Invariant 5: draining rejects late submits, then quiesces clean.
    svc.begin_drain()
    late = svc.submit(make_request(0)).result(timeout=10)
    assert late["status"] == "rejected" and \
        late["code"] == "service_draining", late
    clean = svc.drain()
    assert clean, "drain failed to quiesce an idle service"
    assert svc.stream.status()["sessions"] == 0, (
        "stream sessions survived the drain — held flows must die with "
        "the service generation")

    # graftrecall deterministic scenarios (ISSUE 14, DESIGN.md r18),
    # post-storm so their ordering is exact — the STORM itself runs with
    # the cache off (the library default) so its fault ordinals stay
    # byte-stable across PRs:
    # (a) exact tier: a duplicate of a cold-served pair is answered
    #     cache:exact and byte-identical;
    # (b) near tier: a perturbed duplicate warm-starts from the stored
    #     neighbor and exits warm:cache:k with an honest k;
    # (c) churn: 200 tenants x 500 deposits against a small budget
    #     cannot grow cache bytes past the cap or add /metrics lines.
    from raft_stereo_tpu.serve import ServiceConfig as _SvcCfg
    cache_session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False),
        clock=FakeClock())
    csvc = StereoService(cache_session, _SvcCfg(
        max_queue=16, cache_bytes=32 << 20, cache_near_tol=6.0)).start()
    cl, cr = pairs[0]
    cold = csvc.submit({"id": "c-cold", "left": cl[None],
                        "right": cr[None]}).result(timeout=30)
    assert cold["status"] == "ok" and cold["quality"] == "full", cold
    dup = csvc.submit({"id": "c-dup", "left": cl[None],
                       "right": cr[None]}).result(timeout=30)
    assert dup["quality"] == "cache:exact", dup
    assert dup["disparity"].tobytes() == cold["disparity"].tobytes(), (
        "exact cache hit is not byte-identical to its cold compute")
    near_left = np.clip(cl + rng.normal(0, 2, cl.shape),
                        0, 255).astype(np.float32)
    near = csvc.submit({"id": "c-near", "left": near_left[None],
                        "right": cr[None],
                        "converge_tol": 1e9}).result(timeout=30)
    assert str(near["quality"]).startswith("warm:cache:"), near
    assert int(str(near["quality"]).rsplit(":", 1)[1]) == near["iters"], (
        f"dishonest near-hit label {near['quality']} vs {near['iters']}")
    ccache = csvc.cache
    ccache.max_bytes = 256 << 10
    ccache.per_tenant = 256 << 10
    cache_session.usage.max_tenants = 4  # force the __other__ bound fast
    churn_baseline = None
    for i in range(500):
        lj = cl[None] + np.float32(i % 251)
        creq = {"left": lj, "right": cr[None],
                "tenant": f"churn-{i % 200}"}
        ccache.admit(creq)
        ccache.deposit(creq, {
            "status": "ok", "quality": "full",
            "disparity": np.zeros((H, W), np.float32), "iters": 4})
        assert ccache.status()["bytes"] <= ccache.max_bytes, (
            "cache bytes grew past RAFT_CACHE_BYTES under churn")
        if i == 30:
            churn_baseline = len(csvc.metrics_text().splitlines())
    churn_final = len(csvc.metrics_text().splitlines())
    assert churn_final == churn_baseline, (
        f"/metrics grew {churn_baseline} -> {churn_final} under "
        f"200-tenant cache churn — a label leak")
    cache_status = csvc.cache.status()
    assert csvc.drain(), "cache-scenario service failed to drain"
    assert csvc.cache.status()["entries"] == 0, (
        "cache entries survived the drain")
    elapsed_real = time.monotonic() - t_real0

    # Invariant 1: every outcome is structured.
    responses = list(results.values()) + [late] + extra_responses
    assert len(results) == n
    for r in responses:
        assert r["status"] in ("ok", "rejected", "error"), r
        if r["status"] != "ok":
            assert r.get("code"), r
        else:
            assert np.isfinite(r["disparity"]).all()

    # Invariant 3: registry counters reconcile with collected outcomes.
    reg = svc.registry
    counts = {labels["outcome"]: int(v) for labels, v in
              reg.series("raft_requests_total")}
    expect: dict = {}
    for r in responses:
        key = (r["status"] if r["status"] == "ok"
               else f'{r["status"]}:{r["code"]}')
        expect[key] = expect.get(key, 0) + 1
    degraded = sum(1 for r in responses
                   if r["status"] == "ok" and r.get("quality") != "full")
    if degraded:
        expect["degraded"] = degraded
    assert counts == expect, f"counters {counts} != outcomes {expect}"
    retries_total = int(reg.value("raft_request_retries_total"))
    retries_seen = sum(r.get("retries", 0) for r in responses)
    assert retries_total == retries_seen, (retries_total, retries_seen)

    # Invariant 4: every watchdog bounce left a flight record naming it.
    restarts = {labels["reason"]: int(v) for labels, v in
                reg.series("raft_sched_restarts_total")}
    n_restarts = sum(restarts.values())
    assert n_restarts >= 1, (
        "the storm never exercised a generation bounce — the chaos plan "
        "is vacuous for supervision")
    if spec["hangs"]:
        assert session.faults.hangs_entered >= 1, (
            "hang ordinals never landed on a live invocation — the storm "
            "is vacuous for the device-hang path; retune build_plan()")
    storm_done.set()
    if spec["hangs"]:
        capture_thread.join(timeout=5)
        assert hang_capture["captured"], (
            "/debug/stacks never captured an injected hang's parked "
            "frame (faults.py on_invoke) while a hang was live — the "
            "introspection surface is blind to exactly the state it "
            "exists for")
    hang_stack_captured = hang_capture["captured"]

    # graftdeck invariant: per-tenant device-seconds sum to the
    # accounted program total EXACTLY (integer nanoseconds — the
    # obs/usage.py partition leaks nothing, double-counts nothing, under
    # bounces, retries and zombie discards), and the accounted total
    # reconciles with raft_program_device_seconds_total at float
    # tolerance (the counter is a float sum of the same intervals).
    usage_doc = session.usage.doc()
    tenant_ns = sum(t["device_ns"] for t in usage_doc["by_tenant"].values())
    assert tenant_ns == usage_doc["device_ns_total"], (
        f"per-tenant device-ns sum {tenant_ns} != accounted total "
        f"{usage_doc['device_ns_total']} — the usage partition leaked")
    prog_dev_s = sum(v for _, v in
                     reg.series("raft_program_device_seconds_total"))
    assert abs(usage_doc["device_ns_total"] / 1e9 - prog_dev_s) <= \
        max(1e-6, 1e-9 * prog_dev_s), (
        f"usage-accounted device seconds "
        f"{usage_doc['device_ns_total'] / 1e9} != program counter total "
        f"{prog_dev_s}")

    bounce_records = 0
    for path in session.flight.records():
        with open(path) as f:
            doc = json.load(f)
        if any(str(reason).startswith("watchdog:")
               for reason in doc.get("reasons", [])):
            bounce_records += 1
    assert bounce_records == n_restarts, (
        f"{n_restarts} bounces but {bounce_records} watchdog flight "
        f"records — a watchdog action left no evidence")

    # graftstream storm non-vacuity: the storm must actually have
    # exercised warm joins AND produced at least one honest converged:k
    # response (a third of the storm streams; loose-tolerance members
    # converge at their first boundary whenever they serve ok).
    n_converged_resp = sum(
        1 for r in responses
        if r.get("status") == "ok"
        and str(r.get("quality", "")).startswith("converged:"))
    assert n_converged_resp >= 1, (
        "no storm response carried a converged:k label — the streaming "
        "fault coverage is vacuous; retune the stream mix")
    for r in responses:  # honest-label invariant: k == iters run
        q = str(r.get("quality", ""))
        if q.startswith("converged:"):
            assert int(q.split(":")[1]) == r["iters"], r

    outcome_counts = dict(sorted(expect.items()))
    doc = {
        "metric": "chaos_soak",
        "pass": True,
        "n": n,
        "seed": spec["seed"],
        "outcomes": outcome_counts,
        "stream": {**{k: stream_status[k] for k in
                      ("created", "evicted", "expired", "warm_joins",
                       "converged_exits", "deposits_dropped")},
                   "converged_responses": n_converged_resp},
        "cache": {k: cache_status[k] for k in
                  ("hits", "near_hits", "misses", "evictions",
                   "deposits", "bytes")},
        "restarts": restarts,
        "watchdog_trips": {labels["kind"]: int(v) for labels, v in
                           reg.series("raft_watchdog_trips_total")},
        "retries": retries_total,
        "breaker_trips": session.breaker.trip_count,
        "flight_records": len(session.flight.records()),
        "tenants": len(usage_doc["by_tenant"]),
        "tenant_device_s": round(usage_doc["device_seconds_total"], 4),
        "hang_stack_captured": hang_stack_captured,
        "fault_ordinals": {"invokes": session.faults.invokes,
                           "uploads": session.faults.uploads,
                           "ticks": session.faults.ticks,
                           "hangs_entered": session.faults.hangs_entered},
        "elapsed_real_s": round(elapsed_real, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    emit("chaos_soak_resolved_frac", 1.0, "frac",
         backend=jax.default_backend(), source="scratch/chaos_serve.py",
         extra={"n": n, "restarts": sum(restarts.values()),
                "retries": retries_total,
                "elapsed_real_s": doc["elapsed_real_s"]})
    return 0


# ---------------------------------------------------------------------------
# Wire storm (graftwire, DESIGN.md r14): hostile clients over real loopback
# sockets. The fault plan describes CLIENT behavior; the listener, codec,
# decode pool and service underneath are unmodified production code.
# ---------------------------------------------------------------------------

#: Hard real-time bound on the wire storm (CPU, tiny model, fixed seed).
WIRE_BOUND_S = 120.0

#: Token-bucket burst for the dedicated "hog" tenant — small enough that
#: the seeded storm provably overruns it (quota exactness is asserted).
QUOTA_BURST = 4

#: (status, code) every hostile kind must be answered with; None = the
#: client disconnects without reading (server accounting still asserted).
WIRE_EXPECT = {
    "ok": (200, "ok"),
    "truncated_body": (400, "truncated_body"),
    "stalled_body": (408, "read_timeout"),
    "garbage_image": (400, "bad_image"),
    "bomb_image": (413, "image_too_large"),
    "header_flood": (431, "too_many_headers"),
    "oversize_content_length": (413, "body_too_large"),
    "empty_body": (400, "empty_body"),
    "bad_multipart": (400, "bad_multipart"),
    "wrong_route": (404, "unknown_route"),
    "bad_method": (405, "method_not_allowed"),
    "disconnect_mid_request": None,
}

def _wire_exchange(addr, data: bytes, half_close: bool = False,
                   read_response: bool = True, timeout: float = 30.0):
    """One hostile client: raw bytes out, (status, code, headers) parsed
    from whatever comes back before the server closes the connection —
    or None when the client vanishes without reading."""
    import socket

    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(data)
        if not read_response:
            return None  # disconnect_mid_request: close without reading
        if half_close:
            s.shutdown(socket.SHUT_WR)
        chunks = []
        try:
            while True:
                b = s.recv(65536)
                if not b:
                    break
                chunks.append(b)
        except (socket.timeout, TimeoutError):
            pass
    raw = b"".join(chunks)
    assert raw.startswith(b"HTTP/1."), f"non-HTTP response: {raw[:80]!r}"
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    headers = {}
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        headers[k.decode("latin-1").strip().lower()] = \
            v.decode("latin-1").strip()
    code = "ok" if status == 200 else \
        json.loads(body.partition(b"\r\n\r\n")[0] or b"{}").get("code")
    return status, code, headers


def main_wire() -> int:
    import signal
    import socket
    import threading
    from collections import Counter
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.faults import WireChaosPlan, bomb_png
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.serve import (HttpConfig, HttpFrontend,
                                       InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)
    from raft_stereo_tpu.serve import wire

    n = int(os.environ.get("RAFT_CHAOS_N", "96"))
    seed = int(os.environ.get("RAFT_CHAOS_SEED", "1234"))
    plan = WireChaosPlan.seeded(seed, n, hostile_frac=0.5)
    kind_of = {i: plan.faults.get(i, "ok") for i in range(n)}

    cfg = with_eval_precision(RAFTStereoConfig(
        n_gru_layers=1, hidden_dims=(32, 32, 32),
        corr_levels=2, corr_radius=2))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False,
                      warmup_shapes=((H, W),), warmup_segmented=True))
    svc = StereoService(session, ServiceConfig(max_queue=16)).start()
    fe = HttpFrontend(svc, HttpConfig(
        port=0, read_timeout_ms=300.0,
        tenant_rate=f"0.000001:{QUOTA_BURST}",
        decode_workers=2)).start()
    addr = (fe.host, fe.port)
    reg = fe.registry

    # Client-side request material: two encoded stereo pairs, reused.
    rng = np.random.default_rng(seed)
    png_pairs = []
    for _ in range(2):
        left = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
        right = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
        png_pairs.append((wire.encode_image_png(left),
                          wire.encode_image_png(right)))

    # Tenant assignment: every 3rd well-formed request shares the "hog"
    # bucket (burst QUOTA_BURST, negligible refill -> quota rejections
    # are EXACTLY max(0, n_hog - burst), order-free); every other request
    # gets a unique tenant and can never be quota-limited, so the
    # per-kind expectations above stay deterministic.
    def tenant_for(i: int) -> str:
        return "hog" if kind_of[i] == "ok" and i % 3 == 0 else f"c{i}"

    n_hog = sum(1 for i in range(n)
                if kind_of[i] == "ok" and i % 3 == 0)
    expected_429 = max(0, n_hog - QUOTA_BURST)

    def head_bytes(path: str, ct: str, length: int, tenant: str,
                   method: str = "POST", extra=()) -> bytes:
        lines = [f"{method} {path} HTTP/1.1", "Host: storm",
                 f"Content-Type: {ct}", f"Content-Length: {length}",
                 f"X-Raft-Tenant: {tenant}", "Connection: close"]
        lines += [f"{k}: {v}" for k, v in extra]
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    def play(i: int):
        kind = kind_of[i]
        tenant = tenant_for(i)
        lpng, rpng = png_pairs[i % len(png_pairs)]
        ct, body = wire.build_multipart(
            {"left": lpng, "right": rpng, "id": f"w-{i}".encode()})
        if kind in ("ok", "disconnect_mid_request"):
            data = head_bytes("/v1/stereo", ct, len(body), tenant) + body
            return _wire_exchange(
                addr, data, read_response=(kind == "ok"))
        if kind == "truncated_body":
            cut = body[:int(len(body) * plan.truncate_frac)]
            data = head_bytes("/v1/stereo", ct, len(body), tenant) + cut
            return _wire_exchange(addr, data, half_close=True)
        if kind == "stalled_body":
            cut = body[:int(len(body) * plan.stall_frac)]
            data = head_bytes("/v1/stereo", ct, len(body), tenant) + cut
            # no half-close: the socket simply goes silent; the server's
            # per-read timeout must evict us with a structured 408
            # BEFORE the client gives up at stall_hold_s (the plan field
            # that keeps this fault non-vacuous: hold > per-read timeout)
            assert plan.stall_hold_s > fe.read_timeout_s, (
                "stalled_body is vacuous: the client hangs up before "
                "the server's per-read timeout can fire")
            return _wire_exchange(addr, data, timeout=plan.stall_hold_s)
        if kind == "garbage_image":
            _, gbody = wire.build_multipart(
                {"left": b"\x89PNG garbage", "right": b"more garbage",
                 "id": f"w-{i}".encode()})
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, len(gbody), tenant)
                + gbody)
        if kind == "bomb_image":
            bomb = bomb_png(20_000, 20_000)
            _, bbody = wire.build_multipart(
                {"left": bomb, "right": bomb, "id": f"w-{i}".encode()})
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, len(bbody), tenant)
                + bbody)
        if kind == "header_flood":
            extra = [(f"X-Flood-{j}", "y")
                     for j in range(plan.flood_headers)]
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, 0, tenant,
                                 extra=extra))
        if kind == "oversize_content_length":
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, fe.body_max + 1,
                                 tenant))
        if kind == "empty_body":
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, 0, tenant))
        if kind == "bad_multipart":
            cut = body[:-8]  # consistent length, framing cut short
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, len(cut), tenant)
                + cut)
        if kind == "wrong_route":
            return _wire_exchange(
                addr, head_bytes("/v1/nope", ct, len(body), tenant)
                + body)
        if kind == "bad_method":
            return _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, len(body), tenant,
                                 method="DELETE") + body)
        raise AssertionError(f"unknown fault kind {kind!r}")

    t0 = time.monotonic()
    deadline = t0 + WIRE_BOUND_S
    observed = {}
    with ThreadPoolExecutor(max_workers=8,
                            thread_name_prefix="storm-client") as pool:
        futs = {i: pool.submit(play, i) for i in range(n)}
        for i, f in futs.items():
            observed[i] = f.result(timeout=max(1.0,
                                               deadline - time.monotonic()))

    def responses_total() -> int:
        return sum(int(v) for _, v in
                   reg.series("raft_http_responses_total"))

    # Every request — read or abandoned — must produce exactly ONE
    # accounting entry (abandoned ones finish asynchronously; bounded
    # wait, not a sleep).
    while responses_total() < n:
        assert time.monotonic() < deadline, (
            f"only {responses_total()}/{n} requests ever produced a "
            f"response accounting entry — a socket or Future is stranded")
        time.sleep(0.05)
    elapsed_storm = time.monotonic() - t0

    # -- invariant 1: every read response matches its kind exactly -------
    n_429 = 0
    for i, out in observed.items():
        kind = kind_of[i]
        expect = WIRE_EXPECT[kind]
        if expect is None:
            assert out is None
            continue
        status, code, headers = out
        if kind == "ok" and tenant_for(i) == "hog" and status == 429:
            n_429 += 1
            assert code == "quota_exceeded" and "retry-after" in headers, \
                (i, status, code, headers)
            continue
        assert (status, code) == expect, (i, kind, status, code)
        if code in ("queue_full", "service_draining", "quota_exceeded",
                    "read_timeout"):
            assert "retry-after" in headers or code == "read_timeout"

    # -- invariant 2: per-tenant quota rejections are EXACT --------------
    assert n_429 == expected_429, (
        f"hog tenant saw {n_429} quota rejections, bucket math says "
        f"exactly {expected_429} (n_hog={n_hog}, burst={QUOTA_BURST})")
    tenant_counts = {(labels["tenant"], labels["outcome"]): int(v)
                     for labels, v in
                     reg.series("raft_http_tenant_requests_total")}
    assert tenant_counts.get(("hog", "quota_exceeded"), 0) == expected_429
    assert tenant_counts.get(("hog", "admitted"), 0) == \
        n_hog - expected_429

    # -- invariant 3: counters reconcile exactly with wire outcomes ------
    server = Counter()
    for labels, v in reg.series("raft_http_responses_total"):
        server[labels["code"]] += int(v)
    client = Counter(code for out in observed.values() if out
                     for code in [out[1]])
    unread = sum(1 for out in observed.values() if out is None)
    assert sum(server.values()) == n, (server, n)
    for code in set(server) | set(client):
        if code in ("ok", "client_disconnect"):
            continue
        assert server[code] == client[code], (code, server, client)
    # An abandoned request lands as 'ok' (write beat the close into the
    # dead socket's buffer) or 'client_disconnect' — exactly one of them.
    assert server["ok"] + server["client_disconnect"] == \
        client["ok"] + unread, (server, client, unread)

    # -- invariant 4: zero acceptor/decoder deaths, zero stranded work ---
    crashes = sum(int(v) for _, v in
                  reg.series("raft_http_handler_crashes_total"))
    assert crashes == 0, f"{crashes} handler crash(es) during the storm"
    assert svc._outstanding == 0, (
        f"{svc._outstanding} Future(s) still outstanding after the storm")
    join_deadline = time.monotonic() + 10
    while any("process_request_thread" in t.name
              for t in threading.enumerate()):
        assert time.monotonic() < join_deadline, (
            "connection-handler threads still alive after the storm — "
            "a socket is stranded: "
            + str([t.name for t in threading.enumerate()]))
        time.sleep(0.05)

    # -- invariant 5: mid-storm SIGTERM drains clean ---------------------
    stop_evt = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    try:
        # an admitted row must run to its segment-boundary exit through
        # the drain — pin one in flight before the signal lands
        lpng, rpng = png_pairs[0]
        inflight = svc.submit({
            "id": "drain-pinned",
            "left": np.asarray(
                np.random.default_rng(0).uniform(0, 255, (H, W, 3)),
                np.float32)[None],
            "right": np.asarray(
                np.random.default_rng(1).uniform(0, 255, (H, W, 3)),
                np.float32)[None]})
        os.kill(os.getpid(), signal.SIGTERM)
        assert stop_evt.wait(5), "SIGTERM handler never fired"
        svc.begin_drain()
        late_codes = []
        for j in range(4):
            ct, body = wire.build_multipart(
                {"left": lpng, "right": rpng, "id": f"late-{j}".encode()})
            out = _wire_exchange(
                addr, head_bytes("/v1/stereo", ct, len(body), f"late{j}")
                + body)
            status, code, headers = out
            assert (status, code) == (503, "service_draining"), out
            assert "retry-after" in headers
            late_codes.append(code)
        pinned = inflight.result(timeout=60)
        assert pinned["status"] == "ok", pinned
        assert svc.drain() is True, "drain failed to quiesce"
    finally:
        signal.signal(signal.SIGTERM, prev)
    fe.stop()
    try:
        socket.create_connection(addr, timeout=2).close()
        raise AssertionError("listener still accepting after stop()")
    except ConnectionRefusedError:
        pass  # drained AND stopped accepting: the contract's final step

    elapsed = time.monotonic() - t0
    kind_counts = Counter(kind_of.values())
    doc = {
        "metric": "wire_chaos",
        "pass": True,
        "n": n,
        "seed": seed,
        "kinds": dict(sorted(kind_counts.items())),
        "server_codes": dict(sorted(server.items())),
        "quota": {"hog_requests": n_hog, "burst": QUOTA_BURST,
                  "rejected": n_429},
        "late_draining_503": len(late_codes),
        "handler_crashes": crashes,
        "elapsed_storm_s": round(elapsed_storm, 2),
        "elapsed_real_s": round(elapsed, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    emit("wire_chaos_structured_frac", 1.0, "frac",
         backend=jax.default_backend(), source="scratch/chaos_serve.py",
         extra={"n": n, "quota_rejected": n_429,
                "elapsed_real_s": doc["elapsed_real_s"]})
    return 0


# ---------------------------------------------------------------------------
# Pod mesh storm (graftpod, DESIGN.md r21): one chip of a 2-chip data mesh
# hangs; the bounce must quarantine it ALONE and the survivors keep serving.
# ---------------------------------------------------------------------------

#: Hard real-time bound on the mesh storm (CPU fake devices, tiny model;
#: each chip-probe sweep with a parked probe costs ~2 s real).
MESH_BOUND_S = 120.0


def main_mesh() -> int:
    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.faults import ChaosPlan, FakeClock
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.obs.flight import FlightRecorder
    from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)

    n = int(os.environ.get("RAFT_CHAOS_N", "36"))
    seed = int(os.environ.get("RAFT_CHAOS_SEED", "1234"))
    assert len(jax.devices()) >= 2, (
        f"mesh storm needs >=2 devices, found {len(jax.devices())} — run "
        f"under XLA_FLAGS=--xla_force_host_platform_device_count=8 (the "
        f"__main__ dispatch arms it when unset)")
    rng = np.random.default_rng(seed)
    # Two invoke-hang ordinals past the compile-heavy head (either one
    # suffices to trip the watchdog; non-vacuity is asserted below), and
    # chip 1's post-bounce health probe parks — the chip that "stayed
    # wedged after the bounce freed the invoke".  hang_cap_s must exceed
    # probe_chips' join timeout (2 s) or the parked probe self-releases
    # early and reads healthy.
    plan = ChaosPlan(hang_invokes={10: 10.0, 18: 10.0},
                     hang_chips=(1,), hang_cap_s=5.0)

    cfg = with_eval_precision(RAFTStereoConfig(
        n_gru_layers=1, hidden_dims=(32, 32, 32),
        corr_levels=2, corr_radius=2))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    clock = FakeClock()
    flight_dir = tempfile.mkdtemp(prefix="chaos-mesh-flight-")
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False, mesh_data=2),
        fault_plan=plan, clock=clock,
        flight=FlightRecorder(flight_dir, limit=1000))
    assert session.mesh_active and session.mesh_chips == 2, \
        session.mesh_status()
    assert all(b % 2 == 0 for b in session.batch_buckets), (
        f"mesh bucket rounding never engaged: {session.batch_buckets}")
    svc = StereoService(session, ServiceConfig(
        max_queue=16, watchdog_ms=2000.0, retry_budget=3,
        drain_grace_ms=10_000.0)).start()

    pairs = [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
              rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
             for _ in range(4)]

    def make_request(i) -> dict:
        left, right = pairs[hash(str(i)) % len(pairs)]
        req = {"id": i, "left": left[None], "right": right[None],
               "tenant": f"tenant-{hash(str(i)) % 3}"}
        # A third of the storm rides stream sessions: round-robin chip
        # affinity pins cam-0 -> chip 0, cam-1 -> chip 1, so the
        # quarantine provably has a pinned session to migrate.
        if isinstance(i, int) and i % 3 == 0:
            req["stream"] = f"cam-{(i // 3) % 2}"
        return req

    t_real0 = time.monotonic()
    deadline_real = t_real0 + MESH_BOUND_S
    results: dict = {}
    futs: dict = {}
    submitted = 0
    while len(results) < n:
        assert time.monotonic() < deadline_real, (
            f"mesh storm exceeded its {MESH_BOUND_S}s real-time bound "
            f"with {n - len(results)} Futures unresolved")
        while submitted < n and len(futs) < IN_FLIGHT_CAP:
            futs[submitted] = svc.submit(make_request(submitted))
            submitted += 1
        sup = svc._supervisor
        if sup is not None:
            sup.check_now()
        for rid in [r for r, f in futs.items() if f.done()]:
            results[rid] = futs.pop(rid).result(timeout=1)
        time.sleep(0.002)

    # -- invariant 1: the hang landed and the watchdog bounced -----------
    reg = svc.registry
    assert session.faults.hangs_entered >= 1, (
        "no injected hang ever parked a live invocation — the mesh storm "
        "is vacuous for the device-hang path; retune the ordinals")
    restarts = {labels["reason"]: int(v) for labels, v in
                reg.series("raft_sched_restarts_total")}
    assert restarts.get("device_hang", 0) >= 1, (
        f"no device_hang bounce ever fired: {restarts}")

    # -- invariant 2: the bounce quarantined EXACTLY chip 1 --------------
    mesh = session.mesh_status()
    assert mesh["quarantined"] == [1], (
        f"chip-local quarantine failed — expected exactly chip 1, got "
        f"{mesh}")
    assert mesh["enabled"] and mesh["n_data"] == 1, mesh
    assert mesh["epoch"] >= 1 and mesh["base_n_data"] == 2, mesh
    assert int(reg.value("raft_mesh_chips_quarantined_total")) == 1

    # -- invariant 3: the chip-pinned stream session migrated warm -------
    assert int(reg.value("raft_stream_migrations_total")) >= 1, (
        "chip 1's pinned stream session never migrated off the "
        "quarantined chip")
    by_chip = svc.stream.status()["by_chip"]
    assert all(int(c) == 0 for c in by_chip), (
        f"a stream session is still pinned past the 1-chip mesh: "
        f"{by_chip}")
    warm_before = int(reg.value("raft_stream_warm_joins_total"))
    sfm = make_request(3)       # cam-1: the migrated session's stream
    sfm["id"] = "post-quarantine-warm"
    rwm = svc.submit(sfm).result(timeout=30)
    assert rwm["status"] == "ok", rwm
    assert sfm.get("_flow_init") is not None, (
        "the migrated stream session lost its held warm seed — the seed "
        "is host-side state and must survive a chip quarantine")
    assert int(reg.value("raft_stream_warm_joins_total")) \
        >= warm_before + 1, "the migrated stream frame never warm-joined"

    # -- invariant 4: the SURVIVING chips keep serving -------------------
    post = [svc.submit(make_request(f"post-{j}")).result(timeout=30)
            for j in range(8)]
    for r in post:
        assert r["status"] == "ok", (
            f"post-quarantine serving failed — the bounce was not "
            f"chip-local: {r}")
        assert np.isfinite(r["disparity"]).all()

    # -- invariant 5: every storm outcome is structured ------------------
    responses = list(results.values()) + [rwm] + post
    assert len(results) == n
    for r in responses:
        assert r["status"] in ("ok", "rejected", "error"), r
        if r["status"] != "ok":
            assert r.get("code"), r

    # -- invariant 6: the books reconcile under the mesh -----------------
    # Pod-wide ticks really spanned 2 chips, and the device-seconds
    # partition stays exact integer-ns: one invocation's wall interval is
    # ONE interval no matter how many chips it spanned.
    mesh_ticks = sum(1 for t in session.deck.snapshot()
                     if int(t.get("chips", 1)) > 1)
    assert mesh_ticks >= 1, (
        "no tick ever spanned >1 chip — the mesh storm never exercised a "
        "pod-wide device batch")
    usage_doc = session.usage.doc()
    tenant_ns = sum(t["device_ns"] for t in usage_doc["by_tenant"].values())
    assert tenant_ns == usage_doc["device_ns_total"], (
        f"per-tenant device-ns sum {tenant_ns} != accounted total "
        f"{usage_doc['device_ns_total']} under the mesh")
    prog_dev_s = sum(v for _, v in
                     reg.series("raft_program_device_seconds_total"))
    assert abs(usage_doc["device_ns_total"] / 1e9 - prog_dev_s) <= \
        max(1e-6, 1e-9 * prog_dev_s), (
        usage_doc["device_ns_total"] / 1e9, prog_dev_s)
    cap_doc = session.capacity_status()
    chips = cap_doc.get("chips")
    assert chips and chips["n_data"] == 1 and \
        chips["quarantined"] == [1], chips
    assert len(chips["per_chip"]) == 2, chips
    for row in chips["per_chip"]:
        if row["quarantined"]:
            assert row["headroom_rps"] == 0.0, (
                f"a quarantined chip still advertises headroom: {row}")

    # -- invariant 7: the bounce left chip-naming flight evidence --------
    mesh_records = 0
    for path in session.flight.records():
        with open(path) as f:
            doc = json.load(f)
        mesh_block = doc.get("mesh")
        if mesh_block and mesh_block.get("quarantined") == [1]:
            mesh_records += 1
    assert mesh_records >= 1, (
        "the quarantining bounce left no flight record naming chip 1")

    assert svc.drain(), "mesh-storm service failed to drain"
    elapsed_real = time.monotonic() - t_real0

    outcomes: dict = {}
    for r in responses:
        key = (r["status"] if r["status"] == "ok"
               else f'{r["status"]}:{r["code"]}')
        outcomes[key] = outcomes.get(key, 0) + 1
    doc = {
        "metric": "mesh_chaos",
        "pass": True,
        "n": n,
        "seed": seed,
        "devices": len(jax.devices()),
        "mesh": {k: mesh[k] for k in
                 ("n_data", "base_n_data", "epoch", "quarantined")},
        "outcomes": dict(sorted(outcomes.items())),
        "restarts": restarts,
        "migrations": int(reg.value("raft_stream_migrations_total")),
        "mesh_ticks": mesh_ticks,
        "hangs_entered": session.faults.hangs_entered,
        "elapsed_real_s": round(elapsed_real, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    emit("mesh_chaos_chip_bounce", 1.0, "frac",
         backend=jax.default_backend(), source="scratch/chaos_serve.py",
         extra={"n": n, "restarts": sum(restarts.values()),
                "quarantined": mesh["quarantined"],
                "elapsed_real_s": doc["elapsed_real_s"]})
    return 0


# ---------------------------------------------------------------------------
# Recovery storm (graftheal, DESIGN.md r22): the fault CLEARS.  main_mesh
# proves detection (one-way quarantine under a persistent fault); this storm
# proves the other half of the r22 contract — probation probes re-admit a
# healthy chip, a flapping chip is retired for good, and a poisoned breaker
# rung's half-open canary fails CLOSED until the poison clears.
# ---------------------------------------------------------------------------

#: Hard real-time bound on the recovery storm (CPU fake devices, tiny
#: model; each failed probation probe parks ~2 s real, plus two parity
#: canaries' plain-XLA compiles).
HEAL_BOUND_S = 240.0


def _labeled(reg, name: str, **labels) -> int:
    """Sum of a counter's series rows matching the given labels."""
    return sum(int(v) for lab, v in reg.series(name)
               if all(lab.get(k) == want for k, want in labels.items()))


def main_heal() -> int:
    import numpy as np

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.faults import ChaosPlan, FakeClock
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.obs.capacity import headroom_recovered
    from raft_stereo_tpu.obs.flight import FlightRecorder
    from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                       SessionConfig, StereoService)

    n = int(os.environ.get("RAFT_CHAOS_N", "24"))
    seed = int(os.environ.get("RAFT_CHAOS_SEED", "1234"))
    assert len(jax.devices()) >= 2, (
        f"recovery storm needs >=2 devices, found {len(jax.devices())} — "
        f"run under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
        f"(the __main__ dispatch arms it when unset)")
    rng = np.random.default_rng(seed)

    cfg = with_eval_precision(RAFTStereoConfig(
        n_gru_layers=1, hidden_dims=(32, 32, 32),
        corr_levels=2, corr_radius=2))
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    clock = FakeClock()
    flight_dir = tempfile.mkdtemp(prefix="chaos-heal-flight-")
    # Every forward costs 0.05 s of injected FAKE device time, in every
    # plan of the storm: on a FakeClock nothing else advances the clock,
    # so without this the latency EMAs never warm and the pre/post
    # headroom comparison the acceptance hinges on would be vacuous.
    SLOW = {o: 0.05 for o in range(4000)}
    # The storm STARTS benign (plan swapped in mid-run below): the
    # pre-fault headroom reading must come from clean steady serving.
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      batch_buckets=(1, 4), canary=False, mesh_data=2,
                      warmup_shapes=((H, W),)),
        fault_plan=ChaosPlan(slow_forwards=SLOW), clock=clock,
        flight=FlightRecorder(flight_dir, limit=1000))
    assert session.mesh_active and session.mesh_chips == 2, \
        session.mesh_status()
    hs0 = session.heal_status()
    assert hs0["enabled"], (
        "the recovery storm needs RAFT_HEAL on (the default)")
    base_backoff_s = hs0["backoff_ms"] / 1e3
    flap_cap = int(hs0["flap_cap"])
    assert flap_cap >= 2, (
        f"flap-cap pin needs RAFT_HEAL_FLAP_CAP >= 2, got {flap_cap}")
    # Long stream TTL: the probation sweeps jump the fake clock minutes
    # at a time and the re-placement pin needs the parked sessions alive.
    svc = StereoService(session, ServiceConfig(
        max_queue=16, watchdog_ms=2000.0, retry_budget=3,
        drain_grace_ms=10_000.0,
        stream_ttl_ms=100 * base_backoff_s * 1e3)).start()
    reg = svc.registry

    pairs = [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
              rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
             for _ in range(4)]

    def make_request(i) -> dict:
        left, right = pairs[hash(str(i)) % len(pairs)]
        req = {"id": i, "left": left[None], "right": right[None],
               "tenant": f"tenant-{hash(str(i)) % 3}"}
        # A third of the storm rides stream sessions so the shrink parks
        # them and the re-grow's repin seam has real rows to re-place.
        if isinstance(i, int) and i % 3 == 0:
            req["stream"] = f"cam-{(i // 3) % 2}"
        return req

    t_real0 = time.monotonic()
    deadline_real = t_real0 + HEAL_BOUND_S
    responses: list = []

    def pump(tag: str, count: int) -> None:
        """Closed-loop serve ``count`` requests, supervisor armed."""
        futs: dict = {}
        done = 0
        submitted = 0
        while done < count:
            assert time.monotonic() < deadline_real, (
                f"recovery storm exceeded its {HEAL_BOUND_S}s bound in "
                f"phase {tag} with {count - done} Futures unresolved")
            while submitted < count and len(futs) < IN_FLIGHT_CAP:
                futs[submitted] = svc.submit(
                    make_request(f"{tag}-{submitted}"
                                 if tag != "storm" else submitted))
                submitted += 1
            sup = svc._supervisor
            if sup is not None:
                sup.check_now()
            for rid in [r for r, f in futs.items() if f.done()]:
                responses.append(futs.pop(rid).result(timeout=1))
                done += 1
            time.sleep(0.002)

    def steady_headroom(tag: str):
        """One canonical capacity reading: idle fake time, then require
        the model's winning candidate to be fully warmed.

        The idle gap first: with zero idle the injected device seconds
        fill the whole covered window and saturation clamps to 1.0,
        zeroing the very headroom the acceptance compares.  The
        ``partial`` gate second: the model scores a batch bucket as
        soon as its ADVANCE EMA exists, treating a missing
        prepare/epilogue estimate as 0 — an honest under-informed
        ceiling, but ~2x the warmed number, and WHICH components have
        warmed at read time depends on how the closed-loop traffic
        happened to batch (thread timing).  Pump more steady traffic
        until the winner is complete: the per-forward fake cost is
        constant, so a non-partial reading is deterministic and the
        pre/post comparison measures the fault excursion, not the
        warmup race."""
        cap = None
        for attempt in range(6):
            clock.sleep(30.0)
            cap = session.capacity_status()
            winners = [b_ for b_ in cap["by_bucket"].values()
                       if b_.get("rps") is not None]
            best = max(winners, key=lambda b_: b_["rps"], default=None)
            rows_ = [r_.get("headroom_rps")
                     for r_ in cap.get("chips", {}).get("per_chip", [])]
            total = (sum(v for v in rows_ if v is not None)
                     if any(v is not None for v in rows_) else None)
            if best is not None and not best.get("partial") and total:
                return cap, total
            pump(f"{tag}-warm{attempt}", 8)
        raise AssertionError(
            f"capacity model never fully warmed for the {tag} read — "
            f"the recovery comparison would be vacuous: "
            f"{cap['by_bucket'] if cap else None}")

    # -- phase 0: clean steady serving; pre-fault headroom ---------------
    pump("warm", n)
    cap_pre, pre = steady_headroom("pre")

    # -- phase 1: TRANSIENT fault lands; detection quarantines chip 1 ----
    inv = session.faults.invokes
    session.faults.plan = ChaosPlan(
        hang_invokes={inv + 2: 10.0, inv + 6: 10.0},
        hang_chips=(1,), hang_cap_s=5.0, slow_forwards=SLOW)
    pump("storm", n)
    assert session.faults.hangs_entered >= 1, (
        "no injected hang ever parked a live invocation — the recovery "
        "storm is vacuous for the device-hang path; retune the ordinals")
    restarts = {labels["reason"]: int(v) for labels, v in
                reg.series("raft_sched_restarts_total")}
    assert restarts.get("device_hang", 0) >= 1, (
        f"no device_hang bounce ever fired: {restarts}")
    mesh = session.mesh_status()
    assert mesh["quarantined"] == [1] and mesh["n_data"] == 1, mesh
    by_chip = svc.stream.status()["by_chip"]
    assert all(int(c) == 0 for c in by_chip), (
        f"a stream session is still pinned past the 1-chip mesh: "
        f"{by_chip}")
    # The second hang ordinal can advance the fake clock AFTER the
    # quarantine landed, so MTTR is pinned against the probation
    # record's own quarantined_at, not this read point.
    t_q0 = session._chip_heal[1]["quarantined_at"]

    # Re-base the transient window NOW (plan installation re-bases it):
    # the first probation probe must land INSIDE the still-active window
    # (fails, backoff doubles), the second after it clears (passes).
    clear_after_s = base_backoff_s + 10.0
    session.faults.plan = ChaosPlan(
        hang_chips=(1,), hang_cap_s=5.0,
        clear_after_ms=clear_after_s * 1e3, slow_forwards=SLOW)

    # -- phase 2: recovery is EXPLICIT, detection stays one-way ----------
    # The supervisor's monitor never heals: check_now() with the backoff
    # elapsed must leave the chip quarantined; only heal_sweep() probes.
    res = svc.heal_sweep()
    assert res["mesh"]["probed"] == [], (
        f"a probation probe fired before the backoff elapsed: {res}")
    clock.sleep(base_backoff_s + 1.0)
    sup = svc._supervisor
    if sup is not None:
        sup.check_now()
    assert session.mesh_status()["quarantined"] == [1], (
        "the supervisor's monitor re-admitted a chip — recovery must "
        "never ride the detection path")
    # Still-wedged probe (window active): fail, backoff doubles.
    res = svc.heal_sweep()
    assert res["mesh"]["probed"] == [1] and res["mesh"]["failed"] == [1], res
    chip_hs = session.heal_status()["chips"]["1"]
    assert chip_hs["backoff_ms"] == 2 * base_backoff_s * 1e3, chip_hs
    assert _labeled(reg, "raft_heal_chip_probes_total",
                    result="failed") == 1
    assert session.mesh_status()["quarantined"] == [1]

    # Touch one parked stream session so the re-grow provably re-places
    # a live row (host-side seed held across the whole excursion).
    sfh = make_request(0)
    sfh["id"] = "heal-parked-stream"
    r = svc.submit(sfh).result(timeout=30)
    assert r["status"] == "ok", r
    responses.append(r)

    # -- phase 3: the fault clears; the probe passes; the mesh re-grows --
    clock.sleep(2 * base_backoff_s + 1.0)
    t_sweep = clock.now()
    res = svc.heal_sweep()
    assert res["mesh"]["readmitted"] == [1], (
        f"the cleared fault's probation probe failed to re-admit: {res}")
    mesh = session.mesh_status()
    assert mesh["quarantined"] == [] and mesh["n_data"] == 2, mesh
    assert int(reg.value("raft_heal_chips_readmitted_total")) == 1
    assert _labeled(reg, "raft_heal_chip_probes_total",
                    result="passed") == 1
    assert res["stream_repinned"] >= 1, (
        f"no parked stream session was re-placed onto the re-grown "
        f"mesh: {res}")
    n_repinned = res["stream_repinned"]
    mttr = session.heal_status()["mttr"]
    assert mttr["events"] == 1 and mttr["last_s"] is not None
    # Readmit stamps MTTR BEFORE its re-warm advances the fake clock:
    # bounded by the sweep-entry and sweep-exit clock reads.
    assert (t_sweep - t_q0) <= mttr["last_s"] <= (clock.now() - t_q0), \
        (mttr, t_sweep - t_q0, clock.now() - t_q0)

    # -- phase 4: capacity actually returned (the 10% acceptance) --------
    pump("post", 8)
    # Same measurement protocol as the pre-fault read (steady serving,
    # idle gap, fully-warmed winner), so the saturation term cancels
    # and the comparison isolates what the fault excursion did.
    cap_post, post = steady_headroom("post")
    post_rows = cap_post.get("chips", {}).get("per_chip", [])
    assert all(not r_["quarantined"] for r_ in post_rows), post_rows
    recovered = headroom_recovered(pre, post)
    if os.environ.get("RAFT_CHAOS_DEBUG"):
        print("PRE ", json.dumps(cap_pre["by_bucket"], default=str))
        print("PRE-SAT ", json.dumps(cap_pre.get("saturation"), default=str))
        print("POST", json.dumps(cap_post["by_bucket"], default=str))
        print("POST-SAT", json.dumps(cap_post.get("saturation"), default=str))
    assert recovered is True, (
        f"headroom never recovered: pre={pre:.3f} post={post:.3f} rps")

    # -- phase 5: a poisoned rung's half-open canary fails CLOSED --------
    assert "fuse_iter" not in session.breaker.tripped_names
    session.breaker.trip("fuse_iter", "storm_injected")
    run_cfg_before = session._run_cfg
    fwd = session.faults.forwards
    session.faults.plan = ChaosPlan(
        poison_outputs=tuple(range(fwd, fwd + 64)), slow_forwards=SLOW)
    clock.sleep(base_backoff_s + 1.0)
    res = svc.heal_sweep()
    assert res["breaker"] == {"rung": "fuse_iter", "passed": False}, res
    assert "fuse_iter" in session.breaker.tripped_names, (
        "a FAILED half-open canary untripped the rung")
    assert session._run_cfg is run_cfg_before, (
        "a failed canary re-projected the serving config — the poisoned "
        "rung was served from half-open")
    half_open = session.heal_status()["breaker"]["half_open"]["fuse_iter"]
    assert half_open["backoff_ms"] == 2 * base_backoff_s * 1e3, half_open
    assert half_open["retrips"] == 1 and half_open["probes"] == 1
    assert session.breaker.status()["tripped"]["fuse_iter"]["count"] == 2
    assert _labeled(reg, "raft_heal_rung_probes_total",
                    rung="fuse_iter", result="failed") == 1
    # /healthz visibility: the doubled backoff rides the status document.
    hz = svc.status()["heal"]
    assert hz["breaker"]["half_open"]["fuse_iter"]["backoff_ms"] == \
        2 * base_backoff_s * 1e3, hz["breaker"]
    # The poison clears -> the canary passes -> the rung re-engages.
    session.faults.plan = ChaosPlan(slow_forwards=SLOW)
    clock.sleep(2 * base_backoff_s + 1.0)
    res = svc.heal_sweep()
    assert res["breaker"] == {"rung": "fuse_iter", "passed": True}, res
    assert "fuse_iter" not in session.breaker.tripped_names
    assert _labeled(reg, "raft_heal_untrips_total", rung="fuse_iter") == 1
    assert _labeled(reg, "raft_heal_rung_probes_total",
                    rung="fuse_iter", result="passed") == 1
    pump("postheal", 4)

    # -- phase 6: a FLAPPING chip is retired after exactly flap-cap ------
    readmissions = 1  # phase 3's
    for _ in range(flap_cap - 1):
        assert session.quarantine_chip(1), "flap re-quarantine refused"
        clock.sleep(2 * base_backoff_s + 1.0)
        res = svc.heal_sweep()
        assert res["mesh"]["readmitted"] == [1], res
        readmissions += 1
    assert int(reg.value("raft_heal_chips_readmitted_total")) == \
        readmissions == flap_cap
    assert session.quarantine_chip(1), "final flap quarantine refused"
    chip_hs = session.heal_status()["chips"]["1"]
    assert chip_hs["permanent"] is True, (
        f"chip flapped past the cap yet was not retired: {chip_hs}")
    assert int(reg.value("raft_heal_chips_permanent_total")) == 1
    clock.sleep(100 * base_backoff_s)
    res = svc.heal_sweep()
    assert res["mesh"]["probed"] == [], (
        f"a permanently-quarantined chip was probed again: {res}")
    mesh = session.mesh_status()
    assert mesh["quarantined"] == [1] and mesh["n_data"] == 1, mesh
    assert int(reg.value("raft_heal_chips_readmitted_total")) == flap_cap, (
        "re-admissions moved past the flap cap")
    cap_doc = session.capacity_status()
    flap_row = [r_ for r_ in cap_doc["chips"]["per_chip"]
                if r_["chip"] == 1][0]
    assert flap_row["quarantined"] and flap_row.get("permanent") is True, \
        flap_row
    pump("flapped", 4)

    # -- invariants: structured outcomes + the books reconcile -----------
    for r in responses:
        assert r["status"] in ("ok", "rejected", "error"), r
        if r["status"] != "ok":
            assert r.get("code"), r
        else:
            assert np.isfinite(r["disparity"]).all()
    usage_doc = session.usage.doc()
    tenant_ns = sum(t["device_ns"] for t in usage_doc["by_tenant"].values())
    assert tenant_ns == usage_doc["device_ns_total"], (
        f"per-tenant device-ns sum {tenant_ns} != accounted total "
        f"{usage_doc['device_ns_total']} across the recovery excursion")
    prog_dev_s = sum(v for _, v in
                     reg.series("raft_program_device_seconds_total"))
    assert abs(usage_doc["device_ns_total"] / 1e9 - prog_dev_s) <= \
        max(1e-6, 1e-9 * prog_dev_s), (
        usage_doc["device_ns_total"] / 1e9, prog_dev_s)

    heal_final = session.heal_status()
    assert svc.drain(), "recovery-storm service failed to drain"
    elapsed_real = time.monotonic() - t_real0

    outcomes: dict = {}
    for r in responses:
        key = (r["status"] if r["status"] == "ok"
               else f'{r["status"]}:{r["code"]}')
        outcomes[key] = outcomes.get(key, 0) + 1
    doc = {
        "metric": "heal_chaos",
        "pass": True,
        "n": len(responses),
        "seed": seed,
        "devices": len(jax.devices()),
        "outcomes": dict(sorted(outcomes.items())),
        "restarts": restarts,
        "headroom": {"pre_rps": round(pre, 4),
                     "post_rps": round(post, 4),
                     "recovered": recovered},
        "mttr_s": heal_final["mttr"]["last_s"],
        "mttr_events": heal_final["mttr"]["events"],
        "readmitted": int(reg.value("raft_heal_chips_readmitted_total")),
        "flap_cap": flap_cap,
        "permanent": int(reg.value("raft_heal_chips_permanent_total")),
        "rung_probes": {
            "failed": _labeled(reg, "raft_heal_rung_probes_total",
                               result="failed"),
            "passed": _labeled(reg, "raft_heal_rung_probes_total",
                               result="passed")},
        "stream_repinned": n_repinned,
        "hangs_entered": session.faults.hangs_entered,
        "elapsed_real_s": round(elapsed_real, 2),
        "backend": jax.default_backend(),
    }
    print(json.dumps(doc))

    from raft_stereo_tpu.obs.trajectory import emit
    emit("heal_chaos_recovered", 1.0, "frac",
         backend=jax.default_backend(), source="scratch/chaos_serve.py",
         extra={"n": doc["n"], "mttr_s": doc["mttr_s"],
                "headroom_pre_rps": doc["headroom"]["pre_rps"],
                "headroom_post_rps": doc["headroom"]["post_rps"],
                "readmitted": doc["readmitted"],
                "flap_cap": flap_cap,
                "elapsed_real_s": doc["elapsed_real_s"]})
    return 0


if __name__ == "__main__":
    _wire = "--wire" in sys.argv[1:] or \
        os.environ.get("RAFT_CHAOS_WIRE", "").strip().lower() in (
            "1", "true", "yes", "on")
    _mesh = "--mesh" in sys.argv[1:] or \
        os.environ.get("RAFT_CHAOS_MESH", "").strip().lower() in (
            "1", "true", "yes", "on")
    _heal = "--heal" in sys.argv[1:] or \
        os.environ.get("RAFT_CHAOS_HEAL", "").strip().lower() in (
            "1", "true", "yes", "on")
    if _mesh or _heal:
        # Arm the fake-device pod BEFORE anything imports jax (the same
        # self-arming bench_serve.py --mesh does).
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    _metric = ("wire_chaos" if _wire else
               "heal_chaos" if _heal else
               "mesh_chaos" if _mesh else "chaos_soak")
    try:
        raise SystemExit(main_wire() if _wire
                         else main_heal() if _heal
                         else main_mesh() if _mesh else main())
    except AssertionError as e:
        print(json.dumps({"metric": _metric, "pass": False,
                          "error": str(e)}))
        raise SystemExit(1)

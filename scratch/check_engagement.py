"""r19 engagement asserts: each new fast path PROVABLY engages — and its
kill switch provably disengages it — at both acceptance geometries.

The PR 2 "provably engages" ceremony, extended: instead of predicate
checks alone, this traces the REAL serving programs (``build_program`` —
the exact callables the serving cache jits) to a jaxpr at

- headline geometry (2016x2976, b=1), and
- the serve-batch bucket (384x1248 at b=4 and b=8 — the bench_serve
  shape the old 200k-pixel ``_batch_worthwhile`` fence kept on XLA twins)

and asserts kernel PRESENCE by name in the traced program text:
``_resident_kernel`` (ops/pallas_resident.py), ``_gru1632_kernel``,
``_lookup_kernel`` (the standalone corr gather that must return when the
resident path is killed). Tracing executes nothing — CPU-safe, the
graftverify precedent — and a jaxpr either contains a pallas_call to the
named kernel or it does not: no heuristics.

Also asserts the r19 acceptance ratio analytically: the int8 quad-packed
correlation containers' per-iteration DMA at headline geometry must be
<= 0.6x the bf16 pair-packed layout's (corr/pallas_reg.plan_dma_bytes —
exact BlockSpec arithmetic; the driver's on-chip run corroborates with
the advance rows' compiler bytes_est).

Prints one JSON line; exit 1 on any failed check.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.analysis.knobs import ENV_KNOBS
    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.corr.pallas_reg import (level_widths,
                                                 plan_dma_bytes)
    from raft_stereo_tpu.models.raft_stereo import init_raft_stereo
    from raft_stereo_tpu.serve.session import (_env_overrides,
                                               build_program, resolve_env)

    cfg = with_eval_precision(RAFTStereoConfig(
        corr_implementation="reg_tpu"))
    base_env = {k: None for k in ENV_KNOBS}

    @functools.lru_cache(maxsize=None)
    def params_spec():
        return jax.eval_shape(
            functools.partial(init_raft_stereo, cfg=cfg),
            jax.random.PRNGKey(0))

    @functools.lru_cache(maxsize=None)
    def state_spec(b: int, h: int, w: int):
        prep = build_program("prepare", cfg, 0)
        img = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
        (state,) = jax.eval_shape(prep, params_spec(), img, img)
        return state

    @functools.lru_cache(maxsize=None)
    def advance_text(b: int, h: int, w: int, env_items) -> str:
        env = resolve_env(dict(env_items), base_env)
        with _env_overrides(env):
            fn = build_program("advance", cfg, 8)
            jaxpr = jax.make_jaxpr(fn)(params_spec(), state_spec(b, h, w))
        return str(jaxpr)

    checks = {}

    def check(name: str, ok: bool) -> None:
        checks[name] = bool(ok)
        print(f"{'ok' if ok else 'FAIL'}: {name}", file=sys.stderr)

    # -- headline b=1 ------------------------------------------------------
    t = advance_text(1, 2016, 2976, ())
    check("headline_b1_resident_engages", "_resident_kernel" in t)
    check("headline_b1_gru1632_engages", "_gru1632_kernel" in t)
    t_off = advance_text(1, 2016, 2976, (("RAFT_FUSE_ITER", "0"),))
    check("fuse_iter_off_disengages_resident",
          "_resident_kernel" not in t_off)
    check("fuse_iter_off_restores_standalone_lookup",
          "_lookup_kernel" in t_off and "_gru_kernel" in t_off)
    t_p8 = advance_text(1, 2016, 2976, (("RAFT_CORR_PACK8", "1"),))
    check("pack8_changes_headline_program", t_p8 != t)
    check("pack8_resident_still_engaged", "_resident_kernel" in t_p8)

    # -- serve-batch bucket b=4/8 -----------------------------------------
    for b in (4, 8):
        tb = advance_text(b, 384, 1248, ())
        check(f"serve_b{b}_resident_engages", "_resident_kernel" in tb)
        tb_off = advance_text(b, 384, 1248, (("RAFT_STREAM_BATCH", "0"),))
        check(f"serve_b{b}_stream_batch_off_runs_xla_twins",
              "_resident_kernel" not in tb_off
              and "_gru_kernel" not in tb_off
              and "_gru1632_kernel" not in tb_off)
        check(f"serve_b{b}_corr_kernel_stays_engaged_when_off",
              "_lookup_kernel" in tb_off)

    # -- int8 correlation DMA ratio at headline (analytic, exact) ---------
    factor = cfg.downsample_factor
    widths = level_widths(2976 // factor, cfg.corr_levels)
    bf16_px = plan_dma_bytes(widths, True, False)
    int8_px = plan_dma_bytes(widths, True, True)
    ratio = int8_px / bf16_px
    check("headline_int8_corr_dma_ratio_le_0.6", ratio <= 0.6)

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok,
        "checks": checks,
        "corr_dma_ratio_headline": round(ratio, 4),
        "corr_dma_bf16_bytes_per_px": bf16_px,
        "corr_dma_int8_bytes_per_px": int8_px,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""r19/r24 engagement asserts: each new fast path PROVABLY engages — and
its kill switch provably disengages it — at both acceptance geometries.

The PR 2 "provably engages" ceremony, extended: instead of predicate
checks alone, this traces the REAL serving programs (``build_program`` —
the exact callables the serving cache jits) to a jaxpr at

- headline geometry (2016x2976, b=1), and
- the serve-batch bucket (384x1248 at b=4 and b=8 — the bench_serve
  shape the old 200k-pixel ``_batch_worthwhile`` fence kept on XLA twins)

and asserts kernel PRESENCE by name in the traced program text:
``_resident_kernel`` (ops/pallas_resident.py), ``_gru1632_kernel``,
``_lookup_kernel`` (the standalone corr gather that must return when the
resident path is killed). Tracing executes nothing — CPU-safe, the
graftverify precedent — and a jaxpr either contains a pallas_call to the
named kernel or it does not: no heuristics.

r24 adds the narrow-lane kernel set (``_resident_lane8_kernel``,
``_gru1632_lane8_kernel``, ``_gru_lane8_kernel`` under the RAFT_FUSE_ITER=0
fallback, and the encoder-exit ``_pass_q8_kernel``/``_point2_q8_kernel``):
each is asserted present by name in the armed (RAFT_LANE_PACK8=1) traces
and absent from every default trace — the kill switch provably disengages
the whole lane.

Also asserts the acceptance ratios analytically: the int8 quad-packed
correlation containers' per-iteration DMA must be <= 0.6x the bf16
pair-packed layout's (corr/pallas_reg.plan_dma_bytes — exact BlockSpec
arithmetic; the driver's on-chip run corroborates with the advance rows'
compiler bytes_est), at headline AND serve-batch geometry (the r19 script
only checked headline — the serve bucket's shallower pyramid shifts the
level mix, so the bound is re-proved where batched serving actually
runs). The r24 context-lane ratio (ops/pallas_stream.plan_lane_dma_bytes)
gets the same <= 0.6 bound at both geometries.

Prints one JSON line; exit 1 on any failed check.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from raft_stereo_tpu.analysis.knobs import ENV_KNOBS
    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.corr.pallas_reg import (level_widths,
                                                 plan_dma_bytes)
    from raft_stereo_tpu.models.raft_stereo import init_raft_stereo
    from raft_stereo_tpu.serve.session import (_env_overrides,
                                               build_program, resolve_env)

    cfg = with_eval_precision(RAFTStereoConfig(
        corr_implementation="reg_tpu"))
    base_env = {k: None for k in ENV_KNOBS}

    @functools.lru_cache(maxsize=None)
    def params_spec():
        return jax.eval_shape(
            functools.partial(init_raft_stereo, cfg=cfg),
            jax.random.PRNGKey(0))

    @functools.lru_cache(maxsize=None)
    def _state_spec(b: int, h: int, w: int, lane8: str):
        prep = build_program("prepare", cfg, 0)
        img = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
        (state,) = jax.eval_shape(prep, params_spec(), img, img)
        return state

    def state_spec(b: int, h: int, w: int):
        # Called inside the trace's env window: the carry structure
        # depends on RAFT_LANE_PACK8 (r24 packed context containers ride
        # the state pytree), so the cache re-keys on the live switch.
        return _state_spec(b, h, w, os.environ.get("RAFT_LANE_PACK8", ""))

    @functools.lru_cache(maxsize=None)
    def advance_text(b: int, h: int, w: int, env_items) -> str:
        env = resolve_env(dict(env_items), base_env)
        with _env_overrides(env):
            fn = build_program("advance", cfg, 8)
            jaxpr = jax.make_jaxpr(fn)(params_spec(), state_spec(b, h, w))
        return str(jaxpr)

    @functools.lru_cache(maxsize=None)
    def prepare_text(b: int, h: int, w: int, env_items) -> str:
        env = resolve_env(dict(env_items), base_env)
        with _env_overrides(env):
            fn = build_program("prepare", cfg, 0)
            img = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
            jaxpr = jax.make_jaxpr(fn)(params_spec(), img, img)
        return str(jaxpr)

    checks = {}

    def check(name: str, ok: bool) -> None:
        checks[name] = bool(ok)
        print(f"{'ok' if ok else 'FAIL'}: {name}", file=sys.stderr)

    # -- headline b=1 ------------------------------------------------------
    t = advance_text(1, 2016, 2976, ())
    check("headline_b1_resident_engages", "_resident_kernel" in t)
    check("headline_b1_gru1632_engages", "_gru1632_kernel" in t)
    t_off = advance_text(1, 2016, 2976, (("RAFT_FUSE_ITER", "0"),))
    check("fuse_iter_off_disengages_resident",
          "_resident_kernel" not in t_off)
    check("fuse_iter_off_restores_standalone_lookup",
          "_lookup_kernel" in t_off and "_gru_kernel" in t_off)
    t_p8 = advance_text(1, 2016, 2976, (("RAFT_CORR_PACK8", "1"),))
    check("pack8_changes_headline_program", t_p8 != t)
    check("pack8_resident_still_engaged", "_resident_kernel" in t_p8)

    # -- r24 narrow lanes at headline b=1 ---------------------------------
    LANE8_KERNELS = ("_resident_lane8_kernel", "_gru1632_lane8_kernel",
                     "_gru_lane8_kernel", "_pass_q8_kernel",
                     "_point2_q8_kernel")
    armed = (("RAFT_LANE_PACK8", "1"),)
    t_l8 = advance_text(1, 2016, 2976, armed)
    check("lane8_headline_resident_lane8_engages",
          "_resident_lane8_kernel" in t_l8)
    check("lane8_headline_gru1632_lane8_engages",
          "_gru1632_lane8_kernel" in t_l8)
    t_l8_nofuse = advance_text(1, 2016, 2976,
                               armed + (("RAFT_FUSE_ITER", "0"),))
    check("lane8_fuse_iter_off_gru_lane8_engages",
          "_gru_lane8_kernel" in t_l8_nofuse
          and "_resident_lane8_kernel" not in t_l8_nofuse)
    tp_l8 = prepare_text(1, 2016, 2976, armed)
    check("lane8_headline_prepare_pass_q8_engages", "_pass_q8_kernel" in tp_l8)

    # The optional resblock narrow exit (_point2_q8_kernel) is a library
    # entry point (not yet wired into a serving program kind) — prove the
    # kernel by name from its own public seam, armed vs disarmed.
    from raft_stereo_tpu.models.layers import init_residual_block
    from raft_stereo_tpu.ops.pallas_encoder import (stream_resblock,
                                                    stream_resblock_q8)
    rb_p = jax.eval_shape(functools.partial(
        init_residual_block, in_planes=128, planes=128, norm_fn="instance",
        stride=1), jax.random.PRNGKey(0))
    rb_x = jax.ShapeDtypeStruct((1, 64, 128, 128), jnp.bfloat16)
    with _env_overrides(resolve_env(dict(armed), base_env)):
        t_rbq = str(jax.make_jaxpr(functools.partial(
            stream_resblock_q8, "instance"))(rb_p, rb_x))
    check("lane8_resblock_point2_q8_engages", "_point2_q8_kernel" in t_rbq)
    with _env_overrides(dict(base_env)):
        t_rb = str(jax.make_jaxpr(functools.partial(
            stream_resblock, "instance"))(rb_p, rb_x))
    check("lane8_off_resblock_has_no_q8", "q8" not in t_rb)

    # Kill switch: every default (RAFT_LANE_PACK8 unset) trace in this
    # battery must be free of ALL lane8 kernels.
    for name, text in (("headline_advance", t), ("fuse_iter_off", t_off),
                       ("corr_pack8_only", t_p8),
                       ("headline_prepare", prepare_text(1, 2016, 2976, ()))):
        check(f"lane8_off_disengages_{name}",
              not any(k in text for k in LANE8_KERNELS))

    # -- serve-batch bucket b=4/8 -----------------------------------------
    for b in (4, 8):
        tb = advance_text(b, 384, 1248, ())
        check(f"serve_b{b}_resident_engages", "_resident_kernel" in tb)
        check(f"serve_b{b}_lane8_off_disengaged",
              not any(k in tb for k in LANE8_KERNELS))
        tb_off = advance_text(b, 384, 1248, (("RAFT_STREAM_BATCH", "0"),))
        check(f"serve_b{b}_stream_batch_off_runs_xla_twins",
              "_resident_kernel" not in tb_off
              and "_gru_kernel" not in tb_off
              and "_gru1632_kernel" not in tb_off)
        check(f"serve_b{b}_corr_kernel_stays_engaged_when_off",
              "_lookup_kernel" in tb_off)
        tb_l8 = advance_text(b, 384, 1248, armed)
        check(f"serve_b{b}_lane8_resident_lane8_engages",
              "_resident_lane8_kernel" in tb_l8)

    # -- int8 correlation DMA ratio (analytic, exact) ---------------------
    # r24 bugfix: the r19 script asserted the bound at headline only;
    # batched serving runs the serve bucket, whose shallower pyramid
    # changes the level mix — prove the bound at both.
    factor = cfg.downsample_factor
    corr = {}
    for gname, w_img in (("headline", 2976), ("serve", 1248)):
        widths = level_widths(w_img // factor, cfg.corr_levels)
        bf16_px = plan_dma_bytes(widths, True, False)
        int8_px = plan_dma_bytes(widths, True, True)
        ratio = int8_px / bf16_px
        check(f"{gname}_int8_corr_dma_ratio_le_0.6", ratio <= 0.6)
        corr[gname] = {"bf16": bf16_px, "int8": int8_px,
                       "ratio": round(ratio, 4)}

    # -- r24 context-lane DMA ratio (analytic, exact) ---------------------
    from raft_stereo_tpu.ops.pallas_stream import plan_lane_dma_bytes
    lane = {}
    for gname, (h_img, w_img) in (("headline", (2016, 2976)),
                                  ("serve", (384, 1248))):
        bf16_b = plan_lane_dma_bytes(h_img, w_img, pack8=False)
        int8_b = plan_lane_dma_bytes(h_img, w_img, pack8=True)
        ratio = int8_b / bf16_b
        check(f"{gname}_lane_dma_ratio_le_0.6", ratio <= 0.6)
        lane[gname] = {"bf16": bf16_b, "int8": int8_b,
                       "ratio": round(ratio, 4)}

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok,
        "checks": checks,
        "corr_dma": corr,
        "lane_dma": lane,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

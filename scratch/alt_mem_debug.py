import sys; sys.path.insert(0, "/root/repo")
import re
import jax, jax.numpy as jnp
from raft_stereo_tpu.corr import make_corr_fn

b, h, w, d = 1, 504, 744, 256
args = (jax.ShapeDtypeStruct((b, h, w, d), jnp.float32),
        jax.ShapeDtypeStruct((b, h, w, d), jnp.float32),
        jax.ShapeDtypeStruct((b, h, w), jnp.float32))
compiled = jax.jit(lambda f1, f2, c: make_corr_fn("alt_tpu", f1, f2,
                    num_levels=4, radius=4)(c)).lower(*args).compile()
ma = compiled.memory_analysis()
print("temp:", ma.temp_size_in_bytes/1e6, "MB  out:", ma.output_size_in_bytes/1e6)
txt = compiled.as_text()
# find big allocations in buffer assignment dump if present; else grep fusion shapes
for m in sorted(set(re.findall(r"f32\[[\d,]+\]", txt)), key=lambda s: -eval(s[4:-1].replace(",", "*") or "0"))[:12]:
    sz = eval(m[4:-1].replace(",", "*")) * 4 / 1e6
    if sz > 20:
        print(f"{sz:10.1f} MB  {m}  x{txt.count(m)}")

#!/usr/bin/env python
"""Evaluation CLI — flag-for-flag with the reference ``evaluate_stereo.py:192-243``,
plus TPU corr choices and ``--dataset_root``/``--bucket``.

Mixed precision policy mirrors the reference (:227-230): full-network bf16 is
enabled only for the kernel-backed corr implementations (``*_cuda``/``*_tpu``),
whose lookups accumulate in fp32; the pure-XLA paths keep corr math fp32.
"""

from __future__ import annotations

import argparse
import logging


def build_parser() -> argparse.ArgumentParser:
    from raft_stereo_tpu.config import add_model_args

    parser = argparse.ArgumentParser()
    parser.add_argument('--restore_ckpt', help="restore checkpoint "
                        "(.pth reference weights or native .msgpack)",
                        default=None)
    parser.add_argument('--dataset', help="dataset for evaluation",
                        required=True,
                        choices=["eth3d", "kitti", "things"]
                        + [f"middlebury_{s}" for s in 'FHQ'])
    parser.add_argument('--valid_iters', type=int, default=32,
                        help='number of flow-field updates during forward pass')
    add_model_args(parser)

    # TPU-framework extensions
    parser.add_argument('--dataset_root', default=None,
                        help="root directory holding the datasets/ tree")
    parser.add_argument('--bucket', type=int, default=None,
                        help="pad eval shapes up to multiples of this size "
                        "to share compilations (must be a multiple of 32)")
    parser.add_argument('--segments', type=int, default=1,
                        help="run the refinement scan as this many chained "
                        "segments (must divide valid_iters) — the eval-scale "
                        "A/B for the serving layer's anytime degradation; "
                        "metrics are bit-identical to --segments 1")
    parser.add_argument('--spatial_shard', type=int, default=1,
                        help="shard image height (and the correlation "
                        "volume) over this many devices — full-resolution "
                        "frames that exceed one chip's HBM evaluate across "
                        "the pod")
    return parser


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(levelname)-8s [%(filename)s:%(lineno)d] %(message)s')

    import jax

    from raft_stereo_tpu.config import RAFTStereoConfig
    from raft_stereo_tpu.engine import evaluate as ev
    from raft_stereo_tpu.engine.checkpoint import load_params
    from raft_stereo_tpu.models import init_raft_stereo

    if args.spatial_shard > 1:
        # Multi-host: must run before ANY jax computation initializes the
        # backend (jax.distributed.initialize refuses afterwards).
        from raft_stereo_tpu.parallel.mesh import maybe_distributed_init
        maybe_distributed_init()

    cfg = RAFTStereoConfig.from_namespace(args)

    if args.restore_ckpt is not None:
        logging.info("Loading checkpoint...")
        template = (None if args.restore_ckpt.endswith(".pth")
                    else init_raft_stereo(jax.random.PRNGKey(0), cfg))
        params = load_params(args.restore_ckpt, cfg, template)
        logging.info("Done loading checkpoint")
    else:
        params = init_raft_stereo(jax.random.PRNGKey(0), cfg)

    print(f"The model has {ev.count_parameters(params) / 1e6:.2f}M "
          "learnable parameters.")

    # Kernel-backed corr lookups accumulate in fp32, making full-network
    # mixed precision safe (reference :227-230). One shared policy with
    # demo.py/serve_stereo.py; unlike the old inline expression it also
    # honors an explicit --mixed_precision with an XLA corr choice.
    from raft_stereo_tpu.config import eval_mixed_precision
    use_mixed_precision = eval_mixed_precision(cfg)

    common = dict(iters=args.valid_iters, mixed_prec=use_mixed_precision,
                  root=args.dataset_root)
    if args.segments != 1:
        if args.valid_iters % args.segments:
            raise SystemExit("--segments must divide --valid_iters")
        if args.spatial_shard > 1:
            raise SystemExit(
                "--segments > 1 is not supported with --spatial_shard")
        common["segments"] = args.segments
    if args.spatial_shard > 1:
        from raft_stereo_tpu.parallel import make_mesh
        from raft_stereo_tpu.parallel.mesh import validate_spatial_shard
        try:
            validate_spatial_shard(args.spatial_shard, len(jax.devices()),
                                   jax.local_device_count())
        except ValueError as e:
            raise SystemExit(f"--{e}") from None
        common["mesh"] = make_mesh(n_data=1, n_space=args.spatial_shard)
    if args.bucket is not None:
        # Default (None) is the reference-exact per-shape padding
        # everywhere, including KITTI's timing protocol; --bucket 64
        # opts into shared compilations.
        common["bucket"] = args.bucket
    if args.dataset == 'eth3d':
        ev.validate_eth3d(params, cfg, **common)
    elif args.dataset == 'kitti':
        ev.validate_kitti(params, cfg, **common)
    elif args.dataset.startswith('middlebury_'):
        ev.validate_middlebury(params, cfg, split=args.dataset[-1], **common)
    elif args.dataset == 'things':
        ev.validate_things(params, cfg, **common)


if __name__ == '__main__':
    main()

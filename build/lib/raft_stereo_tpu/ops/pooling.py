"""Average pooling variants used by the reference.

- ``avg_pool_w2``: kernel (1,2) stride (1,2), no padding — halves the width of
  the correlation pyramid / fmap2 (``core/corr.py:123-124,104``). Odd trailing
  column is dropped (torch floor semantics).
- ``pool2x``: kernel 3 stride 2 padding 1 with ``count_include_pad=True``
  (torch default) — cross-scale GRU state downsampling (``core/update.py:87-88``).
- ``pool4x``: kernel 5 stride 4 padding 1 (``core/update.py:90-91``, unused by
  the stereo configs but part of the API surface).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def avg_pool_w2(x: jax.Array) -> jax.Array:
    """Halve the last-but-one (W) axis of (..., W, C) by averaging pairs."""
    *lead, w, c = x.shape
    w2 = (w // 2) * 2
    x = x[..., :w2, :].reshape(*lead, w // 2, 2, c)
    return jnp.mean(x, axis=-2)


def avg_pool_last(x: jax.Array) -> jax.Array:
    """Halve the last axis of (..., W) by averaging pairs (volume pyramid)."""
    *lead, w = x.shape
    w2 = (w // 2) * 2
    x = x[..., :w2].reshape(*lead, w // 2, 2)
    return jnp.mean(x, axis=-1)


def _avg_pool_nhwc(x: jax.Array, window: int, stride: int, pad: int) -> jax.Array:
    summed = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (pad, pad), (pad, pad), (0, 0)))
    # count_include_pad=True: divide by the full window size everywhere.
    return (summed / (window * window)).astype(x.dtype)


def pool2x(x: jax.Array) -> jax.Array:
    return _avg_pool_nhwc(x, window=3, stride=2, pad=1)


def pool4x(x: jax.Array) -> jax.Array:
    return _avg_pool_nhwc(x, window=5, stride=4, pad=1)

"""Bilinear sampling with grid_sample semantics (align_corners=True, zero pad).

The reference samples the correlation volume through
``bilinear_sampler`` (``core/utils/utils.py:59-73``), a pixel-coordinate wrapper
over ``F.grid_sample(align_corners=True)`` that asserts the problem is 1D
(H == 1). Out-of-range taps contribute zero (grid_sample ``padding_mode='zeros'``):
a sample at x gets ``(1-frac)*v[floor(x)] + frac*v[floor(x)+1]`` with each tap
zeroed when its index falls outside ``[0, W-1]``.

TPU implementation note: these samplers are **one-hot reduces, not gathers**.
``out = sum_j v[j] * w(x, j)`` with the interpolation weight built from an
index comparison. XLA lowers per-pixel dynamic gathers to serial loops on TPU
(measured 45x slower) and their VJP to scatters; the one-hot form is regular
VPU/MXU work in both directions, and out-of-range zero padding falls out of
the comparison for free. O(W) work per sample instead of O(1) — on TPU that
trade wins by an order of magnitude.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _onehot_lerp_weights(x: jax.Array, width: int) -> jax.Array:
    """Interpolation weight matrix w[..., j] for zero-padded linear sampling.

    x: (...,) fractional positions -> returns (..., width) fp32 weights with
    ``w[j] = (1-frac) * [j == floor(x)] + frac * [j == floor(x)+1]``.

    Positions, iota, and weights are computed in float32 unconditionally:
    integer positions above 256 are not representable in bfloat16, so a
    bf16 equality comparison would silently drop or duplicate taps for
    width > 256. Callers cast the final weight matrix to the value dtype.
    """
    x = x.astype(jnp.float32)
    x0 = jnp.floor(x)
    frac = (x - x0)[..., None]
    j = jnp.arange(width, dtype=jnp.float32)
    i0 = x0[..., None]
    return jnp.where(j == i0, 1.0 - frac, 0.0) + jnp.where(j == i0 + 1.0,
                                                           frac, 0.0)


def sample_1d_zeros(values: jax.Array, x: jax.Array) -> jax.Array:
    """Sample rows of scalars at fractional positions.

    values: (..., W) — per-row 1D signals (e.g. a correlation-volume row).
    x:      (..., K) — fractional sample positions, batch dims matching values.
    Returns (..., K).
    """
    width = values.shape[-1]
    # Per-tap loop keeps the peak intermediate at (..., W) instead of
    # materializing the full (..., K, W) weight tensor.
    taps = []
    for k in range(x.shape[-1]):
        w = _onehot_lerp_weights(x[..., k], width).astype(values.dtype)
        taps.append(jnp.sum(values * w, axis=-1))
    return jnp.stack(taps, axis=-1)


def sample_rows_zeros(fmap: jax.Array, x: jax.Array) -> jax.Array:
    """Sample feature rows at fractional x positions (vector-valued signal).

    fmap: (..., W, D) — per-row features (e.g. fmap2 rows).
    x:    (..., K)    — fractional sample positions.
    Returns (..., K, D).

    The one-hot weight turns the row gather into a (K, W) @ (W, D) matmul —
    MXU work with the lerp folded into the weights.
    """
    width = fmap.shape[-2]
    w = _onehot_lerp_weights(x, width).astype(fmap.dtype)  # (..., K, W)
    return jnp.einsum("...kw,...wd->...kd", w, fmap)

"""Numerics kernel layer: stateless ops with reference-exact semantics.

All ops operate on NHWC activations (TPU conv-native layout). Each op documents
the reference behavior it reproduces (file:line in /root/reference).
"""

from raft_stereo_tpu.ops.basic import (
    conv2d,
    frozen_batch_norm,
    group_norm,
    instance_norm,
)
from raft_stereo_tpu.ops.coords import coords_grid, upflow
from raft_stereo_tpu.ops.sampler import (
    sample_1d_zeros,
    sample_rows_zeros,
)
from raft_stereo_tpu.ops.pooling import avg_pool_w2, pool2x, pool4x
from raft_stereo_tpu.ops.resize import interp_align_corners
from raft_stereo_tpu.ops.upsample import convex_upsample
from raft_stereo_tpu.ops.padder import InputPadder

__all__ = [
    "conv2d", "frozen_batch_norm", "group_norm", "instance_norm",
    "coords_grid", "upflow",
    "sample_1d_zeros", "sample_rows_zeros",
    "avg_pool_w2", "pool2x", "pool4x",
    "interp_align_corners",
    "convex_upsample",
    "InputPadder",
]

"""Bounded-memory mapping over one axis in fixed-size chunks.

The `alt` correlation paths bound their transient one-hot/volume tensors by
processing a fixed number of rows at a time under ``lax.map``. The pad /
reshape / map / reassemble dance is easy to get wrong (an extra moveaxis once
scrambled batch/row order — see ``tests/test_corr.py``
``test_alt_chunked_matches_reg``), so it lives here once.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp


def map_chunked(fn: Callable[[Tuple[jax.Array, ...]], jax.Array],
                inputs: Sequence[jax.Array], chunk: int,
                axis: int = 0) -> jax.Array:
    """``lax.map`` ``fn`` over ``axis`` of every input, ``chunk`` rows at a time.

    ``fn`` receives a tuple of slices with the original layout but ``axis``
    reduced to ``chunk``, and must return an array with the chunked axis at
    the same position. The axis is zero-padded up to a chunk multiple (so
    peak memory is bounded for every length) and the padded rows are sliced
    off the result — ``fn``'s output on zero rows is discarded, never mixed
    into real rows.
    """
    inputs = tuple(inputs)
    n = inputs[0].shape[axis]
    if n <= chunk:
        return fn(inputs)
    pad = (-n) % chunk
    if pad:
        inputs = tuple(
            jnp.pad(x, [(0, pad) if i == axis else (0, 0)
                        for i in range(x.ndim)])
            for x in inputs)
    g = (n + pad) // chunk

    def split(x):
        x = x.reshape(*x.shape[:axis], g, chunk, *x.shape[axis + 1:])
        return jnp.moveaxis(x, axis, 0)

    out = jax.lax.map(fn, tuple(split(x) for x in inputs))
    out = jnp.moveaxis(out, 0, axis)
    out = out.reshape(*out.shape[:axis], g * chunk, *out.shape[axis + 2:])
    if pad:
        out = jax.lax.slice_in_dim(out, 0, n, axis=axis)
    return out

"""Coordinate grids and fallback flow upsampling.

Reference: ``core/utils/utils.py:76-84``. Channel-last layout: a flow/coords
field is ``(B, H, W, 2)`` with ``[..., 0] = x`` and ``[..., 1] = y`` (the
reference stacks x-first, ``utils.py:78``). The network regresses *negative*
disparity in the x channel (``core/stereo_datasets.py:77``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_stereo_tpu.ops.resize import interp_align_corners


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """(B, H, W, 2) pixel-coordinate grid, x-first."""
    y, x = jnp.meshgrid(jnp.arange(ht, dtype=dtype), jnp.arange(wd, dtype=dtype),
                        indexing="ij")
    grid = jnp.stack([x, y], axis=-1)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def upflow(flow: jax.Array, factor: int = 8) -> jax.Array:
    """``upflow8`` generalized: aligned-corners bilinear upsample and scale.

    Reference ``core/utils/utils.py:82-84``. Only reached when no learned
    upsampling mask exists (kept for API parity with RAFT).
    """
    b, h, w, c = flow.shape
    return factor * interp_align_corners(flow, (factor * h, factor * w))

"""Data layer: format IO, augmentation, datasets, and a prefetching loader.

Replaces the reference's torch ``Dataset``/``DataLoader`` stack
(``core/stereo_datasets.py``, ``core/utils/{frame_utils,augmentor}.py``) with a
numpy-native pipeline that feeds NHWC batches straight to the TPU:

- ``frame_utils`` — PFM/PNG/flo format readers and writers;
- ``photometric`` — numpy color jitter (torchvision-equivalent semantics);
- ``augmentor`` — dense + sparse augmentors with explicit RNG;
- ``datasets`` — the 7 dataset classes + mixing;
- ``loader`` — threaded prefetch loader producing batched numpy arrays.
"""

from raft_stereo_tpu.data.augmentor import FlowAugmentor, SparseFlowAugmentor
from raft_stereo_tpu.data.datasets import (
    ETH3D, KITTI, FallingThings, Middlebury, SceneFlowDatasets, SintelStereo,
    StereoDataset, TartanAir, fetch_dataset)
from raft_stereo_tpu.data.loader import StereoLoader, fetch_dataloader

__all__ = [
    "FlowAugmentor", "SparseFlowAugmentor", "StereoDataset",
    "SceneFlowDatasets", "ETH3D", "SintelStereo", "FallingThings",
    "TartanAir", "KITTI", "Middlebury", "fetch_dataset",
    "StereoLoader", "fetch_dataloader",
]

"""Dense and sparse stereo augmentors (reference ``core/utils/augmentor.py``).

Semantics preserved from the reference, numpy-native with explicit RNG:

- photometric jitter, asymmetric per-eye with prob 0.2 (dense only; the sparse
  augmentor is always symmetric, :204-208);
- eraser occlusion: 1-2 rectangles of img2 filled with its mean color,
  prob 0.5, side 50-100 px (:98-111);
- spatial: log-uniform scale ``2**U(min,max)``, independent x/y stretch with
  prob 0.8 (dense only), clipped so the crop fits (:113-135, :257-273);
- stereo-correct h-flip: swap the eyes and mirror both (:143-146), plus 'hf'
  and 'v' variants;
- y-jitter: img2 cropped at ``y0 + U{-2..2}`` to simulate imperfect
  rectification (:153-160, dense only);
- sparse flow resize: nearest-pixel scatter of valid vectors (:223-255);
- sparse crop with margins y=20 / x=50 (:291-303).

Every random draw goes through a passed-in ``np.random.Generator`` — no global
RNG state, so loader workers are reproducible by construction (the reference
reseeds global RNGs per worker instead, ``stereo_datasets.py:55-61``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import cv2

from raft_stereo_tpu.data.photometric import ColorJitter

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)


def _resize_linear(img: np.ndarray, fx: float, fy: float) -> np.ndarray:
    return cv2.resize(img, None, fx=fx, fy=fy, interpolation=cv2.INTER_LINEAR)


def resize_sparse_flow_map(flow: np.ndarray, valid: np.ndarray,
                           fx: float = 1.0, fy: float = 1.0
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """Rescale a sparse flow field by scattering valid vectors to their
    nearest target pixel (bilinear resize would bleed across the valid mask —
    reference :223-255)."""
    ht, wd = flow.shape[:2]
    ys, xs = np.nonzero(valid >= 1)
    vecs = flow[ys, xs] * [fx, fy]

    ht1, wd1 = int(round(ht * fy)), int(round(wd * fx))
    xs1 = np.round(xs * fx).astype(np.int32)
    ys1 = np.round(ys * fy).astype(np.int32)
    keep = (xs1 > 0) & (xs1 < wd1) & (ys1 > 0) & (ys1 < ht1)

    flow_out = np.zeros((ht1, wd1, 2), np.float32)
    valid_out = np.zeros((ht1, wd1), np.int32)
    flow_out[ys1[keep], xs1[keep]] = vecs[keep]
    valid_out[ys1[keep], xs1[keep]] = 1
    return flow_out, valid_out


class FlowAugmentor:
    """Dense augmentor for datasets with full ground-truth disparity."""

    sparse = False

    def __init__(self, crop_size: Sequence[int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip=True, yjitter: bool = False,
                 saturation_range: Sequence[float] = (0.6, 1.4),
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        self.crop_size = tuple(crop_size)
        self.min_scale, self.max_scale = min_scale, max_scale
        self.spatial_aug_prob = 1.0
        self.stretch_prob = 0.8
        self.max_stretch = 0.2
        self.yjitter = yjitter
        self.do_flip = do_flip
        self.h_flip_prob = 0.5
        self.v_flip_prob = 0.1
        self.photo_aug = ColorJitter(brightness=0.4, contrast=0.4,
                                     saturation=saturation_range,
                                     hue=0.5 / 3.14, gamma=gamma)
        self.asymmetric_color_aug_prob = 0.2
        self.eraser_aug_prob = 0.5
        self.eraser_bounds = (50, 100)

    # -- photometric ------------------------------------------------------

    def color_transform(self, img1, img2, rng):
        if rng.random() < self.asymmetric_color_aug_prob:
            return self.photo_aug(img1, rng), self.photo_aug(img2, rng)
        stack = np.concatenate([img1, img2], axis=0)
        img1, img2 = np.split(self.photo_aug(stack, rng), 2, axis=0)
        return img1, img2

    def eraser_transform(self, img1, img2, rng):
        """Paint random rectangles of img2 with its mean color — simulated
        occlusions that have no stereo correspondence."""
        ht, wd = img1.shape[:2]
        if rng.random() < self.eraser_aug_prob:
            img2 = img2.copy()
            mean_color = img2.reshape(-1, 3).mean(axis=0)
            for _ in range(rng.integers(1, 3)):
                x0 = rng.integers(0, wd)
                y0 = rng.integers(0, ht)
                dx = rng.integers(*self.eraser_bounds)
                dy = rng.integers(*self.eraser_bounds)
                img2[y0:y0 + dy, x0:x0 + dx, :] = mean_color
        return img1, img2

    # -- spatial ----------------------------------------------------------

    def _sample_scales(self, ht, wd, rng, margin: int):
        min_scale = max((self.crop_size[0] + margin) / float(ht),
                        (self.crop_size[1] + margin) / float(wd))
        scale = 2 ** rng.uniform(self.min_scale, self.max_scale)
        scale_x = scale_y = scale
        if self.stretch_prob > 0 and rng.random() < self.stretch_prob:
            scale_x *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
            scale_y *= 2 ** rng.uniform(-self.max_stretch, self.max_stretch)
        return max(scale_x, min_scale), max(scale_y, min_scale)

    def _flip(self, img1, img2, flow, rng, valid=None):
        """Flip augmentations; ``valid`` (sparse GT mask) flips with the flow.

        The reference never flips the sparse valid mask (augmentor.py:275-289
        touches only img/flow), silently misaligning supervision when flips
        are enabled on sparse datasets — fixed here.
        """
        if not self.do_flip:
            return img1, img2, flow, valid
        if self.do_flip == "hf" and rng.random() < self.h_flip_prob:
            img1, img2 = img1[:, ::-1], img2[:, ::-1]
            flow = flow[:, ::-1] * [-1.0, 1.0]
            valid = valid[:, ::-1] if valid is not None else None
        if self.do_flip == "h" and rng.random() < self.h_flip_prob:
            # Stereo-correct: mirroring swaps the roles of the two eyes.
            img1, img2 = img2[:, ::-1], img1[:, ::-1]
        if self.do_flip == "v" and rng.random() < self.v_flip_prob:
            img1, img2 = img1[::-1, :], img2[::-1, :]
            flow = flow[::-1, :] * [1.0, -1.0]
            valid = valid[::-1, :] if valid is not None else None
        return img1, img2, flow, valid

    def spatial_transform(self, img1, img2, flow, rng):
        ch, cw = self.crop_size
        scale_x, scale_y = self._sample_scales(*img1.shape[:2], rng, margin=8)
        if rng.random() < self.spatial_aug_prob:
            img1 = _resize_linear(img1, scale_x, scale_y)
            img2 = _resize_linear(img2, scale_x, scale_y)
            flow = _resize_linear(flow, scale_x, scale_y) * [scale_x, scale_y]
        img1, img2, flow, _ = self._flip(img1, img2, flow, rng)

        if self.yjitter:
            y0 = int(rng.integers(2, img1.shape[0] - ch - 2))
            x0 = int(rng.integers(2, img1.shape[1] - cw - 2))
            y1 = y0 + int(rng.integers(-2, 3))
            img2 = img2[y1:y1 + ch, x0:x0 + cw]
        else:
            y0 = int(rng.integers(0, img1.shape[0] - ch))
            x0 = int(rng.integers(0, img1.shape[1] - cw))
            img2 = img2[y0:y0 + ch, x0:x0 + cw]
        img1 = img1[y0:y0 + ch, x0:x0 + cw]
        flow = flow[y0:y0 + ch, x0:x0 + cw]
        return img1, img2, flow

    def __call__(self, img1, img2, flow, rng: np.random.Generator):
        img1, img2 = self.color_transform(img1, img2, rng)
        img1, img2 = self.eraser_transform(img1, img2, rng)
        img1, img2, flow = self.spatial_transform(img1, img2, flow, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow))


class SparseFlowAugmentor(FlowAugmentor):
    """Augmentor for sparse ground truth (KITTI/ETH3D/Middlebury/Sintel):
    symmetric-only color, scatter-based flow resize, margin crop."""

    sparse = True

    def __init__(self, crop_size: Sequence[int], min_scale: float = -0.2,
                 max_scale: float = 0.5, do_flip=False, yjitter: bool = False,
                 saturation_range: Sequence[float] = (0.7, 1.3),
                 gamma: Sequence[float] = (1, 1, 1, 1)):
        super().__init__(crop_size, min_scale, max_scale, do_flip, yjitter,
                         saturation_range, gamma)
        self.photo_aug = ColorJitter(brightness=0.3, contrast=0.3,
                                     saturation=saturation_range,
                                     hue=0.3 / 3.14, gamma=gamma)
        self.spatial_aug_prob = 0.8
        self.stretch_prob = 0.0  # sparse spatial aug is isotropic (:265-267)
        self.crop_margin = (20, 50)  # (y, x), reference :291-292

    def color_transform(self, img1, img2, rng):
        stack = np.concatenate([img1, img2], axis=0)
        return tuple(np.split(self.photo_aug(stack, rng), 2, axis=0))

    def spatial_transform(self, img1, img2, flow, valid, rng):
        ch, cw = self.crop_size
        scale_x, scale_y = self._sample_scales(*img1.shape[:2], rng, margin=1)
        if rng.random() < self.spatial_aug_prob:
            img1 = _resize_linear(img1, scale_x, scale_y)
            img2 = _resize_linear(img2, scale_x, scale_y)
            flow, valid = resize_sparse_flow_map(flow, valid, scale_x, scale_y)
        img1, img2, flow, valid = self._flip(img1, img2, flow, rng, valid)

        margin_y, margin_x = self.crop_margin
        y0 = int(rng.integers(0, img1.shape[0] - ch + margin_y))
        x0 = int(rng.integers(-margin_x, img1.shape[1] - cw + margin_x))
        y0 = int(np.clip(y0, 0, img1.shape[0] - ch))
        x0 = int(np.clip(x0, 0, img1.shape[1] - cw))
        return (img1[y0:y0 + ch, x0:x0 + cw], img2[y0:y0 + ch, x0:x0 + cw],
                flow[y0:y0 + ch, x0:x0 + cw], valid[y0:y0 + ch, x0:x0 + cw])

    def __call__(self, img1, img2, flow, valid, rng: np.random.Generator):
        img1, img2 = self.color_transform(img1, img2, rng)
        img1, img2 = self.eraser_transform(img1, img2, rng)
        img1, img2, flow, valid = self.spatial_transform(
            img1, img2, flow, valid, rng)
        return (np.ascontiguousarray(img1), np.ascontiguousarray(img2),
                np.ascontiguousarray(flow), np.ascontiguousarray(valid))

"""Stereo datasets: base class, the 7 named datasets, and training-mix logic.

Reference ``core/stereo_datasets.py``. Samples are plain dicts of NHWC-ready
numpy arrays; RNG is explicit (a ``np.random.Generator`` per draw) instead of
the reference's per-worker global reseeding (:55-61).

Sample protocol (``__getitem__(index, rng)``):
- train: ``{"paths", "image1"(H,W,3)f32, "image2", "flow"(H,W,1)f32,
  "valid"(H,W)f32}`` — disparity is encoded as negative flow-x,
  ``flow = -disp`` (:77), so the network regresses along the epipolar line;
- test (``is_test``): ``{"paths", "image1", "image2", "extra_info"}``.

The KITTI constructor accepts ``split=`` as an alias for ``image_set=`` —
the reference's training-mix call ``KITTI(aug_params, split=...)`` (:298)
is a ``TypeError`` against its own constructor (:247); fixed here.
"""

from __future__ import annotations

import copy
import logging
import os
import os.path as osp
import re
from glob import glob
from typing import Callable, List, Optional, Sequence

import numpy as np

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augmentor import FlowAugmentor, SparseFlowAugmentor

logger = logging.getLogger(__name__)

MAX_DISP_DEFAULT = 512  # dense datasets: pixels with |disp| >= 512 are invalid


def _make_augmentor(aug_params: Optional[dict], sparse: bool):
    if aug_params is None or "crop_size" not in aug_params:
        return None
    cls = SparseFlowAugmentor if sparse else FlowAugmentor
    return cls(**aug_params)


class StereoDataset:
    """Base dataset: a list of (image1, image2, disparity) path records."""

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False,
                 reader: Optional[Callable] = None):
        aug_params = dict(aug_params) if aug_params is not None else None
        self.img_pad = aug_params.pop("img_pad", None) if aug_params else None
        self.augmentor = _make_augmentor(aug_params, sparse)
        self.sparse = sparse
        self.disparity_reader = reader or frame_utils.read_gen
        self.is_test = False
        self.image_list: List[List[str]] = []
        self.disparity_list: List[str] = []
        self.extra_info: List = []

    # -- record loading ---------------------------------------------------

    def _read_disparity(self, path):
        disp = self.disparity_reader(path)
        if isinstance(disp, tuple):
            disp, valid = disp
        else:
            valid = disp < MAX_DISP_DEFAULT
        return np.asarray(disp, np.float32), np.asarray(valid)

    def __getitem__(self, index, rng: Optional[np.random.Generator] = None):
        if self.is_test:
            img1 = frame_utils.read_image_rgb(self.image_list[index][0])
            img2 = frame_utils.read_image_rgb(self.image_list[index][1])
            return {"paths": tuple(self.image_list[index]),
                    "image1": img1.astype(np.float32),
                    "image2": img2.astype(np.float32),
                    "extra_info": self.extra_info[index]}

        if rng is None:
            rng = np.random.default_rng()
        index = index % len(self.image_list)
        disp, valid = self._read_disparity(self.disparity_list[index])
        img1 = frame_utils.read_image_rgb(self.image_list[index][0])
        img2 = frame_utils.read_image_rgb(self.image_list[index][1])
        # Disparity as negative flow-x: right-image matches sit to the left.
        flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)

        if self.augmentor is not None:
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(
                    img1, img2, flow, valid, rng)
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow, rng)

        img1 = img1.astype(np.float32)
        img2 = img2.astype(np.float32)
        flow = flow.astype(np.float32)
        if self.sparse:
            valid_out = valid.astype(np.float32)
        else:
            valid_out = ((np.abs(flow[..., 0]) < MAX_DISP_DEFAULT)
                         & (np.abs(flow[..., 1]) < MAX_DISP_DEFAULT)
                         ).astype(np.float32)

        if self.img_pad is not None:
            pad_h, pad_w = self.img_pad
            pad = ((pad_h, pad_h), (pad_w, pad_w), (0, 0))
            img1 = np.pad(img1, pad)
            img2 = np.pad(img2, pad)

        return {
            "paths": tuple(self.image_list[index]) + (self.disparity_list[index],),
            "image1": img1,
            "image2": img2,
            "flow": flow[..., :1],  # only x (disparity) is supervised
            "valid": valid_out,
        }

    # -- mixing operators (reference :111-120) ----------------------------

    def __mul__(self, v: int) -> "StereoDataset":
        out = copy.deepcopy(self)
        out.image_list = v * self.image_list
        out.disparity_list = v * self.disparity_list
        out.extra_info = v * self.extra_info
        return out

    def __add__(self, other) -> "ConcatStereoDataset":
        # Each part keeps its own reader/sparse-flag/augmentor — merging path
        # lists (the reference-style shortcut) would decode every dataset with
        # the first one's reader. Concat dispatches per index instead (what
        # torch's ConcatDataset does for the reference).
        return ConcatStereoDataset([self, other])

    def __len__(self) -> int:
        return len(self.image_list)

    def _add_pairs(self, image1_list, image2_list, disp_list):
        for img1, img2, disp in zip(image1_list, image2_list, disp_list):
            self.image_list.append([img1, img2])
            self.disparity_list.append(disp)


class ConcatStereoDataset:
    """Concatenation of stereo datasets, dispatching each index to the part
    that owns it (so mixed sparse/dense datasets keep their own readers and
    augmentors). Supports the same ``+`` / ``*`` mixing algebra."""

    def __init__(self, parts):
        self.parts = []
        for p in parts:
            self.parts.extend(p.parts if isinstance(p, ConcatStereoDataset)
                              else [p])
        self._cum = np.cumsum([len(p) for p in self.parts])

    def __len__(self) -> int:
        return int(self._cum[-1]) if len(self.parts) else 0

    def __getitem__(self, index, rng: Optional[np.random.Generator] = None):
        index = index % len(self)
        part = int(np.searchsorted(self._cum, index, side="right"))
        local = index - (int(self._cum[part - 1]) if part else 0)
        return self.parts[part].__getitem__(local, rng=rng)

    def __add__(self, other) -> "ConcatStereoDataset":
        return ConcatStereoDataset([self, other])

    def __mul__(self, v: int) -> "ConcatStereoDataset":
        return ConcatStereoDataset(self.parts * v)


class SceneFlowDatasets(StereoDataset):
    """FlyingThings3D + Monkaa + Driving (reference :123-184). The TEST split
    keeps the fixed seed-1000 400-image validation subset."""

    def __init__(self, aug_params=None, root="datasets",
                 dstype="frames_cleanpass", things_test: bool = False):
        super().__init__(aug_params)
        self.root = root
        self.dstype = dstype
        if things_test:
            self._add_things("TEST")
        else:
            self._add_things("TRAIN")
            self._add_monkaa()
            self._add_driving()

    def _glob_pairs(self, left_pattern: str):
        lefts = sorted(glob(left_pattern))
        rights = [p.replace("left", "right") for p in lefts]
        disps = [p.replace(self.dstype, "disparity").replace(".png", ".pfm")
                 for p in lefts]
        return lefts, rights, disps

    def _add_things(self, split: str = "TRAIN"):
        before = len(self.disparity_list)
        root = osp.join(self.root, "FlyingThings3D")
        lefts, rights, disps = self._glob_pairs(
            osp.join(root, self.dstype, split, "*/*/left/*.png"))
        # Fixed validation subset: seed-1000 permutation, first 400 indices
        # (reference :145-152) — reproduced with a local Generator rather than
        # by touching global numpy state.
        val_idxs = set(
            np.random.RandomState(1000).permutation(len(lefts))[:400])
        for idx in range(len(lefts)):
            if split == "TRAIN" or idx in val_idxs:
                self._add_pairs([lefts[idx]], [rights[idx]], [disps[idx]])
        logger.info("Added %d from FlyingThings %s",
                    len(self.disparity_list) - before, self.dstype)

    def _add_monkaa(self):
        before = len(self.disparity_list)
        self._add_pairs(*self._glob_pairs(
            osp.join(self.root, "Monkaa", self.dstype, "*/left/*.png")))
        logger.info("Added %d from Monkaa %s",
                    len(self.disparity_list) - before, self.dstype)

    def _add_driving(self):
        before = len(self.disparity_list)
        self._add_pairs(*self._glob_pairs(
            osp.join(self.root, "Driving", self.dstype, "*/*/*/left/*.png")))
        logger.info("Added %d from Driving %s",
                    len(self.disparity_list) - before, self.dstype)


class ETH3D(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/ETH3D",
                 split: str = "training"):
        super().__init__(aug_params, sparse=True)
        image1_list = sorted(glob(osp.join(root, f"two_view_{split}/*/im0.png")))
        image2_list = sorted(glob(osp.join(root, f"two_view_{split}/*/im1.png")))
        if split == "training":
            disp_list = sorted(
                glob(osp.join(root, "two_view_training_gt/*/disp0GT.pfm")))
        else:  # test split has no GT; reference substitutes a fixed dummy path
            disp_list = [osp.join(root, "two_view_training_gt/playground_1l/"
                                  "disp0GT.pfm")] * len(image1_list)
        self._add_pairs(image1_list, image2_list, disp_list)


class SintelStereo(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/SintelStereo"):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_sintel)
        image1_list = sorted(glob(osp.join(root, "training/*_left/*/frame_*.png")))
        image2_list = sorted(glob(osp.join(root, "training/*_right/*/frame_*.png")))
        # clean + final pass share one disparity directory (reference :205)
        disp_list = sorted(glob(
            osp.join(root, "training/disparities/*/frame_*.png"))) * 2
        for img1, img2, disp in zip(image1_list, image2_list, disp_list):
            if img1.split("/")[-2:] != disp.split("/")[-2:]:
                raise ValueError(f"misaligned Sintel pair: {img1} vs {disp}")
            self._add_pairs([img1], [img2], [disp])


class FallingThings(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/FallingThings"):
        super().__init__(aug_params, reader=frame_utils.read_disp_falling_things)
        if not os.path.exists(root):
            raise FileNotFoundError(root)
        with open(osp.join(root, "filenames.txt")) as f:
            filenames = sorted(f.read().splitlines())
        self._add_pairs(
            [osp.join(root, e) for e in filenames],
            [osp.join(root, e.replace("left.jpg", "right.jpg")) for e in filenames],
            [osp.join(root, e.replace("left.jpg", "left.depth.png"))
             for e in filenames])


class TartanAir(StereoDataset):
    def __init__(self, aug_params=None, root="datasets",
                 keywords: Sequence[str] = ()):
        super().__init__(aug_params, reader=frame_utils.read_disp_tartan_air)
        if not os.path.exists(root):
            raise FileNotFoundError(root)
        with open(osp.join(root, "tartanair_filenames.txt")) as f:
            filenames = sorted(
                s for s in f.read().splitlines()
                if "seasonsforest_winter/Easy" not in s)
        for kw in keywords:
            filenames = sorted(s for s in filenames if kw in s.lower())
        self._add_pairs(
            [osp.join(root, e) for e in filenames],
            [osp.join(root, e.replace("_left", "_right")) for e in filenames],
            [osp.join(root, e.replace("image_left", "depth_left")
                      .replace("left.png", "left_depth.npy"))
             for e in filenames])


class KITTI(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/KITTI",
                 image_set: str = "training", split: Optional[str] = None):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_kitti)
        if not os.path.exists(root):
            raise FileNotFoundError(root)
        # `split` aliases `image_set` (any value containing 'kitti' means
        # training) — fixes the reference's TypeError in the training mix.
        if split is not None:
            image_set = "training" if "kitti" in split else split
        image1_list = sorted(glob(osp.join(root, image_set, "image_2/*_10.png")))
        image2_list = sorted(glob(osp.join(root, image_set, "image_3/*_10.png")))
        if image_set == "training":
            disp_list = sorted(glob(osp.join(root, "training",
                                             "disp_occ_0/*_10.png")))
        else:  # test split: fixed dummy GT path (reference :253)
            disp_list = [osp.join(root, "training/disp_occ_0/000085_10.png")
                         ] * len(image1_list)
        self._add_pairs(image1_list, image2_list, disp_list)


class Middlebury(StereoDataset):
    def __init__(self, aug_params=None, root="datasets/Middlebury",
                 split: str = "F"):
        super().__init__(aug_params, sparse=True,
                         reader=frame_utils.read_disp_middlebury)
        if not os.path.exists(root):
            raise FileNotFoundError(root)
        if split not in ("F", "H", "Q"):
            raise ValueError(f"Middlebury split must be F/H/Q, got {split!r}")
        with open(osp.join(root, "MiddEval3/official_train.txt")) as f:
            official = set(f.read().splitlines())
        scenes = sorted(
            name for name in map(
                osp.basename, glob(osp.join(root, "MiddEval3/trainingF/*")))
            if name in official)
        base = osp.join(root, "MiddEval3", f"training{split}")
        if not scenes:
            raise FileNotFoundError(f"no official_train scenes under {base}")
        self._add_pairs(
            [osp.join(base, name, "im0.png") for name in scenes],
            [osp.join(base, name, "im1.png") for name in scenes],
            [osp.join(base, name, "disp0GT.pfm") for name in scenes])


# ---------------------------------------------------------------------------
# Training mix (reference fetch_dataloader, :277-315)
# ---------------------------------------------------------------------------

def aug_params_from_config(train_cfg) -> dict:
    """Build augmentor kwargs from a TrainConfig (reference :280-286)."""
    aug_params = {
        "crop_size": list(train_cfg.image_size),
        "min_scale": train_cfg.spatial_scale[0],
        "max_scale": train_cfg.spatial_scale[1],
        "do_flip": False,
        "yjitter": not train_cfg.noyjitter,
    }
    if getattr(train_cfg, "saturation_range", None) is not None:
        aug_params["saturation_range"] = train_cfg.saturation_range
    if getattr(train_cfg, "img_gamma", None) is not None:
        aug_params["gamma"] = train_cfg.img_gamma
    if getattr(train_cfg, "do_flip", None) is not None:
        aug_params["do_flip"] = train_cfg.do_flip
    return aug_params


def fetch_dataset(train_cfg, root: Optional[str] = None) -> StereoDataset:
    """Concatenate the requested datasets with the reference's oversampling
    weights: sceneflow = clean*4 + final*4, sintel*140, falling_things*5."""
    aug_params = aug_params_from_config(train_cfg)
    root_kw = {"root": root} if root is not None else {}

    mixed = None
    for name in train_cfg.train_datasets:
        if re.fullmatch("middlebury_.*", name):
            ds = Middlebury(aug_params, split=name.replace("middlebury_", ""),
                            **root_kw)
        elif name == "sceneflow":
            clean = SceneFlowDatasets(aug_params, dstype="frames_cleanpass",
                                      **root_kw)
            final = SceneFlowDatasets(aug_params, dstype="frames_finalpass",
                                      **root_kw)
            ds = clean * 4 + final * 4
        elif "kitti" in name:
            ds = KITTI(aug_params, split=name, **root_kw)
        elif name == "sintel_stereo":
            ds = SintelStereo(aug_params, **root_kw) * 140
        elif name == "falling_things":
            ds = FallingThings(aug_params, **root_kw) * 5
        elif name.startswith("tartan_air"):
            ds = TartanAir(aug_params, keywords=name.split("_")[2:], **root_kw)
        else:
            raise ValueError(f"unknown training dataset {name!r}")
        logger.info("Adding %d samples from %s", len(ds), name)
        mixed = ds if mixed is None else mixed + ds

    if mixed is None:
        raise ValueError("train_datasets is empty")
    logger.info("Training with %d image pairs", len(mixed))
    return mixed

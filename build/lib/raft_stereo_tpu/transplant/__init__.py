from raft_stereo_tpu.transplant.torch_loader import (  # noqa: F401
    load_pth,
    transplant_state_dict,
)

"""Weight transplant: reference PyTorch checkpoints -> param pytree.

Loads the published ``raftstereo-{middlebury,eth3d,realtime}.pth`` checkpoints
(or any reference-architecture state_dict) into this framework's parameter
pytree. The mapping is mechanical:

- strip the ``module.`` prefix (the reference saves the DataParallel-wrapped
  module, ``train_stereo.py:134,184``);
- conv kernels OIHW -> HWIO;
- BatchNorm running statistics become the frozen-BN affine state (the reference
  never updates BN: ``freeze_bn``, ``core/raft_stereo.py:41-44``); the
  ``num_batches_tracked`` counters are dropped;
- InstanceNorm carries no parameters (torch ``affine=False`` default);
- the reference registers the batch-norm residual shortcut's norm twice
  (``norm3`` and ``downsample.1`` alias the same tensors,
  ``core/extractor.py:44-45``); the ``downsample.1`` spelling is read.

Only numpy/torch are needed; no reference code is imported.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _conv(sd: Mapping, name: str) -> Dict:
    p = {"w": jnp.asarray(_np(sd[f"{name}.weight"]).transpose(2, 3, 1, 0))}
    if f"{name}.bias" in sd:
        p["b"] = jnp.asarray(_np(sd[f"{name}.bias"]))
    return p


def _bn(sd: Mapping, name: str) -> Dict:
    return {"scale": jnp.asarray(_np(sd[f"{name}.weight"])),
            "bias": jnp.asarray(_np(sd[f"{name}.bias"])),
            "mean": jnp.asarray(_np(sd[f"{name}.running_mean"])),
            "var": jnp.asarray(_np(sd[f"{name}.running_var"]))}


def _norm(sd: Mapping, name: str, norm_fn: str) -> Dict:
    if norm_fn == "batch":
        return _bn(sd, name)
    if norm_fn == "group":
        return {"scale": jnp.asarray(_np(sd[f"{name}.weight"])),
                "bias": jnp.asarray(_np(sd[f"{name}.bias"]))}
    return {}  # instance / none: stateless


def _residual_block(sd: Mapping, name: str, norm_fn: str) -> Dict:
    p = {"conv1": _conv(sd, f"{name}.conv1"),
         "conv2": _conv(sd, f"{name}.conv2"),
         "norm1": _norm(sd, f"{name}.norm1", norm_fn),
         "norm2": _norm(sd, f"{name}.norm2", norm_fn)}
    if f"{name}.downsample.0.weight" in sd:
        p["downsample"] = {"conv": _conv(sd, f"{name}.downsample.0"),
                           "norm": _norm(sd, f"{name}.downsample.1", norm_fn)}
    return p


def _stage(sd: Mapping, name: str, norm_fn: str) -> list:
    return [_residual_block(sd, f"{name}.0", norm_fn),
            _residual_block(sd, f"{name}.1", norm_fn)]


def _basic_encoder(sd: Mapping, prefix: str, norm_fn: str) -> Dict:
    return {"conv1": _conv(sd, f"{prefix}.conv1"),
            "norm1": _norm(sd, f"{prefix}.norm1", norm_fn),
            "layer1": _stage(sd, f"{prefix}.layer1", norm_fn),
            "layer2": _stage(sd, f"{prefix}.layer2", norm_fn),
            "layer3": _stage(sd, f"{prefix}.layer3", norm_fn),
            "conv2": _conv(sd, f"{prefix}.conv2")}


def _multi_encoder(sd: Mapping, prefix: str, norm_fn: str, n_heads: int) -> Dict:
    p = {"conv1": _conv(sd, f"{prefix}.conv1"),
         "norm1": _norm(sd, f"{prefix}.norm1", norm_fn),
         "layer1": _stage(sd, f"{prefix}.layer1", norm_fn),
         "layer2": _stage(sd, f"{prefix}.layer2", norm_fn),
         "layer3": _stage(sd, f"{prefix}.layer3", norm_fn),
         "layer4": _stage(sd, f"{prefix}.layer4", norm_fn),
         "layer5": _stage(sd, f"{prefix}.layer5", norm_fn)}
    for scale in ("outputs08", "outputs16"):
        p[scale] = [{"res": _residual_block(sd, f"{prefix}.{scale}.{j}.0", norm_fn),
                     "conv": _conv(sd, f"{prefix}.{scale}.{j}.1")}
                    for j in range(n_heads)]
    p["outputs32"] = [{"conv": _conv(sd, f"{prefix}.outputs32.{j}")}
                      for j in range(n_heads)]
    return p


def _gru(sd: Mapping, name: str) -> Dict:
    return {g: _conv(sd, f"{name}.{g}") for g in ("convz", "convr", "convq")}


def _update_block(sd: Mapping, prefix: str) -> Dict:
    return {
        "encoder": {c: _conv(sd, f"{prefix}.encoder.{c}")
                    for c in ("convc1", "convc2", "convf1", "convf2", "conv")},
        "gru08": _gru(sd, f"{prefix}.gru08"),
        "gru16": _gru(sd, f"{prefix}.gru16"),
        "gru32": _gru(sd, f"{prefix}.gru32"),
        "flow_head": {"conv1": _conv(sd, f"{prefix}.flow_head.conv1"),
                      "conv2": _conv(sd, f"{prefix}.flow_head.conv2")},
        "mask": {"conv1": _conv(sd, f"{prefix}.mask.0"),
                 "conv2": _conv(sd, f"{prefix}.mask.2")},
    }


def transplant_state_dict(state_dict: Mapping, cfg: RAFTStereoConfig) -> Dict:
    """Convert a reference state_dict (torch tensors or numpy) to a param pytree."""
    sd = {k[len("module."):] if k.startswith("module.") else k: v
          for k, v in state_dict.items()}
    params = {
        "cnet": _multi_encoder(sd, "cnet", "batch", n_heads=2),
        "update_block": _update_block(sd, "update_block"),
        "context_zqr_convs": [_conv(sd, f"context_zqr_convs.{i}")
                              for i in range(cfg.n_gru_layers)],
    }
    if cfg.shared_backbone:
        params["conv2"] = {"res": _residual_block(sd, "conv2.0", "instance"),
                           "conv": _conv(sd, "conv2.1")}
    else:
        params["fnet"] = _basic_encoder(sd, "fnet", "instance")
    return params


def load_pth(path: str, cfg: RAFTStereoConfig) -> Dict:
    """Load a reference ``.pth`` checkpoint into a param pytree."""
    import torch  # local import: torch is only needed for transplant
    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    return transplant_state_dict(state_dict, cfg)

"""Full-state checkpointing: params + optimizer + step in one bundle.

The reference saves model weights only (``torch.save(model.state_dict())``,
``train_stereo.py:184``), so a resumed run restarts the OneCycle schedule from
zero — flagged in SURVEY §5 as a deliberate improvement target. Here a
checkpoint is the complete training state, serialized with flax msgpack
(pytree-structure-preserving, works for optax named-tuple states), written
atomically (tmp file + rename) so preemption mid-save never corrupts the
latest checkpoint.

``load_params`` additionally accepts the reference's ``.pth`` checkpoints via
the transplant shim, so all published RAFT-Stereo weights load anywhere our
checkpoints do.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
from flax import serialization

CKPT_SUFFIX = ".msgpack"


def save_checkpoint(path: str, params, opt_state=None, step: int = 0) -> str:
    """Serialize (params, opt_state, step) atomically; returns the path."""
    state = {"params": jax.device_get(params),
             "opt_state": (jax.device_get(opt_state)
                           if opt_state is not None else None),
             "step": step}
    blob = serialization.to_bytes(state)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_checkpoint(path: str, params_template, opt_state_template=None
                    ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state, step); templates define the pytree shape."""
    with open(path, "rb") as f:
        blob = f.read()
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": 0}
    state = serialization.from_bytes(template, blob)
    return state["params"], state["opt_state"], int(state["step"])


def load_params(path: str, cfg, params_template=None):
    """Load model params from either a native bundle or a reference ``.pth``."""
    if path.endswith(".pth"):
        from raft_stereo_tpu.transplant import load_pth
        return load_pth(path, cfg)
    params, _, _ = load_checkpoint(path, params_template)
    return params

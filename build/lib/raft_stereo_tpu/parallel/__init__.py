"""Parallel execution: device meshes, shardings, distributed init.

The reference's only parallelism is single-process ``nn.DataParallel``
(``train_stereo.py:134``) — replicate/scatter/gather over GPUs. The TPU-native
equivalent is a sharding annotation, not a subsystem: batch-shard the data over
a ``Mesh``, replicate params, and let XLA insert the gradient ``psum`` over
ICI/DCN. A second, optional ``space`` axis shards image height — and with it
the correlation volume — for full-resolution inputs (the 'long-context'
analog; SURVEY.md §5), with XLA providing the conv halo exchanges.
"""

from raft_stereo_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
    spatial_sharding,
)

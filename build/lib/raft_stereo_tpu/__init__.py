"""raft_stereo_tpu — a TPU-native (JAX / XLA / Pallas / pjit) stereo-matching framework.

Provides the full capability surface of RAFT-Stereo (iterative disparity refinement
over a multi-scale 1D correlation pyramid with a hierarchy of convolutional GRUs),
re-designed TPU-first:

- functional core: the model is a pure function ``(params, img1, img2) -> predictions``
  over a params pytree; the GRU refinement loop is a ``jax.lax.scan``;
- NHWC activations / HWIO kernels (TPU conv native layout);
- four interchangeable correlation implementations behind one protocol:
  ``reg`` / ``alt`` (pure XLA) and ``reg_tpu`` / ``alt_tpu`` (Pallas kernels);
- bf16-compute / fp32-param mixed precision (no grad scaler needed);
- data parallelism via ``jax.sharding`` over a device ``Mesh`` (XLA collectives),
  with optional width-axis sharding of the correlation volume for full-resolution
  inputs;
- a weight-transplant shim that loads the published PyTorch ``.pth`` checkpoints.
"""

__version__ = "0.1.0"

from raft_stereo_tpu.config import RAFTStereoConfig  # noqa: F401

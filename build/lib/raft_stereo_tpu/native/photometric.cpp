// Native photometric-augmentation kernels for the data pipeline.
//
// The numpy implementation (../data/photometric.py) allocates several
// full-frame float temporaries per jitter op (factor*img, (1-f)*other, clip
// all materialize); at FlyingThings resolution that is ~70 ms per sample and
// dominates loader throughput (scratch/bench_loader.py). These kernels apply
// the same torchvision-semantics ops in place on one float32 buffer.
//
// Semantics mirror ../data/photometric.py exactly (same op maths, same
// float32 arithmetic per pixel); the only intentional difference is the
// contrast op's mean reduction, accumulated here in double instead of
// numpy's pairwise float32 — a ~1e-5 relative difference on a blend
// *scalar*, bounded by the parity test (tests/test_native.py).
//
// The hue op is NOT here: it goes through OpenCV's uint8 HSV fixed-point
// conversion (photometric.py adjust_hue), which is already native; callers
// split the op sequence around it.
//
// Exported C ABI (ctypes, see __init__.py): all buffers are contiguous
// float32 RGB, npix = H*W pixels (3 floats each), modified in place.

#include <cmath>
#include <cstdint>

namespace {

constexpr float kGrayR = 0.299f, kGrayG = 0.587f, kGrayB = 0.114f;  // ITU-R 601

inline float clip255(float v) {
    return v < 0.0f ? 0.0f : (v > 255.0f ? 255.0f : v);
}

}  // namespace

extern "C" {

// out = clip(factor * img, 0, 255)  — torchvision brightness blend-with-zero.
void rst_brightness(float* img, int64_t npix, float factor) {
    int64_t n = npix * 3;
    for (int64_t i = 0; i < n; ++i) img[i] = clip255(factor * img[i]);
}

// out = clip(factor * img + (1-factor) * mean_gray(img), 0, 255).
void rst_contrast(float* img, int64_t npix, float factor) {
    double acc = 0.0;
    for (int64_t p = 0; p < npix; ++p) {
        const float* px = img + 3 * p;
        acc += kGrayR * px[0] + kGrayG * px[1] + kGrayB * px[2];
    }
    const float mean_gray = static_cast<float>(acc / static_cast<double>(npix));
    const float rest = (1.0f - factor) * mean_gray;
    int64_t n = npix * 3;
    for (int64_t i = 0; i < n; ++i) img[i] = clip255(factor * img[i] + rest);
}

// out = clip(factor * img + (1-factor) * gray(px), 0, 255), per-pixel gray.
void rst_saturation(float* img, int64_t npix, float factor) {
    const float rest = 1.0f - factor;
    for (int64_t p = 0; p < npix; ++p) {
        float* px = img + 3 * p;
        const float gray = kGrayR * px[0] + kGrayG * px[1] + kGrayB * px[2];
        const float g = rest * gray;
        px[0] = clip255(factor * px[0] + g);
        px[1] = clip255(factor * px[1] + g);
        px[2] = clip255(factor * px[2] + g);
    }
}

// out = clip(255 * gain * (img/255)^gamma, 0, 255) via a 4096-entry LUT with
// linear interpolation: img values are float but lie in [0, 255] (every
// upstream op clips), and powf per pixel is ~10x the LUT cost. Lerp error on
// the power curve at this resolution is < 2e-3 counts (pinned by
// tests/test_native.py), vanishing under the final uint8 cast.
void rst_gamma(float* img, int64_t npix, float gamma, float gain) {
    constexpr int kN = 4096;
    float lut[kN + 1];
    for (int i = 0; i <= kN; ++i) {
        float x = static_cast<float>(i) / static_cast<float>(kN);
        lut[i] = clip255(255.0f * gain * powf(x, gamma));
    }
    const float scale = static_cast<float>(kN) / 255.0f;
    int64_t n = npix * 3;
    for (int64_t i = 0; i < n; ++i) {
        const float pos = img[i] * scale;
        // A clipped pixel at exactly 255.0 maps to pos == kN; clamp the cell
        // so the lerp endpoints stay inside the kN+1-entry table (frac
        // becomes 1.0 and the result is exactly lut[kN]).
        const int idx = pos >= static_cast<float>(kN)
                            ? kN - 1 : static_cast<int>(pos);
        const float frac = pos - static_cast<float>(idx);
        img[i] = lut[idx] + frac * (lut[idx + 1] - lut[idx]);
    }
}

// Apply a sequence of {0: brightness, 1: contrast, 2: saturation} ops in
// order — one boundary crossing for a whole hue-free run of jitter ops.
void rst_jitter_ops(float* img, int64_t npix, const int32_t* ops, int32_t n_ops,
                    float brightness, float contrast, float saturation) {
    for (int32_t k = 0; k < n_ops; ++k) {
        switch (ops[k]) {
            case 0: rst_brightness(img, npix, brightness); break;
            case 1: rst_contrast(img, npix, contrast); break;
            case 2: rst_saturation(img, npix, saturation); break;
            default: break;  // unknown op: no-op rather than UB
        }
    }
}

}  // extern "C"

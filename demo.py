#!/usr/bin/env python
"""Demo CLI — flag-for-flag with the reference ``demo.py:53-75``.

Glob a left/right image list, run the model in test mode, save the disparity
as a jet-colormap PNG (sign-flipped back to positive) and optionally ``.npy``.

Runs through ``raft_stereo_tpu.serve.InferenceSession`` (``--bucket``): a
mixed-size glob shares compiled programs instead of recompiling per frame;
the default bucket of 32 reproduces the reference per-shape padding formula
exactly, so outputs are byte-identical to the pre-session path (test-pinned
in ``tests/test_serve.py``).
"""

from __future__ import annotations

import argparse
import glob
import logging
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    from raft_stereo_tpu.config import add_model_args

    parser = argparse.ArgumentParser()
    parser.add_argument('--restore_ckpt', help="restore checkpoint",
                        required=True)
    parser.add_argument('--save_numpy', action='store_true',
                        help='save output as numpy arrays')
    parser.add_argument('-l', '--left_imgs',
                        help="path to all first (left) frames",
                        default="datasets/Middlebury/MiddEval3/testH/*/im0.png")
    parser.add_argument('-r', '--right_imgs',
                        help="path to all second (right) frames",
                        default="datasets/Middlebury/MiddEval3/testH/*/im1.png")
    parser.add_argument('--output_directory',
                        help="directory to save output", default="demo_output")
    parser.add_argument('--valid_iters', type=int, default=32,
                        help='number of flow-field updates during forward pass')
    parser.add_argument('--video', action='store_true',
                        help="treat the sorted glob as ONE ordered video "
                        "sequence and run it through a graftstream "
                        "session: each frame's 1/8-res disparity "
                        "warm-starts the next (prepare_warm), and warm "
                        "frames exit early when the per-segment "
                        "delta-flow norm falls below --converge_tol — "
                        "per-frame iterations + quality labels are "
                        "printed. The first frame (no prior disparity) "
                        "is bit-identical to the single-pair path "
                        "(pinned in tests/test_stream.py)")
    parser.add_argument('--segments', type=int, default=4,
                        help="video mode: host-visible segments the "
                        "refinement splits into (must divide "
                        "valid_iters); convergence is checked at "
                        "segment boundaries. Ignored without --video")
    parser.add_argument('--converge_tol', type=float, default=None,
                        help="video mode: convergence tolerance "
                        "(px/iter segment-mean |delta_x| at 1/8 res); "
                        "default RAFT_CONVERGE_TOL else 0.01; 0 "
                        "disables the early exit")
    parser.add_argument('--bucket', type=int, default=32,
                        help="pad shapes to multiples of this (multiple of "
                        "32) so a mixed-size glob shares compiled programs; "
                        "the default 32 reproduces the reference per-shape "
                        "padding formula exactly (bit-identical output), "
                        "larger buckets trade a little edge padding for "
                        "fewer compiles")
    add_model_args(parser)
    return parser


def demo(args) -> None:
    import jax
    import numpy as np

    from raft_stereo_tpu.config import RAFTStereoConfig, with_eval_precision
    from raft_stereo_tpu.data.frame_utils import read_image_rgb
    from raft_stereo_tpu.engine.checkpoint import load_params
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.serve import InferenceSession, SessionConfig

    cfg = RAFTStereoConfig.from_namespace(args)
    template = (None if args.restore_ckpt.endswith(".pth")
                else init_raft_stereo(jax.random.PRNGKey(0), cfg))
    params = load_params(args.restore_ckpt, cfg, template)
    cfg = with_eval_precision(cfg)
    # The session runs the SAME single-scan program make_eval_forward
    # compiled (byte-identical output, test-pinned) but bucket-caches
    # compilations, so a mixed-size glob stops recompiling per frame.
    # Video mode splits the scan into --segments chunks so the stream
    # runner can warm-start frames and exit at convergence boundaries
    # (k segments of m iters are bit-identical to one k*m scan — the
    # PR 3 composition pins — so the split itself changes no output).
    segments = args.segments if args.video else 1
    if args.video and args.valid_iters % segments:
        raise SystemExit(f"--segments {segments} must divide "
                         f"--valid_iters {args.valid_iters}")
    session = InferenceSession(params, cfg, SessionConfig(
        valid_iters=args.valid_iters, bucket=args.bucket,
        segments=segments, canary=False))
    runner = None
    if args.video:
        from raft_stereo_tpu.serve import StreamRunner
        runner = StreamRunner(session, converge_tol=args.converge_tol)

    output_directory = Path(args.output_directory)
    output_directory.mkdir(exist_ok=True)

    left_images = sorted(glob.glob(args.left_imgs, recursive=True))
    right_images = sorted(glob.glob(args.right_imgs, recursive=True))
    print(f"Found {len(left_images)} images. "
          f"Saving files to {output_directory}/")

    from concurrent.futures import ThreadPoolExecutor

    from matplotlib import pyplot as plt

    from raft_stereo_tpu.engine.evaluate import prefetch_samples

    class _PairLoader:
        """Indexable over (left, right) paths — lets the demo share the
        validators' decode-ahead generator (``prefetch_samples``)."""

        def __init__(self, pairs):
            self.pairs = pairs

        def __len__(self):
            return len(self.pairs)

        def __getitem__(self, i):
            f1, f2 = self.pairs[i]
            return (f1, read_image_rgb(f1).astype(np.float32)[None],
                    read_image_rgb(f2).astype(np.float32)[None])

    def save_one(file_stem, flow_up):
        if args.save_numpy:
            np.save(output_directory / f"{file_stem}.npy", flow_up.squeeze())
        plt.imsave(output_directory / f"{file_stem}.png", -flow_up.squeeze(),
                   cmap='jet')

    # Decode the next pair (prefetch_samples) and encode the previous result
    # on background threads while the chip runs the current forward: at full
    # resolution the jet-PNG encode alone costs about as much host time as
    # the forward costs device time. At most one save is in flight, awaited
    # in order, so outputs and memory stay bounded.
    from raft_stereo_tpu.serve import InputRejected, SessionError

    loader = _PairLoader(list(zip(left_images, right_images)))
    skipped = 0
    with ThreadPoolExecutor(max_workers=1) as saver:
        pending_save = None
        for imfile1, image1, image2 in prefetch_samples(loader):
            try:
                if runner is not None:
                    result = runner.infer(image1, image2)
                    print(f"frame {runner.frames - 1}: {imfile1} "
                          f"iters={result.iters} "
                          f"quality={result.quality}")
                else:
                    result = session.infer(image1, image2)
            except (InputRejected, SessionError) as e:
                # One bad frame (NaN pixels, non-finite disparity) must
                # not abort the rest of the glob — log and keep going.
                logging.error("skipping %s: %s", imfile1, e)
                skipped += 1
                continue
            # result.disparity is the positive disparity; the save path
            # below keeps the reference sign conventions (npy stores the
            # raw negative-disparity flow; -(-x) is bitwise x).
            flow_up = -result.disparity

            if pending_save is not None:
                pending_save.result()
            pending_save = saver.submit(save_one, imfile1.split('/')[-2],
                                        flow_up)
        if pending_save is not None:
            pending_save.result()
    if skipped:
        print(f"Skipped {skipped} of {len(left_images)} pairs "
              "(structured per-frame errors above)")


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    demo(args)


if __name__ == '__main__':
    main()

#!/usr/bin/env python
"""Serving CLI — drive the resilient inference subsystem from the shell.

Globs left/right image pairs (like ``demo.py``), stands up an
``InferenceSession`` + ``StereoService`` (bucketed compile cache, circuit
breaker, optional startup parity canary, per-request deadlines with
anytime degradation), streams every pair through the bounded queue, and
prints one JSON line per response plus the final ``/healthz`` status
document. See README "Serving quickstart" and DESIGN.md "Serving &
degradation".

Examples::

    # plain serving, bucketed compiles, warmed first shape
    python serve_stereo.py --restore_ckpt models/raftstereo.msgpack \
        -l 'imgs/*_left.png' -r 'imgs/*_right.png' --bucket 64

    # 200 ms deadline per frame: late frames degrade instead of timing out
    python serve_stereo.py --restore_ckpt ... -l ... -r ... \
        --deadline_ms 200 --segments 4

    # continuous batching: up to 8 requests share one device batch,
    # joining/leaving at segment boundaries (throughput mode)
    python serve_stereo.py --restore_ckpt ... -l ... -r ... \
        --max_batch 8 --workers 8

    # network ingress (graftwire, DESIGN.md r14): POST /v1/stereo over
    # HTTP/1.1, real /healthz + /metrics endpoints, SIGTERM drains clean
    python serve_stereo.py --restore_ckpt ... --http_port 8080 \
        --max_batch 8 --warmup 544x960
"""

from __future__ import annotations

import argparse
import glob
import json
import logging
import threading
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    from raft_stereo_tpu.config import add_model_args

    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument('--restore_ckpt', default=None,
                        help="checkpoint (.pth reference weights or native "
                        ".msgpack); omitted = random init (smoke runs)")
    parser.add_argument('-l', '--left_imgs', default=None,
                        help="glob for left frames (batch mode; not "
                        "needed with --http_port)")
    parser.add_argument('-r', '--right_imgs', default=None,
                        help="glob for right frames (batch mode)")
    parser.add_argument('--output_directory', default=None,
                        help="save disparity .npy files here (optional)")
    parser.add_argument('--valid_iters', type=int, default=32,
                        help='refinement iterations for an undegraded pass')
    # Serving knobs
    parser.add_argument('--bucket', type=int, default=64,
                        help="pad request shapes to multiples of this "
                        "(multiple of 32) so mixed sizes share compiles")
    parser.add_argument('--segments', type=int, default=4,
                        help="host-visible scan segments for deadline "
                        "requests (must divide valid_iters)")
    parser.add_argument('--deadline_ms', type=float, default=None,
                        help="per-request deadline; omitted = no degradation")
    parser.add_argument('--max_queue', type=int, default=8,
                        help="bounded queue depth (full -> explicit reject)")
    parser.add_argument('--workers', type=int, default=1,
                        help="worker threads draining the queue "
                        "(sequential mode; with --max_batch > 1 one "
                        "scheduler thread replaces the pool and this only "
                        "caps the CLI's in-flight requests)")
    parser.add_argument('--max_batch', type=int, default=1,
                        help="continuous batching: up to this many "
                        "requests share one device batch, joining at tick "
                        "boundaries and exiting at segment boundaries "
                        "(1 = sequential serving)")
    parser.add_argument('--tick_ms', type=float, default=None,
                        help="scheduler idle-poll interval (batched mode; "
                        "default RAFT_SCHED_TICK_MS or 2 ms)")
    # graftpod: pod-scale serving (DESIGN.md r21)
    parser.add_argument('--mesh_data', type=int, default=None,
                        help="shard the device batch over this many chips "
                        "(data mesh): one ingress drives N devices, batch "
                        "buckets round up to multiples of N, per-chip "
                        "occupancy/saturation surfaces on /healthz "
                        "(default RAFT_SERVE_MESH_DATA or 1 = single "
                        "device; validate off-chip with JAX_PLATFORMS=cpu "
                        "XLA_FLAGS=--xla_force_host_platform_device_"
                        "count=8)")
    parser.add_argument('--max_pixels', type=int, default=8 << 20,
                        help="admission cap on per-image area")
    # graftlane (r24) + r19 pack opt-ins: CLI sugar over the env kill
    # switches. setdefault semantics — an EXPLICIT RAFT_*_PACK8 env value
    # (including 0) wins over the flag, so an operator's kill-switch
    # export is never silently re-armed by a stale launch script.
    parser.add_argument('--pack8', action='store_true',
                        help="arm the int8 quad-packed correlation "
                        "containers (RAFT_CORR_PACK8=1 unless that env "
                        "var is already set)")
    parser.add_argument('--lane_pack8', action='store_true',
                        help="arm the int8 packed context lanes for "
                        "per-iteration feature/context traffic "
                        "(RAFT_LANE_PACK8=1 unless that env var is "
                        "already set)")
    parser.add_argument('--warmup', default=None,
                        help="comma-separated HxW image shapes to "
                        "pre-compile, e.g. '544x960,736x1280'")
    parser.add_argument('--no_canary', action='store_true',
                        help="skip the startup fast-vs-XLA parity canary")
    parser.add_argument('--no_heal', action='store_true',
                        help="disable the recovery plane (RAFT_HEAL=0 "
                        "equivalent): breaker trips, chip quarantines "
                        "and restart-budget exhaustion stay one-way")
    parser.add_argument('--no_half_res', action='store_true',
                        help="never degrade to half resolution")
    parser.add_argument('--status_json', default=None,
                        help="also write the final /healthz status here")
    parser.add_argument('--metrics_prom', default=None,
                        help="write the final Prometheus /metrics text "
                        "here (the same registry /healthz derives from; "
                        "RAFT_TRACE=<path.jsonl> additionally streams "
                        "per-request span timelines, RAFT_PROFILE_DIR "
                        "arms on-demand jax.profiler windows)")
    parser.add_argument('--ledger_out', default=None,
                        help="write the device ledger dump here (inspect "
                        "with `python -m raft_stereo_tpu.obs.ledger "
                        "report`): per-program compiler flops/bytes/peak "
                        "HBM, MFU attribution, cache HBM accounting")
    parser.add_argument('--slo_ms', type=float, default=None,
                        help="latency SLO: a served request slower than "
                        "this (or any breaker trip / missed deadline / "
                        "non-finite output) persists a bounded flight "
                        "record to RAFT_FLIGHT_DIR")
    # graftguard: supervision + drain (DESIGN.md r13). The CLI defaults
    # the watchdog ON (the library default is off so test rigs with fake
    # clocks never race a real-time monitor).
    parser.add_argument('--watchdog_ms', type=float, default=10_000.0,
                        help="hang-watchdog deadline floor: a device "
                        "invocation older than max(EMA*4, this) bounces "
                        "the scheduler generation and re-admits its rows "
                        "(0 disables; default 10s)")
    parser.add_argument('--retry_budget', type=int, default=None,
                        help="bounded re-admissions per request for "
                        "transient failures (uploader death, generation "
                        "bounce, a first non-finite output); responses "
                        "carry 'retries: k' (default RAFT_RETRY_BUDGET "
                        "or 2)")
    parser.add_argument('--drain_grace_ms', type=float, default=None,
                        help="SIGTERM/SIGINT graceful-drain hard "
                        "deadline: admitted requests run to their "
                        "segment-boundary exits within this window, "
                        "then the rest resolve service_stopped (default "
                        "RAFT_DRAIN_GRACE_MS or 10s)")
    # graftstream: streaming video stereo (DESIGN.md r17)
    parser.add_argument('--stream_sessions', type=int, default=None,
                        help="global bound on live stream sessions "
                        "(X-Raft-Session warm-start table; default "
                        "RAFT_STREAM_SESSIONS or 128)")
    parser.add_argument('--stream_ttl_ms', type=float, default=None,
                        help="idle stream-session expiry (default "
                        "RAFT_STREAM_TTL_MS or 60s)")
    parser.add_argument('--converge_tol', type=float, default=None,
                        help="convergence early-exit tolerance stamped "
                        "on warm frames: segment-mean per-iteration "
                        "|delta_x| at 1/8 res, px (0 disables; default "
                        "RAFT_CONVERGE_TOL or 0.01)")
    # graftrecall: content-addressed response cache (DESIGN.md r18).
    # The CLI defaults the cache ON (the library default is off so test
    # rigs and embedders opt in — the watchdog precedent).
    parser.add_argument('--cache_bytes', type=int, default=None,
                        help="host-RAM budget for the two-tier response "
                        "cache: exact hits (sha256 of the padded pair + "
                        "program fingerprint + tenant) serve the stored "
                        "response bit-identically at zero device "
                        "seconds, labeled cache:exact (0 disables; "
                        "default RAFT_CACHE_BYTES or 256 MiB)")
    parser.add_argument('--cache_near_tol', type=float, default=None,
                        help="near-duplicate tier threshold (mean "
                        "block-signature difference, gray levels): a "
                        "close-enough stored scene seeds coords1 "
                        "through prepare_warm and the response is "
                        "labeled warm:cache:<iters> (0 disables; "
                        "default RAFT_CACHE_NEAR_TOL or 0)")
    # graftwire: network ingress (DESIGN.md r14)
    parser.add_argument('--http_port', type=int, default=None,
                        help="serve POST /v1/stereo + GET /healthz "
                        "+ GET /metrics over HTTP/1.1 on this port "
                        "instead of running the glob batch driver "
                        "(0 = ephemeral; omit the flag entirely for "
                        "batch mode — RAFT_HTTP_PORT applies to "
                        "embedded HttpConfig use, not this flag)")
    parser.add_argument('--http_host', default="127.0.0.1",
                        help="ingress bind address (default loopback; "
                        "widen to 0.0.0.0 deliberately)")
    parser.add_argument('--tenant_rate', default=None,
                        help="per-tenant admission quota 'rate[:burst]' "
                        "requests/s keyed by X-Raft-Tenant (default "
                        "RAFT_TENANT_RATE or unlimited)")
    parser.add_argument('--decode_workers', type=int, default=2,
                        help="decode-offload pool width: HTTP mode "
                        "decodes request images here instead of on "
                        "acceptor threads; batch mode prefetches file "
                        "decode ahead of admission (decode is ~33 "
                        "ms/sample and caps the host path)")
    # graftfleet: supervisor readiness handshake (DESIGN.md r20)
    parser.add_argument('--ready_fd', type=int, default=None,
                        help="inherited file descriptor to write the "
                        "RAFT_HTTP_PORT=<n> readiness handshake to "
                        "(then closed) — lets a fleet supervisor await "
                        "readiness via a pipe instead of parsing "
                        "stdout; the same line always goes to stdout "
                        "too (HTTP mode only)")
    add_model_args(parser)
    return parser


def _cli_cache_bytes(args):
    """CLI default for the response cache: ON at 256 MiB (graftrecall,
    DESIGN.md r18).  Precedence: --cache_bytes flag (0 disables) >
    RAFT_CACHE_BYTES env (an explicit 0 there disables too) > 256 MiB.
    The LIBRARY ServiceConfig default stays off — the watchdog stance:
    the production CLI arms it, embedded rigs opt in."""
    import os

    from raft_stereo_tpu.serve.cache import (DEFAULT_CACHE_BYTES,
                                             resolve_cache_bytes)
    if args.cache_bytes is not None:
        return args.cache_bytes
    if os.environ.get("RAFT_CACHE_BYTES", "").strip():
        return resolve_cache_bytes(None)
    return DEFAULT_CACHE_BYTES


def _parse_warmup(spec):
    if not spec:
        return ()
    shapes = []
    for part in spec.split(','):
        h, _, w = part.strip().partition('x')
        shapes.append((int(h), int(w)))
    return tuple(shapes)


def iter_decoded_pairs(pairs, decode_one, workers: int = 2,
                       lookahead=None):
    """Decode offload for the closed-loop batch driver: yield
    ``(left_path, right_path, future)`` in submission order with file
    decode running in a small thread pool up to ``lookahead`` pairs
    ahead of admission.

    Before this, the submit loop paid ~33 ms/sample of PNG decode
    (BASELINE.md) INLINE between submissions — serializing host decode
    ahead of admission exactly like the pre-PR 5 upload path serialized
    transfers. Ordering is preserved (a deque of futures, consumed
    FIFO), so outputs are byte-identical to the sequential decode path
    (test-pinned in tests/test_http.py); the bounded lookahead keeps
    peak memory at ``lookahead`` decoded pairs regardless of glob size.
    A consumer that stops consuming (drain) just cancels what it skips —
    the pool dies with the generator."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    lookahead = max(1, lookahead if lookahead is not None
                    else 2 * max(1, workers))
    pool = ThreadPoolExecutor(max_workers=max(1, workers),
                              thread_name_prefix="stereo-cli-decode")
    queue = deque()
    it = iter(pairs)

    def pump() -> None:
        while len(queue) < lookahead:
            try:
                f1, f2 = next(it)
            except StopIteration:
                return
            queue.append((f1, f2, pool.submit(
                lambda a=f1, b=f2: (decode_one(a), decode_one(b)))))

    try:
        pump()
        while queue:
            f1, f2, fut = queue.popleft()
            yield f1, f2, fut
            pump()
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def serve(args) -> int:
    # Mode validation needs only args — run it before any model load or
    # warmup compile so a missing-glob invocation fails in milliseconds,
    # not after minutes of checkpoint read + jit (argparse can't express
    # "required unless --http_port", so it lives here).
    if args.http_port is None and (not args.left_imgs
                                   or not args.right_imgs):
        raise SystemExit("batch mode needs -l/--left_imgs and "
                         "-r/--right_imgs (or serve the network with "
                         "--http_port)")

    # Pack opt-ins must land before ANY program trace (the switches are
    # read at trace time); explicit env always wins over the flag.
    if args.pack8 or args.lane_pack8:
        import os
        if args.pack8:
            os.environ.setdefault("RAFT_CORR_PACK8", "1")
        if args.lane_pack8:
            os.environ.setdefault("RAFT_LANE_PACK8", "1")

    import jax
    import numpy as np

    from raft_stereo_tpu.config import (RAFTStereoConfig,
                                        with_eval_precision)
    from raft_stereo_tpu.data.frame_utils import read_image_rgb
    from raft_stereo_tpu.engine.checkpoint import load_params
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.serve import (AdmissionConfig, InferenceSession,
                                       ServiceConfig, SessionConfig,
                                       StereoService)

    cfg = RAFTStereoConfig.from_namespace(args)
    if args.restore_ckpt is not None:
        template = (None if args.restore_ckpt.endswith(".pth")
                    else init_raft_stereo(jax.random.PRNGKey(0), cfg))
        params = load_params(args.restore_ckpt, cfg, template)
    else:
        logging.warning("no --restore_ckpt: serving RANDOM weights "
                        "(wiring smoke only)")
        params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    cfg = with_eval_precision(cfg)  # the one shared inference bf16 policy

    session = InferenceSession(
        params, cfg,
        SessionConfig(
            valid_iters=args.valid_iters,
            segments=args.segments,
            bucket=args.bucket,
            warmup_shapes=_parse_warmup(args.warmup),
            warmup_segmented=args.deadline_ms is not None,
            canary=not args.no_canary,
            allow_half_res=not args.no_half_res,
            max_batch=args.max_batch,
            mesh_data=args.mesh_data,
            heal=False if args.no_heal else None,
            admission=AdmissionConfig(max_pixels=args.max_pixels)))
    service = StereoService(session, ServiceConfig(
        max_queue=args.max_queue, workers=args.workers,
        tick_ms=args.tick_ms, slo_ms=args.slo_ms,
        watchdog_ms=args.watchdog_ms, retry_budget=args.retry_budget,
        drain_grace_ms=args.drain_grace_ms,
        stream_sessions=args.stream_sessions,
        stream_ttl_ms=args.stream_ttl_ms,
        converge_tol=args.converge_tol,
        cache_bytes=_cli_cache_bytes(args),
        cache_near_tol=args.cache_near_tol))

    # Graceful drain on SIGTERM/SIGINT (ROADMAP open item 4): the handler
    # only sets a flag (async-signal-safe); the submit loop below flips
    # the service into draining at the next response boundary — admitted
    # requests run to their segment-boundary exits with honest labels,
    # late submits are rejected ``service_draining``, telemetry flushes,
    # and a clean preemption exits 0. A SECOND signal restores the
    # default disposition and redelivers itself — the operator's
    # escalation path when the graceful drain is wedged.
    import os
    import signal
    stop_requested = threading.Event()

    def _request_drain(signum, frame):  # noqa: ARG001 — signal signature
        if stop_requested.is_set():
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        stop_requested.set()

    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev_handlers[sig] = signal.signal(sig, _request_drain)
        except ValueError:  # non-main thread (embedded use): skip
            pass

    from raft_stereo_tpu.serve.supervise import resolve_drain_grace_ms
    grace_s = resolve_drain_grace_ms(args.drain_grace_ms) / 1e3

    def write_artifacts() -> None:
        status = service.status()
        print(json.dumps(status, indent=2, default=str))
        if args.status_json:
            Path(args.status_json).write_text(
                json.dumps(status, indent=2, default=str))
        if args.metrics_prom:
            Path(args.metrics_prom).write_text(service.metrics_text())
        if args.ledger_out:
            from raft_stereo_tpu.obs.ledger import save_doc
            save_doc(session.ledger_doc(), args.ledger_out)

    # -- network ingress mode (graftwire, DESIGN.md r14) -------------------
    if args.http_port is not None:
        from raft_stereo_tpu.serve import HttpConfig, HttpFrontend
        service.start()
        frontend = HttpFrontend(service, HttpConfig(
            host=args.http_host, port=args.http_port,
            tenant_rate=args.tenant_rate,
            decode_workers=args.decode_workers)).start()
        print(json.dumps({
            "event": "listening",
            "endpoint": f"http://{frontend.host}:{frontend.port}",
            "routes": ["POST /v1/stereo", "GET /healthz", "GET /metrics"],
        }), flush=True)
        # graftfleet readiness handshake: ONE machine-parseable line on
        # stdout, printed only here — after warmup compiles, after the
        # listener is accepting — so a supervisor that reads it can
        # route traffic immediately.  --ready_fd gets the same line on
        # an inherited pipe (write+close; EOF doubles as a liveness
        # signal), sparing the supervisor a stdout parse.  flush=True
        # everywhere: a block-buffered pipe would hold the handshake
        # hostage until the 4 KiB stdio buffer fills.
        handshake = f"RAFT_HTTP_PORT={frontend.port}\n"
        print(handshake, end="", flush=True)
        if args.ready_fd is not None:
            try:
                os.write(args.ready_fd, handshake.encode())
                os.close(args.ready_fd)
            except OSError:
                # A supervisor that died between fork and handshake is
                # its problem; the instance serves regardless.
                pass
        try:
            while not stop_requested.wait(0.2):
                # graftheal: the production recovery drive point — the
                # wait loop, NOT the Supervisor's monitor thread
                # (detection and recovery stay on separate triggers; the
                # chaos battery pins the detector's one-way monotonicity
                # mid-storm).  A sweep with nothing in probation is two
                # lock peeks; probes/canaries only run once a probation
                # deadline elapses.  Failure-isolated: a dying sweep
                # must never take the serve loop down with it.
                try:
                    service.heal_sweep()
                except Exception:
                    logging.exception("heal sweep failed")
            # SIGTERM rides the PR 9 drain: the very same state machine
            # in-process callers get — late wire requests are answered
            # 503 service_draining by the still-listening frontend,
            # admitted rows run to their segment-boundary exits within
            # the grace window, THEN the listener stops accepting.
            print(json.dumps({"event": "draining",
                              "reason": "signal received"}), flush=True)
            clean = service.drain(grace_s)
            print(json.dumps({"event": "drained", "clean": clean}),
                  flush=True)
        finally:
            frontend.stop()
            for sig, handler in prev_handlers.items():
                signal.signal(sig, handler)
        write_artifacts()
        return 0

    # -- glob batch-driver mode (globs validated before model load) --------
    left_images = sorted(glob.glob(args.left_imgs, recursive=True))
    right_images = sorted(glob.glob(args.right_imgs, recursive=True))
    if len(left_images) != len(right_images):
        raise SystemExit(
            f"left glob matched {len(left_images)} files but right glob "
            f"matched {len(right_images)} — zip would silently drop the "
            "difference; fix the globs")
    print(f"Found {len(left_images)} pairs.")
    out_dir = Path(args.output_directory) if args.output_directory else None
    if out_dir is not None:
        out_dir.mkdir(parents=True, exist_ok=True)

    import time
    from concurrent.futures import TimeoutError as FuturesTimeout

    failures = 0
    seq = 0
    draining = False
    # Bounds the consume loop once a drain begins: grace expiry force-
    # stops the service (resolving everything resolvable), and a Future
    # that survives even that (a wedged device call with supervision
    # off) is abandoned honestly rather than hanging the exit path.
    drain_track = {"deadline": None, "stopped": False}

    def begin_drain_once() -> None:
        nonlocal draining
        if not draining:
            draining = True
            print(json.dumps({"event": "draining",
                              "reason": "signal received"}))
            service.begin_drain()

    def consume(fut) -> None:
        nonlocal failures, seq
        # Short-poll instead of a blocking result(): the signal handler
        # only sets a flag, so the drain flip must happen here, on the
        # submit loop's thread, within one poll interval of the signal.
        while True:
            try:
                resp = fut.result(timeout=0.2)
                break
            except FuturesTimeout:
                if not stop_requested.is_set():
                    continue
                begin_drain_once()
                now = time.monotonic()
                if drain_track["deadline"] is None:
                    drain_track["deadline"] = now + grace_s
                elif not drain_track["stopped"] and \
                        now >= drain_track["deadline"]:
                    drain_track["stopped"] = True
                    service.stop()  # force-resolve the still-resolvable
                elif drain_track["stopped"] and \
                        now >= drain_track["deadline"] + 5.0:
                    failures += 1
                    print(json.dumps({
                        "status": "error", "code": "abandoned_at_drain",
                        "message": "Future unresolved past the drain "
                                   "hard deadline (wedged device call "
                                   "with supervision off?)"}))
                    return
        line = {k: v for k, v in resp.items() if k != "disparity"}
        print(json.dumps(line, default=str))
        if resp["status"] != "ok":
            # Draining rejections are the *intended* shutdown contract,
            # not serving failures — they must not flip the exit code.
            if resp.get("code") != "service_draining":
                failures += 1
        elif out_dir is not None:
            # Sequence-prefixed: Middlebury-style globs (*/im0.png) share
            # one stem across every scene, which would silently overwrite.
            stem = f"{seq:05d}_{Path(resp['id']).stem}"
            np.save(out_dir / f"{stem}_disp.npy", resp["disparity"])
        seq += 1

    # In-flight cap for this closed-loop driver: the queue bound normally,
    # but only the device concurrency when requests carry deadlines — a
    # deadline is stamped at submit time, so anything parked behind busy
    # capacity would burn its whole budget queued and be rejected
    # deadline_exceeded_in_queue instead of degrading. With --max_batch
    # the device serves up to max_batch rows concurrently, so the cap must
    # be at least that or the driver itself would starve the batch.
    concurrency = args.max_batch if args.max_batch > 1 else args.workers
    inflight_cap = max(
        1, concurrency if args.deadline_ms is not None
        else max(args.max_queue, args.max_batch))

    service.start()
    try:
        # Drain as we submit: this batch driver respects the service's
        # backpressure by capping its own in-flight requests below the
        # queue bound instead of firing the whole glob at a bounded queue
        # (which would correctly reject most of it with queue_full —
        # the right answer for an open-loop network caller, the wrong
        # one for a closed-loop batch job).
        from collections import deque
        pending = deque()

        def decode_one(path):
            return read_image_rgb(path).astype(np.float32)[None]

        # Decode rides a small thread pool AHEAD of admission
        # (iter_decoded_pairs): the submit loop no longer serializes
        # ~33 ms/sample of PNG decode between submissions, and ordering
        # — hence output bytes — is unchanged (FIFO future consumption,
        # pinned in tests/test_http.py).
        pairs = list(zip(left_images, right_images))
        decode_stream = iter_decoded_pairs(
            pairs, decode_one, workers=args.decode_workers)
        drained_from = None
        for i, (f1, f2, decoded) in enumerate(decode_stream):
            if stop_requested.is_set():
                # Stop the decode pump FIRST (closing the generator
                # cancels every queued decode — the pump refills the
                # pool per yield, so cancelling just this future would
                # keep burning ~33 ms/sample on doomed files), then
                # stub-submit the remainder through the drain below.
                begin_drain_once()
                decode_stream.close()
                drained_from = i
                break
            while len(pending) >= inflight_cap:
                consume(pending.popleft())
            try:
                left, right = decoded.result()
            except Exception as e:  # noqa: BLE001 — hostile-file boundary
                # One unreadable/oversized file (e.g. ImageTooLarge from
                # the decode-bomb cap) is one structured failure line,
                # never an aborted run with the rest of the glob
                # unserved.
                failures += 1
                code = getattr(e, "code", "decode_failed")
                print(json.dumps({
                    "id": f1, "status": "rejected", "code": code,
                    "message": f"{type(e).__name__}: {e}"}))
                continue
            request = {"id": f1, "left": left, "right": right}
            if args.deadline_ms is not None:
                request["deadline_ms"] = args.deadline_ms
            pending.append(service.submit(request))
        if drained_from is not None:
            # Submit through the drain WITHOUT waiting for decode: the
            # drain flip above precedes the submits, so rejection is
            # guaranteed — the printed service_draining line still names
            # each file that was NOT served (the wire-level proof), at
            # stub cost instead of a full image decode per doomed
            # request.
            stub = np.zeros((1, 32, 32, 3), dtype=np.float32)
            for f1, _f2 in pairs[drained_from:]:
                pending.append(service.submit(
                    {"id": f1, "left": stub, "right": stub}))
        while pending:
            consume(pending.popleft())
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)
        if stop_requested.is_set():
            # A drain whose hard deadline already force-stopped work is
            # NOT clean, even though drain() on the now-stopped service
            # quiesces instantly — an orchestrator must not read a
            # timed-out drain as graceful.
            clean = service.drain() and not drain_track["stopped"]
            print(json.dumps({"event": "drained", "clean": clean}))
        else:
            service.stop()

    write_artifacts()
    if failures:
        # Real failures flip the exit code even when a drain signal
        # arrived — an orchestrator must not read a preempted run with
        # genuinely failed requests as clean. Draining rejections are
        # the intended shutdown contract and never count.
        print(f"{failures}/{len(left_images)} requests failed")
        return 1
    # Flight records flushed per-response, final metrics/status written
    # above — a clean run (drained-on-signal included) is exit 0.
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    return serve(args)


if __name__ == '__main__':
    raise SystemExit(main())

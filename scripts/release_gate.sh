#!/usr/bin/env bash
# Mechanical release gate (VERDICT r5 item 5) — ONE command folding:
#   1. the tier-1 suite (scripts/run_tier1.sh — includes the kernel
#      oracle parity batteries in interpret mode),
#   2. a bench wiring smoke on CPU (no pin writes),
#   3. on a TPU host: the pinned-checksum bench gate for all three
#      recorded configs (headline reg_tpu, alt_tpu, realtime) and the
#      compiled-on-chip kernel battery.
# Nonzero exit on any failure; run it in the final hour BEFORE committing
# (the failure mode this prevents is exactly how r4 broke).
set -uo pipefail
cd "$(dirname "$0")/.."
fail=0
step() { echo; echo "== $* =="; }

# Consolidated perf-trajectory artifact (DESIGN.md r11): every bench step
# below emits its headline metric (fps/chip, requests/s, steps/s) into
# ONE TRAJECTORY.json via RAFT_TRAJECTORY; the trajectory gate at the end
# checks all of them against the pinned bands in trajectory_bands.json.
# Gitignored and echoed on failure, like analysis_report.json.
export RAFT_TRAJECTORY="$PWD/TRAJECTORY.json"
rm -f "$RAFT_TRAJECTORY"

# Device-ledger artifact (DESIGN.md r12): the serve bench dumps its
# session's program ledger here; the report step below enforces that
# every cached program has a ledger row. Gitignored, echoed on failure.
export RAFT_LEDGER="$PWD/LEDGER.json"
rm -f "$RAFT_LEDGER"

# graftlint first: it is the cheapest step (milliseconds, no jax) and a
# finding here — an unregistered knob, an import-time kill-switch read, a
# half-locked attribute — invalidates everything the later steps would
# measure (DESIGN.md "Static analysis (r8)").
step "graftlint (zero unsuppressed findings)"
bash scripts/lint.sh || { echo "FAIL: graftlint"; fail=1; }

# graftlock (DESIGN.md "Concurrency contracts (r23)"): the concurrency
# suite — whole-repo lock-order graph vs the committed LOCK_ORDER.md
# manifest (drift is a finding), Future lifecycle on the serving paths,
# blocking calls and IO sinks under held locks, _locked discipline,
# thread join/stop coverage. Still AST-only: milliseconds, no jax.
step "graftlock (lock-order manifest + concurrency contracts)"
bash scripts/lint.sh --concurrency || { echo "FAIL: graftlock"; fail=1; }

# graftverify second (DESIGN.md "Trace-level analysis (r10)"): traces the
# real entry points at headline geometry on CPU (~40 s, no TPU touched)
# and proves the jaxpr/HLO-level invariants the AST stage can only grep
# for — rung non-vacuity, knob/cache-key agreement, dtype discipline,
# donation. The merged graftlint+graftverify JSON report is the release
# artifact; on failure it is echoed so the findings are in the log.
step "graftverify (trace-level invariants, merged JSON artifact)"
if env JAX_PLATFORMS=cpu python -m raft_stereo_tpu.analysis --trace --json \
        > analysis_report.json; then
    echo "ok: analysis_report.json written"
else
    cat analysis_report.json
    echo "FAIL: graftverify"; fail=1
fi

step "tier-1 suite"
bash scripts/run_tier1.sh || { echo "FAIL: tier-1"; fail=1; }

# Serving fault storm (ISSUE 3 acceptance): the FULL `serve` battery,
# slow-marked members included — N requests with injected compile
# failures, deadline overruns and malformed inputs interleaved; asserts
# zero crashes, honest quality labels, the breaker ladder ending at plain
# XLA with all trips recorded, and ZERO breaker trips on the clean path
# (tests/test_serve.py::test_clean_path_zero_trips).
step "serving fault storm (injected compile failures / deadline overruns / bad inputs)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py \
    tests/test_batch_serve.py tests/test_supervise.py -q -m serve \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: serving fault storm"; fail=1; }

# Chaos soak (ISSUE 9 acceptance, DESIGN.md r13): 200 seeded requests
# through the real batched StereoService under a composite fault storm
# (device hangs, a tick-loop crash, an uploader crash, a compile failure,
# poisoned outputs, slow forwards on FakeClock) with the watchdog pumped.
# Asserts IN-PROCESS: 100% structured resolution inside a 60 s real-time
# bound, zero abandoned Futures/deadlocks, monotone breaker trips,
# counters reconciling with outcomes, and a flight record behind every
# generation bounce. CPU always (fixed seed), one JSON line into the
# trajectory artifact; the soak artifact is echoed on failure.
step "chaos soak (seeded fault storm vs supervision invariants)"
if env JAX_PLATFORMS=cpu python scratch/chaos_serve.py > chaos_soak.json; then
    cat chaos_soak.json
else
    echo "--- chaos_soak.json ---"; cat chaos_soak.json
    echo "FAIL: chaos soak"; fail=1
fi

# Runtime lock witness (ISSUE 19 acceptance, DESIGN.md r23): re-run the
# chaos soak with every threading.Lock/RLock wrapped by the graftlock
# witness — each OBSERVED nested acquisition must appear as an edge in
# the static lock-order graph (observed ⊆ static, so the manifest and
# the GC201 cycle check provably cover what the code actually does).
# The soak's own invariants are asserted too; one JSON verdict line.
step "lock witness (observed acquisition orders vs LOCK_ORDER.md)"
if env JAX_PLATFORMS=cpu python scratch/check_witness.py > witness.json; then
    cat witness.json
else
    echo "--- witness.json ---"; cat witness.json
    echo "FAIL: lock witness"; fail=1
fi

# Wire chaos storm (ISSUE 10 acceptance, DESIGN.md r14): the same seeded
# determinism, but the faults are HOSTILE CLIENTS over real loopback
# sockets against the unmodified graftwire ingress — truncated/stalled
# bodies, decompression bombs, header floods, mid-request disconnects.
# Asserts: every request gets exactly ONE structured HTTP response, zero
# acceptor-thread deaths, zero stranded sockets/Futures, per-tenant quota
# rejections exact, counters reconciling with wire outcomes, and a
# mid-storm SIGTERM draining clean (late requests 503 service_draining,
# the pinned admitted row finishes, exit 0). CPU always, fixed seed.
step "wire chaos storm (hostile clients over loopback vs ingress invariants)"
if env JAX_PLATFORMS=cpu python scratch/chaos_serve.py --wire > wire_chaos.json; then
    cat wire_chaos.json
else
    echo "--- wire_chaos.json ---"; cat wire_chaos.json
    echo "FAIL: wire chaos storm"; fail=1
fi

# graftwire ingress battery (ISSUE 10 satellite): codec units, the
# malformed-request battery over real sockets, loopback parity, quota
# exactness, the decompression-bomb regression. Tier-1 runs it too; the
# gate repeats it so an ingress regression names itself here even when
# someone runs the gate alone.
step "graftwire ingress battery (hostile-input + loopback parity)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_http.py -q -m http \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: graftwire ingress battery"; fail=1; }

# Operator plane (ISSUE 12, DESIGN.md r15): the /debug/* endpoints on
# the LIVE CLI service — tick flight-deck, per-tenant usage rollup,
# all-thread stack dump, resolved-config snapshot — each validated for
# JSON schema AND boundedness (byte caps asserted), plus the /healthz
# capacity block, ending in a clean SIGTERM drain.
step "operator plane (/debug endpoints on the live CLI service)"
if env JAX_PLATFORMS=cpu python scratch/check_debug_endpoints.py \
        > debug_endpoints.json; then
    cat debug_endpoints.json
else
    echo "--- debug_endpoints.json ---"; cat debug_endpoints.json
    echo "FAIL: operator-plane debug endpoints"; fail=1
fi

# Observability battery (ISSUE 7 + 8 acceptance): FakeClock span
# timelines that reconcile with reported latency, the /metrics golden,
# the trajectory-gate failure mode, the flat-memory reservoir pin, the
# device-ledger fallback paths and the flight-recorder smoke (injected
# SLO breach -> exactly one bounded record).
step "observability battery (graftscope: spans, /metrics, ledger, flight, trajectory)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q -m obs \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: observability battery"; fail=1; }

# graftdeck battery (ISSUE 12): the tick flight-deck's three-way
# FakeClock reconciliation (deck == trace == counters, both serving
# modes), per-tenant usage exactness + hostile-label hygiene, the
# capacity model, and the debug introspection surfaces.
step "operator-plane battery (graftdeck: deck, usage, capacity, stacks)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_deck.py -q -m deck \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: operator-plane battery"; fail=1; }

# graftstream battery (ISSUE 13, DESIGN.md r17): prepare_warm parity
# pins (zero-flow bitwise vs cold prepare; warm chain vs the reference
# flow_init forward), honest converged:k labels with deck/usage/counter
# joins, session-table bounds under a 200-session storm, TTL
# expiry-mid-flight, and bounce re-admission with the held flow_init.
step "streaming battery (graftstream: warm starts, convergence, session table)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_stream.py -q -m stream \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: streaming battery"; fail=1; }

# graftrecall battery (ISSUE 14, DESIGN.md r18): exact-hit bitwise
# parity + the zero-device-seconds three-way reconciliation,
# fingerprint-change invalidation, tenant isolation/sub-caps, TTL +
# byte-cap accounting, near-tier warm:cache labels, the 200-tenant
# churn-storm bound (bytes + /metrics provably flat), drain drop
# semantics and the RAFT_CACHE_DIR disk spill.  The chaos soak above
# runs the cache scenarios against the live service, the serve bench
# below runs the repeat-traffic third, and check_debug_endpoints.py
# asserts hit counters through the live CLI wire.
step "response-cache battery (graftrecall: exact/near tiers, tenancy, bounds)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_cache.py -q -m cache \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: response-cache battery"; fail=1; }

# graftresident battery (ISSUE 15, DESIGN.md r19): the resident-iteration
# bitwise pins (mega-kernel vs the serial fused composition), the int8
# packed-correlation error-budget pins, and the B>1 streamed-kernel
# parity battery (batch 4/8 bitwise vs the per-sample serial loop, odd
# shapes, any_batch grads) — interpret mode on CPU, compiled twins via
# RAFT_TEST_ONCHIP=1 in the on-chip battery below.
step "resident-iteration battery (graftresident: resident/pack8/stream-batch pins)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_fused_stream.py \
    tests/test_corr.py tests/test_batch_serve.py -q \
    -k "resident or pack8 or stream_batch" \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: resident-iteration battery"; fail=1; }

# r19/r24 engagement asserts (the PR 2 "provably engages" ceremony,
# extended): trace the REAL serving programs to a jaxpr at headline
# (2016x2976 b=1) AND the serve-batch bucket (b=4/8) and assert each new
# kernel is present by name — plus its kill switch provably disengaging
# it — and the int8 correlation AND context-lane DMA ratios <= 0.6x bf16
# at BOTH geometries (exact BlockSpec arithmetic; CPU-safe, nothing
# executes).
step "r19/r24 engagement asserts (resident/pack8/lane8 at both geometries)"
if env JAX_PLATFORMS=cpu python scratch/check_engagement.py > engagement.json; then
    cat engagement.json
else
    echo "--- engagement.json ---"; cat engagement.json
    echo "FAIL: r19/r24 engagement asserts"; fail=1
fi

# graftlane battery (ISSUE 20, DESIGN.md r24): the packed-context pins —
# container error budget (<= scale/2), batched-rows scale independence,
# the lane-math geometry battery, STE gradient bitwiseness, armed
# forward == prepare+segments bitwise, the encoder-exit q8 epilogue's
# bitwise-to-host-pack contract, and RAFT_LANE_PACK8=0 byte-identity.
step "narrow-lane battery (graftlane: packed context containers)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_lane_pack8.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: narrow-lane battery"; fail=1; }

# graftlane serve battery (ISSUE 20 satellite): the FULL serve battery
# once more from the DOUBLE-ARMED base (both pack opt-ins on) — the
# breaker ladder, canary and fault storm must hold in the operational
# state the r24 rung exists to degrade from, not only at defaults.
step "serving fault storm from the double-armed base (corr+lane pack8)"
env JAX_PLATFORMS=cpu RAFT_CORR_PACK8=1 RAFT_LANE_PACK8=1 \
    python -m pytest tests/test_serve.py -q -m serve \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: double-armed serving fault storm"; fail=1; }

# graftfleet battery (ISSUE 16, DESIGN.md r20): the fleet supervisor
# lifecycle against stub instances (tests/fleet_stub.py speaks the
# handshake + /healthz schema in milliseconds) — launch/probe/route
# discipline, headroom weights + saturation backpressure, session
# handoff, kill -9 failover under the restart budget, warmup-death
# retries, budget-exhaustion degradation, SIGKILL drain escalation,
# rolling deploys (and the abort-keeps-old path), the /fleet rollup
# rules, and the RAFT_FLEET_* knob contract.
step "fleet battery (graftfleet: supervisor, routing, deploys, budgets)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m fleet \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: fleet battery"; fail=1; }

# Fleet chaos storm (ISSUE 16 acceptance): 2 REAL serve_stereo.py
# subprocess instances (tiny model) behind the fleet router under mixed
# cold/stream/dup traffic, with a kill -9 of the stream-pinned instance
# AND a fingerprint-changing rolling deploy mid-storm. Asserts: 100%
# structured responses, zero dropped stream sessions (warm joins resume
# on the surviving/new instance), generation + fingerprint advanced,
# and the router's per-instance books reconciling EXACTLY with each
# instance's own raft_requests_total. One JSON verdict line.
step "fleet chaos storm (kill -9 + rolling deploy over real instances)"
if env JAX_PLATFORMS=cpu python scratch/chaos_fleet.py > chaos_fleet.json; then
    cat chaos_fleet.json
else
    echo "--- chaos_fleet.json ---"; cat chaos_fleet.json
    echo "FAIL: fleet chaos storm"; fail=1
fi

# graftpod mesh-serve battery (ISSUE 17, DESIGN.md r21): mesh-sharded
# batched responses vs single-device at the same bucket, the
# local_batch_rows edge battery, chip-affinity + migrate-on-bounce with
# the host-side warm seed, integer-ns reconciliation when one invoke
# spans N chips, quarantine shrink, per-chip capacity. Runs under the
# 8-fake-device CPU topology (tests/conftest.py arms it).
step "mesh-serve battery (graftpod: sharded parity, affinity, per-chip books)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_mesh_serve.py -q -m mesh \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: mesh-serve battery"; fail=1; }

# Mesh chaos storm (ISSUE 17 acceptance): a 2-chip data mesh on 8 fake
# CPU devices takes an injected device hang whose post-bounce probe
# parks on exactly ONE chip — asserts the bounce quarantines that chip
# alone, the mesh shrinks to the divisor width, the chip-pinned stream
# sessions migrate WARM (held seed is host-side), the surviving chips
# keep serving, and the device-seconds books still reconcile exactly.
step "mesh chaos storm (one-chip hang vs chip-local quarantine)"
if env JAX_PLATFORMS=cpu python scratch/chaos_serve.py --mesh > mesh_chaos.json; then
    cat mesh_chaos.json
else
    echo "--- mesh_chaos.json ---"; cat mesh_chaos.json
    echo "FAIL: mesh chaos storm"; fail=1
fi

# graftheal battery (ISSUE 18, DESIGN.md r22): the recovery plane's
# half-open probation state machines on FakeClock — breaker rungs
# re-engage in strict reverse trip order behind a passing parity canary
# (a failed canary re-trips with doubled backoff and never touches
# serving state), a quarantined chip re-grows the mesh with bitwise
# response parity and zero mid-request compiles, the flap cap is exact,
# fleet restart budgets refill on the decay clock, and RAFT_HEAL=0
# provably restores the one-way semantics for all three ladders.
step "recovery-plane battery (graftheal: probation, canary gate, flap cap)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_heal.py -q -m heal \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || { echo "FAIL: recovery-plane battery"; fail=1; }

# Recovery storm (ISSUE 18 acceptance): the fault-CLEARS chaos storm —
# a transient chip hang quarantines one chip of a 2-chip mesh, the
# too-early sweep provably no-ops and the detection path never heals,
# a failed probe doubles the backoff, then the fault window clears and
# the explicit heal_sweep() re-grows the mesh (re-warmed before any row
# routes, stream sessions re-placed), headroom recovers to within 10%
# of the pre-fault reading, a poisoned rung fails CLOSED from half-open
# before re-engaging, the flap cap retires a flapping chip permanently,
# and the books still reconcile. MTTR lands in the JSON verdict and the
# trajectory artifact.
step "recovery storm (fault-clears chaos: quarantine -> probation -> re-grow)"
if env JAX_PLATFORMS=cpu python scratch/chaos_serve.py --heal > heal_chaos.json; then
    cat heal_chaos.json
else
    echo "--- heal_chaos.json ---"; cat heal_chaos.json
    echo "FAIL: recovery storm"; fail=1
fi

# Mesh scaling bench smoke (ISSUE 17 acceptance wiring): sweep
# n_data in {1,2,4,8} over fake CPU devices and emit rps_per_chip +
# mesh_scaling_efficiency into the trajectory. On this single-core CPU
# the efficiency NUMBER is meaningless (all 8 "chips" share one core),
# so the smoke asserts the fields emit; the >=0.75x-linear bar lands
# with the on-chip run like every other perf acceptance.
step "mesh scaling bench smoke (n_data sweep over fake devices)"
env JAX_PLATFORMS=cpu RAFT_SERVE_BENCH_TINY=1 \
    python scratch/bench_serve.py --mesh \
    || { echo "FAIL: mesh bench smoke"; fail=1; }

backend=$(python - <<'EOF'
import jax
print(jax.default_backend())
EOF
)
echo "backend: $backend"

# Streaming convergence bench (ISSUE 13 acceptance): cold-vs-warm
# iterations-to-convergence on a synthetic panning sequence through the
# real StreamRunner — warm frames must average <= half the cold
# iterations at the same tolerance.  Iteration counts are
# backend-independent, so this bar gates on CPU; the fps numbers become
# meaningful (and land in the trajectory) on the on-chip run.
step "streaming bench (warm-start >=2x iterations-to-convergence)"
if [ "$backend" != "tpu" ]; then
    stream_bench_cmd="env JAX_PLATFORMS=cpu python scratch/bench_stream.py"
else
    stream_bench_cmd="python scratch/bench_stream.py"
fi
if $stream_bench_cmd > bench_stream.json; then
    cat bench_stream.json
else
    echo "--- bench_stream.json ---"; cat bench_stream.json
    echo "FAIL: streaming bench"; fail=1
fi

# Serve-throughput bench (ISSUE 5 acceptance): requests/s through the real
# StereoService, sequential vs continuous batching, one JSON line. On CPU
# this is a wiring smoke (tiny model; CPU conv throughput is ~linear in
# batch, so no speedup is expected); the >=2x-at-batch>=4 bar applies to
# the on-chip run.
# RAFT_SERVE_BENCH_LOOPBACK=1 adds the graftwire loopback-network mode
# (ROADMAP item 4): the same workload over real sockets, requests/s vs
# in-process in the same JSON line — the CPU run doubles as the wire
# smoke, the TPU run pins the wire overhead on real hardware.
step "serve throughput bench (continuous batching vs sequential + loopback)"
if [ "$backend" != "tpu" ]; then
    env JAX_PLATFORMS=cpu RAFT_SERVE_BENCH_TINY=1 \
        RAFT_SERVE_BENCH_LOOPBACK=1 \
        python scratch/bench_serve.py \
        || { echo "FAIL: serve bench smoke"; fail=1; }
else
    env RAFT_SERVE_BENCH_LOOPBACK=1 python scratch/bench_serve.py \
        || { echo "FAIL: serve throughput bench"; fail=1; }
fi

# Device-ledger report (ISSUE 8 acceptance): the serve bench above dumped
# its session's ledger; every program still cached after the battery must
# have a row (exit 1 otherwise), and the per-program flops/HBM table +
# MFU attribution are echoed into the gate log either way.
step "device ledger report (every cached program has a ledger row)"
if [ -f "$RAFT_LEDGER" ]; then
    python -m raft_stereo_tpu.obs.ledger report "$RAFT_LEDGER" \
        || { echo "--- LEDGER.json ---"; cat "$RAFT_LEDGER";
             echo "FAIL: device ledger report"; fail=1; }
else
    echo "FAIL: serve bench wrote no $RAFT_LEDGER"; fail=1
fi

# Train-throughput bench: steps/s into the trajectory. On CPU a tiny
# wiring smoke (TRAIN_BENCH_TINY: 32-dim model, ~40 s); on chip the full
# reference-config run including the overfit assertion.
step "train throughput bench (steps/s into the trajectory)"
if [ "$backend" != "tpu" ]; then
    env JAX_PLATFORMS=cpu TRAIN_BENCH_TINY=1 python scratch/bench_train.py \
        || { echo "FAIL: train bench smoke"; fail=1; }
else
    python scratch/bench_train.py \
        || { echo "FAIL: train throughput bench"; fail=1; }
fi

if [ "$backend" != "tpu" ]; then
    step "bench wiring smoke (CPU, tiny shape, no pin writes)"
    RAFT_BENCH_AUTOPIN=0 RAFT_BENCH_H=64 RAFT_BENCH_W=96 \
        RAFT_BENCH_ITERS=2 RAFT_BENCH_FRAMES=2 JAX_PLATFORMS=cpu \
        python bench.py || { echo "FAIL: bench smoke"; fail=1; }
    echo "SKIP: pinned-config bench gate + on-chip battery (no TPU here)"
else
    # RAFT_BENCH_AUTOPIN=1 here is the ONLY sanctioned first-pin path: a
    # missing config/statistic gets recorded (loudly, never overwriting an
    # existing value) as an explicit gate step — a bare `python bench.py`
    # never mutates bench_checksum_ref.json. Check `git diff
    # bench_checksum_ref.json` after a run that printed "PINNED".
    step "bench checksum gate: headline (reg_tpu bf16)"
    RAFT_BENCH_AUTOPIN=1 python bench.py \
        || { echo "FAIL: headline bench gate"; fail=1; }

    step "bench checksum gate: alt_tpu"
    RAFT_BENCH_AUTOPIN=1 RAFT_BENCH_CORR=alt_tpu python bench.py \
        || { echo "FAIL: alt_tpu bench gate"; fail=1; }

    step "bench checksum gate: realtime config"
    RAFT_BENCH_AUTOPIN=1 \
        RAFT_BENCH_SHARED=1 RAFT_BENCH_DOWNSAMPLE=3 RAFT_BENCH_GRU_LAYERS=2 \
        RAFT_BENCH_SLOW_FAST=1 RAFT_BENCH_ITERS=7 \
        RAFT_BENCH_H=384 RAFT_BENCH_W=1248 python bench.py \
        || { echo "FAIL: realtime bench gate"; fail=1; }

    step "compiled-on-chip kernel battery"
    bash scripts/run_onchip_battery.sh \
        || { echo "FAIL: on-chip battery"; fail=1; }
fi

# Perf-trajectory gate: every emitted metric with a pinned band in
# trajectory_bands.json must sit above its floor. --autopin (TPU only —
# CPU numbers are machine-local, namespaced, never pinned) records a band
# for a first-seen metric, loudly, mirroring RAFT_BENCH_AUTOPIN; check
# `git diff trajectory_bands.json` after a run that printed "PINNED".
step "perf trajectory gate (fps/chip + requests/s + steps/s vs pinned bands)"
traj_flags=""
if [ "$backend" = "tpu" ]; then traj_flags="--autopin"; fi
if python -m raft_stereo_tpu.obs.trajectory check "$RAFT_TRAJECTORY" \
        --bands trajectory_bands.json $traj_flags; then
    echo "ok: trajectory in band ($RAFT_TRAJECTORY)"
else
    echo "--- TRAJECTORY.json ---"
    cat "$RAFT_TRAJECTORY" 2>/dev/null
    echo "FAIL: perf trajectory gate"; fail=1
fi

echo
if [ "$fail" -ne 0 ]; then
    echo "RELEASE GATE: FAIL"
else
    echo "RELEASE GATE: PASS ($backend)"
fi
exit $fail

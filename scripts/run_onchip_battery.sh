#!/usr/bin/env bash
# Compiled-on-TPU certification of the Pallas kernel oracle batteries
# (VERDICT r5 item 2): the same parity tests the CPU tier runs in
# interpret mode, executed through the real Mosaic/XLA:TPU stack — the
# regression class interpret mode cannot see (r4's packed-stem bug).
#
# Usage: scripts/run_onchip_battery.sh [logfile]
# Run on a host with a reachable TPU backend; commits its log under
# scratch/ (e.g. scratch/onchip_pytest_r6.log) for the round record.
set -o pipefail
cd "$(dirname "$0")/.."
log="${1:-scratch/onchip_pytest_$(date +%Y%m%d).log}"

RAFT_TEST_ONCHIP=1 python -m pytest -m 'kernel_battery and not slow' -q \
    -p no:cacheprovider tests/test_corr.py tests/test_fused_stream.py \
    2>&1 | tee "$log"
rc=${PIPESTATUS[0]}

python - <<'EOF'
import jax
backend = jax.default_backend()
print(f"battery backend: {backend}"
      + ("" if backend == "tpu"
         else "  (WARNING: not a TPU — kernels ran in interpret mode; "
              "this log does NOT certify the compiled path)"))
EOF
exit $rc

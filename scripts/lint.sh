#!/usr/bin/env bash
# graftlint gate — zero unsuppressed findings over the package tree
# (DESIGN.md "Static analysis (r8)"). Wired as a step in
# scripts/release_gate.sh; run locally after any change to models/ops/
# corr/serve:
#
#   bash scripts/lint.sh                 # full tree
#   bash scripts/lint.sh --changed-only  # git-changed files only
#   bash scripts/lint.sh <paths...>      # explicit targets (tests use this
#                                        # to prove an injected violation
#                                        # fails the gate)
#
# Exits with the linter's status: 0 clean, 1 findings, 2 internal error.
# No jax import — this is milliseconds, not minutes.
set -uo pipefail
cd "$(dirname "$0")/.."
exec python -m raft_stereo_tpu.analysis "$@"

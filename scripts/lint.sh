#!/usr/bin/env bash
# graftlint gate — zero unsuppressed findings over the package tree
# (DESIGN.md "Static analysis (r8)"). Wired as a step in
# scripts/release_gate.sh; run locally after any change to models/ops/
# corr/serve:
#
#   bash scripts/lint.sh                 # full tree (AST only, milliseconds)
#   bash scripts/lint.sh --changed-only  # git-changed files only
#   bash scripts/lint.sh --trace         # + graftverify (GV101-GV105):
#                                        # trace-level jaxpr/HLO analysis,
#                                        # ~40 s on CPU (DESIGN.md r10)
#   bash scripts/lint.sh --concurrency   # + graftlock (GC201-GC206):
#                                        # lock-order graph vs LOCK_ORDER.md,
#                                        # Future lifecycle, sinks under
#                                        # locks (DESIGN.md r23; still AST
#                                        # only — no jax, milliseconds)
#   bash scripts/lint.sh <paths...>      # explicit targets (tests use this
#                                        # to prove an injected violation
#                                        # fails the gate)
#
# Exits with the analyzer's status: 0 clean, 1 findings, 2 internal error.
# The default AST stage never imports jax; the --trace stage does — it is
# pinned to the CPU backend so the analyzer can never grab (or wait on)
# a TPU.
set -uo pipefail
cd "$(dirname "$0")/.."
for a in "$@"; do
    if [ "$a" = "--trace" ]; then
        export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
        break
    fi
done
exec python -m raft_stereo_tpu.analysis "$@"

#!/usr/bin/env bash
# Canonical tier-1 gate (ROADMAP.md "Tier-1 verify") — the ONE command
# builders and CI run; keep it in sync with ROADMAP.md.
#
# Includes the `serve` marker battery (tests/test_serve.py: sessions,
# circuit breaker, deadline degradation, fault storm) minus its few
# slow-marked members, which scripts/release_gate.sh runs in full.
#
# Prints DOTS_PASSED=<n> (count of passing-test dots) and exits with
# pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 1020 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc

#!/usr/bin/env python
"""Training CLI — flag-for-flag with the reference ``train_stereo.py:214-258``,
plus the TPU corr choices (``reg_tpu``/``alt_tpu``) and ``--dataset_root``.
"""

from __future__ import annotations

import argparse
import logging


def build_parser() -> argparse.ArgumentParser:
    from raft_stereo_tpu.config import add_model_args

    parser = argparse.ArgumentParser()
    parser.add_argument('--name', default='raft-stereo',
                        help="name your experiment")
    parser.add_argument('--restore_ckpt', help="restore checkpoint "
                        "(.pth transplants reference weights; .msgpack "
                        "restores full state incl. optimizer and step; a "
                        "DIRECTORY auto-resumes from its newest valid "
                        "bundle, skipping truncated/corrupt ones)")

    # Training parameters
    parser.add_argument('--batch_size', type=int, default=6,
                        help="batch size used during training.")
    parser.add_argument('--train_datasets', nargs='+', default=['sceneflow'],
                        help="training datasets.")
    parser.add_argument('--lr', type=float, default=0.0002,
                        help="max learning rate.")
    parser.add_argument('--num_steps', type=int, default=100000,
                        help="length of training schedule.")
    parser.add_argument('--image_size', type=int, nargs='+',
                        default=[320, 720],
                        help="size of the random image crops used during training.")
    parser.add_argument('--train_iters', type=int, default=16,
                        help="number of updates to the disparity field in each forward pass.")
    parser.add_argument('--wdecay', type=float, default=.00001,
                        help="Weight decay in optimizer.")

    # Validation parameters
    parser.add_argument('--valid_iters', type=int, default=32,
                        help='number of flow-field updates during validation forward pass')

    # Architecture choices (shared flag set, incl. reg_tpu/alt_tpu)
    add_model_args(parser)

    # Data augmentation
    parser.add_argument('--img_gamma', type=float, nargs='+', default=None,
                        help="gamma range")
    parser.add_argument('--saturation_range', type=float, nargs='+',
                        default=None, help='color saturation')
    parser.add_argument('--do_flip', default=False, choices=['h', 'v'],
                        help='flip the images horizontally or vertically')
    parser.add_argument('--spatial_scale', type=float, nargs='+',
                        default=[0, 0], help='re-scale the images randomly')
    parser.add_argument('--noyjitter', action='store_true',
                        help="don't simulate imperfect rectification")

    # TPU-framework extensions
    parser.add_argument('--dataset_root', default=None,
                        help="root directory holding the datasets/ tree")
    parser.add_argument('--num_workers', type=int, default=None,
                        help="loader worker threads (default: SLURM sizing)")
    parser.add_argument('--seed', type=int, default=1234)
    parser.add_argument('--trace_dir', default=None,
                        help="profile one steady-state train step into this "
                             "directory (jax.profiler trace)")
    parser.add_argument('--spatial_shard', type=int, default=1,
                        help="shard each sample's height over this many "
                             "devices (mesh 'space' axis) in addition to "
                             "batch data parallelism")
    parser.add_argument('--fused_train', action='store_true',
                        help="engage the streaming Pallas scan-body kernels "
                             "in the train step (save-kernel-outputs remat "
                             "policy; measured +16%% steps/s at the "
                             "reference crop config)")

    # Fault tolerance (DESIGN.md "Failure recovery")
    parser.add_argument('--max_bad_steps', type=int, default=5,
                        help="skip non-finite steps (params/opt_state "
                             "untouched) and abort only after this many "
                             "CONSECUTIVE bad steps; 0 = abort on first")
    parser.add_argument('--keep_ckpts', type=int, default=3,
                        help="keep-last-K retention over periodic "
                             "checkpoints (preempt/epoch/final bundles are "
                             "never pruned); 0 keeps all")
    parser.add_argument('--data_retries', type=int, default=2,
                        help="per-sample IO/decode retries before the "
                             "sample is quarantined and deterministically "
                             "substituted")
    parser.add_argument('--data_retry_backoff', type=float, default=0.05,
                        help="base seconds of the loader's exponential "
                             "per-sample retry backoff")
    return parser


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format='%(asctime)s %(levelname)-8s [%(filename)s:%(lineno)d] %(message)s')
    from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
    from raft_stereo_tpu.engine.train import train

    cfg = RAFTStereoConfig.from_namespace(args)
    tcfg = TrainConfig.from_namespace(args)
    train(cfg, tcfg, data_root=args.dataset_root)


if __name__ == '__main__':
    main()

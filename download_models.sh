#!/bin/bash
# Fetch the published RAFT-Stereo checkpoints
# (raftstereo-{middlebury,eth3d,realtime,sceneflow}.pth) from the upstream
# release bundle. Port of /root/reference/download_models.sh. The .pth files
# load through the transplant shim: pass them to --restore_ckpt on any CLI
# (.pth is auto-detected and converted, evaluate_stereo.py:58 / demo.py:50).
set -euo pipefail

mkdir -p models
(
  cd models
  wget -nc https://www.dropbox.com/s/q4312z8g5znhhkp/models.zip
  unzip -n models.zip
  rm -f models.zip
)

"""Tests for loss and optimizer semantics."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.engine import (
    make_optimizer, onecycle_linear_schedule, sequence_loss)


def test_sequence_loss_weights_and_mask(rng):
    n, b, h, w = 3, 2, 8, 10
    preds = jnp.asarray(rng.standard_normal((n, b, h, w, 1)).astype(np.float32))
    gt = jnp.asarray(rng.standard_normal((b, h, w, 1)).astype(np.float32))
    valid = jnp.asarray((rng.uniform(size=(b, h, w)) > 0.3).astype(np.float32))

    loss, metrics = sequence_loss(preds, gt, valid)

    # Numpy oracle of the documented formula.
    gamma_adj = 0.9 ** (15.0 / (n - 1))
    mask = (np.asarray(valid) >= 0.5) & (np.abs(np.asarray(gt[..., 0])) < 700)
    expect = 0.0
    for i in range(n):
        werr = np.abs(np.asarray(preds[i, ..., 0]) - np.asarray(gt[..., 0]))[mask]
        expect += gamma_adj ** (n - i - 1) * werr.mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)

    epe = np.abs(np.asarray(preds[-1, ..., 0]) - np.asarray(gt[..., 0]))[mask]
    np.testing.assert_allclose(float(metrics["epe"]), epe.mean(), rtol=1e-5)
    np.testing.assert_allclose(float(metrics["1px"]), (epe < 1).mean(), rtol=1e-5)


def test_sequence_loss_max_flow_cutoff():
    preds = jnp.zeros((2, 1, 4, 4, 1))
    gt = jnp.full((1, 4, 4, 1), 800.0)  # all beyond max_flow
    valid = jnp.ones((1, 4, 4))
    loss, metrics = sequence_loss(preds, gt, valid)
    assert float(loss) == 0.0


def test_onecycle_schedule_matches_torch():
    import torch
    max_lr, total = 2e-4, 1000
    sched = onecycle_linear_schedule(max_lr, total)
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.AdamW([p], lr=max_lr)
    tsched = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr, total_steps=total, pct_start=0.01,
        cycle_momentum=False, anneal_strategy="linear")
    torch_lrs, ours = [], []
    for step in range(total):
        torch_lrs.append(opt.param_groups[0]["lr"])
        ours.append(float(sched(step)))
        opt.step()
        tsched.step()
    # fp32 schedule vs torch's float64: tiny absolute slack near min_lr.
    np.testing.assert_allclose(ours, torch_lrs, rtol=1e-5, atol=1e-10)


def test_optimizer_decreases_simple_loss(rng):
    tx, _ = make_optimizer(lr=1e-2, num_steps=100)
    params = {"w": jnp.asarray(rng.standard_normal(4).astype(np.float32))}
    opt_state = tx.init(params)

    def loss(p):
        return jnp.sum(p["w"] ** 2)

    import optax
    l0 = float(loss(params))
    for _ in range(50):
        grads = jax.grad(loss)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < l0


def test_sequence_loss_nan_guard(rng):
    """The reference asserts no NaN/Inf in predictions or loss
    (train_stereo.py:48-56); here the invariant is the 'finite' metric the
    train loop raises on (FloatingPointError in engine/train.py)."""
    import jax.numpy as jnp
    preds = jnp.asarray(rng.normal(size=(3, 1, 8, 12, 1)).astype(np.float32))
    gt = jnp.asarray(rng.normal(size=(1, 8, 12, 1)).astype(np.float32))
    valid = jnp.ones((1, 8, 12), jnp.float32)
    _, metrics = sequence_loss(preds, gt, valid)
    assert float(metrics["finite"]) == 1.0
    bad = preds.at[1, 0, 3, 4, 0].set(jnp.nan)
    _, metrics = sequence_loss(bad, gt, valid)
    assert float(metrics["finite"]) == 0.0
    bad = preds.at[0, 0, 0, 0, 0].set(jnp.inf)
    _, metrics = sequence_loss(bad, gt, valid)
    assert float(metrics["finite"]) == 0.0

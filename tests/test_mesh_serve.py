"""graftpod pod-serving battery (ISSUE 17, DESIGN.md r21).

Mesh-sharded batched serving on the 8-fake-device CPU topology
(tests/conftest.py arms ``xla_force_host_platform_device_count=8``):

- knob resolution (named ValueErrors, kill switch, explicit-config wins);
- the n_data=1 path stays byte-identical (cache keys carry NO mesh
  component off-mesh — the fallback contract);
- mesh-sharded batched responses match single-device serving at the
  SAME batch bucket (B=4 and B=8, odd unpadded widths, pad rows,
  warm+cold mixed in one tick) under the cross-batch-size comparison
  discipline of tests/test_batch_serve.py (``assert_rows_match``);
- quarantine shrinks the mesh to the largest divisor of the base extent
  that fits the survivors, bumps the epoch (re-keying programs), and the
  shrunken mesh still serves;
- chip-affinity placement round-robins new stream sessions over the
  data shards and migrate-on-bounce keeps the held warm seed (it is
  HOST-side memory);
- the device-seconds books stay exact integer-ns partitions when one
  invoke spans N chips (one wall interval, never multiplied by the chip
  span), and the per-chip capacity plane reports honestly.
"""

import time

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.obs.capacity import saturation_per_chip
from raft_stereo_tpu.parallel.mesh import local_batch_rows, make_mesh
from raft_stereo_tpu.serve import (BatchScheduler, InferenceSession,
                                   SessionConfig)
from raft_stereo_tpu.serve.session import (resolve_mesh_fallback,
                                           resolve_serve_mesh_data)
from raft_stereo_tpu.serve.stream import StreamManager
from raft_stereo_tpu.serve.validate import AdmissionConfig, validate_pair
from tests.test_batch_serve import assert_rows_match

pytestmark = pytest.mark.mesh

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # not multiples of 32: every request really is padded


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pairs():
    rng = np.random.default_rng(7)
    return [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
             rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
            for _ in range(8)]


def make_session(params, cfg, *, mesh_data=None, max_batch=4,
                 batch_buckets=(1, 4), plan=None, **kw):
    scfg = SessionConfig(valid_iters=4, segments=2, max_batch=max_batch,
                         batch_buckets=batch_buckets, canary=False,
                         mesh_data=mesh_data, **kw)
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=FakeClock())


def canonical(pair):
    return validate_pair(pair[0], pair[1], AdmissionConfig())


def make_request(pair, rid=None, tenant=None, **extra):
    left, right = canonical(pair)
    req = {"id": rid, "left": left, "right": right}
    if tenant is not None:
        req["tenant"] = tenant
    req.update(extra)
    return req


def run_sched(session, requests, *, stream=None, one_tick=True):
    """Drive a scheduler to completion; with ``one_tick`` every joiner
    must be admissible before the first tick so they share one batch."""
    out = {}
    sched = BatchScheduler(
        session, resolve=lambda rq, rs: out.__setitem__(rq["id"], rs),
        stream=stream)
    for rq in requests:
        sched.submit(rq)
    if one_tick:
        for bucket in sched._buckets.values():
            for row in list(bucket.pending):
                assert row.uploaded.wait(timeout=30)
    spins = 0
    while len(out) < len(requests):
        if not sched.run_tick():
            time.sleep(0.002)
        spins += 1
        assert spins < 4000, "scheduler made no progress"
    status = sched.status()
    sched.shutdown()
    assert len(out) == len(requests)
    return out, status


# ---------------------------------------------------------------------------
# Knob resolution and the n_data=1 fallback contract.
# ---------------------------------------------------------------------------


def test_mesh_knob_resolution_named_errors(monkeypatch):
    monkeypatch.delenv("RAFT_SERVE_MESH_DATA", raising=False)
    assert resolve_serve_mesh_data() == 1          # unset: pre-pod default
    monkeypatch.setenv("RAFT_SERVE_MESH_DATA", "nope")
    with pytest.raises(ValueError, match="RAFT_SERVE_MESH_DATA"):
        resolve_serve_mesh_data()
    monkeypatch.setenv("RAFT_SERVE_MESH_DATA", "0")
    with pytest.raises(ValueError, match=">= 1"):
        resolve_serve_mesh_data()
    monkeypatch.setenv("RAFT_SERVE_MESH_DATA", "4")
    assert resolve_serve_mesh_data() == 4
    assert resolve_serve_mesh_data(2) == 2         # explicit config wins
    with pytest.raises(ValueError, match=">= 1"):
        resolve_serve_mesh_data(0)
    monkeypatch.delenv("RAFT_SERVE_MESH_FALLBACK", raising=False)
    assert resolve_mesh_fallback() is False
    for raw in ("1", "true", "yes"):
        monkeypatch.setenv("RAFT_SERVE_MESH_FALLBACK", raw)
        assert resolve_mesh_fallback() is True
    monkeypatch.setenv("RAFT_SERVE_MESH_FALLBACK", "0")
    assert resolve_mesh_fallback() is False


def test_mesh_fallback_keeps_single_device_path(monkeypatch, tiny_params,
                                                tiny_cfg):
    """The kill switch forces n_data=1 with cache keys BYTE-identical to
    a never-meshed session — the fallback is the pre-pod code path, not
    a 1-chip mesh."""
    plain = make_session(tiny_params, tiny_cfg)
    monkeypatch.setenv("RAFT_SERVE_MESH_DATA", "4")
    monkeypatch.setenv("RAFT_SERVE_MESH_FALLBACK", "1")
    off = make_session(tiny_params, tiny_cfg)
    assert not off.mesh_active and off.mesh_chips == 1
    assert off.batch_buckets == plain.batch_buckets == (1, 4)
    k_off = off.cache_key("advance", 64, 64, 2, b=4)
    k_plain = plain.cache_key("advance", 64, 64, 2, b=4)
    assert k_off == k_plain
    assert not any(isinstance(c, tuple) and c and c[0] == "mesh"
                   for c in k_off)


def test_mesh_session_activates_and_keys(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    assert sess.mesh_active and sess.mesh_chips == 2
    # Bucket rounding: every bucket divisible by the mesh extent, so the
    # leading dim always shards evenly ((1, 4) -> (2, 4)).
    assert sess.batch_buckets == (2, 4)
    st = sess.mesh_status()
    assert st["enabled"] and st["n_data"] == 2 and st["base_n_data"] == 2
    assert st["epoch"] == 0 and st["quarantined"] == []
    assert len(st["devices"]) == 2          # the POD, not the whole host
    # Mesh rides the program-cache key as a trailing component (like the
    # batch bucket) — never the config fingerprint, so the host-side
    # response cache stays ONE cache above all chips.
    k = sess.cache_key("advance", 64, 64, 2, b=4)
    assert k[-1] == ("mesh", 2, 0)
    plain = make_session(tiny_params, tiny_cfg)
    assert plain.cache_key("advance", 64, 64, 2, b=4) == k[:-1]
    assert sess.fingerprint_id() == plain.fingerprint_id()


def test_local_batch_rows_mesh_edges():
    """The divisibility rule the bucket rounding enforces, at the mesh
    seam: an indivisible batch (or n_data > batch) has no contiguous
    per-process row range."""
    mesh4 = make_mesh(n_data=4, n_space=1)
    assert local_batch_rows(mesh4, 8) == slice(0, 8)
    assert local_batch_rows(mesh4, 4) == slice(0, 4)
    assert local_batch_rows(mesh4, 6) is None      # 6 % 4 != 0
    mesh8 = make_mesh(n_data=8, n_space=1)
    assert local_batch_rows(mesh8, 4) is None      # n_data > batch
    assert local_batch_rows(mesh8, 8) == slice(0, 8)
    mesh1 = make_mesh(n_data=1, n_space=1)
    assert local_batch_rows(mesh1, 3) == slice(0, 3)


# ---------------------------------------------------------------------------
# Parity: mesh-sharded batched responses == single-device at the same
# bucket (cross-batch-SIZE comparisons stay out — both sides ride the
# identical bucket, only the sharding differs, which is exactly the
# graftpod claim under test).
# ---------------------------------------------------------------------------


def test_mesh_scheduler_parity_b4_and_pad_rows(tiny_params, tiny_cfg,
                                               pairs):
    mesh_sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    plain = make_session(tiny_params, tiny_cfg)
    # B=4: one full tick at bucket 4 on both sessions.
    want, _ = run_sched(plain, [make_request(p, rid=i)
                                for i, p in enumerate(pairs[:4])])
    got, st4 = run_sched(mesh_sess, [make_request(p, rid=i)
                                     for i, p in enumerate(pairs[:4])])
    for i in range(4):
        assert got[i]["status"] == want[i]["status"] == "ok"
        assert got[i]["quality"] == "full"
        assert_rows_match(got[i]["disparity"], want[i]["disparity"],
                          f"b4 row {i}")
    # B=3 -> bucket 4 with one pad row: pads land in pad_waste, never in
    # occupancy (live-row truth), and parity holds next to the pad.
    # Histograms are session-lifetime, so pin the DELTA over the b4 run.
    got3, st3 = run_sched(mesh_sess, [make_request(p, rid=i)
                                      for i, p in enumerate(pairs[:3])])
    for i in range(3):
        assert got3[i]["status"] == "ok"
        assert_rows_match(got3[i]["disparity"], want[i]["disparity"],
                          f"b3 row {i}")
    assert st3["pad_waste"] > st4["pad_waste"]
    assert st3["occupancy_hist"].get("3", 0) >= \
        st4["occupancy_hist"].get("3", 0) + 1
    assert st3["occupancy_hist"].get("4", 0) == \
        st4["occupancy_hist"].get("4", 0), \
        "the pad row leaked into occupancy as a live row"


def test_mesh_scheduler_parity_b8(tiny_params, tiny_cfg, pairs):
    """B=8 over a 4-chip mesh (2 rows per chip) vs single-device at the
    same bucket — the widest pod shape the fake-device topology covers
    without sharing a bucket program between the runs."""
    mesh_sess = make_session(tiny_params, tiny_cfg, mesh_data=4,
                             max_batch=8, batch_buckets=(1, 8))
    assert mesh_sess.batch_buckets == (4, 8)
    plain = make_session(tiny_params, tiny_cfg, max_batch=8,
                         batch_buckets=(1, 8))
    reqs = [make_request(p, rid=i) for i, p in enumerate(pairs)]
    want, _ = run_sched(plain, [make_request(p, rid=i)
                                for i, p in enumerate(pairs)])
    got, _ = run_sched(mesh_sess, reqs)
    for i in range(8):
        assert got[i]["status"] == "ok" and got[i]["quality"] == "full"
        assert_rows_match(got[i]["disparity"], want[i]["disparity"],
                          f"b8 row {i}")


def test_mesh_warm_cold_mixed_one_tick_parity(tiny_params, tiny_cfg,
                                              pairs):
    """A warm joiner (held flow seed) and cold joiners share one
    mesh-sharded tick; every response matches the single-device run of
    the same mix, and the warm row genuinely warm-started."""
    l, r = canonical(pairs[0])
    f = tiny_cfg.downsample_factor

    def requests(sess):
        ph, pw = sess.padder_for(l.shape).padded_shape
        rng = np.random.default_rng(9)
        flow = rng.uniform(-1, 1,
                           (1, ph // f, pw // f, 1)).astype(np.float32)
        return ([{"id": "w", "left": l, "right": r, "_flow_init": flow}]
                + [make_request(pairs[1 + i], rid=f"c{i}")
                   for i in range(2)])

    mesh_sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    plain = make_session(tiny_params, tiny_cfg)
    warm_before = int(
        mesh_sess.registry.value("raft_stream_warm_joins_total"))
    want, _ = run_sched(plain, requests(plain),
                        stream=StreamManager(plain))
    got, _ = run_sched(mesh_sess, requests(mesh_sess),
                       stream=StreamManager(mesh_sess))
    for rid in ("w", "c0", "c1"):
        assert got[rid]["status"] == want[rid]["status"] == "ok"
        assert_rows_match(got[rid]["disparity"], want[rid]["disparity"],
                          f"mixed row {rid}")
    assert int(mesh_sess.registry.value(
        "raft_stream_warm_joins_total")) == warm_before + 1
    # Non-vacuity: the warm row's seed actually changed its result.
    assert got["w"]["disparity"].tobytes() != \
        got["c0"]["disparity"].tobytes()


# ---------------------------------------------------------------------------
# Quarantine: chip-local shrink, epoch re-key, survivors keep serving.
# ---------------------------------------------------------------------------


def test_mesh_quarantine_shrink_and_rekey(tiny_params, tiny_cfg, pairs):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=4,
                        max_batch=8, batch_buckets=(1, 8))
    k0 = sess.cache_key("advance", 64, 64, 2, b=8)
    assert k0[-1] == ("mesh", 4, 0)
    # Chip 2 of 4 hangs: 3 survivors, largest divisor of 4 that fits is
    # 2 — the mesh shrinks, the epoch bumps, programs re-key.
    assert sess.quarantine_chip(2)
    assert sess.mesh_chips == 2
    st = sess.mesh_status()
    assert st["quarantined"] == [2] and st["epoch"] == 1
    assert [d["chip"] for d in st["devices"] if d["quarantined"]] == [2]
    k1 = sess.cache_key("advance", 64, 64, 2, b=8)
    assert k1[-1] == ("mesh", 2, 1) and k1 != k0
    # Idempotence + bounds: re-quarantine and out-of-range are refused.
    assert not sess.quarantine_chip(2)
    assert not sess.quarantine_chip(99)
    assert int(sess.registry.value(
        "raft_mesh_chips_quarantined_total")) == 1
    # The shrunken mesh still serves (batch 8 now shards 4 rows/chip).
    out, _ = run_sched(sess, [make_request(p, rid=i)
                              for i, p in enumerate(pairs[:8])])
    assert all(out[i]["status"] == "ok" for i in range(8))
    # Shrink to the floor: two more hangs leave one healthy chip, which
    # keeps a (1,1) mesh so placement lands on a HEALTHY device.
    assert sess.quarantine_chip(0)
    assert sess.quarantine_chip(1)
    assert sess.mesh_chips == 1 and sess.mesh_active
    assert sess.mesh_status()["quarantined"] == [0, 1, 2]


# ---------------------------------------------------------------------------
# Chip affinity + migrate-on-bounce: the held seed is HOST-side.
# ---------------------------------------------------------------------------


def test_mesh_chip_affinity_and_warm_migration(tiny_params, tiny_cfg,
                                               pairs):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    manager = StreamManager(sess)
    l, r = canonical(pairs[0])
    ph, pw = sess.padder_for(l.shape).padded_shape
    f = tiny_cfg.downsample_factor

    def admit(cam):
        req = {"id": cam, "left": l, "right": r, "stream": cam}
        manager.admit(req)
        return req

    # Round-robin placement over the data shards, stamped at admit.
    r_a, r_b = admit("cam-a"), admit("cam-b")
    assert {r_a["_chip"], r_b["_chip"]} == {0, 1}
    assert manager.status()["by_chip"] == {"0": 1, "1": 1}
    # Deposit a served frame's flow into the chip-1 session.
    victim = r_a if r_a["_chip"] == 1 else r_b
    cam1 = victim["id"]
    victim["_stream_flow"] = np.ones(
        (1, ph // f, pw // f, 1), np.float32)
    victim["_stream_shape"] = (ph, pw)
    manager.deposit(victim, {"status": "ok"})
    # Chip 1 quarantined, mesh shrunk to 1: the pinned session migrates
    # (chip pin cleared on a 1-wide mesh) and its next frame is STILL
    # WARM — the held flow is host memory, not device state.
    migrated = manager.migrate_off_chips([1], 1)
    assert migrated >= 1
    assert int(sess.registry.value(
        "raft_stream_migrations_total")) == migrated
    # The chip-0 session's pin is still valid on the 1-wide mesh and is
    # NOT disturbed; only the quarantined chip's session moved.
    assert manager.status()["by_chip"] == {"0": 1}
    nxt = admit(cam1)
    assert nxt.get("_chip") is None
    assert nxt.get("_flow_init") is not None, (
        "the migrated session lost its held warm seed")
    # On a still-multi-chip mesh the migrated session gets a NEW shard.
    sess2 = make_session(tiny_params, tiny_cfg, mesh_data=2)
    m2 = StreamManager(sess2)
    q = {"id": "x", "left": l, "right": r, "stream": "x"}
    m2.admit(q)
    assert q["_chip"] == 0
    assert m2.migrate_off_chips([0], 2) == 1
    q2 = {"id": "x", "left": l, "right": r, "stream": "x"}
    m2.admit(q2)
    assert q2["_chip"] in (0, 1)


# ---------------------------------------------------------------------------
# Books: one invoke spanning N chips is ONE wall interval — the PR 12
# three-way reconciliation survives the mesh, and the capacity plane
# reports per-chip truth.
# ---------------------------------------------------------------------------


def test_mesh_device_seconds_reconcile_exact(tiny_params, tiny_cfg,
                                             pairs):
    # slow_forwards injects exact device time under the FakeClock (the
    # test_deck.py rig) — nonzero durations with zero real sleeping.
    sess = make_session(
        tiny_params, tiny_cfg, mesh_data=2,
        plan=ServeFaultPlan(slow_forwards={i: 0.25 for i in range(128)}))
    out, st = run_sched(sess, [
        make_request(pairs[i], rid=i, tenant=f"t{i % 2}")
        for i in range(3)])
    assert all(out[i]["status"] == "ok" for i in range(3))
    # Pod-wide ticks really spanned 2 chips...
    ticks = sess.deck.snapshot()
    assert any(int(t.get("chips", 1)) > 1 for t in ticks)
    # ...yet the pads stayed out of occupancy (3 live rows on bucket 4)
    assert st["pad_waste"] > 0
    assert st["occupancy_hist"].get("3", 0) >= 1
    # Integer-ns partition: per-tenant device-ns sum EQUALS the
    # accounted total exactly — an invoke's interval was counted once,
    # never once per chip.
    doc = sess.usage.doc()
    tenant_ns = sum(t["device_ns"] for t in doc["by_tenant"].values())
    assert tenant_ns == doc["device_ns_total"]
    assert doc["device_ns_total"] > 0
    # Counter reconciliation at float tolerance (the counter is a float
    # sum of the same intervals).
    prog_dev_s = sum(v for _, v in sess.registry.series(
        "raft_program_device_seconds_total"))
    assert abs(doc["device_ns_total"] / 1e9 - prog_dev_s) <= \
        max(1e-6, 1e-9 * prog_dev_s)
    # Per-chip saturation: a 2-chip record busies chips 0 and 1 with the
    # SAME interval (never split, never doubled); chips outside the pod
    # have no history -> ratio None, not a fabricated 0.
    rows = saturation_per_chip(ticks, 4, now=sess.clock.now() + 1.0,
                               window_s=60.0)
    assert rows[0]["busy_s"] == pytest.approx(rows[1]["busy_s"])
    assert rows[0]["busy_s"] > 0
    assert rows[2]["ratio"] is None and rows[3]["ratio"] is None


def test_mesh_capacity_status_per_chip(tiny_params, tiny_cfg, pairs):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    out, _ = run_sched(sess, [make_request(pairs[0], rid=0),
                              make_request(pairs[1], rid=1)])
    assert out[0]["status"] == out[1]["status"] == "ok"
    doc = sess.capacity_status()
    chips = doc.get("chips")
    assert chips is not None
    assert chips["n_data"] == 2 and chips["base_n_data"] == 2
    assert chips["quarantined"] == []
    assert len(chips["per_chip"]) == 2
    for row in chips["per_chip"]:
        assert row["quarantined"] is False
    # Quarantine flips the row and zeroes its headroom — an operator
    # reading /healthz sees the dead chip, not averaged-away health.
    assert sess.quarantine_chip(1)
    doc2 = sess.capacity_status()
    chips2 = doc2["chips"]
    assert chips2["n_data"] == 1 and chips2["quarantined"] == [1]
    row1 = chips2["per_chip"][1]
    assert row1["quarantined"] is True and row1["headroom_rps"] == 0.0
    # A single-device session publishes NO chips block (no fabricated
    # per-chip rows on the pre-pod path).
    plain = make_session(tiny_params, tiny_cfg)
    assert "chips" not in plain.capacity_status()

"""graftfleet battery — the fleet supervisor over STUB instances.

Every test here launches real subprocesses and talks over real loopback
sockets, but the instance is ``tests/fleet_stub.py`` — a stdlib server
speaking the exact handshake + /healthz schema contract — so the whole
lifecycle (launch, probe, route, kill, replace, roll, drain) fits the
tier-1 budget.  The real ``serve_stereo.py`` children are exercised by
the release gate's ``scratch/chaos_fleet.py``.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

import pytest

from raft_stereo_tpu.obs.fleet import rollup
from raft_stereo_tpu.serve.fleet import (FleetConfig, FleetFrontend,
                                         FleetSupervisor, InstanceSpec,
                                         default_command,
                                         resolve_fleet_instances,
                                         resolve_fleet_probe_ms,
                                         resolve_fleet_restart_budget,
                                         resolve_fleet_warmup_timeout_ms)

pytestmark = pytest.mark.fleet

STUB = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "fleet_stub.py")


def stub_command(extra=None):
    extra = extra or (lambda spec: [])

    def cmd(spec):
        return [sys.executable, STUB, *extra(spec)]

    return cmd


def make_fleet(n=2, budget=3, extra=None, **cfg_kw):
    cfg_kw.setdefault("warmup_timeout_ms", 30_000.0)
    cfg_kw.setdefault("drain_grace_ms", 5_000.0)
    cfg = FleetConfig(instances=n, restart_budget=budget, probe_ms=0,
                      restart_backoff_s=0.01,
                      command=stub_command(extra), **cfg_kw)
    return FleetSupervisor(cfg)


def post(supervisor, body=b"{}", session=None):
    headers = {"Content-Type": "application/json"}
    if session is not None:
        headers["X-Raft-Session"] = session
    status, ctype, payload, _ = supervisor.forward(headers, body)
    return status, json.loads(payload)


# -- knob resolution -------------------------------------------------------

def test_knob_resolution_precedence(monkeypatch):
    monkeypatch.setenv("RAFT_FLEET_INSTANCES", "5")
    monkeypatch.setenv("RAFT_FLEET_RESTART_BUDGET", "7")
    monkeypatch.setenv("RAFT_FLEET_PROBE_MS", "123")
    monkeypatch.setenv("RAFT_FLEET_WARMUP_TIMEOUT_MS", "456")
    assert resolve_fleet_instances() == 5
    assert resolve_fleet_restart_budget() == 7
    assert resolve_fleet_probe_ms() == 123.0
    assert resolve_fleet_warmup_timeout_ms() == 456.0
    # explicit config beats env
    assert resolve_fleet_instances(3) == 3
    assert resolve_fleet_restart_budget(1) == 1
    # floor of one instance
    assert resolve_fleet_instances(0) == 1


def test_knob_parse_error_names_the_variable(monkeypatch):
    monkeypatch.setenv("RAFT_FLEET_RESTART_BUDGET", "many")
    with pytest.raises(ValueError, match="RAFT_FLEET_RESTART_BUDGET"):
        resolve_fleet_restart_budget()
    monkeypatch.setenv("RAFT_FLEET_PROBE_MS", "soon")
    with pytest.raises(ValueError, match="RAFT_FLEET_PROBE_MS"):
        resolve_fleet_probe_ms()


def test_default_command_shape():
    argv = default_command(InstanceSpec(slot=0, generation=1,
                                        args=("--max_batch", "4")))
    assert argv[1].endswith("serve_stereo.py")
    assert argv[2:4] == ["--http_port", "0"]
    assert argv[-2:] == ["--max_batch", "4"]


# -- launch / handshake ----------------------------------------------------

def test_launch_handshake_and_status():
    sup = make_fleet(n=2)
    with sup:
        ports = {inst.port for inst in sup._slots}
        assert len(ports) == 2 and None not in ports
        sup.poke()
        doc = sup.status()
        assert doc["schema"] == 1
        assert doc["instances"] == 2
        assert doc["states"].get("ready") == 2
        assert doc["generation"] == 1
        assert doc["degraded_slots"] == 0
        assert doc["fingerprints"] == ["stub-fp"]
        assert doc["counters"]["instances_total"] == 2
        assert doc["counters"]["restarts_total"] == 0
        # each by_instance row carries its slot, health fields, and the
        # per-slot restart-budget ledger (graftheal satellite: the
        # first launch of a generation is free)
        for i, row in enumerate(doc["by_instance"]):
            assert row["state"] == "ready"
            assert "uptime_s" in row and "headroom_rps" in row
            assert row["slot"] == i
            assert row["restarts_spent"] == 0
            assert row["budget_remaining"] == 3
        assert doc["restart_budget"] == 3
        assert doc["heal"]["enabled"] is True
        assert doc["heal"]["refill_ms"] > 0
        assert doc["heal"]["slot_relaunches_total"] == 0
    # stop() drained both cleanly (SIGTERM kills the stub fast)
    assert int(sup.registry.value("raft_fleet_draining_total")) == 2
    assert int(sup.registry.value(
        "raft_fleet_kill_escalations_total")) == 0


# -- routing ---------------------------------------------------------------

def test_routing_prefers_headroom():
    # slot 0 advertises 10x the headroom of slot 1
    extra = lambda spec: ["--headroom",  # noqa: E731
                          "100" if spec.slot == 0 else "10"]
    sup = make_fleet(n=2, extra=extra)
    with sup:
        sup.poke()  # populate the health docs the weights read
        for _ in range(4):
            status, doc = post(sup)
            assert status == 200 and doc["status"] == "ok"
        books = sup.books()
        big = sup._slots[0].uid
        small = sup._slots[1].uid
        assert books[big]["answered"] > books[small]["answered"]


def test_saturated_instance_backpressured():
    extra = lambda spec: (  # noqa: E731
        ["--saturation", "0.99", "--headroom", "1000"]
        if spec.slot == 0 else ["--saturation", "0.1"])
    sup = make_fleet(n=2, extra=extra)
    with sup:
        sup.poke()
        for _ in range(3):
            status, _doc = post(sup)
            assert status == 200
        books = sup.books()
        assert books[sup._slots[0].uid]["answered"] == 0
        assert books[sup._slots[1].uid]["answered"] == 3


def test_session_affinity_pins_one_instance():
    sup = make_fleet(n=2)
    with sup:
        sup.poke()
        for _ in range(5):
            status, _doc = post(sup, session="cam-7")
            assert status == 200
        books = sup.books()
        answered = sorted(b["answered"] for b in books.values())
        assert answered == [0, 5], books


def test_books_reconcile_with_instance_counters():
    sup = make_fleet(n=2)
    with sup:
        sup.poke()
        for i in range(6):
            status, _doc = post(sup, session=f"cam-{i % 3}")
            assert status == 200
        sup.poke()  # refresh health docs -> instance request counters
        books = sup.books()
        for inst in sup._slots:
            served = inst.last_doc["requests"].get("ok", 0)
            assert served == books[inst.uid]["answered"]
            assert books[inst.uid]["undelivered"] == 0


# -- preemption ------------------------------------------------------------

def test_kill9_fails_over_and_replaces():
    sup = make_fleet(n=2)
    with sup:
        sup.poke()
        status, _doc = post(sup, session="cam-1")
        assert status == 200
        pinned_uid = None
        for inst in sup._slots:
            if sup.books()[inst.uid]["answered"] == 1:
                pinned_uid = inst.uid
                victim = inst
        assert pinned_uid is not None
        victim.proc.kill()
        victim.proc.wait(timeout=10)
        # The pinned instance is gone: the SAME session's next frame is
        # handed off to the surviving instance, structured 200, counted.
        status, doc = post(sup, session="cam-1")
        assert status == 200 and doc["status"] == "ok"
        assert int(sup.registry.value("raft_fleet_reroutes_total")) >= 1
        # The probe pass replaces the dead slot (same budget pool).
        sup.poke()
        assert all(i is not None and i.state == "ready"
                   for i in sup._slots)
        assert int(sup.registry.value("raft_fleet_restarts_total")) == 1
        new_uids = {i.uid for i in sup._slots}
        assert pinned_uid not in new_uids


def test_sick_health_block_triggers_replacement():
    # The PR 9 surface: a 200 /healthz whose own supervision block says
    # the scheduler heartbeat died is UNHEALTHY — replaced on the next
    # probe pass, no socket failure needed.
    extra = lambda spec: ["--sick-after", "1"]  # noqa: E731
    sup = make_fleet(n=1, extra=extra)
    with sup:
        sup.poke()
        old_uid = sup._slots[0].uid
        status, _doc = post(sup)
        assert status == 200
        sup.poke()  # sees scheduler_died -> kill + replace
        assert sup._slots[0] is not None
        assert sup._slots[0].uid != old_uid
        assert sup._slots[0].state == "ready"
        assert int(sup.registry.value("raft_fleet_restarts_total")) == 1


def test_all_dead_is_structured_not_hung():
    sup = make_fleet(n=1)
    with sup:
        inst = sup._slots[0]
        inst.proc.kill()
        inst.proc.wait(timeout=10)
        status, doc = post(sup)
        assert status == 503
        assert doc["status"] == "rejected"
        assert doc["code"] == "no_healthy_instance"


# -- warmup-death restart budget (satellite) -------------------------------

def test_warmup_death_retries_within_budget(tmp_path):
    countdown = tmp_path / "die"
    countdown.write_text("2")  # die twice, then come up
    extra = lambda spec: ["--die-before-ready",  # noqa: E731
                          str(countdown)]
    sup = make_fleet(n=1, budget=3, extra=extra)
    with sup:
        assert sup._slots[0] is not None
        assert sup._slots[0].state == "ready"
        assert int(sup.registry.value("raft_fleet_restarts_total")) == 2
        assert int(sup.registry.value(
            "raft_fleet_instances_total")) == 3
        assert sup.status()["degraded_slots"] == 0


def test_warmup_death_budget_exhausted_degrades(tmp_path):
    countdown = tmp_path / "die"
    countdown.write_text("99")  # always dies during warmup
    extra = lambda spec: (  # noqa: E731
        ["--die-before-ready", str(countdown)] if spec.slot == 0
        else [])
    sup = make_fleet(n=2, budget=2, extra=extra)
    with sup:
        # Slot 0 degraded after 1 + budget attempts — NOT a crash loop;
        # slot 1 serves on.
        assert sup._slots[0] is None
        assert sup._slots[1] is not None
        assert int(sup.registry.value("raft_fleet_restarts_total")) == 2
        doc = sup.status()
        assert doc["degraded_slots"] == 1
        assert doc["states"].get("degraded") == 1
        # the degraded row pins its exhausted ledger on /fleet/healthz
        row0 = doc["by_instance"][0]
        assert row0["state"] == "degraded" and row0["slot"] == 0
        assert row0["uid"] is None
        assert row0["restarts_spent"] == 2
        assert row0["budget_remaining"] == 0
        assert doc["by_instance"][1]["budget_remaining"] == 2
        status, resp = post(sup)
        assert status == 200 and resp["status"] == "ok"


# -- drain escalation (satellite) ------------------------------------------

def test_drain_overrun_escalates_to_sigkill():
    extra = lambda spec: ["--ignore-term"]  # noqa: E731
    sup = make_fleet(n=1, extra=extra, drain_grace_ms=300.0)
    sup.start()
    inst = sup._slots[0]
    sup.stop()
    assert int(sup.registry.value(
        "raft_fleet_kill_escalations_total")) == 1
    assert int(sup.registry.value("raft_fleet_draining_total")) == 1
    assert not inst.alive


# -- rolling deploy --------------------------------------------------------

def test_rolling_deploy_shifts_fingerprint_and_drains_old():
    def extra(spec):
        return list(spec.args)

    sup = make_fleet(n=2, extra=extra,
                     instance_args=("--fingerprint", "fp-A"))
    with sup:
        sup.poke()
        assert sup.status()["fingerprints"] == ["fp-A"]
        old_uids = {i.uid for i in sup._slots}
        status, _doc = post(sup, session="cam-roll")
        assert status == 200
        report = sup.deploy(
            instance_args=("--fingerprint", "fp-B"))
        assert report["completed"] is True
        assert report["generation"] == 2
        assert all(s["rolled"] for s in report["slots"])
        assert {i.uid for i in sup._slots}.isdisjoint(old_uids)
        sup.poke()
        doc = sup.status()
        assert doc["fingerprints"] == ["fp-B"]
        assert doc["generation"] == 2
        # the pinned session survived the roll: handed off, served by
        # the new generation
        status, resp = post(sup, session="cam-roll")
        assert status == 200 and resp["fingerprint_id"] == "fp-B"
        assert int(sup.registry.value(
            "raft_fleet_draining_total")) == 2
        assert int(sup.registry.value(
            "raft_fleet_reroutes_total")) >= 1


def test_rolling_deploy_failure_keeps_old_generation(tmp_path):
    countdown = tmp_path / "die"
    countdown.write_text("0")

    def extra(spec):
        # generation 2 launches always die during warmup
        if spec.generation >= 2:
            return ["--die-before-ready", str(countdown)]
        return []

    sup = make_fleet(n=2, budget=1, extra=extra)
    with sup:
        sup.poke()
        old_uids = {i.uid for i in sup._slots}
        countdown.write_text("99")
        report = sup.deploy()
        assert report["completed"] is False
        assert report["slots"][0]["rolled"] is False
        # the old generation still serves — an aborted roll is not an
        # outage
        assert {i.uid for i in sup._slots} == old_uids
        status, doc = post(sup)
        assert status == 200 and doc["status"] == "ok"


# -- fleet ingress ---------------------------------------------------------

def test_fleet_frontend_end_to_end():
    sup = make_fleet(n=2)
    with sup:
        sup.poke()
        fe = FleetFrontend(sup).start()
        try:
            base = f"http://127.0.0.1:{fe.port}"
            req = urllib.request.Request(
                base + "/v1/stereo", data=b"{}", method="POST",
                headers={"Content-Type": "application/json",
                         "X-Raft-Session": "cam-fe"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
                doc = json.loads(resp.read())
            assert doc["status"] == "ok"
            assert doc["session"] == "cam-fe"
            with urllib.request.urlopen(base + "/fleet/healthz",
                                        timeout=30) as resp:
                health = json.loads(resp.read())
            assert health["instances"] == 2
            assert health["books"]
            assert sum(b["answered"]
                       for b in health["books"].values()) == 1
            with urllib.request.urlopen(base + "/fleet/metrics",
                                        timeout=30) as resp:
                text = resp.read().decode()
            assert "raft_fleet_instances_total" in text
            assert "raft_fleet_reroutes_total" in text
            # unknown routes are structured JSON, not stdlib HTML
            try:
                urllib.request.urlopen(base + "/nope", timeout=30)
                raised = None
            except urllib.error.HTTPError as e:
                raised = json.loads(e.read())
            assert raised and raised["code"] == "not_found"
        finally:
            fe.stop()


# -- rollup (obs/fleet.py) -------------------------------------------------

def test_rollup_aggregation_rules():
    rows = [
        {"uid": "a", "state": "ready", "doc": {
            "fingerprint_id": "f1", "uptime_s": 10.0,
            "requests": {"ok": 3, "rejected:queue_full": 1},
            "stream": {"sessions": 2}, "cache": {"entries": 5},
            "capacity": {"by_bucket": {"x": {"headroom_rps": 4.0}},
                         "saturation": {"ratio": 0.2}}}},
        {"uid": "b", "state": "ready", "doc": {
            "fingerprint_id": "f2", "uptime_s": 3.0,
            "requests": {"ok": 2},
            "stream": {"sessions": 1}, "cache": {"entries": 0},
            "capacity": {"by_bucket": {"x": {"headroom_rps": 1.5}},
                         "saturation": {"ratio": 0.9}}}},
        {"uid": None, "state": "degraded", "doc": None},
    ]
    doc = rollup(rows)
    assert doc["instances"] == 3
    assert doc["states"] == {"ready": 2, "degraded": 1}
    assert doc["requests"] == {"ok": 5, "rejected:queue_full": 1}
    assert doc["fingerprints"] == ["f1", "f2"] and doc["rolling"]
    assert doc["headroom_rps"] == pytest.approx(5.5)
    assert doc["saturation"] == 0.9          # max, not mean
    assert doc["uptime_min_s"] == 3.0        # youngest bounds warmth
    assert doc["stream_sessions"] == 3
    assert doc["cache_entries"] == 5


def test_rollup_survives_truncated_docs():
    doc = rollup([{"uid": "a", "state": "ready",
                   "doc": {"requests": "garbage",
                           "capacity": {"by_bucket": None}}}])
    assert doc["instances"] == 1
    assert doc["requests"] == {}
    assert doc["headroom_rps"] is None

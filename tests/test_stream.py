"""graftstream battery: warm-start parity pins, honest converged labels,
session-table bounds under churn, TTL expiry, bounce re-admission with
the held flow, and the knob resolution contract.

Everything runs on CPU with the tiny model; FakeClock drives TTL math
deterministically.  The batched service fixture is module-scoped (the
program cache is the point of the session).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock
from raft_stereo_tpu.models import (init_raft_stereo, raft_stereo_epilogue,
                                    raft_stereo_forward,
                                    raft_stereo_prepare,
                                    raft_stereo_segment_carry)
from raft_stereo_tpu.serve import (BatchScheduler, InferenceSession,
                                   ServiceConfig, SessionConfig,
                                   StereoService, StreamRunner)
from raft_stereo_tpu.serve.stream import (StreamManager,
                                          resolve_converge_tol,
                                          resolve_stream_sessions,
                                          resolve_stream_ttl_ms)
from raft_stereo_tpu.serve.validate import AdmissionConfig, validate_pair

pytestmark = pytest.mark.stream

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # not multiples of 32: padding really engages


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(11)
    return (rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32))


def canonical(pair):
    return validate_pair(pair[0], pair[1], AdmissionConfig())


def padded(pair):
    """Model-level tests need bucket-padded shapes (the raw 40x60 is not
    divisible by the 1/8 downsample) — the same padding serving applies."""
    from raft_stereo_tpu.ops.padder import InputPadder
    i1, i2 = canonical(pair)
    p = InputPadder(i1.shape, divis_by=32, bucket=32)
    return p.pad_np(i1, i2)


@pytest.fixture(scope="module")
def bsvc(tiny_params, tiny_cfg):
    """Shared batched service (programs accumulate across tests)."""
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      canary=False),
        clock=FakeClock())
    svc = StereoService(session, ServiceConfig(max_queue=16)).start()
    yield svc
    svc.stop()


# ---------------------------------------------------------------------------
# Model seam: the prepare_warm program and its parity contract.
# ---------------------------------------------------------------------------


def test_prepare_warm_zero_flow_is_bitwise_cold_prepare(tiny_params,
                                                        tiny_cfg, pair):
    """The ISSUE 13 warm-start parity pin: prepare_warm with an all-zero
    flow_init computes coords0 + 0.0 — bit-identical to the cold prepare
    (every other carry leaf never sees the flow operand)."""
    from raft_stereo_tpu.serve.session import build_program
    i1, i2 = padded(pair)
    cold = jax.jit(build_program("prepare", tiny_cfg, 0))(
        tiny_params, i1, i2)[0]
    f = tiny_cfg.downsample_factor
    zeros = np.zeros((1, i1.shape[1] // f, i1.shape[2] // f, 1),
                     np.float32)
    warm = jax.jit(build_program("prepare_warm", tiny_cfg, 0))(
        tiny_params, i1, i2, zeros)[0]
    flat_c, tree_c = jax.tree_util.tree_flatten(cold)
    flat_w, tree_w = jax.tree_util.tree_flatten(warm)
    assert tree_c == tree_w
    for a, b in zip(flat_c, flat_w):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_warm_chain_matches_forward_with_flow_init(tiny_params, tiny_cfg,
                                                   pair):
    """prepare_warm(flow) + advance chain + epilogue reproduces the
    reference test-mode forward with the same flow_init.  Bit-identical
    HERE because (a) the x-only seed keeps flow_y == 0, and (b) at tiny
    CPU shapes the fused motion encoder is disengaged on both paths, so
    fuse_motion=True (warm advance) vs False (reference flow_init
    forward) selects the same apply_motion_encoder ops.  On-chip at
    kernel-engaging shapes the warm path keeps the FUSED motion encoder
    (legal: the y==0 invariant holds by construction) while the
    reference forward disables it — there the comparison is canary-band,
    not bitwise (DESIGN.md r17 documents this)."""
    from raft_stereo_tpu.serve.session import build_program
    i1, i2 = padded(pair)
    f = tiny_cfg.downsample_factor
    h8, w8 = i1.shape[1] // f, i1.shape[2] // f
    rng = np.random.default_rng(5)
    flow_x = rng.uniform(-1.5, 1.5, (1, h8, w8, 1)).astype(np.float32)
    flow_full = jnp.concatenate(
        [jnp.asarray(flow_x), jnp.zeros((1, h8, w8, 1), jnp.float32)],
        axis=-1)

    def ref(p, a, b):
        return raft_stereo_forward(p, tiny_cfg, a, b, iters=4,
                                   flow_init=flow_full, test_mode=True)
    _, up_ref = jax.jit(ref)(tiny_params, i1, i2)

    (state,) = jax.jit(build_program("prepare_warm", tiny_cfg, 0))(
        tiny_params, i1, i2, flow_x)
    for _ in range(2):
        state, _, _ = jax.jit(
            build_program("advance", tiny_cfg, 2))(tiny_params, state)
    up, _flow_low = jax.jit(build_program("epilogue", tiny_cfg, 0))(
        tiny_params, state)
    assert np.asarray(up).tobytes() == np.asarray(up_ref).tobytes()


def test_advance_dnorm_is_segment_mean_delta(tiny_params, tiny_cfg, pair):
    """The convergence monitor's definition is pinned: dnorm ==
    mean |coords1_out - coords1_in|_x / iters, per row."""
    i1, i2 = padded(pair)
    state = jax.jit(lambda p, a, b: raft_stereo_prepare(
        p, tiny_cfg, a, b))(tiny_params, i1, i2)
    new_state, dnorm = jax.jit(
        lambda p, s: raft_stereo_segment_carry(p, tiny_cfg, s, iters=2))(
        tiny_params, state)
    expect = np.abs(np.asarray(new_state["coords1"])
                    - np.asarray(state["coords1"]))[..., 0].mean() / 2
    assert np.asarray(dnorm)[0] == pytest.approx(expect, rel=1e-6)


# ---------------------------------------------------------------------------
# Serving: first-frame parity, honest labels, deck/usage/counter joins.
# ---------------------------------------------------------------------------


def test_stream_first_frame_bitwise_stateless_and_warm_converges(bsvc,
                                                                 pair):
    session = bsvc.session
    l, r = pair
    f1 = bsvc.submit({"id": "f1", "left": l, "right": r,
                      "stream": "cam-a"}).result(timeout=300)
    ref = bsvc.submit({"id": "ref", "left": l,
                       "right": r}).result(timeout=300)
    assert f1["status"] == ref["status"] == "ok"
    assert f1["quality"] == "full"
    assert f1["disparity"].tobytes() == ref["disparity"].tobytes()

    # Frame 2 warm-starts (same padded bucket) and, with an absurdly
    # loose tolerance, exits at the FIRST segment boundary with the
    # honest converged:k label — k == iterations actually run.
    f2 = bsvc.submit({"id": "f2", "left": l, "right": r,
                      "stream": "cam-a",
                      "converge_tol": 1e9}).result(timeout=300)
    assert f2["status"] == "ok"
    assert f2["quality"] == "converged:2" and f2["iters"] == 2

    st = bsvc.status()["stream"]
    assert st["warm_joins"] >= 1 and st["converged_exits"] >= 1

    # The PR 12 three-way surfaces extend to the new kind: the deck tick
    # rows carry warm-join/converged counts, the program counters grew a
    # prepare_warm series, and the usage rollup attributes the stream
    # events to the tenant.  The Future resolves INSIDE the tick (before
    # end_tick publishes the record), so poll briefly for the ring.
    import time
    for _ in range(500):
        ticks = session.deck.snapshot()
        if sum(t.get("warm_joins", 0) for t in ticks) >= 1 and \
                sum(t.get("converged", 0) for t in ticks) >= 1:
            break
        time.sleep(0.01)
    assert sum(t.get("warm_joins", 0) for t in ticks) >= 1
    assert sum(t.get("converged", 0) for t in ticks) >= 1
    kinds = {labels["kind"] for labels, _ in
             bsvc.registry.series("raft_program_calls_total")}
    assert "prepare_warm" in kinds
    usage = session.usage.doc()
    assert usage["by_tenant"]["default"]["stream"]["warm_joins"] >= 1
    assert usage["by_tenant"]["default"]["stream"]["converged_exits"] >= 1

    # Warm rows' trace spans carry the prepare_warm kind with a tick
    # link (the span-timeline side of the reconciliation).
    trace = None
    for t in session.tracer.timelines():
        if t.get("request_id") == "f2":
            trace = t
    assert trace is not None
    warm_spans = [s for s in trace["spans"]
                  if s["kind"] == "prepare_warm"]
    assert warm_spans and \
        warm_spans[0].get("attrs", {}).get("tick") is not None


def test_sequential_stream_path_warm_join(tiny_params, tiny_cfg, pair):
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, canary=False),
        clock=FakeClock())
    svc = StereoService(session, ServiceConfig(max_queue=4,
                                               workers=1)).start()
    try:
        l, r = pair
        f1 = svc.handle({"id": "f1", "left": l, "right": r,
                         "stream": "cam-s"})
        ref = svc.handle({"id": "ref", "left": l, "right": r})
        assert f1["status"] == "ok"
        assert f1["disparity"].tobytes() == ref["disparity"].tobytes()
        f2 = svc.handle({"id": "f2", "left": l, "right": r,
                         "stream": "cam-s", "converge_tol": 1e9})
        assert f2["quality"] == "converged:2" and f2["iters"] == 2
        st = svc.status()["stream"]
        assert st["warm_joins"] == 1 and st["converged_exits"] == 1
    finally:
        svc.stop()
    # Sessions die on stop.
    assert svc.stream.status()["sessions"] == 0


def test_stream_runner_first_frame_parity(tiny_params, tiny_cfg, pair):
    """demo.py --video's first frame is bit-identical to the single-pair
    path (the satellite pin)."""
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, canary=False),
        clock=FakeClock())
    runner = StreamRunner(session)
    l, r = pair
    out = runner.infer(l, r)
    ref = session.infer(l, r)
    assert out.quality == "full"
    assert out.disparity.tobytes() == ref.disparity.tobytes()
    # Frame 2 warm-starts; with the tolerance forced loose it converges
    # with the honest label.
    runner.converge_tol = 1e9
    out2 = runner.infer(l, r)
    assert out2.quality == "converged:2" and out2.iters == 2
    assert runner.warm_frames == 1


# ---------------------------------------------------------------------------
# Session table: bounds under churn, TTL, bounce re-admission.
# ---------------------------------------------------------------------------


def _admitted(manager, session, pair, tenant, sid):
    left, right = pair
    req = {"left": left, "right": right, "tenant": tenant, "stream": sid}
    manager.admit(req)
    return req


def test_session_storm_cannot_grow_table_or_metrics(tiny_params, tiny_cfg,
                                                    pair):
    """The ISSUE 13 bounds pin: a 200-session storm past the cap leaves
    the table at its cap and /metrics flat (mirror of the PR 10/12
    tenant-label hygiene pins — stream counters are global or keyed by
    the BOUNDED tenant label, never by session id)."""
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, canary=False),
        clock=FakeClock())
    manager = StreamManager(session, max_sessions=8, per_tenant=4)
    l, r = canonical(pair)
    for i in range(20):
        _admitted(manager, session, (l, r), f"t-{i % 3}", f"cam-{i}")
    lines_before = session.registry.render_prometheus().count("\n")
    for i in range(20, 200):
        _admitted(manager, session, (l, r), f"t-{i % 3}", f"cam-{i}")
    lines_after = session.registry.render_prometheus().count("\n")
    st = manager.status()
    assert st["sessions"] <= 8
    assert all(v <= 4 for v in st["per_tenant"].values())
    assert st["evicted"] >= 190
    assert lines_after == lines_before, (
        "session churn grew /metrics — a label leaked per session id")


def test_per_tenant_cap_cannot_displace_other_tenants(tiny_params,
                                                      tiny_cfg, pair):
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, canary=False),
        clock=FakeClock())
    manager = StreamManager(session, max_sessions=16, per_tenant=2)
    l, r = canonical(pair)
    _admitted(manager, session, (l, r), "victim", "cam-0")
    for i in range(50):  # one hostile tenant churning session names
        _admitted(manager, session, (l, r), "hog", f"cam-{i}")
    st = manager.status()
    assert st["per_tenant"].get("hog", 0) <= 2
    assert st["per_tenant"].get("victim") == 1, (
        "a tenant at its own cap displaced another tenant's session")


def test_ttl_expiry_and_midflight_deposit_drop(tiny_params, tiny_cfg,
                                               pair):
    clock = FakeClock()
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, canary=False),
        clock=clock)
    manager = StreamManager(session, ttl_ms=1000.0)
    l, r = canonical(pair)
    req = _admitted(manager, session, (l, r), "t", "cam")
    assert manager.status()["sessions"] == 1
    # The frame is in flight when the TTL expires...
    clock.sleep(2.0)
    req["_stream_flow"] = np.zeros((1, 8, 8, 1), np.float32)
    req["_stream_shape"] = (64, 64)
    manager.deposit(req, {"status": "ok"})
    st = manager.status()
    # ...so the deposit lands as a counted drop, never a resurrection.
    assert st["sessions"] == 0
    assert st["expired"] == 1 and st["deposits_dropped"] == 1
    # The next frame of that stream simply starts cold.
    req2 = _admitted(manager, session, (l, r), "t", "cam")
    assert req2.get("_flow_init") is None
    assert manager.status()["sessions"] == 1


def test_bounce_harvest_keeps_warm_seed(tiny_params, tiny_cfg, pair):
    """The held flow_init rides the REQUEST dict, so a generation
    bounce's harvest/re-admit cycle keeps the row warm: the re-admitted
    row runs prepare_warm (counted), not a cold prepare."""
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=2,
                      canary=False),
        clock=FakeClock())
    manager = StreamManager(session)
    l, r = canonical(pair)
    f = tiny_cfg.downsample_factor
    padder = session.padder_for(l.shape)
    ph, pw = padder.padded_shape
    flow = np.zeros((1, ph // f, pw // f, 1), np.float32)

    responses = []
    sched = BatchScheduler(session,
                           resolve=lambda rq, rs: responses.append(rs),
                           stream=manager)
    req = {"id": "warm", "left": l, "right": r, "_flow_init": flow,
           "_converge_tol": 1e9, "_stream": ("t", "cam")}
    sched.submit(req)
    # Generation bounce before any tick ran: defunct + harvest.
    sched.defunct = True
    harvested = sched.harvest()
    assert harvested == [req]
    assert harvested[0].get("_flow_init") is not None, (
        "harvest dropped the warm-start seed")

    # Re-admission into a fresh generation stays warm.
    sched2 = BatchScheduler(session,
                            resolve=lambda rq, rs: responses.append(rs),
                            stream=manager, generation=1)
    sched2.submit(req)
    before = int(session.registry.value("raft_stream_warm_joins_total"))
    import time
    for _ in range(2000):
        if responses:
            break
        if not sched2.run_tick():
            time.sleep(0.002)
    assert responses and responses[0]["status"] == "ok"
    assert responses[0]["quality"] == "converged:2"
    after = int(session.registry.value("raft_stream_warm_joins_total"))
    assert after == before + 1
    sched2.shutdown()


def test_mixed_cold_and_warm_joiners_share_one_batch(tiny_params,
                                                     tiny_cfg, pair):
    """Warm and cold rows prepare through different programs but advance
    in ONE batch — and the warm row's result is byte-identical to the
    same warm request served alone (batch-row independence extends to
    the prepare_warm seam)."""
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      canary=False),
        clock=FakeClock())
    l, r = canonical(pair)
    f = tiny_cfg.downsample_factor
    ph, pw = session.padder_for(l.shape).padded_shape
    rng = np.random.default_rng(9)
    flow = rng.uniform(-1, 1, (1, ph // f, pw // f, 1)).astype(np.float32)

    def run(requests):
        out = {}
        sched = BatchScheduler(
            session, resolve=lambda rq, rs: out.__setitem__(rq["id"], rs))
        for rq in requests:
            sched.submit(rq)
        # All joiners must land in ONE tick (same batch bucket both
        # runs — cross-batch-size bitwise identity is canary-band on
        # this container, within-bucket identity is the pinned claim).
        import time
        for bucket in sched._buckets.values():
            for row in list(bucket.pending):
                assert row.uploaded.wait(timeout=30)
        for _ in range(4000):
            if len(out) == len(requests):
                break
            if not sched.run_tick():
                time.sleep(0.002)
        sched.shutdown()
        assert len(out) == len(requests)
        return out

    def warm_req():
        return {"id": "w", "left": l, "right": r,
                "_flow_init": flow.copy()}

    def cold_req(i, rid=None):
        return {"id": rid or f"c{i}", "left": l, "right": r}

    # Both runs advance at batch bucket 4 (batch_bucket(3) ==
    # batch_bucket(4) == 4) but with DIFFERENT batch compositions: the
    # warm row's bytes must not depend on its batchmates.
    a = run([warm_req(), cold_req(0), cold_req(1)])
    b = run([warm_req(), cold_req(2), cold_req(3), cold_req(4)])
    assert a["w"]["status"] == b["w"]["status"] == "ok"
    assert a["w"]["disparity"].tobytes() == b["w"]["disparity"].tobytes()
    # A cold row's bytes are independent of whether a warm row rode the
    # batch next to it (same bucket, same live-row count).
    c = run([cold_req(0), cold_req(5), cold_req(6)])
    assert a["c0"]["disparity"].tobytes() == \
        c["c0"]["disparity"].tobytes()
    # The warm row genuinely warm-started: its result differs from the
    # cold rows' (a zero-information warm start would be vacuous here).
    assert a["w"]["disparity"].tobytes() != a["c0"]["disparity"].tobytes()


# ---------------------------------------------------------------------------
# Knob resolution contract.
# ---------------------------------------------------------------------------


def test_knob_resolution_named_errors(monkeypatch):
    monkeypatch.setenv("RAFT_STREAM_SESSIONS", "nope")
    with pytest.raises(ValueError, match="RAFT_STREAM_SESSIONS"):
        resolve_stream_sessions()
    monkeypatch.setenv("RAFT_STREAM_SESSIONS", "0")
    with pytest.raises(ValueError, match="RAFT_STREAM_SESSIONS"):
        resolve_stream_sessions()
    monkeypatch.setenv("RAFT_STREAM_SESSIONS", "32")
    assert resolve_stream_sessions() == 32
    assert resolve_stream_sessions(4) == 4

    monkeypatch.setenv("RAFT_STREAM_TTL_MS", "-5")
    with pytest.raises(ValueError, match="RAFT_STREAM_TTL_MS"):
        resolve_stream_ttl_ms()
    monkeypatch.setenv("RAFT_STREAM_TTL_MS", "2500")
    assert resolve_stream_ttl_ms() == 2500.0

    monkeypatch.setenv("RAFT_CONVERGE_TOL", "junk")
    with pytest.raises(ValueError, match="RAFT_CONVERGE_TOL"):
        resolve_converge_tol()
    monkeypatch.setenv("RAFT_CONVERGE_TOL", "-0.1")
    with pytest.raises(ValueError, match="RAFT_CONVERGE_TOL"):
        resolve_converge_tol()
    monkeypatch.setenv("RAFT_CONVERGE_TOL", "0.25")
    assert resolve_converge_tol() == 0.25
    monkeypatch.delenv("RAFT_CONVERGE_TOL")
    assert resolve_converge_tol() == 0.01

"""Unit tests for the numerics kernel layer against torch (CPU) oracles.

torch here is purely a *semantics oracle* for the conventions the reference
relies on (symmetric conv padding, align_corners sampling/resize, pooling with
count_include_pad, unfold ordering); no reference code is involved.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import jax.numpy as jnp

from raft_stereo_tpu import ops


def t2j(x):
    """NCHW torch tensor -> NHWC jnp array."""
    return jnp.asarray(x.detach().numpy().transpose(0, 2, 3, 1))


def j2t(x):
    """NHWC jnp array -> NCHW torch tensor."""
    return torch.from_numpy(np.asarray(x).transpose(0, 3, 1, 2))


@pytest.mark.parametrize("stride,pad,k", [(1, 1, 3), (2, 1, 3), (2, 3, 7), (1, 0, 1)])
def test_conv2d_matches_torch(rng, stride, pad, k):
    x = rng.standard_normal((2, 10, 12, 5), dtype=np.float32)
    w = rng.standard_normal((k, k, 5, 7), dtype=np.float32) * 0.1
    b = rng.standard_normal((7,), dtype=np.float32)
    out = ops.conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                     stride=stride, padding=pad)
    ref = tF.conv2d(j2t(x), torch.from_numpy(w.transpose(3, 2, 0, 1)),
                    torch.from_numpy(b), stride=stride, padding=pad)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=2e-5)


def test_instance_norm_matches_torch(rng):
    x = rng.standard_normal((2, 8, 9, 6), dtype=np.float32) * 3 + 1
    out = ops.instance_norm(jnp.asarray(x))
    ref = torch.nn.InstanceNorm2d(6)(j2t(x))
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


def test_instance_norm_one_pass_cancellation_bound(rng):
    """Pin the documented bf16 one-pass limitation (ops/basic.py).

    The E[x^2]-E[x]^2 variance form is used ONLY because this model's bf16
    activations are O(1)-scale. This test pins the VARIANCE of both forms
    (fp32 accumulation, mirroring basic.py's arithmetic) against the exact
    fp64 value: at O(1) scale both are accurate AND the full bf16
    instance_norm agrees with an exact oracle; at mean/std ~ 1e3 the
    one-pass variance loses most of its bits while the two-pass form does
    not — the documented reason not to reuse this path for
    large-dynamic-range inputs.
    """
    def variances(x32):
        xb = jnp.asarray(x32, jnp.bfloat16)
        mean = jnp.mean(xb, axis=(1, 2), dtype=jnp.float32)
        one = (jnp.mean(jnp.square(xb.astype(jnp.float32)), axis=(1, 2))
               - jnp.square(mean))
        two = jnp.mean(jnp.square(xb.astype(jnp.float32)
                                  - mean[:, None, None, :]), axis=(1, 2))
        x64 = np.asarray(xb).astype(np.float64)
        exact = ((x64 - x64.mean(axis=(1, 2), keepdims=True)) ** 2
                 ).mean(axis=(1, 2))
        rel = lambda v: np.abs(np.asarray(v, np.float64) - exact).max() / exact.max()
        return rel(one), rel(two)

    small = rng.standard_normal((1, 16, 16, 4)).astype(np.float32)
    rel_one, rel_two = variances(small)
    assert rel_one < 1e-2 and rel_two < 1e-2, (rel_one, rel_two)
    # And the production function agrees with an exact fp64 oracle at this
    # (the model's) activation scale, up to bf16 input/output rounding.
    out = ops.instance_norm(jnp.asarray(small, jnp.bfloat16))
    xb64 = np.asarray(jnp.asarray(small, jnp.bfloat16)).astype(np.float64)
    m = xb64.mean(axis=(1, 2), keepdims=True)
    oracle = (xb64 - m) / np.sqrt(((xb64 - m) ** 2).mean(
        axis=(1, 2), keepdims=True) + 1e-5)
    assert np.abs(np.asarray(out, np.float64) - oracle).max() < 0.05

    rel_one_big, rel_two_big = variances(small + 1000.0)  # mean/std ~ 1e3
    # One-pass cancellation destroys the variance here; two-pass survives.
    # If the first floor ever fails, the one-pass form is gone from
    # basic.py and this canary (plus its NOTE) can be retired.
    assert rel_one_big > 10 * rel_two_big, (rel_one_big, rel_two_big)
    assert rel_one_big > 0.01, rel_one_big  # measured 0.0149 (vs 0.0 two-pass)


def test_frozen_batch_norm_matches_torch_eval(rng):
    c = 6
    x = rng.standard_normal((2, 8, 9, c), dtype=np.float32)
    bn = torch.nn.BatchNorm2d(c)
    with torch.no_grad():
        bn.weight.copy_(torch.from_numpy(rng.standard_normal(c).astype(np.float32)))
        bn.bias.copy_(torch.from_numpy(rng.standard_normal(c).astype(np.float32)))
        bn.running_mean.copy_(torch.from_numpy(rng.standard_normal(c).astype(np.float32)))
        bn.running_var.copy_(torch.from_numpy(np.abs(rng.standard_normal(c)).astype(np.float32) + 0.5))
    bn.eval()
    params = {"scale": jnp.asarray(bn.weight.detach().numpy()),
              "bias": jnp.asarray(bn.bias.detach().numpy()),
              "mean": jnp.asarray(bn.running_mean.numpy()),
              "var": jnp.asarray(bn.running_var.numpy())}
    out = ops.frozen_batch_norm(jnp.asarray(x), params)
    ref = bn(j2t(x))
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


def test_group_norm_matches_torch(rng):
    c, g = 16, 2
    x = rng.standard_normal((2, 6, 7, c), dtype=np.float32)
    gn = torch.nn.GroupNorm(g, c)
    with torch.no_grad():
        gn.weight.copy_(torch.from_numpy(rng.standard_normal(c).astype(np.float32)))
        gn.bias.copy_(torch.from_numpy(rng.standard_normal(c).astype(np.float32)))
    params = {"scale": jnp.asarray(gn.weight.detach().numpy()),
              "bias": jnp.asarray(gn.bias.detach().numpy())}
    out = ops.group_norm(jnp.asarray(x), params, g)
    np.testing.assert_allclose(np.asarray(out), t2j(gn(j2t(x))), atol=1e-5)


@pytest.mark.parametrize("h,w", [(8, 9), (7, 12)])
def test_pool2x_matches_torch(rng, h, w):
    x = rng.standard_normal((2, h, w, 3), dtype=np.float32)
    out = ops.pool2x(jnp.asarray(x))
    ref = tF.avg_pool2d(j2t(x), 3, stride=2, padding=1)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


def test_pool4x_matches_torch(rng):
    x = rng.standard_normal((1, 12, 16, 3), dtype=np.float32)
    out = ops.pool4x(jnp.asarray(x))
    ref = tF.avg_pool2d(j2t(x), 5, stride=4, padding=1)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


@pytest.mark.parametrize("w", [8, 9])
def test_avg_pool_w2_matches_torch(rng, w):
    x = rng.standard_normal((2, 5, w, 4), dtype=np.float32)
    out = ops.avg_pool_w2(jnp.asarray(x))
    ref = tF.avg_pool2d(j2t(x), (1, 2), stride=(1, 2))
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


@pytest.mark.parametrize("size", [(12, 20), (5, 7), (8, 10)])
def test_interp_align_corners_matches_torch(rng, size):
    x = rng.standard_normal((2, 8, 10, 3), dtype=np.float32)
    out = ops.interp_align_corners(jnp.asarray(x), size)
    ref = tF.interpolate(j2t(x), size=size, mode="bilinear", align_corners=True)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


def test_sample_1d_zeros_matches_grid_sample(rng):
    """1D lerp with zero padding == grid_sample(align_corners=True) on a 1-row image."""
    n, w, k = 6, 16, 9
    values = rng.standard_normal((n, w), dtype=np.float32)
    # Positions straddling the borders to exercise zero-padding.
    x = rng.uniform(-3, w + 2, size=(n, k)).astype(np.float32)
    out = ops.sample_1d_zeros(jnp.asarray(values), jnp.asarray(x))
    img = torch.from_numpy(values).view(n, 1, 1, w)
    xg = 2 * torch.from_numpy(x) / (w - 1) - 1
    grid = torch.stack([xg, torch.zeros_like(xg)], dim=-1).view(n, 1, k, 2)
    ref = tF.grid_sample(img, grid, align_corners=True).view(n, k)
    np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=1e-5)


def test_sample_1d_zeros_bf16_values_wide_row(rng):
    """bf16 inputs must not corrupt tap indexing for width > 256.

    Integer positions above 256 are unrepresentable in bf16; weights are
    computed in fp32 internally so only the final product is bf16-precision.
    """
    n, w = 2, 512
    values = rng.standard_normal((n, w), dtype=np.float32)
    x = np.array([[300.0, 400.25, 511.0], [257.0, 510.5, 0.5]], np.float32)
    out = ops.sample_1d_zeros(jnp.asarray(values, jnp.bfloat16),
                              jnp.asarray(x))
    x0 = np.floor(x).astype(int)
    frac = x - x0
    ref = (values[np.arange(n)[:, None], x0] * (1 - frac)
           + values[np.arange(n)[:, None], np.minimum(x0 + 1, w - 1)] * frac)
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               atol=0.05, rtol=0.05)


def test_sample_rows_zeros_matches_grid_sample(rng):
    n, w, d, k = 4, 12, 5, 7
    fmap = rng.standard_normal((n, w, d), dtype=np.float32)
    x = rng.uniform(-2, w + 1, size=(n, k)).astype(np.float32)
    out = ops.sample_rows_zeros(jnp.asarray(fmap), jnp.asarray(x))
    img = torch.from_numpy(fmap.transpose(0, 2, 1)).view(n, d, 1, w)
    xg = 2 * torch.from_numpy(x) / (w - 1) - 1
    grid = torch.stack([xg, torch.zeros_like(xg)], dim=-1).view(n, 1, k, 2)
    ref = tF.grid_sample(img, grid, align_corners=True).view(n, d, k)
    np.testing.assert_allclose(np.asarray(out), ref.numpy().transpose(0, 2, 1), atol=1e-5)


@pytest.mark.parametrize("factor", [4, 8])
def test_convex_upsample_matches_torch_unfold(rng, factor):
    """Oracle: softmax-mask convex combination built with torch.unfold directly."""
    b, h, w, d = 2, 5, 6, 2
    flow = rng.standard_normal((b, h, w, d), dtype=np.float32)
    mask = rng.standard_normal((b, h, w, factor * factor * 9), dtype=np.float32)
    out = ops.convex_upsample(jnp.asarray(flow), jnp.asarray(mask), factor)

    tflow = j2t(flow)
    tmask = j2t(mask).view(b, 1, 9, factor, factor, h, w)
    tmask = torch.softmax(tmask, dim=2)
    patches = tF.unfold(factor * tflow, (3, 3), padding=1).view(b, d, 9, 1, 1, h, w)
    ref = torch.sum(tmask * patches, dim=2)
    ref = ref.permute(0, 1, 4, 2, 5, 3).reshape(b, d, factor * h, factor * w)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)


def test_coords_grid():
    g = ops.coords_grid(2, 3, 4)
    assert g.shape == (2, 3, 4, 2)
    np.testing.assert_array_equal(np.asarray(g[0, :, :, 0]),
                                  np.tile(np.arange(4, dtype=np.float32), (3, 1)))
    np.testing.assert_array_equal(np.asarray(g[1, :, :, 1]),
                                  np.tile(np.arange(3, dtype=np.float32)[:, None], (1, 4)))


def test_upflow_matches_torch(rng):
    x = rng.standard_normal((1, 4, 5, 2), dtype=np.float32)
    out = ops.upflow(jnp.asarray(x), 8)
    ref = 8 * tF.interpolate(j2t(x), size=(32, 40), mode="bilinear", align_corners=True)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-4)


@pytest.mark.parametrize("hw,divis", [((46, 62), 32), ((64, 96), 32), ((375, 1242), 32)])
def test_input_padder_roundtrip(rng, hw, divis):
    h, w = hw
    x = rng.standard_normal((1, h, w, 3), dtype=np.float32)
    padder = ops.InputPadder((1, h, w, 3), divis_by=divis)
    (padded,) = padder.pad(jnp.asarray(x))
    ph, pw = padder.padded_shape
    assert ph % divis == 0 and pw % divis == 0
    assert padded.shape == (1, ph, pw, 3)
    np.testing.assert_array_equal(np.asarray(padder.unpad(padded)), x)
    # Reference quirk (utils.py:11-12): already-divisible sizes stay unpadded.
    if h % divis == 0 and w % divis == 0:
        assert (ph, pw) == (h, w)


def test_input_padder_bucketing():
    padder = ops.InputPadder((1, 375, 1242, 3), divis_by=32, bucket=64)
    ph, pw = padder.padded_shape
    assert ph % 64 == 0 and pw % 64 == 0


# ---------------------------------------------------------------------------
# dead-in-reference parity stubs: SepConvGRU + BottleneckBlock
# ---------------------------------------------------------------------------

def _conv_p(m):
    return {"w": jnp.asarray(m.weight.detach().numpy().transpose(2, 3, 1, 0)),
            "b": jnp.asarray(m.bias.detach().numpy())}


def test_sep_conv_gru_matches_torch(rng):
    """Oracle for the ported-but-unused SepConvGRU (ref core/update.py:34-62)."""
    from raft_stereo_tpu.models.update import apply_sep_conv_gru

    hidden, cin = 16, 24
    convs = {}
    torch.manual_seed(3)
    for name, k, pad in [("convz1", (1, 5), (0, 2)), ("convr1", (1, 5), (0, 2)),
                         ("convq1", (1, 5), (0, 2)), ("convz2", (5, 1), (2, 0)),
                         ("convr2", (5, 1), (2, 0)), ("convq2", (5, 1), (2, 0))]:
        convs[name] = torch.nn.Conv2d(hidden + cin, hidden, k, padding=pad)

    h0 = rng.standard_normal((2, 6, 7, hidden), dtype=np.float32)
    x = rng.standard_normal((2, 6, 7, cin), dtype=np.float32)

    ht = j2t(h0)
    xt = j2t(x)
    with torch.no_grad():
        for suffix in ("1", "2"):
            hx = torch.cat([ht, xt], dim=1)
            z = torch.sigmoid(convs["convz" + suffix](hx))
            r = torch.sigmoid(convs["convr" + suffix](hx))
            q = torch.tanh(convs["convq" + suffix](torch.cat([r * ht, xt], dim=1)))
            ht = (1 - z) * ht + z * q

    p = {name: _conv_p(m) for name, m in convs.items()}
    out = apply_sep_conv_gru(p, jnp.asarray(h0), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), t2j(ht), atol=1e-5)


@pytest.mark.parametrize("norm_fn,stride", [("group", 1), ("group", 2),
                                            ("instance", 2), ("none", 1)])
def test_bottleneck_block_matches_torch(rng, norm_fn, stride):
    """Oracle for the ported-but-unused BottleneckBlock (ref extractor.py:64-120)."""
    from raft_stereo_tpu.models.layers import apply_bottleneck_block

    # Reference quirk: downsample exists only for stride != 1, so stride-1
    # blocks require in_planes == planes (extractor.py:103-108).
    planes = 16
    in_planes = planes if stride == 1 else 12
    torch.manual_seed(4)
    conv1 = torch.nn.Conv2d(in_planes, planes // 4, 1)
    conv2 = torch.nn.Conv2d(planes // 4, planes // 4, 3, padding=1, stride=stride)
    conv3 = torch.nn.Conv2d(planes // 4, planes, 1)
    groups = planes // 8

    def norm(c):
        if norm_fn == "group":
            return torch.nn.GroupNorm(groups, c)
        if norm_fn == "instance":
            return torch.nn.InstanceNorm2d(c)
        return torch.nn.Identity()

    n1, n2, n3, n4 = norm(planes // 4), norm(planes // 4), norm(planes), norm(planes)
    down = torch.nn.Conv2d(in_planes, planes, 1, stride=stride) if stride != 1 else None

    x = rng.standard_normal((2, 8, 10, in_planes), dtype=np.float32)
    xt = j2t(x)
    with torch.no_grad():
        y = torch.relu(n1(conv1(xt)))
        y = torch.relu(n2(conv2(y)))
        y = torch.relu(n3(conv3(y)))
        sc = n4(down(xt)) if down is not None else xt
        ref = torch.relu(sc + y)

    def norm_p(m, c):
        if norm_fn == "group":
            return {"scale": jnp.asarray(m.weight.detach().numpy()),
                    "bias": jnp.asarray(m.bias.detach().numpy())}
        return {}

    p = {"conv1": _conv_p(conv1), "conv2": _conv_p(conv2), "conv3": _conv_p(conv3),
         "norm1": norm_p(n1, planes // 4), "norm2": norm_p(n2, planes // 4),
         "norm3": norm_p(n3, planes)}
    if down is not None:
        p["downsample"] = {"conv": _conv_p(down), "norm": norm_p(n4, planes)}

    out = apply_bottleneck_block(p, jnp.asarray(x), norm_fn, stride=stride)
    np.testing.assert_allclose(np.asarray(out), t2j(ref), atol=1e-5)

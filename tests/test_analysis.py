"""graftlint battery: per-checker positive/negative fixtures, the
historical-regression fixtures (the PR 3 import-time ``ENABLE`` bug, knob
drift, fingerprint drift), the clean-tree tier-1 gate, and the CLI /
lint.sh wiring.

No jax import anywhere on these paths — the linter must stay
milliseconds-fast in any environment.
"""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from raft_stereo_tpu.analysis import knobs
from raft_stereo_tpu.analysis.core import (Project, collect_files,
                                           run_analysis, run_checkers)

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "raft_stereo_tpu"


def lint(tmp_path, files, **kw):
    """Write ``files`` (relpath -> source) under ``tmp_path``, lint it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_analysis([str(tmp_path)], base=str(tmp_path), **kw)


def codes(report):
    return sorted(f.code for f in report.findings)


# Mini fixtures shared across checkers -------------------------------------

GUARD_SRC = """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class FastPath:
        name: str
        env_var: str = None
        cfg_field: str = None

    DEFAULT_LADDER = (
        FastPath(name="my_kernel", env_var="RAFT_MYKERN"),
        FastPath(name="cfg_rung", cfg_field="fused_update"),
    )
"""

CFG_SRC = """
    import dataclasses

    @dataclasses.dataclass
    class RAFTStereoConfig:
        corr_implementation: str = "reg"
        corr_levels: int = 4
        fused_update: bool = True
        mixed_precision: bool = False
"""

KERNEL_SRC = """
    import os
    from jax.experimental import pallas as pl

    def enabled():
        return os.environ.get("RAFT_MYKERN", "1") != "0"

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def run(x):
        if not enabled():
            return x
        return pl.pallas_call(kernel)(x)
"""


# ---------------------------------------------------------------------------
# framework: suppressions, parse errors, filters
# ---------------------------------------------------------------------------

BAD_GL001 = """
    import os
    FLAG = os.environ.get("RAFT_THING", "1")
"""


def test_finding_detected_and_fails(tmp_path):
    rep = lint(tmp_path, {"m.py": BAD_GL001})
    assert codes(rep) == ["GL001"] and not rep.ok


def test_inline_suppression_with_reason(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        FLAG = os.environ.get("RAFT_THING", "1")  # graftlint: disable=GL001 (test-only constant)
    """})
    assert rep.ok
    assert [f.code for f in rep.suppressed] == ["GL001"]
    assert rep.suppressed[0].suppress_reason == "test-only constant"


def test_preceding_comment_line_suppression(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        # graftlint: disable=GL001 (import-time on purpose here)
        FLAG = os.environ.get("RAFT_THING", "1")
    """})
    assert rep.ok and [f.code for f in rep.suppressed] == ["GL001"]


def test_wrong_code_suppression_does_not_apply(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        FLAG = os.environ.get("RAFT_THING", "1")  # graftlint: disable=GL002 (wrong code)
    """})
    # the GL002 suppression neither applies to the GL001 finding nor
    # suppresses anything at all — which makes it STALE, and a stale
    # suppression is itself a meta finding (dead suppressions rot into
    # false confidence; GL000 names them so they get deleted)
    assert codes(rep) == ["GL000", "GL001"]


def test_suppression_without_reason_is_gl000(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        FLAG = os.environ.get("RAFT_THING", "1")  # graftlint: disable=GL001
    """})
    # the reasonless suppression does NOT suppress, and is itself flagged
    assert codes(rep) == ["GL000", "GL001"]


def test_parse_error_is_gl000(tmp_path):
    rep = lint(tmp_path, {"m.py": "def broken(:\n"})
    assert codes(rep) == ["GL000"]


def test_select_filter_keeps_gl000(tmp_path):
    rep = lint(tmp_path, {"m.py": BAD_GL001, "b.py": "def broken(:\n"},
               select=("GL004",))
    assert codes(rep) == ["GL000"]  # GL001 filtered, GL000 never filterable


def test_only_paths_filter(tmp_path):
    files = {"a.py": BAD_GL001, "b.py": BAD_GL001}
    for rel, src in files.items():
        (tmp_path / rel).write_text(textwrap.dedent(src))
    rep = run_analysis([str(tmp_path)], base=str(tmp_path),
                       only_paths={str(tmp_path / "a.py")})
    assert [f.path for f in rep.findings] == ["a.py"]


# ---------------------------------------------------------------------------
# GL001 — import-time kill-switch read
# ---------------------------------------------------------------------------

def test_gl001_pr3_enable_regression(tmp_path):
    # The literal shape of the bug PR 3 fixed in ops/pallas_encoder.py:
    # the kill switch read once, at import, into a module constant — the
    # breaker's runtime env flip never reached later traces.
    rep = lint(tmp_path, {"ops/pallas_encoder.py": """
        import os as _os

        ENABLE = _os.environ.get("RAFT_FUSED_ENCODERS", "1").lower() not in (
            "0", "false", "no", "")
    """})
    assert "GL001" in codes(rep)
    assert "RAFT_FUSED_ENCODERS" in rep.findings[0].message


def test_gl001_trace_time_read_ok(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os as _os

        def ENABLE():
            return _os.environ.get("RAFT_FUSED_ENCODERS", "1") != "0"
    """})
    assert rep.ok


def test_gl001_lru_cached_read_flagged(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import functools
        import os

        @functools.lru_cache(maxsize=None)
        def enabled():
            return os.environ.get("RAFT_SWITCH", "1") != "0"
    """})
    assert codes(rep) == ["GL001"]


def test_gl001_class_scope_read_flagged(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os

        class K:
            FLAG = os.environ.get("RAFT_SWITCH", "1")
    """})
    assert codes(rep) == ["GL001"]


def test_gl001_non_raft_key_ignored(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        HOME = os.environ.get("HOME", "/root")
        CACHE = os.environ["XDG_CACHE_HOME"]
    """})
    assert rep.ok


def test_gl001_survives_dotted_os_path_import(tmp_path):
    # `import os.path` binds the root name `os`; the alias map must not
    # resolve os.environ to os.path.environ and hide the read (this
    # exact hole once made the PR 3 regression fixture invisible)
    rep = lint(tmp_path, {"m.py": """
        import os.path

        ENABLE = os.environ.get("RAFT_FUSED_ENCODERS", "1")
    """})
    assert codes(rep) == ["GL001"]


def test_gl001_getenv_and_subscript_forms(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        A = os.getenv("RAFT_A")
        B = os.environ["RAFT_B"]
    """})
    assert codes(rep) == ["GL001", "GL001"]


# ---------------------------------------------------------------------------
# GL002 — knob-registry drift
# ---------------------------------------------------------------------------

def test_gl002_unregistered_knob_in_ops(tmp_path):
    rep = lint(tmp_path, {"ops/k.py": """
        import os

        def tile():
            return int(os.environ.get("RAFT_NEW_TILE", 256))
    """}, knobs=("RAFT_OTHER",))
    assert codes(rep) == ["GL002"]


def test_gl002_registered_knob_ok(tmp_path):
    rep = lint(tmp_path, {"ops/k.py": """
        import os

        def tile():
            return int(os.environ.get("RAFT_NEW_TILE", 256))
    """}, knobs=("RAFT_NEW_TILE",))
    assert rep.ok


def test_gl002_host_module_unregistered_fires(tmp_path):
    # Widened scan (r10): a RAFT_* read in serve/ or native/ must appear
    # in SOME registry — previously invisible to lint.
    rep = lint(tmp_path, {"serve/k.py": """
        import os

        def f():
            return os.environ.get("RAFT_WHATEVER", "1")
    """}, knobs=(), serve_knobs=())
    assert codes(rep) == ["GL002"]
    assert "host/serving module" in rep.findings[0].message


def test_gl002_host_module_any_registry_ok(tmp_path):
    src = {"native/k.py": """
        import os

        def f():
            return os.environ.get("RAFT_PIPE", "1")
    """}
    # serve/native reads may live in the host/serving registries...
    assert lint(tmp_path, dict(src), knobs=(),
                serve_knobs=("RAFT_PIPE",)).ok
    # ...or in ENV_KNOBS (a forward knob legitimately read from serve/).
    assert lint(tmp_path, dict(src), knobs=("RAFT_PIPE",),
                serve_knobs=()).ok


def test_gl002_outside_scanned_dirs_ignored(tmp_path):
    # engine/ stays outside the scan (data/ joined it in r14 — the
    # decode-bomb cap made env reads there policy, not plumbing).
    rep = lint(tmp_path, {"engine/k.py": """
        import os

        def f():
            return os.environ.get("RAFT_WHATEVER", "1")
    """}, knobs=(), serve_knobs=())
    assert rep.ok


def test_gl002_real_tree_native_knob_registered():
    # RAFT_NATIVE (native/__init__.py) is covered by HOST_ENV_KNOBS; drop
    # it from the host registries and GL002 must fire at the read site —
    # the widened scan provably sees native/.
    files = collect_files([str(PACKAGE)], base=str(REPO))
    rep = run_checkers(Project(files, serve_knobs=knobs.SERVE_ENV_KNOBS))
    hits = [f for f in rep.findings if f.code == "GL002"
            and "RAFT_NATIVE" in f.message]
    assert hits and hits[0].path.endswith("native/__init__.py")


def test_gl002_real_tree_obs_knob_registered():
    # RAFT_TRACE (obs/tracing.py Tracer) is covered by HOST_ENV_KNOBS;
    # drop it and GL002 must fire at the read site — the r11-widened scan
    # provably sees obs/ (same for RAFT_PROFILE_DIR / RAFT_TRAJECTORY,
    # which this registry drop leaves covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_TRACE")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_TRACE" in hits[0].message
    assert hits[0].path.endswith("obs/tracing.py")


def test_gl002_real_tree_flight_knob_registered():
    # RAFT_FLIGHT_DIR (obs/flight.py FlightRecorder) is covered by
    # HOST_ENV_KNOBS; drop it and GL002 must fire at the read site — the
    # r12 flight-recorder knob cannot silently drift out of the registry
    # (the drop leaves RAFT_LEDGER covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_FLIGHT_DIR")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_FLIGHT_DIR" in hits[0].message
    assert hits[0].path.endswith("obs/flight.py")


def test_gl002_real_tree_watchdog_knob_registered():
    # RAFT_WATCHDOG_MS (serve/supervise.py resolve_watchdog_ms) is
    # covered by SERVE_ENV_KNOBS; drop it and GL002 must fire at the
    # read site — the r13 supervision knobs cannot silently drift out of
    # the registry (the drop leaves RAFT_RETRY_BUDGET /
    # RAFT_DRAIN_GRACE_MS covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_WATCHDOG_MS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_WATCHDOG_MS" in hits[0].message
    assert hits[0].path.endswith("serve/supervise.py")


def test_gl002_real_tree_http_knob_registered():
    # RAFT_HTTP_BODY_MAX (serve/http.py resolve_body_max) is covered by
    # SERVE_ENV_KNOBS; drop it and GL002 must fire at the read site — the
    # r14 ingress knobs cannot silently drift out of the registry (the
    # drop leaves RAFT_HTTP_PORT / RAFT_HTTP_READ_TIMEOUT_MS /
    # RAFT_TENANT_RATE covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_HTTP_BODY_MAX")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_HTTP_BODY_MAX" in hits[0].message
    assert hits[0].path.endswith("serve/http.py")


def test_gl002_real_tree_decode_cap_knob_registered():
    # RAFT_DECODE_MAX_PIXELS (data/frame_utils.py, the decompression-bomb
    # cap) is covered by HOST_ENV_KNOBS; drop it and GL002 must fire at
    # the read site — the r14-widened scan provably sees data/ (before
    # the widening, an env read there was invisible to lint).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_DECODE_MAX_PIXELS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_DECODE_MAX_PIXELS" in hits[0].message
    assert hits[0].path.endswith("data/frame_utils.py")


def test_gl002_real_tree_deck_knob_registered():
    # RAFT_DECK_TICKS (obs/deck.py resolve_deck_ticks, the tick
    # flight-deck ring depth) is covered by HOST_ENV_KNOBS; drop it and
    # GL002 must fire at the read site — the r15 operator-plane knobs
    # cannot silently drift out of the registry (the drop leaves
    # RAFT_CAPACITY_WINDOW_MS covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_DECK_TICKS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_DECK_TICKS" in hits[0].message
    assert hits[0].path.endswith("obs/deck.py")


def test_gl002_real_tree_capacity_window_knob_registered():
    # Same pin for RAFT_CAPACITY_WINDOW_MS (obs/capacity.py, the
    # saturation sliding window).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_CAPACITY_WINDOW_MS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_CAPACITY_WINDOW_MS" in hits[0].message
    assert hits[0].path.endswith("obs/capacity.py")


def test_gl002_real_tree_stream_knob_registered():
    # RAFT_STREAM_SESSIONS (serve/stream.py resolve_stream_sessions, the
    # graftstream session-table cap) is covered by HOST_ENV_KNOBS; drop
    # it and GL002 must fire at the read site — the r17 streaming knobs
    # cannot silently drift out of the registry (the drop leaves
    # RAFT_STREAM_TTL_MS / RAFT_CONVERGE_TOL covered so the hit is
    # unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_STREAM_SESSIONS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_STREAM_SESSIONS" in hits[0].message
    assert hits[0].path.endswith("serve/stream.py")


def test_gl002_real_tree_cache_knob_registered():
    # RAFT_CACHE_BYTES (serve/cache.py resolve_cache_bytes, the
    # graftrecall response-cache budget) is covered by HOST_ENV_KNOBS;
    # drop it and GL002 must fire at the read site — the r18 cache knobs
    # cannot silently drift out of the registry (the drop leaves
    # RAFT_CACHE_TTL_MS / RAFT_CACHE_NEAR_TOL / RAFT_CACHE_DIR covered
    # so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_CACHE_BYTES")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_CACHE_BYTES" in hits[0].message
    assert hits[0].path.endswith("serve/cache.py")


def test_gl002_real_tree_mesh_knob_registered():
    # RAFT_SERVE_MESH_DATA (serve/session.py resolve_serve_mesh_data,
    # the graftpod data-mesh extent) is covered by HOST_ENV_KNOBS; drop
    # it and GL002 must fire at the read site — the r21 pod knobs cannot
    # silently drift out of the registry (the drop leaves
    # RAFT_SERVE_MESH_FALLBACK covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_SERVE_MESH_DATA")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_SERVE_MESH_DATA" in hits[0].message
    assert hits[0].path.endswith("serve/session.py")


def test_gl002_real_tree_heal_knob_registered():
    # RAFT_HEAL_BACKOFF_MS (serve/heal.py resolve_heal_backoff_ms, the
    # r22 recovery-plane probation backoff) is covered by
    # HOST_ENV_KNOBS; drop it and GL002 must fire at the read site — the
    # recovery-pacing knobs cannot silently drift out of the registry
    # (the drop leaves RAFT_HEAL / RAFT_HEAL_FLAP_CAP /
    # RAFT_HEAL_REFILL_MS covered so the hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_HEAL_BACKOFF_MS")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_HEAL_BACKOFF_MS" in hits[0].message
    assert hits[0].path.endswith("serve/heal.py")


def test_gl002_real_tree_dropped_knob_fails():
    # Acceptance fixture: drop RAFT_CORR_TILE from the registry while its
    # read still exists in corr/pallas_reg.py -> GL002 must fire.
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.ENV_KNOBS if k != "RAFT_CORR_TILE")
    rep = run_checkers(Project(files, knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_CORR_TILE" in hits[0].message
    assert hits[0].path.endswith("corr/pallas_reg.py")


# ---------------------------------------------------------------------------
# GL003 — cache-key completeness
# ---------------------------------------------------------------------------

def test_gl003_missing_field_flagged(tmp_path):
    rep = lint(tmp_path, {"cfg.py": CFG_SRC, "session.py": """
        def config_fingerprint(cfg):
            return (cfg.corr_implementation, cfg.corr_levels,
                    cfg.fused_update)
    """})
    assert codes(rep) == ["GL003"]
    assert "mixed_precision" in rep.findings[0].message


def test_gl003_explicit_complete_ok(tmp_path):
    rep = lint(tmp_path, {"cfg.py": CFG_SRC, "session.py": """
        def config_fingerprint(cfg):
            return (cfg.corr_implementation, cfg.corr_levels,
                    cfg.fused_update, getattr(cfg, "mixed_precision"))
    """})
    assert rep.ok


def test_gl003_dataclasses_fields_ok(tmp_path):
    # the shipped pattern: generic iteration is conservative-by-default
    rep = lint(tmp_path, {"cfg.py": CFG_SRC, "session.py": """
        import dataclasses

        def config_fingerprint(cfg):
            return tuple(sorted(
                (f.name, repr(getattr(cfg, f.name)))
                for f in dataclasses.fields(cfg)))
    """})
    assert rep.ok


def test_gl003_new_config_field_breaks_stale_fingerprint(tmp_path):
    # the drift direction that bites: config GROWS a field, the
    # hand-enumerated fingerprint doesn't — new field aliases programs
    rep = lint(tmp_path, {
        "cfg.py": CFG_SRC.replace(
            "mixed_precision: bool = False",
            "mixed_precision: bool = False\n        new_knob: int = 0"),
        "session.py": """
        def config_fingerprint(cfg):
            return (cfg.corr_implementation, cfg.corr_levels,
                    cfg.fused_update, cfg.mixed_precision)
    """})
    assert codes(rep) == ["GL003"]
    assert "new_knob" in rep.findings[0].message


def test_gl003_helper_named_fields_does_not_disable_check(tmp_path):
    # only a call resolving to dataclasses.fields counts as generic
    # iteration; an arbitrary helper named `fields` must not silence GL003
    rep = lint(tmp_path, {"cfg.py": CFG_SRC, "session.py": """
        def fields(x):
            return x

        def config_fingerprint(cfg):
            return fields((cfg.corr_implementation, cfg.corr_levels))
    """})
    assert "GL003" in codes(rep)


def test_gl003_from_import_fields_alias_ok(tmp_path):
    rep = lint(tmp_path, {"cfg.py": CFG_SRC, "session.py": """
        from dataclasses import fields as dc_fields

        def config_fingerprint(cfg):
            return tuple((f.name, getattr(cfg, f.name))
                         for f in dc_fields(cfg))
    """})
    assert rep.ok


# ---------------------------------------------------------------------------
# GL004 — lock discipline
# ---------------------------------------------------------------------------

def test_gl004_half_guarded_attr_flagged(tmp_path):
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._metrics = {}

            def ok(self):
                with self._lock:
                    self._metrics["a"] = 1

            def racy(self):
                self._metrics["b"] = 2
    """})
    assert codes(rep) == ["GL004"]
    assert "racy" in rep.findings[0].message


def test_gl004_all_guarded_ok(tmp_path):
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = {}

            def a(self):
                with self._lock:
                    self._q["a"] = 1

            def b(self):
                with self._lock:
                    self._q.pop("a", None)
    """})
    assert rep.ok


def test_gl004_init_mutation_exempt(tmp_path):
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = {}
                self._q["seed"] = 1

            def a(self):
                with self._lock:
                    self._q["a"] = 1
    """})
    assert rep.ok


def test_gl004_never_guarded_attr_ignored(tmp_path):
    # an attribute with no guarded site anywhere is not this bug class
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                self.count += 1
    """})
    assert rep.ok


def test_gl004_mutator_call_outside_lock(tmp_path):
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def a(self):
                with self._lock:
                    self.items.append(1)

            def b(self):
                self.items.clear()
    """})
    assert codes(rep) == ["GL004"]


def test_gl004_no_common_lock_flagged(tmp_path):
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.q = {}

            def m1(self):
                with self._a:
                    self.q["x"] = 1

            def m2(self):
                with self._b:
                    self.q["y"] = 2
    """})
    assert codes(rep) == ["GL004"]
    assert "no common lock" in rep.findings[0].message


def test_gl004_nested_lock_shares_common(tmp_path):
    # session.py's real pattern: _estimates popped under _cache_lock AND
    # _est_lock nested; common lock with _record_time's bare _est_lock
    rep = lint(tmp_path, {"serve/s.py": """
        import threading

        class S:
            def __init__(self):
                self._cache_lock = threading.Lock()
                self._est_lock = threading.Lock()
                self._est = {}

            def record(self, k, v):
                with self._est_lock:
                    self._est[k] = v

            def evict(self, k):
                with self._cache_lock:
                    with self._est_lock:
                        self._est.pop(k, None)
    """})
    assert rep.ok


# ---------------------------------------------------------------------------
# GL005 — trace purity
# ---------------------------------------------------------------------------

def test_gl005_time_in_jitted_fn(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import time
        import jax

        @jax.jit
        def fwd(x):
            t0 = time.time()
            return x + t0
    """})
    assert codes(rep) == ["GL005"]


def test_gl005_env_read_in_scan_body(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import os
        from jax import lax

        def forward(xs):
            def step(carry, x):
                if os.environ.get("DEBUG"):
                    x = x * 2
                return carry, x
            return lax.scan(step, 0, xs)
    """})
    assert codes(rep) == ["GL005"]


def test_gl005_np_random_in_pallas_kernel(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import numpy as np
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] + np.random.rand()

        def run(x):
            return pl.pallas_call(kernel)(x)
    """})
    # GL006 also fires (unregistered pallas module) — expected here
    assert "GL005" in codes(rep)


def test_gl005_global_and_module_dict_mutation(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import jax

        _CACHE = {}
        _COUNT = 0

        @jax.jit
        def fwd(x):
            global _COUNT
            _COUNT += 1
            _CACHE["last"] = x
            return x
    """})
    assert "GL005" in codes(rep) and len(codes(rep)) >= 2


def test_gl005_pure_traced_fn_ok(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def fwd(x):
            return jnp.sum(x * 2)

        def forward(xs):
            def step(c, x):
                return c + x, c
            return lax.scan(step, 0, xs)
    """})
    assert rep.ok


def test_gl005_impure_untraced_fn_ok(tmp_path):
    # trace-time-by-design helpers (corr_tile, ENABLE) live OUTSIDE the
    # traced closure — the checker must not chase the call graph
    rep = lint(tmp_path, {"m.py": """
        import os
        import time

        def build_fn():
            tile = int(os.environ.get("RAFT_TILE", 256))
            t0 = time.time()
            return tile, t0
    """})
    assert rep.ok


def test_gl005_jit_called_form_and_partial(tmp_path):
    rep = lint(tmp_path, {"m.py": """
        import functools
        import time
        import jax

        def fwd(x):
            return x + time.time()

        f = jax.jit(fwd)

        @functools.partial(jax.jit, static_argnums=0)
        def g(n, x):
            return x * time.perf_counter()
    """})
    assert codes(rep) == ["GL005", "GL005"]


def test_gl005_untraced_namesake_not_flagged(tmp_path):
    # a host-side helper sharing a traced closure's name must not be
    # flagged: name resolution follows Python scoping at the call site
    rep = lint(tmp_path, {"m.py": """
        import time
        from jax import lax

        def forward(xs):
            def step(c, x):
                return c + x, c
            return lax.scan(step, 0, xs)

        def step(label):
            return time.perf_counter(), label
    """})
    assert rep.ok


def test_env_write_is_not_a_read(tmp_path):
    # os.environ["RAFT_X"] = "1" is a WRITE — no GL001/GL002
    rep = lint(tmp_path, {"ops/m.py": """
        import os
        os.environ["RAFT_DEBUG_DUMP"] = "1"

        def clear():
            del os.environ["RAFT_DEBUG_DUMP"]
    """}, knobs=())
    assert rep.ok


# ---------------------------------------------------------------------------
# GL006 — kill-switch coverage
# ---------------------------------------------------------------------------

def _gl006(rep):
    return [f for f in rep.findings if f.code == "GL006"]


def test_gl006_unregistered_pallas_module(tmp_path):
    rep = lint(tmp_path, {"ops/newkern.py": KERNEL_SRC,
                          "serve/guard.py": GUARD_SRC},
               knobs=("RAFT_MYKERN",), kernel_entries={})
    assert [f.path for f in _gl006(rep)] == ["ops/newkern.py"]


def test_gl006_registered_module_ok(tmp_path):
    rep = lint(tmp_path, {"ops/newkern.py": KERNEL_SRC,
                          "serve/guard.py": GUARD_SRC,
                          "cfg.py": CFG_SRC},
               knobs=("RAFT_MYKERN",),
               kernel_entries={"ops/newkern.py":
                               knobs.KernelEntry(rungs=("my_kernel",))})
    assert not _gl006(rep) and rep.ok


def test_gl006_unknown_rung_flagged(tmp_path):
    rep = lint(tmp_path, {"ops/newkern.py": KERNEL_SRC,
                          "serve/guard.py": GUARD_SRC},
               knobs=("RAFT_MYKERN",),
               kernel_entries={"ops/newkern.py":
                               knobs.KernelEntry(rungs=("renamed_rung",))})
    hits = _gl006(rep)
    assert hits and "renamed_rung" in hits[0].message


def test_gl006_switch_never_read_flagged(tmp_path):
    src = KERNEL_SRC.replace('os.environ.get("RAFT_MYKERN", "1") != "0"',
                             "True")
    rep = lint(tmp_path, {"ops/newkern.py": src,
                          "serve/guard.py": GUARD_SRC},
               knobs=("RAFT_MYKERN",),
               kernel_entries={"ops/newkern.py":
                               knobs.KernelEntry(rungs=("my_kernel",))})
    hits = _gl006(rep)
    assert hits and "RAFT_MYKERN" in hits[0].message


def test_gl006_cfg_rung_field_must_exist(tmp_path):
    rep = lint(tmp_path, {"ops/newkern.py": KERNEL_SRC,
                          "serve/guard.py": GUARD_SRC,
                          "cfg.py": CFG_SRC.replace(
                               "fused_update", "renamed_field")},
               knobs=("RAFT_MYKERN",),
               kernel_entries={"ops/newkern.py":
                               knobs.KernelEntry(
                                   rungs=("my_kernel", "cfg_rung"))})
    hits = _gl006(rep)
    assert hits and "fused_update" in hits[0].message


def test_gl006_exempt_module_ok(tmp_path):
    src = KERNEL_SRC.replace('os.environ.get("RAFT_MYKERN", "1") != "0"',
                             "True")
    rep = lint(tmp_path, {"ops/newkern.py": src,
                          "serve/guard.py": GUARD_SRC},
               knobs=(), kernel_entries={
                   "ops/newkern.py": knobs.KernelEntry(
                       exempt="debug-only kernel, never served")})
    assert not _gl006(rep)


def test_gl006_suffix_match_is_segment_bounded(tmp_path):
    # 'xcorr/pallas_reg.py' must NOT inherit the 'corr/pallas_reg.py'
    # registry entry — it needs its own declaration
    rep = lint(tmp_path, {"xcorr/pallas_reg.py": KERNEL_SRC,
                          "serve/guard.py": GUARD_SRC},
               knobs=("RAFT_MYKERN",),
               kernel_entries={"corr/pallas_reg.py":
                               knobs.KernelEntry(rungs=("my_kernel",))})
    hits = _gl006(rep)
    assert hits and "no entry" in hits[0].message


def test_gl006_stale_entry_flagged(tmp_path):
    rep = lint(tmp_path, {"ops/nokernel.py": "X = 1\n",
                          "serve/guard.py": GUARD_SRC},
               kernel_entries={"ops/nokernel.py":
                               knobs.KernelEntry(rungs=("my_kernel",))})
    hits = _gl006(rep)
    assert hits and "stale" in hits[0].message


def test_gl006_real_tree_dropped_entry_fails():
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = dict(knobs.KERNEL_ENTRY_POINTS)
    del reduced["ops/pallas_encoder.py"]
    rep = run_checkers(Project(files, kernel_entries=reduced))
    hits = [f for f in rep.findings if f.code == "GL006"]
    assert hits and hits[0].path.endswith("ops/pallas_encoder.py")


# ---------------------------------------------------------------------------
# the real tree: clean, and NOT vacuously so
# ---------------------------------------------------------------------------

def test_real_tree_zero_unsuppressed_findings():
    """Tier-1 gate: the package linted with the real registries is clean.
    Any new finding must be fixed or suppressed-with-reason in the same
    change that introduces it."""
    rep = run_analysis([str(PACKAGE)], base=str(REPO))
    assert rep.findings == [], "\n" + rep.render_text()


def test_real_tree_checks_are_not_vacuous():
    """Guard the guards: the cross-file context every checker needs must
    actually resolve on the real tree — a refactor that silently breaks
    extraction (e.g. DEFAULT_LADDER becoming unparseable to the linter)
    would otherwise turn GL003/GL005/GL006 into no-ops."""
    from raft_stereo_tpu.analysis.checkers.gl005_trace_purity import \
        _traced_functions
    from raft_stereo_tpu.analysis.checkers.gl006_kill_switch import \
        _pallas_calls
    files = collect_files([str(PACKAGE)], base=str(REPO))
    proj = Project(files)
    ladder = proj.ladder()
    assert ladder is not None and len(ladder) == 10
    assert {r.name for r in ladder} >= {"corr_kernel", "fused_update"}
    fields = proj.config_fields()
    assert fields is not None and "corr_implementation" in fields
    traced = sum(len(_traced_functions(sf)) for sf in files
                 if sf.tree is not None)
    assert traced >= 5  # session closures, eval/train steps, scan bodies
    pallas_modules = [sf.relpath for sf in files
                      if sf.tree is not None and _pallas_calls(sf)]
    assert sorted(pallas_modules) == [
        "raft_stereo_tpu/corr/pallas_alt.py",
        "raft_stereo_tpu/corr/pallas_reg.py",
        "raft_stereo_tpu/ops/pallas_encoder.py",
        "raft_stereo_tpu/ops/pallas_resident.py",
        "raft_stereo_tpu/ops/pallas_stream.py",
    ]


def test_registry_is_single_source_of_truth():
    """session.py and guard.py consume analysis/knobs.py, and every
    env-var ladder rung is registered (the three-hand-synced-lists
    failure mode this PR removes)."""
    from raft_stereo_tpu.serve import guard, session
    assert session._ENV_KNOBS is knobs.ENV_KNOBS
    for p in guard.DEFAULT_LADDER:
        if p.env_var is not None:
            assert p.env_var in knobs.ENV_KNOBS, p.name


# ---------------------------------------------------------------------------
# CLI + scripts wiring
# ---------------------------------------------------------------------------

def _run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "raft_stereo_tpu.analysis",
                           *args], cwd=str(cwd), capture_output=True,
                          text=True)


def test_cli_clean_tree_exits_zero():
    res = _run_cli([str(PACKAGE)])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 finding(s)" in res.stdout


def test_cli_violation_exits_one_and_json(tmp_path):
    bad = tmp_path / "ops"
    bad.mkdir()
    (bad / "k.py").write_text(
        'import os\nE = os.environ.get("RAFT_X", "1")\n')
    res = _run_cli(["--json", str(tmp_path)])
    assert res.returncode == 1
    payload = json.loads(res.stdout)
    assert payload["ok"] is False
    assert [f["code"] for f in payload["findings"]] == ["GL001", "GL002"]


def test_cli_list_checkers():
    res = _run_cli(["--list-checkers"])
    assert res.returncode == 0
    for code in ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006"):
        assert code in res.stdout


def test_cli_nonexistent_path_is_usage_error():
    res = _run_cli(["/nonexistent/never"])
    assert res.returncode == 2


def test_lint_sh_clean_and_injected_violation(tmp_path):
    """The release-gate step: scripts/lint.sh is clean on the real tree
    and exits nonzero on an injected violation (acceptance criterion)."""
    script = REPO / "scripts" / "lint.sh"
    res = subprocess.run(["bash", str(script)], cwd=str(REPO),
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    bad = tmp_path / "serve"
    bad.mkdir()
    (bad / "racy.py").write_text(textwrap.dedent("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = {}

            def a(self):
                with self._lock:
                    self.q["a"] = 1

            def b(self):
                self.q["b"] = 2
    """))
    res = subprocess.run(["bash", str(script), str(tmp_path)],
                         cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 1
    assert "GL004" in res.stdout


def test_release_gate_runs_lint_step():
    gate = (REPO / "scripts" / "release_gate.sh").read_text()
    assert "lint.sh" in gate and "graftlint" in gate


def test_suite_inventory_pinned():
    """The three-suite inventory: GL 6 + GV 5 + GC 6, unique codes, all
    selectable.  A checker added or dropped without updating this pin
    (and the docs that enumerate the suites) fails here by name."""
    from raft_stereo_tpu.analysis.checkers import ALL_CHECKERS
    from raft_stereo_tpu.analysis.trace.checkers import ALL_TRACE_CHECKERS
    from raft_stereo_tpu.analysis.concurrency.checkers import \
        ALL_CONCURRENCY_CHECKERS
    gl = [c.code for c in ALL_CHECKERS]
    gv = [c.code for c in ALL_TRACE_CHECKERS]
    gc = [c.code for c in ALL_CONCURRENCY_CHECKERS]
    assert gl == [f"GL00{i}" for i in range(1, 7)]
    assert gv == [f"GV10{i}" for i in range(1, 6)]
    assert gc == [f"GC20{i}" for i in range(1, 7)]
    all_codes = gl + gv + gc
    assert len(all_codes) == len(set(all_codes)) == 17
    res = _run_cli(["--list-checkers"])
    assert res.returncode == 0
    for code in all_codes:
        assert code in res.stdout


def test_gl002_real_tree_fleet_knob_registered():
    # RAFT_FLEET_RESTART_BUDGET (serve/fleet.py
    # resolve_fleet_restart_budget, the per-slot replacement allowance)
    # is covered by HOST_ENV_KNOBS; drop it and GL002 must fire at the
    # read site — the r20 fleet-supervisor knobs cannot silently drift
    # out of the registry (the drop leaves RAFT_FLEET_INSTANCES /
    # RAFT_FLEET_PROBE_MS / RAFT_FLEET_WARMUP_TIMEOUT_MS covered so the
    # hit is unambiguous).
    files = collect_files([str(PACKAGE)], base=str(REPO))
    reduced = tuple(k for k in knobs.SERVE_ENV_KNOBS + knobs.HOST_ENV_KNOBS
                    if k != "RAFT_FLEET_RESTART_BUDGET")
    rep = run_checkers(Project(files, serve_knobs=reduced))
    hits = [f for f in rep.findings if f.code == "GL002"]
    assert hits and "RAFT_FLEET_RESTART_BUDGET" in hits[0].message
    assert hits[0].path.endswith("serve/fleet.py")

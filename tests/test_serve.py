"""Serving-layer battery: segmented-scan equivalence, session bucketing +
compile-cache keying, circuit-breaker ladder, parity canary, deadline-aware
degradation, queue backpressure, and the fault-storm acceptance run.

Everything runs on CPU with a tiny model config; every fault is injected
through an explicit ``ServeFaultPlan`` (deterministic ordinals, FakeClock
deadlines — zero real sleeping in the deadline tests).
"""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import (FakeClock, ServeFaultPlan, ServeFaults,
                                    malformed_pairs)
from raft_stereo_tpu.models import (init_raft_stereo, raft_stereo_forward,
                                    raft_stereo_inference,
                                    raft_stereo_prepare, raft_stereo_segment)
from raft_stereo_tpu.ops.padder import InputPadder, bucket_shape
from raft_stereo_tpu.serve import (DeadlineExceeded, InferenceFailed,
                                   InferenceSession, InputRejected,
                                   ServiceConfig, SessionConfig,
                                   StereoService)

pytestmark = pytest.mark.serve

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # deliberately not multiples of 32: bucketing must pad


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(0)
    return (rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (H, W, 3)).astype(np.float32))


def make_session(params, cfg, *, valid_iters=4, segments=2, plan=None,
                 clock=None, **kw):
    scfg = SessionConfig(valid_iters=valid_iters, segments=segments,
                         canary=kw.pop("canary", False), **kw)
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=clock or FakeClock())


@pytest.fixture(scope="module")
def clean_session(tiny_params, tiny_cfg):
    """Shared fault-free session for the read-only tests; warms its one
    bucket (full + segmented programs) at construction."""
    return make_session(tiny_params, tiny_cfg, valid_iters=4, segments=2,
                        warmup_shapes=((H, W),), warmup_segmented=True)


# ---------------------------------------------------------------------------
# Anytime property: segmented scan == single scan, bit for bit.


def test_segments_compose_bit_identical(tiny_params, tiny_cfg, pair):
    """k segments of m iters from carried (net, coords1) state == one
    k*m-iter scan — the invariant deadline degradation stands on."""
    cfg = tiny_cfg
    i1, i2 = (x[None] for x in pair)
    prep = jax.jit(lambda p, a, b: raft_stereo_prepare(p, cfg, a, b))
    seg = {m: jax.jit(
        lambda p, s, m=m: raft_stereo_segment(p, cfg, s, iters=m))
        for m in (2, 4)}
    state0 = prep(tiny_params, i1, i2)

    _, low_a, up_a = seg[4](tiny_params, state0)
    state = state0
    for _ in range(2):
        state, low_b, up_b = seg[2](tiny_params, state)

    assert np.asarray(up_a).tobytes() == np.asarray(up_b).tobytes()
    assert np.asarray(low_a).tobytes() == np.asarray(low_b).tobytes()


def test_inference_segments_matches_single_scan(tiny_params, tiny_cfg, pair):
    """raft_stereo_inference(segments=k) == the test-mode forward."""
    cfg = tiny_cfg
    i1, i2 = (x[None] for x in pair)
    low_ref, up_ref = jax.jit(
        lambda p, a, b: raft_stereo_forward(p, cfg, a, b, iters=4,
                                            test_mode=True))(
        tiny_params, i1, i2)
    low_seg, up_seg = jax.jit(
        lambda p, a, b: raft_stereo_inference(p, cfg, a, b, iters=4,
                                              segments=2))(
        tiny_params, i1, i2)
    assert np.asarray(up_seg).tobytes() == np.asarray(up_ref).tobytes()
    assert np.asarray(low_seg).tobytes() == np.asarray(low_ref).tobytes()


def test_inference_rejects_bad_segmenting(tiny_params, tiny_cfg, pair):
    with pytest.raises(ValueError, match="divisible"):
        raft_stereo_inference(tiny_params, tiny_cfg, pair[0][None],
                              pair[1][None], iters=4, segments=3)
    with pytest.raises(ValueError, match="segments"):
        raft_stereo_inference(tiny_params, tiny_cfg, pair[0][None],
                              pair[1][None], iters=4, segments=0)


# ---------------------------------------------------------------------------
# Session: bucketing, output contract, eval-path equivalence.


def test_session_serves_full_quality(clean_session, pair):
    # warmup compiled the bucket's programs at construction: full,
    # prepare, segment — plus the half bucket's prepare/segment (the
    # degrade policy only routes half_res onto warm programs) — plus,
    # since graftstream (r17), the b=1 streaming trio
    # prepare_warm/advance/epilogue (stream_infer must never pay a
    # compile mid-request on a warmed bucket)
    warm_compiles = clean_session.metrics()["compiles"]
    assert warm_compiles == 8
    res = clean_session.infer(*pair)
    assert res.quality == "full" and not res.degraded
    assert res.iters == 4
    assert res.disparity.shape == (H, W)
    assert np.isfinite(res.disparity).all()
    assert res.padded_shape == (64, 64)  # 40x60 bucketed to /32
    # a warmed bucket pays zero compiles at request time
    assert clean_session.metrics()["compiles"] == warm_compiles


def test_session_matches_eval_forward_bytes(clean_session, tiny_params,
                                            tiny_cfg, pair):
    """The session's single-scan path is byte-identical to the program
    engine/evaluate.make_eval_forward (and hence demo.py) compiles."""
    from raft_stereo_tpu.engine.evaluate import make_eval_forward
    forward = make_eval_forward(tiny_params, tiny_cfg, 4)
    i1, i2 = (x[None] for x in pair)
    padder = InputPadder(i1.shape, divis_by=32)
    p1, p2 = padder.pad_np(i1, i2)
    flow_up, _ = forward(p1, p2)
    ref = padder.unpad_np(np.asarray(flow_up))[0, ..., 0]
    res = clean_session.infer(*pair)
    assert (-res.disparity).tobytes() == ref.tobytes()


def test_session_deadline_path_bit_identical_when_unconstrained(
        clean_session, pair):
    """A generous deadline runs all segments — same bytes as the full
    single-scan path (the anytime split is free of quality cost)."""
    ref = clean_session.infer(*pair)
    res = clean_session.infer(*pair, budget_s=1e6)
    assert res.quality == "full"
    assert res.disparity.tobytes() == ref.disparity.tobytes()


def test_session_accepts_batched_and_unbatched(clean_session, pair):
    a = clean_session.infer(pair[0], pair[1])
    b = clean_session.infer(pair[0][None], pair[1][None])
    assert a.disparity.tobytes() == b.disparity.tobytes()


# ---------------------------------------------------------------------------
# Admission control.


def test_malformed_inputs_rejected(clean_session):
    cases = malformed_pairs(
        h=H, w=W,
        oversize_pixels=clean_session.cfg.admission.max_pixels)
    expected_codes = {
        "nan_pixels": "nonfinite_input",
        "inf_pixels": "nonfinite_input",
        "five_channel": "bad_channels",
        "zero_area": "zero_area",
        "mismatched_shapes": "shape_mismatch",
        "wrong_rank": "wrong_rank",
        # np.asarray converts the nested list to a rank-2 numeric array
        "not_an_array": "wrong_rank",
        "oversized": "too_large",
    }
    for name, (left, right) in cases.items():
        with pytest.raises(InputRejected) as ei:
            clean_session.infer(left, right)
        assert ei.value.code == expected_codes[name], name


def test_nonfinite_output_is_structured_error(tiny_params, tiny_cfg, pair):
    """A silently-corrupted kernel output (injected NaN) must become a
    structured InferenceFailed, never a served frame."""
    sess = make_session(tiny_params, tiny_cfg,
                        plan=ServeFaultPlan(poison_outputs=(0,)))
    with pytest.raises(InferenceFailed) as ei:
        sess.infer(*pair)
    assert ei.value.code == "nonfinite_output"
    assert sess.metrics()["nonfinite_outputs"] == 1
    # the program is fine; the next (unpoisoned) request serves
    res = sess.infer(*pair)
    assert res.quality == "full"


# ---------------------------------------------------------------------------
# Compile cache: keying, LRU bound, per-bucket locks.


def test_cache_key_covers_every_config_field(tiny_params, tiny_cfg):
    """Regression (the bug class this PR fixes): sessions differing ONLY
    in corr_implementation must never share a compiled program."""
    alt_cfg = RAFTStereoConfig(**{**TINY, "corr_implementation": "alt"})
    s_reg = make_session(tiny_params, tiny_cfg)
    s_alt = make_session(tiny_params, alt_cfg)
    k_reg = s_reg.cache_key("full", 64, 64, 4)
    k_alt = s_alt.cache_key("full", 64, 64, 4)
    assert k_reg != k_alt
    # a kernel env switch is snapshotted at session construction: a
    # session built under the flipped switch keys differently, while an
    # existing session's keys stay stable (concurrent traces temporarily
    # mutate the process env — live reads would bleed across threads)
    import os
    os.environ["RAFT_FUSE_GRU1632"] = "0"
    try:
        s_flip = make_session(tiny_params, tiny_cfg)
        assert s_flip.cache_key("full", 64, 64, 4) != k_reg
        assert s_reg.cache_key("full", 64, 64, 4) == k_reg
    finally:
        del os.environ["RAFT_FUSE_GRU1632"]
    # mixed_precision / fused flags / iters segmenting all key distinctly
    mp_cfg = RAFTStereoConfig(**{**TINY, "mixed_precision": True})
    assert (make_session(tiny_params, mp_cfg).cache_key("full", 64, 64, 4)
            != k_reg)
    assert s_reg.cache_key("segment", 64, 64, 2) != \
        s_reg.cache_key("segment", 64, 64, 4)


def test_breaker_trip_rekeys_cache(tiny_params, tiny_cfg, pair):
    """After an EFFECTIVE trip the old fast-path program is unreachable —
    the same bucket compiles a fresh program under the new fingerprint.
    A projection no-op trip (corr_kernel on an already-XLA corr) keys
    identically: the program is the same program, so it is shared."""
    sess = make_session(tiny_params, tiny_cfg)
    sess.infer(*pair)
    before = sess.metrics()["compiles"]
    key_before = sess.cache_key("full", 64, 64, 4)
    sess.breaker.trip("corr_kernel", "manual")  # reg -> reg: no-op
    sess._rebuild("test")
    assert sess.cache_key("full", 64, 64, 4) == key_before
    sess.breaker.trip("fuse_gru1632", "manual")  # env switch: effective
    sess._rebuild("test")
    assert sess.cache_key("full", 64, 64, 4) != key_before
    sess.infer(*pair)
    assert sess.metrics()["compiles"] == before + 1


@pytest.mark.slow  # 3 compiles; release_gate's serve step still runs it
def test_lru_bound_evicts(tiny_params, tiny_cfg):
    rng = np.random.default_rng(1)
    sess = make_session(tiny_params, tiny_cfg, max_programs=2)
    for (h, w) in ((32, 32), (32, 64), (64, 32)):
        img = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        sess.infer(img, img)
    m = sess.metrics()
    assert m["compiles"] == 3
    assert m["evictions"] == 1
    assert len(sess._cache) == 2


def test_concurrent_first_requests_compile_once(tiny_params, tiny_cfg, pair):
    """Per-bucket compile locks: two racing first requests, one compile."""
    sess = make_session(tiny_params, tiny_cfg,
                        plan=ServeFaultPlan(slow_builds={0: 0.3}))
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [ex.submit(sess.infer, *pair) for _ in range(2)]
        results = [f.result() for f in futs]
    assert sess.metrics()["compiles"] == 1
    assert results[0].disparity.tobytes() == results[1].disparity.tobytes()


# ---------------------------------------------------------------------------
# Circuit breaker.


LADDER_NAMES = ("fuse_iter", "lane_pack8", "corr_pack8", "stream_batch",
                "fuse_gru1632", "stream_tail", "packed_l2", "corr_kernel",
                "fused_encoders", "fused_update")


def test_breaker_walks_ladder_to_plain_xla(tiny_params, pair):
    """Repeated unattributable compile failures trip every rung in order;
    the plain-XLA rebuild serves the request that triggered the walk."""
    cfg = RAFTStereoConfig(**{**TINY, "corr_implementation": "reg_tpu"})
    plan = ServeFaultPlan(compile_errors={
        i: ("mosaic" if i == 3 else "oom") for i in range(len(LADDER_NAMES))})
    sess = make_session(tiny_params, cfg, plan=plan)
    res = sess.infer(*pair)
    assert res.quality == "full"
    assert sess.breaker.tripped_names == LADDER_NAMES
    assert sess.breaker.exhausted
    assert sess._run_cfg.corr_implementation == "reg"  # XLA twin
    assert sess._run_cfg.fused_update is False
    # every env-switched rung is exported off for subsequent traces
    assert sess._env == {"RAFT_FUSE_ITER": "0", "RAFT_LANE_PACK8": "0",
                         "RAFT_CORR_PACK8": "0", "RAFT_STREAM_BATCH": "0",
                         "RAFT_FUSE_GRU1632": "0", "RAFT_STREAM_TAIL": "0",
                         "RAFT_PACKED_L2": "0", "RAFT_FUSED_ENCODERS": "0"}
    st = sess.breaker.status()
    assert st["trip_count"] == len(LADDER_NAMES) and st["exhausted"]
    assert all(r["reason"] == "compile_failure"
               for r in st["tripped"].values())


def test_breaker_matcher_targets_rung(tiny_params, tiny_cfg, pair):
    plan = ServeFaultPlan(compile_errors={0: "mosaic:stream_tail raw1 pass"})
    sess = make_session(tiny_params, tiny_cfg, plan=plan)
    sess.infer(*pair)
    assert sess.breaker.tripped_names == ("stream_tail",)


def test_breaker_exhaustion_is_structured(tiny_params, tiny_cfg, pair):
    """Failures past the bottom rung surface as ladder_exhausted."""
    # ordinals 0..len-1 trip every rung; the next ordinal fails the
    # plain-XLA build itself -> ladder_exhausted. Later ordinals are clean.
    plan = ServeFaultPlan(
        compile_errors={i: "oom" for i in range(len(LADDER_NAMES) + 1)})
    sess = make_session(tiny_params, tiny_cfg, plan=plan)
    with pytest.raises(InferenceFailed) as ei:
        sess.infer(*pair)
    assert ei.value.code == "ladder_exhausted"
    # the injector budget is spent: the session recovers on retry
    res = sess.infer(*pair)
    assert res.quality == "full"


def _quant_armed() -> bool:
    """True when a pack8 opt-in is armed in the surrounding env (the
    release gate's double-armed storm). The two canary-attribution tests
    below pin trip attribution under the premise that every fast path is
    IN-BAND vs plain XLA — at this suite's random weights the armed
    quantization legitimately drifts ~3.5 px out of the canary band
    (the corr_pack8 precedent: op-level budgets are pinned, end-to-end
    protection at deployment weights IS the canary mechanism), so the
    canary rightly trips the quantization rungs and the premise fails."""
    import os
    return any(
        os.environ.get(v, "0").strip().lower() in ("1", "true", "yes", "on")
        for v in ("RAFT_CORR_PACK8", "RAFT_LANE_PACK8"))


@pytest.mark.skipif(_quant_armed(), reason="canary attribution pins need "
                    "in-band fast paths; armed pack8 at random weights is "
                    "out of band by design")
def test_canary_catches_corrupted_kernel_output(tiny_params, tiny_cfg):
    """Startup canary vs plain XLA: a poisoned fast-path forward trips a
    rung and the rebuilt session comes up serving."""
    plan = ServeFaultPlan(poison_outputs=(0,))
    sess = make_session(tiny_params, tiny_cfg, plan=plan, canary=True,
                        canary_shape=(32, 48), canary_iters=2)
    assert sess._canary_state == {
        "enabled": True, "ran": True, "passed": True, "attempts": 2}
    # An unattributable canary mismatch trips the FIRST untripped rung
    # in ladder order — fuse_iter since r19 led the ladder.
    assert sess.breaker.tripped_names == ("fuse_iter",)
    assert sess.breaker.status()["tripped"]["fuse_iter"]["reason"] == \
        "canary_mismatch"


@pytest.mark.slow  # release_gate's serve step still runs it
@pytest.mark.skipif(_quant_armed(), reason="canary attribution pins need "
                    "in-band fast paths; armed pack8 at random weights is "
                    "out of band by design")
def test_canary_clean_pass_no_trips(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg, canary=True,
                        canary_shape=(32, 48), canary_iters=2)
    assert sess._canary_state["passed"] is True
    assert sess.breaker.trip_count == 0


# ---------------------------------------------------------------------------
# Deadline-aware degradation.


def test_deadline_reduced_iters(tiny_params, tiny_cfg, pair):
    """Budget expires mid-scan -> best-so-far with an honest label."""
    clk = FakeClock()
    # ordinal 0 = prepare, 1 = first segment (injected 50 fake-seconds)
    sess = make_session(tiny_params, tiny_cfg, clock=clk,
                        plan=ServeFaultPlan(slow_forwards={1: 50.0}))
    res = sess.infer(*pair, budget_s=10.0)
    assert res.quality == "reduced_iters:2"
    assert res.iters == 2
    assert res.deadline_missed  # the first segment alone overran
    assert np.isfinite(res.disparity).all()
    assert sess.metrics()["degraded"] == 1


def test_deadline_stops_before_overrunning_segment(tiny_params, tiny_cfg,
                                                   pair):
    """With a recorded estimate, the policy stops EARLY (no overrun) and
    deadline_missed stays False."""
    clk = FakeClock()
    # segment invocations cost 30 fake-seconds (ordinals 0 and 3 are the
    # two requests' prepare calls — instant)
    sess = make_session(tiny_params, tiny_cfg, clock=clk,
                        plan=ServeFaultPlan(
                            slow_forwards={1: 30.0, 2: 30.0, 4: 30.0}))
    sess.infer(*pair, budget_s=1000.0)        # seeds the segment EMA
    res = sess.infer(*pair, budget_s=45.0)    # fits one segment, not two
    assert res.quality == "reduced_iters:2"
    assert not res.deadline_missed


def test_deadline_half_res(tiny_params, tiny_cfg, pair):
    """When the EMAs prove even one full-res segment cannot fit — and the
    half-res programs are already warm — the pair runs at half resolution
    and is labeled half_res."""
    clk = FakeClock()
    # Construction warms full + half buckets (invocation ordinals 0-4;
    # warming runs are deliberately NOT recorded into the EMAs — they
    # carry compile time in production). Request 0 (ordinals 8-10 — the
    # r17 warmup adds prepare_warm/advance/epilogue runs at 5-7 — each
    # slowed 40 fake-seconds) seeds the full-res prepare/segment EMAs;
    # the half-res programs stay instant.
    sess = make_session(tiny_params, tiny_cfg, clock=clk,
                        warmup_shapes=((H, W),), warmup_segmented=True,
                        plan=ServeFaultPlan(
                            slow_forwards={8: 40.0, 9: 40.0, 10: 40.0}))
    seed = sess.infer(*pair, budget_s=1e6)   # seeds prep=40, seg=40
    assert seed.quality == "full"
    res = sess.infer(*pair, budget_s=20.0)
    assert res.quality == "half_res"
    assert res.degraded
    assert res.disparity.shape == (H, W)      # restored to input geometry
    assert np.isfinite(res.disparity).all()

    # half-res disabled: same budget falls back to reduced iterations
    res2 = sess.infer(*pair, budget_s=20.0, allow_half_res=False)
    assert res2.quality.startswith("reduced_iters:")

    # a session whose half bucket was never warmed refuses the half-res
    # route (a cold compile would dwarf any budget) and reduces instead
    cold = make_session(tiny_params, tiny_cfg, clock=FakeClock(),
                        plan=ServeFaultPlan(
                            slow_forwards={2: 40.0, 3: 40.0,
                                           4: 40.0, 5: 40.0}))
    cold.infer(*pair, budget_s=1e6)           # warm full bucket (0-2)
    cold.infer(*pair, budget_s=1e6)           # seed full-res EMAs (3-5)
    res3 = cold.infer(*pair, budget_s=20.0)
    assert res3.quality.startswith("reduced_iters:")


def test_deadline_already_expired(clean_session, pair):
    with pytest.raises(DeadlineExceeded):
        clean_session.infer(*pair, budget_s=-1.0)


# ---------------------------------------------------------------------------
# Service: queue, backpressure, health.


def test_service_ok_and_health(tiny_params, tiny_cfg, pair):
    sess = make_session(tiny_params, tiny_cfg)
    with StereoService(sess, ServiceConfig(max_queue=4, workers=1)) as svc:
        resp = svc.submit({"id": "r1", "left": pair[0],
                           "right": pair[1]}).result()
    assert resp["status"] == "ok" and resp["id"] == "r1"
    assert resp["quality"] == "full"
    assert np.isfinite(resp["disparity"]).all()
    st = svc.status()
    assert st["requests"]["ok"] == 1
    assert st["latency_ms"]["n"] == 1
    assert st["session"]["breaker"]["trip_count"] == 0


def test_service_rejects_malformed_before_queueing(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg)
    bad = malformed_pairs(h=H, w=W)["five_channel"]
    svc = StereoService(sess)  # not started: validation is synchronous
    resp = svc.submit({"left": bad[0], "right": bad[1]}).result()
    assert resp["status"] == "rejected"
    assert resp["code"] == "invalid_input:bad_channels"
    assert sess.metrics()["compiles"] == 0  # never touched a device


@pytest.mark.slow  # spawns a worker; release_gate's serve step runs it
def test_service_queue_full_backpressure(tiny_params, tiny_cfg, pair):
    """One busy worker + depth-1 queue: the third concurrent request gets
    an immediate structured queue_full rejection."""
    import threading
    import time

    class GateClock:
        """Real monotonic clock whose injected 'slowness' blocks on an
        event the test releases — the worker is provably busy while the
        backpressure assertions run, with zero timing sensitivity."""

        def __init__(self):
            self.gate = threading.Event()

        @staticmethod
        def now():
            return time.monotonic()

        def sleep(self, _seconds):
            assert self.gate.wait(timeout=30)

    clk = GateClock()
    sess = make_session(tiny_params, tiny_cfg, clock=clk,
                        plan=ServeFaultPlan(
                            slow_forwards={1: 1.0, 2: 1.0}))
    sess.infer(*pair)  # pre-compile; consumes forward ordinal 0
    with StereoService(sess, ServiceConfig(max_queue=1, workers=1)) as svc:
        f1 = svc.submit({"id": 1, "left": pair[0], "right": pair[1]})
        # wait until the worker has f1's forward done and is parked in
        # the injected slowness (ordinal 1 consumed)
        for _ in range(3000):
            if sess.faults.forwards >= 2:
                break
            time.sleep(0.01)
        f2 = svc.submit({"id": 2, "left": pair[0], "right": pair[1]})
        f3 = svc.submit({"id": 3, "left": pair[0], "right": pair[1]})
        resp3 = f3.result(timeout=5)   # rejected synchronously at submit
        clk.gate.set()                 # release the worker
        statuses = {f.result(timeout=30)["id"]: f.result()
                    for f in (f1, f2)}
    assert resp3["status"] == "rejected"
    assert resp3["code"] == "queue_full"
    assert statuses[1]["status"] == "ok"
    assert statuses[2]["status"] == "ok"
    assert svc.status()["requests"]["rejected:queue_full"] == 1


@pytest.mark.slow  # spawns a worker; release_gate's serve step runs it
def test_service_stop_drains_queued_futures(tiny_params, tiny_cfg, pair):
    """stop() must resolve still-queued Futures with a structured
    rejection — an abandoned Future deadlocks its caller forever."""
    import threading
    import time

    class GateClock:
        def __init__(self):
            self.gate = threading.Event()

        @staticmethod
        def now():
            return time.monotonic()

        def sleep(self, _seconds):
            assert self.gate.wait(timeout=30)

    clk = GateClock()
    sess = make_session(tiny_params, tiny_cfg, clock=clk,
                        plan=ServeFaultPlan(slow_forwards={1: 1.0}))
    sess.infer(*pair)  # pre-compile; consumes forward ordinal 0
    svc = StereoService(sess, ServiceConfig(max_queue=4, workers=1)).start()
    f1 = svc.submit({"id": 1, "left": pair[0], "right": pair[1]})
    for _ in range(3000):  # worker parked in f1's injected slowness
        if sess.faults.forwards >= 2:
            break
        time.sleep(0.01)
    f2 = svc.submit({"id": 2, "left": pair[0], "right": pair[1]})
    clk.gate.set()
    stopper = threading.Thread(target=svc.stop)
    stopper.start()
    r2 = f2.result(timeout=30)
    stopper.join(timeout=30)
    assert f1.result(timeout=30)["status"] == "ok"
    # f2 either ran (worker dequeued it before exiting) or was drained
    # with the structured stop rejection — never left unresolved.
    assert r2["status"] in ("ok", "rejected")
    if r2["status"] == "rejected":
        assert r2["code"] == "service_stopped"


def test_service_deadline_expires_in_queue(tiny_params, tiny_cfg, pair):
    """A request whose deadline passes while queued is rejected on
    dequeue without touching the device."""
    clk = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, clock=clk)
    svc = StereoService(sess)
    req = {"left": pair[0], "right": pair[1], "deadline_ms": 1000.0}
    assert svc._admit(req) is None
    clk.sleep(2.0)  # deadline passes while "queued"
    resp = svc._respond(req)
    assert resp["status"] == "rejected"
    assert resp["code"] == "deadline_exceeded_in_queue"


# ---------------------------------------------------------------------------
# The fault storm (release-gate acceptance): compile failures + deadline
# overruns + malformed inputs interleaved into one request stream; the
# session must never crash, every response must be a valid labeled
# disparity or a structured rejection, and the breaker must end at plain
# XLA with all trips recorded.


def test_fault_storm(tiny_params):
    cfg = RAFTStereoConfig(**{**TINY, "corr_implementation": "reg_tpu"})
    clk = FakeClock()
    plan = ServeFaultPlan(
        # first builds: the first request's program walks the whole ladder
        compile_errors={i: ("mosaic" if i == 1 else "oom")
                        for i in range(len(LADDER_NAMES))},
        # ordinal 0: request 1's forward; 1-3: request 3's prepare/segments
        slow_forwards={2: 100.0},
    )
    sess = make_session(tiny_params, cfg, plan=plan, clock=clk)
    svc = StereoService(sess, ServiceConfig(max_queue=8, workers=1))
    rng = np.random.default_rng(3)

    def good():
        return (rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
                rng.uniform(0, 255, (H, W, 3)).astype(np.float32))

    bad = malformed_pairs(h=H, w=W)
    g1, g2, g3, g4 = good(), good(), good(), good()
    stream = [
        {"id": "ok-1", "left": g1[0], "right": g1[1]},
        {"id": "nan", "left": bad["nan_pixels"][0],
         "right": bad["nan_pixels"][1]},
        {"id": "deadline", "left": g2[0], "right": g2[1],
         "deadline_ms": 50_000.0},
        {"id": "channels", "left": bad["five_channel"][0],
         "right": bad["five_channel"][1]},
        {"id": "zero", "left": bad["zero_area"][0],
         "right": bad["zero_area"][1]},
        {"id": "ok-2", "left": g3[0], "right": g3[1]},
        {"id": "mismatch", "left": bad["mismatched_shapes"][0],
         "right": bad["mismatched_shapes"][1]},
        {"id": "deadline-2", "left": g4[0], "right": g4[1],
         "deadline_ms": 1e9},
    ]
    responses = {r["id"]: svc.handle(r) for r in stream}

    # zero crashes: every response is structured
    assert all(r["status"] in ("ok", "rejected", "error")
               for r in responses.values())
    # honest quality labels on every served frame
    assert responses["ok-1"]["status"] == "ok"
    assert responses["ok-1"]["quality"] == "full"
    assert responses["deadline"]["status"] == "ok"
    assert responses["deadline"]["quality"] == "reduced_iters:2"
    assert responses["deadline"]["deadline_missed"]
    assert responses["ok-2"]["quality"] == "full"
    assert responses["deadline-2"]["quality"] == "full"
    for rid in ("ok-1", "deadline", "ok-2", "deadline-2"):
        assert np.isfinite(responses[rid]["disparity"]).all()
    # structured rejections with the right codes
    assert responses["nan"]["code"] == "invalid_input:nonfinite_input"
    assert responses["channels"]["code"] == "invalid_input:bad_channels"
    assert responses["zero"]["code"] == "invalid_input:zero_area"
    assert responses["mismatch"]["code"] == "invalid_input:shape_mismatch"
    # the breaker ladder ended at plain XLA with all trips recorded
    assert sess.breaker.exhausted
    assert sess.breaker.tripped_names == LADDER_NAMES
    assert sess._run_cfg.corr_implementation == "reg"
    assert sess._run_cfg.fused_update is False
    # health reflects the storm
    st = svc.status()
    assert st["requests"]["ok"] == 4
    assert st["requests"]["degraded"] == 1
    assert st["session"]["breaker"]["trip_count"] == len(LADDER_NAMES)
    assert st["session"]["counts"]["requests_ok"] == 4


def test_clean_path_zero_trips(clean_session, pair):
    """Release-gate invariant: a fault-free session serves with ZERO
    breaker trips (a trip in the clean path means a kernel is broken)."""
    clean_session.infer(*pair)
    assert clean_session.breaker.trip_count == 0
    assert clean_session.metrics()["rebuilds"] == 0


# ---------------------------------------------------------------------------
# Demo path: byte-identical output through the session.


def test_demo_byte_identical_through_session(tiny_params, tiny_cfg,
                                             tmp_path):
    """demo.py routed through InferenceSession: for a fixed input shape
    the saved .npy is byte-identical to the pre-session eval path."""
    pytest.importorskip("matplotlib")
    from PIL import Image

    import demo
    from raft_stereo_tpu.engine.checkpoint import save_checkpoint
    from raft_stereo_tpu.engine.evaluate import make_eval_forward

    rng = np.random.default_rng(5)
    h, w = 64, 96
    left = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    right = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    scene = tmp_path / "scene0"
    scene.mkdir()
    Image.fromarray(left).save(scene / "im0.png")
    Image.fromarray(right).save(scene / "im1.png")
    ckpt = str(tmp_path / "tiny.msgpack")
    save_checkpoint(ckpt, tiny_params)
    out_dir = tmp_path / "out"

    demo.main([
        "--restore_ckpt", ckpt,
        "-l", str(tmp_path / "*/im0.png"),
        "-r", str(tmp_path / "*/im1.png"),
        "--output_directory", str(out_dir),
        "--save_numpy", "--valid_iters", "2",
        "--n_gru_layers", "1", "--hidden_dims", "32", "32", "32",
        "--corr_levels", "2", "--corr_radius", "2",
    ])

    got = np.load(out_dir / "scene0.npy")
    forward = make_eval_forward(tiny_params, tiny_cfg, 2)
    i1 = left.astype(np.float32)[None]
    i2 = right.astype(np.float32)[None]
    padder = InputPadder(i1.shape, divis_by=32)
    p1, p2 = padder.pad_np(i1, i2)
    flow_up, _ = forward(p1, p2)
    ref = padder.unpad_np(np.asarray(flow_up))[0, ..., 0]
    assert got.tobytes() == ref.tobytes()


# ---------------------------------------------------------------------------
# Injector / padder unit checks.


def test_serve_faults_ordinals_deterministic():
    plan = ServeFaultPlan(compile_errors={1: "oom"}, poison_outputs=(2,))
    faults = ServeFaults(plan)
    assert faults.on_build() == 0
    from raft_stereo_tpu.faults import InjectedKernelError
    with pytest.raises(InjectedKernelError, match="RESOURCE_EXHAUSTED"):
        faults.on_build()
    assert faults.on_build() == 2
    assert [faults.on_forward() for _ in range(3)] == [0, 1, 2]
    assert not faults.poisoned(1)
    assert faults.poisoned(2)


def test_fake_clock_sleep_advances():
    clk = FakeClock(start=5.0)
    assert clk.now() == 5.0
    clk.sleep(2.5)
    assert clk.now() == 7.5


def test_unpad_np_matches_unpad(rng):
    import jax.numpy as jnp
    x = rng.uniform(size=(1, 40, 60, 1)).astype(np.float32)
    padder = InputPadder(x.shape, divis_by=32, bucket=32)
    (xp,) = padder.pad_np(x)
    a = np.asarray(padder.unpad(jnp.asarray(xp)))
    b = padder.unpad_np(xp)
    assert a.tobytes() == b.tobytes()
    assert b.shape == x.shape
    assert bucket_shape((40, 60), 32) == (64, 64)
    assert bucket_shape((64, 64), 64) == (64, 64)


# ---------------------------------------------------------------------------
# Failure-classification table (guard.py KERNEL_FAILURE_MARKERS)
# ---------------------------------------------------------------------------

def test_kernel_failure_marker_table():
    """Every marker in the named table classifies, with a category and a
    note (the table replaced an anonymous inline tuple — each entry must
    say what it matches and why it is specific enough to trust)."""
    from raft_stereo_tpu.serve.guard import (KERNEL_FAILURE_MARKERS,
                                             is_kernel_failure,
                                             match_failure_marker)
    assert len(KERNEL_FAILURE_MARKERS) >= 6
    for marker in KERNEL_FAILURE_MARKERS:
        assert marker.substring == marker.substring.lower()
        assert marker.category in ("oom", "kernel_compiler", "xla_runtime")
        assert marker.note
        exc = RuntimeError(f"prefix {marker.substring.upper()} suffix")
        assert match_failure_marker(exc) is marker
        assert is_kernel_failure(exc)
    # Non-kernel exceptions must propagate, not walk the ladder.
    for benign in (ValueError("bad argument"), KeyError("missing"),
                   RuntimeError("deadline blown")):
        assert match_failure_marker(benign) is None
        assert not is_kernel_failure(benign)


def test_kernel_failure_by_type_name():
    from raft_stereo_tpu.serve.guard import is_kernel_failure

    class XlaRuntimeError(Exception):
        pass

    assert is_kernel_failure(XlaRuntimeError("totally generic message"))


def test_unclassified_kernel_failure_falls_to_next_in_order():
    """Regression for the classification contract: a kernel failure whose
    message matches NO rung matchers must fall through to the first
    untripped rung in ladder order — and after that rung trips, the same
    generic failure targets the NEXT rung, never re-trips the dark one."""
    from raft_stereo_tpu.serve.guard import KernelCircuitBreaker

    class XlaRuntimeError(Exception):
        pass

    breaker = KernelCircuitBreaker()
    generic = XlaRuntimeError("INTERNAL: something nonspecific happened")
    order = [p.name for p in breaker.ladder]
    walked = []
    for _ in order:
        path = breaker.classify(generic)
        walked.append(path.name)
        breaker.trip(path.name, "runtime_failure", generic)
    assert walked == order
    assert breaker.classify(generic) is None  # exhausted
    assert breaker.exhausted


def test_matcher_beats_ladder_order_until_tripped():
    """A message matching a deep rung's matchers trips THAT rung first;
    once it is dark, the same message falls back to next-in-order."""
    from raft_stereo_tpu.serve.guard import KernelCircuitBreaker
    breaker = KernelCircuitBreaker()
    exc = RuntimeError("mosaic verify failed in pallas_reg gather_lerp")
    assert breaker.classify(exc).name == "corr_kernel"
    breaker.trip("corr_kernel", "compile_failure", exc)
    # corr_kernel is dark -> first untripped in ladder order
    assert breaker.classify(exc).name == breaker.ladder[0].name

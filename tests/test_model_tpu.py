"""TPU-hardware model-level checks (skipped on the CPU test topology).

The bf16 mixed-precision path only exists on hardware (tests/conftest.py
forces fp32 CPU); this pins its end-to-end numerics so precision
regressions in the conv/norm/corr dtype policies are caught by
``pytest tests/test_model_tpu.py --noconftest`` on the chip.

Weights come from the torch reference's seed-1234 init via the transplant
shim: a randomly-initialized *jax*-keyed model turns out to be a chaotic
iteration (tiny precision perturbations grow to tens of px over the GRU
recurrence), while the reference init contracts — matching the behavior
trained checkpoints have, which is what the bound must reflect.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.transplant import transplant_state_dict

REFERENCE = Path("/root/reference")

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu" or not (REFERENCE / "core").is_dir(),
    reason="requires TPU hardware + the reference checkout (weights oracle)")


@pytest.fixture(scope="module")
def params():
    import argparse
    import torch
    if str(REFERENCE) not in sys.path:
        sys.path.insert(0, str(REFERENCE))
    from core.raft_stereo import RAFTStereo
    torch.manual_seed(1234)
    model = RAFTStereo(argparse.Namespace(
        corr_implementation="reg", shared_backbone=False, corr_levels=4,
        corr_radius=4, n_downsample=2, slow_fast_gru=False, n_gru_layers=3,
        hidden_dims=[128, 128, 128], mixed_precision=False))
    return transplant_state_dict(model.state_dict(), RAFTStereoConfig())


@pytest.mark.parametrize("impl", ["reg_tpu", "alt_tpu"])
def test_bf16_drift_vs_fp32_bounded(params, impl):
    """Mixed-precision disparity must stay sub-pixel-close to fp32 reg.

    Measured 2026-07-30 at 32 iters / 384x512: mean |Δ| 0.043 px
    (BASELINE.md). The bound is loose against run-to-run compiler
    variation but an order of magnitude under the smallest benchmark
    outlier threshold (1 px).
    """
    rng = np.random.default_rng(5)
    h, w, shift = 128, 256, 9
    base = rng.uniform(0, 255, (1, h, w + 32, 3)).astype(np.float32)
    img1 = jnp.asarray(base[:, :, 32:, :])
    img2 = jnp.asarray(base[:, :, 32 - shift:-shift, :])

    _, up32 = raft_stereo_forward(params, RAFTStereoConfig(), img1, img2,
                                  iters=32, test_mode=True)
    cfg16 = RAFTStereoConfig(corr_implementation=impl, mixed_precision=True)
    _, up16 = raft_stereo_forward(params, cfg16, img1, img2,
                                  iters=32, test_mode=True)
    d = np.abs(np.asarray(up16) - np.asarray(up32))
    assert d.mean() < 0.2, (d.mean(), d.max())
    assert np.isfinite(np.asarray(up16)).all()

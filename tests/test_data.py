"""Data layer tests: format IO, augmentors, datasets, loader.

Synthetic dataset trees are built in tmp dirs with the exact directory layouts
the reference globs expect (core/stereo_datasets.py), so the glob logic is
exercised for real.
"""

import json
import os
import os.path as osp

import numpy as np
import pytest
from PIL import Image

import cv2

from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.data.augmentor import (
    FlowAugmentor, SparseFlowAugmentor, resize_sparse_flow_map)
from raft_stereo_tpu.data.datasets import (
    KITTI, Middlebury, SceneFlowDatasets, SintelStereo, StereoDataset,
    fetch_dataset)
from raft_stereo_tpu.data.loader import StereoLoader, collate
from raft_stereo_tpu.data.photometric import ColorJitter


# ---------------------------------------------------------------------------
# frame_utils
# ---------------------------------------------------------------------------

def test_pfm_roundtrip(tmp_path, rng):
    disp = rng.uniform(0, 300, (7, 9)).astype(np.float32)
    path = str(tmp_path / "x.pfm")
    frame_utils.write_pfm(path, disp)
    out = frame_utils.read_pfm(path)
    np.testing.assert_array_equal(out, disp)


def test_pfm_big_endian(tmp_path):
    disp = np.arange(12, dtype=np.float32).reshape(3, 4)
    path = str(tmp_path / "be.pfm")
    with open(path, "wb") as f:
        f.write(b"Pf\n4 3\n1.0\n")
        f.write(np.flipud(disp).astype(">f4").tobytes())
    np.testing.assert_array_equal(frame_utils.read_pfm(path), disp)


def test_pfm_color_and_read_gen_drops_last_channel(tmp_path):
    data = np.random.default_rng(0).uniform(size=(5, 6, 3)).astype(np.float32)
    path = str(tmp_path / "c.pfm")
    with open(path, "wb") as f:
        f.write(b"PF\n6 5\n-1.0\n")
        f.write(np.flipud(data).astype("<f4").tobytes())
    out = frame_utils.read_pfm(path)
    np.testing.assert_allclose(out, data, rtol=1e-6)
    gen = frame_utils.read_gen(path)
    assert gen.shape == (5, 6, 2)  # last channel dropped (reference :182-186)


def test_flo_roundtrip(tmp_path, rng):
    flow = rng.normal(size=(5, 8, 2)).astype(np.float32)
    path = str(tmp_path / "f.flo")
    frame_utils.write_flow(path, flow)
    np.testing.assert_array_equal(frame_utils.read_flow(path), flow)
    # Bad magic -> None
    with open(path, "r+b") as f:
        f.write(np.asarray([1.0], np.float32).tobytes())
    assert frame_utils.read_flow(path) is None


def test_kitti_disp_roundtrip(tmp_path):
    disp = np.zeros((4, 5), np.float32)
    disp[1, 2] = 37.5
    disp[3, 3] = 100.25
    path = str(tmp_path / "d.png")
    cv2.imwrite(path, (disp * 256).astype(np.uint16))
    out, valid = frame_utils.read_disp_kitti(path)
    np.testing.assert_array_equal(out, disp)
    assert valid.sum() == 2 and valid[1, 2] and valid[3, 3]


def test_kitti_flow_roundtrip(tmp_path, rng):
    flow = (rng.normal(size=(4, 5, 2)) * 10).round(2).astype(np.float32)
    # Representable at 1/64 px resolution:
    flow = np.round(flow * 64) / 64
    path = str(tmp_path / "fl.png")
    frame_utils.write_flow_kitti(path, flow)
    out, valid = frame_utils.read_flow_kitti(path)
    np.testing.assert_array_equal(out, flow)
    assert valid.all()


def test_sintel_decode_no_uint8_overflow(tmp_path):
    # disp = 4r + g/64 + b/16384; r=100 -> disp 400 would wrap under uint8 math
    rgb = np.zeros((3, 4, 3), np.uint8)
    rgb[..., 0] = 100
    rgb[..., 1] = 128
    disp_dir = tmp_path / "disparities" / "s"
    occ_dir = tmp_path / "occlusions" / "s"
    disp_dir.mkdir(parents=True)
    occ_dir.mkdir(parents=True)
    Image.fromarray(rgb).save(disp_dir / "frame_0001.png")
    Image.fromarray(np.zeros((3, 4), np.uint8)).save(occ_dir / "frame_0001.png")
    disp, valid = frame_utils.read_disp_sintel(str(disp_dir / "frame_0001.png"))
    np.testing.assert_allclose(disp, 400 + 128 / 64.0)
    assert valid.all()


def test_falling_things_reader(tmp_path):
    depth = np.full((4, 6), 2000, np.uint16)
    Image.fromarray(depth).save(tmp_path / "x.left.depth.png")
    with open(tmp_path / "_camera_settings.json", "w") as f:
        json.dump({"camera_settings":
                   [{"intrinsic_settings": {"fx": 768.0}}]}, f)
    disp, valid = frame_utils.read_disp_falling_things(
        str(tmp_path / "x.left.depth.png"))
    np.testing.assert_allclose(disp, 768.0 * 600 / 2000)
    assert valid.all()


def test_tartan_air_reader(tmp_path):
    np.save(tmp_path / "d.npy", np.full((3, 3), 8.0, np.float32))
    disp, valid = frame_utils.read_disp_tartan_air(str(tmp_path / "d.npy"))
    np.testing.assert_allclose(disp, 10.0)
    assert valid.all()


def test_middlebury_reader(tmp_path):
    disp = np.full((4, 5), 12.5, np.float32)
    frame_utils.write_pfm(str(tmp_path / "disp0GT.pfm"), disp)
    mask = np.full((4, 5), 255, np.uint8)
    mask[0, 0] = 128
    Image.fromarray(mask).save(tmp_path / "mask0nocc.png")
    out, nocc = frame_utils.read_disp_middlebury(str(tmp_path / "disp0GT.pfm"))
    np.testing.assert_array_equal(out, disp)
    assert not nocc[0, 0] and nocc.sum() == 19


# ---------------------------------------------------------------------------
# photometric + augmentors
# ---------------------------------------------------------------------------

def test_color_jitter_identity(rng):
    img = rng.integers(0, 256, (16, 20, 3), dtype=np.uint8)
    out = ColorJitter(0.0, 0.0, 0.0, 0.0)(img, np.random.default_rng(1))
    np.testing.assert_array_equal(out, img)


def test_color_jitter_brightness_monotonic(rng):
    img = rng.integers(50, 200, (8, 8, 3), dtype=np.uint8)
    bright = ColorJitter(brightness=(1.5, 1.5))(img, np.random.default_rng(0))
    dark = ColorJitter(brightness=(0.5, 0.5))(img, np.random.default_rng(0))
    assert bright.mean() > img.mean() > dark.mean()


def _rand_pair(rng, h=64, w=96):
    img1 = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    img2 = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    flow = np.stack([-rng.uniform(0, 30, (h, w)).astype(np.float32),
                     np.zeros((h, w), np.float32)], axis=-1)
    return img1, img2, flow


def test_dense_augmentor_shapes_and_determinism(rng):
    aug = FlowAugmentor(crop_size=(32, 48), yjitter=True)
    img1, img2, flow = _rand_pair(rng)
    o1 = aug(img1, img2, flow, np.random.default_rng(7))
    o2 = aug(img1, img2, flow, np.random.default_rng(7))
    o3 = aug(img1, img2, flow, np.random.default_rng(8))
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)
    assert o1[0].shape == (32, 48, 3) and o1[2].shape == (32, 48, 2)
    assert any(not np.array_equal(a, b) for a, b in zip(o1, o3))


def test_dense_augmentor_does_not_mutate_inputs(rng):
    aug = FlowAugmentor(crop_size=(32, 48))
    img1, img2, flow = _rand_pair(rng)
    img2_orig = img2.copy()
    # Draw until the eraser path triggers (prob 0.5).
    for seed in range(10):
        aug(img1, img2, flow, np.random.default_rng(seed))
    np.testing.assert_array_equal(img2, img2_orig)


def test_stereo_hflip_swaps_eyes():
    aug = FlowAugmentor(crop_size=(32, 48), do_flip="h")
    aug.spatial_aug_prob = 0.0  # isolate the flip
    aug.asymmetric_color_aug_prob = -1.0
    aug.photo_aug = ColorJitter()
    aug.eraser_aug_prob = -1.0
    rng0 = np.random.default_rng(3)
    img1 = np.zeros((40, 56, 3), np.uint8)
    img2 = np.full((40, 56, 3), 200, np.uint8)
    img1[:, :28] = 50  # left half darker
    flow = np.zeros((40, 56, 2), np.float32)
    o1, o2, _ = aug(img1, img2, flow, rng0)
    # With h-flip prob 0.5 and this seed the swap may or may not fire; force it
    aug.h_flip_prob = 1.1
    o1, o2, _ = aug(img1, img2, flow, np.random.default_rng(3))
    assert (o1 == 200).all()  # img1 is now the mirrored old img2


def test_sparse_resize_scatter():
    flow = np.zeros((10, 12, 2), np.float32)
    valid = np.zeros((10, 12), np.float32)
    flow[4, 6] = [-8.0, 0.0]
    valid[4, 6] = 1
    out_flow, out_valid = resize_sparse_flow_map(flow, valid, fx=2.0, fy=2.0)
    assert out_flow.shape == (20, 24, 2) and out_valid.sum() == 1
    np.testing.assert_allclose(out_flow[8, 12], [-16.0, 0.0])


def test_sparse_augmentor_shapes(rng):
    aug = SparseFlowAugmentor(crop_size=(32, 48))
    img1, img2, flow = _rand_pair(rng)
    valid = (rng.uniform(size=flow.shape[:2]) > 0.5).astype(np.float32)
    o1, o2, of, ov = aug(img1, img2, flow, valid, np.random.default_rng(0))
    assert o1.shape == (32, 48, 3) and of.shape == (32, 48, 2)
    assert ov.shape == (32, 48)


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

def _write_png(path, arr):
    os.makedirs(osp.dirname(str(path)), exist_ok=True)
    Image.fromarray(arr).save(path)


def _make_sceneflow_tree(root, n=3, h=48, w=64, gray=False):
    """datasets/FlyingThings3D/{dstype,disparity}/TRAIN/A/0000/{left,right}"""
    rng = np.random.default_rng(5)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        for i in range(n):
            base = osp.join(root, "FlyingThings3D", dstype, "TRAIN", "A",
                            f"{i:04d}")
            if gray:
                img = rng.integers(0, 256, (h, w), dtype=np.uint8)
            else:
                img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            _write_png(osp.join(base, "left", "0006.png"), img)
            _write_png(osp.join(base, "right", "0006.png"), img)
    for i in range(n):
        base = osp.join(root, "FlyingThings3D", "disparity", "TRAIN", "A",
                        f"{i:04d}")
        os.makedirs(osp.join(base, "left"), exist_ok=True)
        disp = np.full((h, w), 5.25, np.float32)
        frame_utils.write_pfm(osp.join(base, "left", "0006.pfm"), disp)


def test_sceneflow_dataset_and_flow_sign(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    assert len(ds) == 3
    sample = ds.__getitem__(0, rng=np.random.default_rng(0))
    assert sample["image1"].shape == (48, 64, 3)
    assert sample["flow"].shape == (48, 64, 1)
    np.testing.assert_allclose(sample["flow"][..., 0], -5.25)  # flow = -disp
    assert sample["valid"].all()
    assert len(sample["paths"]) == 3


def test_grayscale_tiling(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root, gray=True)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    s = ds.__getitem__(0, rng=np.random.default_rng(0))
    assert s["image1"].shape == (48, 64, 3)
    assert (s["image1"][..., 0] == s["image1"][..., 1]).all()


def test_dataset_mul_and_add(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    assert len(ds * 4) == 12
    assert len(ds * 2 + ds) == 9


def test_heterogeneous_mix_keeps_per_part_readers(tmp_path):
    """sceneflow (dense, PFM via read_gen) + kitti (sparse, PNG/256): each
    part of the mix must decode with its own reader in both concat orders."""
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=2, h=40, w=60)
    kroot = osp.join(root, "KITTI")
    img = np.random.default_rng(0).integers(0, 256, (40, 60, 3), dtype=np.uint8)
    for cam in ("image_2", "image_3"):
        _write_png(osp.join(kroot, "training", cam, "000000_10.png"), img)
    os.makedirs(osp.join(kroot, "training", "disp_occ_0"), exist_ok=True)
    cv2.imwrite(osp.join(kroot, "training", "disp_occ_0", "000000_10.png"),
                (np.full((40, 60), 37.5) * 256).astype(np.uint16))

    sf = SceneFlowDatasets(aug_params=None, root=root)
    ki = KITTI(aug_params=None, root=kroot)
    for mix, k_index in ((sf + ki, len(sf)), (ki + sf, 0)):
        assert len(mix) == 3
        k_sample = mix.__getitem__(k_index, rng=np.random.default_rng(0))
        np.testing.assert_allclose(k_sample["flow"][..., 0], -37.5)
        sf_sample = mix.__getitem__((k_index + 1) % 3,
                                    rng=np.random.default_rng(0))
        np.testing.assert_allclose(sf_sample["flow"][..., 0], -5.25)


def test_fetch_dataset_sceneflow_weights(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root)

    class Cfg:
        train_datasets = ("sceneflow",)
        image_size = (32, 48)
        spatial_scale = (-0.2, 0.4)
        noyjitter = False
        saturation_range = None
        img_gamma = None
        do_flip = None

    ds = fetch_dataset(Cfg(), root=root)
    # clean*4 + final*4 over 3 pairs each = 24
    assert len(ds) == 24
    s = ds.__getitem__(0, rng=np.random.default_rng(0))
    assert s["image1"].shape == (32, 48, 3)


def test_kitti_split_alias(tmp_path):
    root = str(tmp_path / "KITTI")
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (40, 60, 3), dtype=np.uint8)
    for cam in ("image_2", "image_3"):
        _write_png(osp.join(root, "training", cam, "000000_10.png"), img)
    os.makedirs(osp.join(root, "training", "disp_occ_0"), exist_ok=True)
    cv2.imwrite(osp.join(root, "training", "disp_occ_0", "000000_10.png"),
                (np.full((40, 60), 3.0) * 256).astype(np.uint16))
    # The reference's fetch_dataloader passes split=<name>, which TypeErrors
    # against its own constructor; the alias must work here.
    ds = KITTI(aug_params=None, root=root, split="kitti")
    assert len(ds) == 1
    s = ds.__getitem__(0, rng=np.random.default_rng(0))
    np.testing.assert_allclose(s["flow"][..., 0], -3.0)
    assert s["valid"].all()


def test_is_test_branch(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    ds.is_test = True
    ds.extra_info = [["a"]] * len(ds)
    s = ds[0]
    assert set(s) == {"paths", "image1", "image2", "extra_info"}


def test_img_pad(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root)
    ds = SceneFlowDatasets(aug_params={"img_pad": (2, 3)}, root=root)
    s = ds.__getitem__(0, rng=np.random.default_rng(0))
    assert s["image1"].shape == (48 + 4, 64 + 6, 3)
    assert s["flow"].shape == (48, 64, 1)  # flow is not padded (reference)


# ---------------------------------------------------------------------------
# loader
# ---------------------------------------------------------------------------

def test_loader_batches_and_determinism(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=5)
    aug = {"crop_size": [32, 48], "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": False, "yjitter": True}
    ds = SceneFlowDatasets(aug_params=aug, root=root)

    def run_epoch():
        loader = StereoLoader(ds, batch_size=2, num_workers=2, seed=11)
        return list(loader)

    b1, b2 = run_epoch(), run_epoch()
    assert len(b1) == 2  # 5 samples, batch 2, drop_last
    assert b1[0]["image1"].shape == (2, 32, 48, 3)
    assert b1[0]["flow"].shape == (2, 32, 48, 1)
    assert b1[0]["valid"].shape == (2, 32, 48)
    for x, y in zip(b1, b2):
        for k in ("image1", "image2", "flow", "valid"):
            np.testing.assert_array_equal(x[k], y[k])


def test_loader_rejects_zero_batches(tmp_path):
    """drop_last leaving zero batches must fail fast, not hang train()."""
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=3)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    with pytest.raises(ValueError, match="zero batches"):
        StereoLoader(ds, batch_size=8, num_workers=1)


def test_loader_epoch_advances_order(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=5)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    loader = StereoLoader(ds, batch_size=2, num_workers=2, seed=0,
                          return_paths=True)
    e1 = [b["paths"] for b in loader]
    e2 = [b["paths"] for b in loader]
    assert loader.epoch == 2
    assert e1 != e2  # different shuffle per epoch (almost surely for 5!)


def test_loader_early_break_does_not_replay_epoch(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=5)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    loader = StereoLoader(ds, batch_size=2, num_workers=2, seed=0,
                          return_paths=True)
    first = next(iter(loader))  # abandon the iterator mid-epoch
    second = next(iter(loader))
    assert loader.epoch == 2
    assert first["paths"] != second["paths"]


def test_loader_batch_is_pure_pytree(tmp_path):
    root = str(tmp_path)
    _make_sceneflow_tree(root, n=3)
    ds = SceneFlowDatasets(aug_params=None, root=root)
    batch = next(iter(StereoLoader(ds, batch_size=2, num_workers=1)))
    assert all(isinstance(v, np.ndarray) for v in batch.values())


def test_sparse_vflip_flips_valid_mask():
    aug = SparseFlowAugmentor(crop_size=(8, 8), do_flip="v")
    aug.spatial_aug_prob = -1.0
    aug.eraser_aug_prob = -1.0
    aug.photo_aug = ColorJitter()
    aug.v_flip_prob = 1.1  # force the flip
    aug.crop_margin = (1, 1)  # integers(0, 0+1): the only crop is (0, 0)
    img = np.zeros((8, 8, 3), np.uint8)
    flow = np.zeros((8, 8, 2), np.float32)
    valid = np.zeros((8, 8), np.float32)
    flow[0, 3] = [-4.0, 0.0]
    valid[0, 3] = 1
    _, _, of, ov = aug(img, img, flow, valid, np.random.default_rng(0))
    assert ov[7, 3] == 1 and ov[0, 3] == 0  # mask flipped with the flow
    np.testing.assert_allclose(of[7, 3], [-4.0, 0.0])


def test_device_prefetch_order_dtype_and_flush():
    """Background-thread device placement: order preserved, every batch
    yielded (incl. the tail buffer), images downcast when image_dtype is
    set, non-image arrays untouched."""
    import jax.numpy as jnp

    from raft_stereo_tpu.data.loader import device_prefetch

    batches = [{"image1": np.full((1, 4, 4, 3), i, np.float32),
                "image2": np.zeros((1, 4, 4, 3), np.float32),
                "flow": np.zeros((1, 4, 4, 1), np.float32),
                "valid": np.ones((1, 4, 4), np.float32)}
               for i in range(5)]

    out = list(device_prefetch(iter(batches), image_dtype=jnp.bfloat16))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert float(b["image1"][0, 0, 0, 0]) == float(i)  # order preserved
        assert b["image1"].dtype == jnp.bfloat16
        assert b["flow"].dtype == jnp.float32  # only images downcast

    out = list(device_prefetch(iter(batches)))
    assert out[3]["image1"].dtype == jnp.float32  # no dtype override


def test_loader_local_rows_partition(tmp_path):
    """Pod input sharding: a row-local loader decodes ONLY its rows, and
    the union of two half-loaders equals the full loader's batch bit-for-
    bit (global positions key the RNG, so partitioning changes no values)."""
    import numpy as np
    from raft_stereo_tpu.data.loader import StereoLoader

    class CountingDataset:
        def __init__(self):
            self.calls = []

        def __len__(self):
            return 8

        def __getitem__(self, i, rng=None):
            self.calls.append(i)
            v = float(rng.uniform())
            return {"image1": np.full((4, 6, 3), i, np.float32) + v,
                    "image2": np.zeros((4, 6, 3), np.float32),
                    "flow": np.zeros((4, 6, 1), np.float32),
                    "valid": np.ones((4, 6), np.float32)}

    def batches(local):
        ds = CountingDataset()
        loader = StereoLoader(ds, batch_size=4, shuffle=True, num_workers=1,
                              seed=7, local_rows=local)
        out = [b for b in loader]
        return ds, out

    ds_full, full = batches(None)
    ds_a, part_a = batches(slice(0, 2))
    ds_b, part_b = batches(slice(2, 4))
    assert len(ds_a.calls) == len(ds_full.calls) // 2
    assert len(ds_b.calls) == len(ds_full.calls) // 2
    for f, a, b in zip(full, part_a, part_b):
        np.testing.assert_array_equal(f["image1"][:2], a["image1"])
        np.testing.assert_array_equal(f["image1"][2:], b["image1"])


# ---------------------------------------------------------------------------
# fetch_dataloader worker sizing (SLURM_CPUS_PER_TASK)
# ---------------------------------------------------------------------------

def test_fetch_dataloader_small_cpu_allocation_clamps(monkeypatch):
    """SLURM_CPUS_PER_TASK=1 must yield ONE worker, not -1 (a 1-2 CPU
    allocation previously produced 0/negative workers)."""
    from types import SimpleNamespace

    from raft_stereo_tpu.data import loader as loader_mod

    class _Dummy:
        def __len__(self):
            return 8

        def __getitem__(self, i, rng=None):
            raise NotImplementedError

    monkeypatch.setattr(loader_mod, "fetch_dataset",
                        lambda cfg, root=None: _Dummy())
    for cpus, expect in (("1", 1), ("2", 1), ("3", 1), ("8", 6)):
        monkeypatch.setenv("SLURM_CPUS_PER_TASK", cpus)
        dl = loader_mod.fetch_dataloader(
            SimpleNamespace(batch_size=2, num_workers=None))
        assert dl.num_workers == expect, (cpus, dl.num_workers)


def test_fetch_dataloader_garbage_cpu_allocation_is_clear_error(monkeypatch):
    from types import SimpleNamespace

    from raft_stereo_tpu.data import loader as loader_mod

    monkeypatch.setattr(loader_mod, "fetch_dataset",
                        lambda cfg, root=None: None)
    monkeypatch.setenv("SLURM_CPUS_PER_TASK", "4(x2)")
    with pytest.raises(ValueError, match="SLURM_CPUS_PER_TASK"):
        loader_mod.fetch_dataloader(
            SimpleNamespace(batch_size=2, num_workers=None))

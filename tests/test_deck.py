"""graftdeck battery: the operator plane (DESIGN.md "Operator plane
(r15)") — tick flight-deck, per-tenant usage accounting, capacity &
saturation model, live debug introspection.

The integration tests drive the REAL serving stack (tiny model, CPU) on
a FakeClock with plan-driven injected device time, so the load-bearing
claims are equalities, not tolerances:

- **three-way reconciliation** (the ISSUE 12 acceptance bar): the
  deck's per-tick steady device seconds == the request's trace span
  timeline == the ``raft_program_device_seconds_total`` counter delta,
  exactly, in BOTH the scheduler and sequential serving modes;
- **exact tenant partition**: per-tenant device nanoseconds sum to the
  accounted total as an integer equality, and hostile tenant-name churn
  past the label bound lands in ``__other__`` without growing
  ``/metrics`` (the PR 10 quota-label regression, now for usage);
- **introspection**: during an injected device hang, the all-thread
  stack dump names the parked invocation frame; the four ``/debug/*``
  endpoints serve bounded JSON through the hardened ingress.
"""

import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import ChaosPlan, FakeClock, ServeFaultPlan
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.obs import capacity as cap
from raft_stereo_tpu.obs import deck as deck_mod
from raft_stereo_tpu.obs import usage as usage_mod
from raft_stereo_tpu.obs.deck import TickDeck, thread_stacks
from raft_stereo_tpu.obs.flight import FlightRecorder
from raft_stereo_tpu.obs.metrics import MetricsRegistry
from raft_stereo_tpu.obs.tracing import Tracer
from raft_stereo_tpu.obs.usage import (UsageAccountant, partition_ints,
                                       sanitize_tenant)
from raft_stereo_tpu.serve import (HttpConfig, HttpFrontend,
                                   InferenceSession, ServiceConfig,
                                   SessionConfig, StereoService)
from raft_stereo_tpu.serve import wire

pytestmark = pytest.mark.deck

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60

#: Every device invocation advances the FakeClock by this much — exact
#: nonzero durations with zero real sleeping (test_obs.py's rig).
TICK = 0.25


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(3)
    return (rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (H, W, 3)).astype(np.float32))


def slow_plan(n: int = 128) -> ServeFaultPlan:
    return ServeFaultPlan(slow_forwards={i: TICK for i in range(n)})


def make_session(params, cfg, *, max_batch=1, valid_iters=4, segments=2,
                 plan=None, clock=None, flight=None):
    scfg = SessionConfig(valid_iters=valid_iters, segments=segments,
                         max_batch=max_batch, canary=False)
    clock = clock or FakeClock()
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=clock, flight=flight,
                            tracer=Tracer(clock=clock, sink=""))


def device_counter_total(registry) -> float:
    return sum(v for _, v in
               registry.series("raft_program_device_seconds_total"))


def trace_device_span_sum_s(trace_doc) -> float:
    """Steady device-span seconds of one request timeline (warming spans
    are compile-inclusive and binned apart, like the counters)."""
    return sum(s["ms"] / 1e3 for s in trace_doc["spans"]
               if s["kind"] in ("prepare", "segment", "advance",
                                "epilogue", "full")
               and not s.get("attrs", {}).get("warming"))


# ---------------------------------------------------------------------------
# Units: knob resolution, ring bound, partition, labels, stacks, report.
# ---------------------------------------------------------------------------


def test_resolve_deck_ticks_env(monkeypatch):
    monkeypatch.delenv("RAFT_DECK_TICKS", raising=False)
    assert deck_mod.resolve_deck_ticks() == 1024
    assert deck_mod.resolve_deck_ticks(16) == 16
    monkeypatch.setenv("RAFT_DECK_TICKS", "64")
    assert deck_mod.resolve_deck_ticks() == 64
    monkeypatch.setenv("RAFT_DECK_TICKS", "soon")
    with pytest.raises(ValueError, match="RAFT_DECK_TICKS"):
        deck_mod.resolve_deck_ticks()
    monkeypatch.setenv("RAFT_DECK_TICKS", "0")
    with pytest.raises(ValueError, match="RAFT_DECK_TICKS"):
        deck_mod.resolve_deck_ticks()


def test_resolve_capacity_window_env(monkeypatch):
    monkeypatch.delenv("RAFT_CAPACITY_WINDOW_MS", raising=False)
    assert cap.resolve_capacity_window_s() == 60.0
    monkeypatch.setenv("RAFT_CAPACITY_WINDOW_MS", "5000")
    assert cap.resolve_capacity_window_s() == 5.0
    monkeypatch.setenv("RAFT_CAPACITY_WINDOW_MS", "never")
    with pytest.raises(ValueError, match="RAFT_CAPACITY_WINDOW_MS"):
        cap.resolve_capacity_window_s()


def test_deck_ring_bounded_and_dropped_counted():
    clk = FakeClock()
    deck = TickDeck(clock=clk, ticks=4)
    for i in range(10):
        t = deck.begin_tick(bucket="64x64", generation=1, queue_depth=0)
        clk.sleep(0.1)
        deck.end_tick(t)
    st = deck.status()
    assert st == {"ring": 4, "recorded": 10, "dropped": 6, "warm_records": 0}
    # an OPEN tick (seq allocated, not yet ringed) must never read as a
    # spurious ring drop on a concurrent /debug/ticks scrape
    open_tick = deck.begin_tick(bucket="64x64")
    st = deck.status()
    assert st["recorded"] == 11 and st["dropped"] == 6
    deck.end_tick(open_tick)
    assert deck.status()["dropped"] == 7
    doc = deck.doc()
    assert len(doc["ticks"]) == 4
    assert [t["seq"] for t in doc["ticks"]] == [7, 8, 9, 10]
    assert len(deck.doc(n=2)["ticks"]) == 2  # ?n= bounds it further


def test_deck_open_tick_is_thread_local():
    """A zombie generation's thread can never accumulate into a fresh
    generation's open tick — the open slot is per-thread."""
    clk = FakeClock()
    deck = TickDeck(clock=clk, ticks=8)
    tick = deck.begin_tick(bucket="64x64")
    seen = {}

    def other():
        seen["current"] = deck.current()
        seen["seq"] = deck.note_invocation(
            kind="advance", program="p", b=1, h=64, w=64, t0=0.0,
            t1=1.0, host_s=0.0, device_s=1.0, warming=False)

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["current"] is None       # other thread sees no open tick
    assert seen["seq"] is not None       # so it recorded standalone
    assert tick.device_s == 0.0          # and the open tick is untouched
    deck.end_tick(tick)


def test_partition_ints_exact():
    rng = np.random.default_rng(0)
    for _ in range(200):
        total = int(rng.integers(0, 10**12))
        n = int(rng.integers(1, 17))
        shares = partition_ints(total, n)
        assert sum(shares) == total
        assert max(shares) - min(shares) <= 1
    with pytest.raises(ValueError):
        partition_ints(10, 0)


def test_usage_label_first_come_bounded():
    u = UsageAccountant(MetricsRegistry(), max_tenants=3)
    assert u.label("a") == "a" and u.label("b") == "b"
    assert u.label("c") == "c"
    assert u.label("d") == usage_mod.OVERFLOW_LABEL  # bound reached
    assert u.label("a") == "a"                       # known names keep theirs
    assert u.label(None) == usage_mod.OVERFLOW_LABEL  # 'default' was late
    assert sanitize_tenant("Bad Tenant!\n") == "Bad_Tenant__"
    assert sanitize_tenant("") == "default"


def test_usage_metrics_bounded_under_tenant_churn():
    """The ISSUE 12 satellite: hostile tenant-name churn past the bound
    lands in __other__ WITHOUT growing /metrics — the registry keeps
    every label combination forever, so the bound must live here."""
    reg = MetricsRegistry()
    u = UsageAccountant(reg, max_tenants=4)
    for i in range(50):
        label = u.label(f"churn-{i}")
        u.count_request(label, "ok")
        u.add_device([label], 0.001)
    baseline_lines = len(reg.render_prometheus().splitlines())
    n_series = len(reg.series("raft_tenant_requests_total"))
    assert n_series == 5  # 4 first-come names + __other__
    for i in range(50, 200):  # keep churning: nothing may grow
        label = u.label(f"churn-{i}")
        assert label == usage_mod.OVERFLOW_LABEL
        u.count_request(label, "ok")
        u.add_device([label], 0.001)
    assert len(reg.series("raft_tenant_requests_total")) == n_series
    assert len(reg.render_prometheus().splitlines()) == baseline_lines
    doc = u.doc()
    assert doc["overflow_active"] and doc["tenants_tracked"] == 4
    assert len(doc["by_tenant"]) == 5


def test_usage_add_device_exact_across_riders():
    u = UsageAccountant(MetricsRegistry(), max_tenants=8)
    rng = np.random.default_rng(1)
    for _ in range(100):
        riders = [u.label(f"t{int(rng.integers(0, 5))}")
                  for _ in range(int(rng.integers(1, 9)))]
        u.add_device(riders, float(rng.uniform(0, 2.0)),
                     flops=float(rng.integers(0, 10**9)))
    doc = u.doc()
    assert sum(t["device_ns"] for t in doc["by_tenant"].values()) \
        == doc["device_ns_total"]
    assert sum(t["flops"] for t in doc["by_tenant"].values()) \
        == doc["flops_total"]


def test_capacity_model_math():
    rows = [
        {"kind": "prepare", "b": 4, "h": 64, "w": 64, "iters": 0,
         "est": 0.1},
        {"kind": "advance", "b": 4, "h": 64, "w": 64, "iters": 2,
         "est": 0.2},
        {"kind": "epilogue", "b": 4, "h": 64, "w": 64, "iters": 0,
         "est": 0.1},
        {"kind": "full", "b": 1, "h": 96, "w": 128, "iters": 32,
         "est": 2.0},
    ]
    doc = cap.model(rows, segments=2, valid_iters=4)
    m = doc["by_bucket"]["64x64"]
    # 4 rows / (0.1 + 2*0.2 + 0.1) s = 6.666... requests/s
    assert m["mode"] == "batched" and m["batch"] == 4
    assert m["rps"] == pytest.approx(4 / 0.6)
    assert not m["partial"]
    assert doc["by_bucket"]["96x128"]["rps"] == pytest.approx(0.5)
    assert doc["best_rps"] == pytest.approx(4 / 0.6)


def test_capacity_model_absent_is_honest():
    doc = cap.model([], segments=2, valid_iters=4)
    assert doc["by_bucket"] == {} and doc["best_rps"] is None
    # an advance-less bucket reports None, never a fabricated number
    doc = cap.model([{"kind": "prepare", "b": 1, "h": 64, "w": 64,
                      "iters": 0, "est": 0.1}], segments=2, valid_iters=4)
    assert doc["by_bucket"]["64x64"]["rps"] is None
    # missing prepare/epilogue estimates flag the row partial
    doc = cap.model([{"kind": "advance", "b": 2, "h": 64, "w": 64,
                      "iters": 2, "est": 0.5}], segments=2, valid_iters=4)
    m = doc["by_bucket"]["64x64"]
    assert m["partial"] and m["rps"] == pytest.approx(2 / 1.0)


def test_saturation_window():
    rows = [  # 2 s of device time across 4 s of wall
        {"t_start": 0.0, "t_end": 2.0, "device_s": 1.5, "warm_s": 0.0},
        {"t_start": 2.0, "t_end": 4.0, "device_s": 0.0, "warm_s": 0.5},
    ]
    sat = cap.saturation(rows, now=4.0, window_s=60.0)
    assert sat["ratio"] == pytest.approx(2.0 / 4.0)
    assert sat["covered_s"] == pytest.approx(4.0)
    # records straddling the window edge are clipped proportionally
    sat = cap.saturation(rows, now=4.0, window_s=1.0)
    assert sat["ratio"] == pytest.approx(0.25 / 1.0)
    # no history: absence, never a fabricated 0
    assert cap.saturation([], now=4.0) is None
    assert cap.saturation([{"t_start": 0.0, "t_end": None}], now=4.0) \
        is None


def test_thread_stacks_bounded_and_names_self():
    doc = thread_stacks(max_frames=8)
    assert doc["schema"] == 1 and doc["threads"]
    me = [t for t in doc["threads"] if t["current"]]
    assert len(me) == 1
    assert any(f["function"] == "test_thread_stacks_bounded_and_names_self"
               for f in me[0]["frames"])
    assert all(len(t["frames"]) <= 8 for t in doc["threads"])
    tiny = thread_stacks(max_threads=1)
    assert len(tiny["threads"]) == 1
    assert tiny["truncated"] == (tiny["thread_count"] > 1)


def _deck_cli(args, input_text=None):
    return subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.obs.deck"] + args,
        capture_output=True, text=True, input=input_text)


def test_deck_report_cli(tmp_path):
    clk = FakeClock()
    deck = TickDeck(clock=clk, ticks=64)
    for i in range(6):
        t = deck.begin_tick(bucket="64x64", generation=1, queue_depth=i)
        t.batch, t.occupancy, t.pad_rows = 4, 3, 1
        clk.sleep(0.5)
        deck.end_tick(t)
        clk.sleep(0.1)  # idle gap between ticks
    path = tmp_path / "ticks.json"
    path.write_text(json.dumps(deck.doc()))
    res = _deck_cli(["report", str(path)])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "occupancy histogram" in res.stdout
    assert "pad waste by bucket" in res.stdout
    assert "64x64: 6/24 = 25.0%" in res.stdout
    assert "idle gaps between ticks" in res.stdout
    assert "n=5" in res.stdout
    # stdin form
    res = _deck_cli(["report", "-"], input_text=json.dumps(deck.doc()))
    assert res.returncode == 0
    # malformed can never read as a clean report
    path.write_text("{not json")
    assert _deck_cli(["report", str(path)]).returncode == 2
    path.write_text(json.dumps({"schema": 1, "ticks": [{"bad": 1}]}))
    assert _deck_cli(["report", str(path)]).returncode == 2


# ---------------------------------------------------------------------------
# Three-way reconciliation (the acceptance bar), both serving modes.
# ---------------------------------------------------------------------------


def test_scheduler_mode_three_way_reconciliation(tiny_params, tiny_cfg,
                                                 pair):
    """Deck per-tick device seconds == trace span timeline ==
    raft_program_device_seconds_total delta, exactly, under FakeClock —
    and the tick records carry the full operator schema."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        plan=slow_plan(), clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        # First request warms every program (compile-inclusive time is
        # binned as warm_s, excluded from all three sides).
        assert svc.submit({"id": "w", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
        dev0 = device_counter_total(sess.registry)
        seq0 = sess.deck.status()["recorded"]
        resp = svc.submit({"id": "r", "left": pair[0], "right": pair[1],
                           "tenant": "alice"}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    counter_delta = device_counter_total(sess.registry) - dev0
    new_rows = [t for t in sess.deck.snapshot() if t["seq"] >= seq0]
    deck_dev = sum(t["device_s"] for t in new_rows)
    doc = sess.tracer.last()
    span_dev = trace_device_span_sum_s(doc)
    # prepare + 2 advances + epilogue at TICK injected device time each.
    assert counter_delta == pytest.approx(4 * TICK, abs=0)
    assert deck_dev == counter_delta        # exact, not approx
    assert span_dev == counter_delta
    # The tick records carry the operator schema.
    ticks = [t for t in new_rows if t["kind"] == "tick"]
    assert ticks, new_rows
    adv = [t for t in ticks if t["batch"] > 0]
    assert adv and all(t["bucket"] == "64x64" for t in adv)
    assert all(t["generation"] == 1 for t in ticks)
    assert all(t["queue_depth"] is not None for t in ticks)
    assert sum(t["joins"] for t in ticks) == 1
    assert sum(t["exits"] for t in ticks) == 1
    assert all(t["pad_rows"] == t["batch"] - t["occupancy"] for t in adv)
    assert all(t["program"] and "advance" in t["program"] for t in adv)
    # Every device span names the tick it rode (the flight-record link).
    tick_seqs = {t["seq"] for t in ticks}
    for s in doc["spans"]:
        if s["kind"] in ("prepare", "advance", "epilogue"):
            assert s["attrs"]["tick"] in tick_seqs


def test_sequential_mode_three_way_reconciliation(tiny_params, tiny_cfg,
                                                  pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=4,
                                           workers=1)) as svc:
        assert svc.submit({"id": "w", "left": pair[0], "right": pair[1],
                           "deadline_ms": 1e9}).result(timeout=120)[
                               "status"] == "ok"
        dev0 = device_counter_total(sess.registry)
        seq0 = sess.deck.status()["recorded"]
        resp = svc.submit({"id": "r", "left": pair[0], "right": pair[1],
                           "deadline_ms": 1e9}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    counter_delta = device_counter_total(sess.registry) - dev0
    new_rows = [t for t in sess.deck.snapshot() if t["seq"] >= seq0]
    deck_dev = sum(t["device_s"] for t in new_rows)
    doc = sess.tracer.last()
    span_dev = trace_device_span_sum_s(doc)
    # prepare + 2 segments at TICK injected device time each.
    assert counter_delta == pytest.approx(3 * TICK, abs=0)
    assert deck_dev == counter_delta
    assert span_dev == counter_delta
    # Standalone rows: per-invocation, kind-labeled, span-linked.
    assert all(t["kind"] in ("prepare", "segment") for t in new_rows)
    seqs = {t["seq"] for t in new_rows}
    linked = [s["attrs"]["tick"] for s in doc["spans"]
              if s.get("attrs", {}).get("tick") is not None]
    assert linked and set(linked) <= seqs


def test_flight_record_names_tick_range(tmp_path, tiny_params, tiny_cfg,
                                        pair):
    """An SLO post-mortem names the exact ticks the request rode."""
    clock = FakeClock()
    flight = FlightRecorder(out_dir=str(tmp_path), limit=8)
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        plan=slow_plan(), clock=clock, flight=flight)
    with StereoService(sess, ServiceConfig(max_queue=8,
                                           slo_ms=100.0)) as svc:
        resp = svc.submit({"id": "r", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"
    paths = flight.records()
    assert len(paths) == 1
    doc = json.loads(open(paths[0]).read())
    assert doc["ticks"] is not None
    spans = doc["trace"]["spans"]
    span_ticks = sorted({s["attrs"]["tick"] for s in spans
                         if s.get("attrs", {}).get("tick") is not None})
    assert doc["ticks"]["first"] == span_ticks[0]
    assert doc["ticks"]["last"] == span_ticks[-1]
    assert doc["ticks"]["count"] == len(span_ticks)


# ---------------------------------------------------------------------------
# Per-tenant usage accounting end to end.
# ---------------------------------------------------------------------------


def test_usage_tenants_end_to_end_batched(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        plan=slow_plan(), clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        for rid, tenant in (("w", "alice"), ("a", "alice"), ("b", "bob")):
            assert svc.submit({"id": rid, "left": pair[0],
                               "right": pair[1], "tenant": tenant}
                              ).result(timeout=120)["status"] == "ok"
    doc = sess.usage.doc()
    assert set(doc["by_tenant"]) >= {"alice", "bob"}
    # integer-exact: nothing leaked, nothing double-attributed
    assert sum(t["device_ns"] for t in doc["by_tenant"].values()) \
        == doc["device_ns_total"]
    # the accounted total reconciles with the program counter (same
    # intervals, float vs int-ns accumulation)
    prog = device_counter_total(sess.registry)
    assert doc["device_ns_total"] / 1e9 == pytest.approx(prog, abs=1e-6)
    # outcome counters per tenant mirror the responses
    assert doc["by_tenant"]["alice"]["requests"]["ok"] == 2
    assert doc["by_tenant"]["bob"]["requests"]["ok"] == 1
    assert int(sess.registry.value("raft_tenant_requests_total",
                                   tenant="bob", outcome="ok")) == 1
    assert sess.registry.value("raft_tenant_device_seconds_total",
                               tenant="bob") > 0
    # /healthz summary block
    st = sess.status()["usage"]
    assert st["tenants_tracked"] >= 2


def test_usage_sequential_default_tenant(tiny_params, tiny_cfg, pair):
    """In-process callers without a tenant land under 'default' — the
    partition stays exhaustive in sequential mode too."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=4)) as svc:
        for rid in ("w", "r"):
            assert svc.submit({"id": rid, "left": pair[0],
                               "right": pair[1]}).result(timeout=120)[
                                   "status"] == "ok"
    doc = sess.usage.doc()
    assert list(doc["by_tenant"]) == ["default"]
    assert doc["by_tenant"]["default"]["device_ns"] \
        == doc["device_ns_total"] > 0
    assert doc["by_tenant"]["default"]["requests"]["ok"] == 2


# ---------------------------------------------------------------------------
# Capacity model against the live session.
# ---------------------------------------------------------------------------


def test_capacity_after_serving(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        plan=slow_plan(), clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        for rid in ("w", "r"):
            assert svc.submit({"id": rid, "left": pair[0],
                               "right": pair[1]}).result(timeout=120)[
                                   "status"] == "ok"
        status = svc.status()
    capdoc = status["capacity"]
    m = capdoc["by_bucket"]["64x64"]
    # warmed EMAs exist for prepare/advance/epilogue at b=1: with TICK
    # injected per invocation, one request costs prepare + 2 advances +
    # epilogue = 4 * TICK -> 1 request/s.
    assert m["rps"] == pytest.approx(1.0)
    assert m["mode"] == "batched" and not m["partial"]
    sat = capdoc["saturation"]
    assert sat is not None and sat["ratio"] > 0
    # headroom gauge published: rps * (1 - saturation)
    assert m["headroom_rps"] == pytest.approx(
        m["rps"] * max(0.0, 1.0 - sat["ratio"]))
    assert sess.registry.value("raft_capacity_headroom",
                               bucket="64x64") == m["headroom_rps"]
    assert sess.registry.value(
        "raft_capacity_saturation") == sat["ratio"]


# ---------------------------------------------------------------------------
# Build info (the scrape-identity satellite).
# ---------------------------------------------------------------------------


def test_build_info_gauges(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg)
    text = sess.registry.render_prometheus()
    assert "# TYPE raft_build_info gauge" in text
    series = sess.registry.series("raft_build_info")
    assert len(series) == 1
    labels, value = series[0]
    assert value == 1.0
    assert labels["fingerprint"] == sess.fingerprint_id()
    assert labels["backend"] == "cpu"
    assert labels["jax"] and labels["python"]
    assert sess.registry.value("raft_process_start_time_seconds") > 0


# ---------------------------------------------------------------------------
# Debug introspection: the injected-hang acceptance + live endpoints.
# ---------------------------------------------------------------------------


def test_stacks_name_injected_hang_parked_frame(tiny_params, tiny_cfg,
                                                pair):
    """During an injected device hang, the dump names the parked
    invocation frame (faults.py on_invoke inside the watch bracket)."""
    clock = FakeClock()
    plan = ChaosPlan(hang_invokes={1: 5.0}, hang_cap_s=30.0)
    sess = make_session(tiny_params, tiny_cfg, plan=plan, clock=clock)
    sess.infer(pair[0][None], pair[1][None])  # ordinal 0: warms, no hang
    done = {}

    def victim():
        done["resp"] = sess.infer(pair[0][None], pair[1][None])

    t = threading.Thread(target=victim, name="hang-victim", daemon=True)
    t.start()
    assert sess.faults.wait_hang_entered(1, timeout=30)
    doc = thread_stacks()
    parked = [th for th in doc["threads"]
              if any(f["function"] == "on_invoke"
                     and f["file"].endswith("faults.py")
                     for f in th["frames"])]
    assert parked, [th["name"] for th in doc["threads"]]
    assert parked[0]["name"] == "hang-victim"
    # the watchdog's view agrees: the invocation is registered in-flight
    assert sess.watch.count == 1
    sess.faults.release_hangs()
    t.join(timeout=60)
    assert done["resp"].quality == "full"


@pytest.fixture(scope="module")
def live_frontend(tiny_params, tiny_cfg):
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=2,
                      canary=False))
    svc = StereoService(session, ServiceConfig(max_queue=8)).start()
    with HttpFrontend(svc, HttpConfig(port=0)) as fe:
        # one real wire request so every surface has content
        rng = np.random.default_rng(0)
        left = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
        right = rng.uniform(0, 255, (H, W, 3)).astype(np.uint8)
        ct, body = wire.build_multipart(
            {"left": wire.encode_image_png(left),
             "right": wire.encode_image_png(right), "id": b"d-0"})
        req = urllib.request.Request(
            f"http://{fe.host}:{fe.port}/v1/stereo", data=body,
            method="POST", headers={"Content-Type": ct,
                                    "X-Raft-Tenant": "deck-tenant"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
        yield fe
    svc.stop()


def _debug_get(fe, path):
    with urllib.request.urlopen(
            f"http://{fe.host}:{fe.port}{path}", timeout=30) as r:
        return r.status, r.read()


def test_debug_ticks_endpoint(live_frontend):
    status, raw = _debug_get(live_frontend, "/debug/ticks")
    assert status == 200
    doc = json.loads(raw)
    assert doc["schema"] == 1 and doc["ticks"]
    assert len(doc["ticks"]) <= doc["ring"]
    assert {"seq", "kind", "t_start", "device_s", "warm_s",
            "bucket"} <= set(doc["ticks"][0])
    _, raw = _debug_get(live_frontend, "/debug/ticks?n=1")
    assert len(json.loads(raw)["ticks"]) == 1
    # hostile ?n= is ignored, never a 500
    status, _ = _debug_get(live_frontend, "/debug/ticks?n=lots")
    assert status == 200


def test_debug_usage_endpoint(live_frontend):
    status, raw = _debug_get(live_frontend, "/debug/usage")
    assert status == 200
    doc = json.loads(raw)
    assert "deck-tenant" in doc["by_tenant"]
    row = doc["by_tenant"]["deck-tenant"]
    assert row["bytes_in"] > 0 and row["bytes_out"] > 0
    assert row["requests"].get("ok") == 1
    assert sum(t["device_ns"] for t in doc["by_tenant"].values()) \
        == doc["device_ns_total"]


def test_debug_stacks_endpoint(live_frontend):
    status, raw = _debug_get(live_frontend, "/debug/stacks")
    assert status == 200
    doc = json.loads(raw)
    names = {t["name"] for t in doc["threads"]}
    assert any(n and "http-listener" in n for n in names), names
    assert all(len(t["frames"]) <= 32 for t in doc["threads"])
    assert len(raw) < (1 << 20)  # bounded by construction


def test_debug_config_endpoint(live_frontend):
    status, raw = _debug_get(live_frontend, "/debug/config")
    assert status == 200
    doc = json.loads(raw)
    for key in ("fingerprint", "session_cfg", "service_cfg", "ingress",
                "env_knobs", "breaker", "batch_buckets", "programs",
                "deck"):
        assert key in doc, key
    assert doc["session_cfg"]["max_batch"] == 2
    assert doc["service_cfg"]["max_queue"] == 8
    assert all(p["id"] for p in doc["programs"])
    # the resolved knob snapshot covers the kernel switch set
    assert "RAFT_CORR_TILE" in doc["env_knobs"]


def test_debug_endpoints_ride_the_same_defenses(live_frontend):
    """POST to a debug route is 405 (not a crash, not a 404), HEAD is
    the header-only twin, and every response is counted."""
    fe = live_frontend
    req = urllib.request.Request(
        f"http://{fe.host}:{fe.port}/debug/ticks", data=b"",
        method="POST")
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=30)
    assert exc.value.code == 405
    head = urllib.request.Request(
        f"http://{fe.host}:{fe.port}/debug/usage", method="HEAD")
    with urllib.request.urlopen(head, timeout=30) as r:
        assert r.status == 200 and r.read() == b""
    for code in ("debug_ticks", "debug_usage", "debug_stacks",
                 "debug_config"):
        assert fe.registry.value("raft_http_responses_total",
                                 status="200", code=code) >= 1

"""Multi-host distributed training, actually executed.

Spawns TWO OS processes that form a real jax.distributed pod over TCP
(Gloo), 4 virtual CPU devices each (global mesh = 8). Both run the same
``train()``; the deterministic per-sample loader RNG means each host
materializes the same global batch and ``device_put`` keeps only its
addressable shards. This executes the code paths the single-process suite
can only reason about: ``maybe_distributed_init``'s explicit topology,
pod-spanning mesh construction, lead-only checkpoint/log writes, and the
pod-wide preemption agreement (SIGTERM lands on the NON-lead process; the
per-step flag allgather must stop both at the same step).

Slow: two fresh processes compile the tiny train step (~4 min each,
serialized on a one-core host).
"""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tests.test_eval_engine import _tiny_things_tree

pytestmark = pytest.mark.slow

REPO = str(Path(__file__).resolve().parent.parent)

CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.engine.train import train

# Count decoded samples: with the process-sharded input pipeline each
# process must touch ONLY its global-batch rows (half the decode work).
import raft_stereo_tpu.engine.train as T
_orig_fetch = T.fetch_dataloader
decoded = []
def counting_fetch(tcfg, root=None, local_rows=None):
    loader = _orig_fetch(tcfg, root=root, local_rows=local_rows)
    ds = loader.dataset
    orig_get = ds.__getitem__
    def counted(i, rng=None):
        decoded.append(i)
        return orig_get(i, rng=rng)
    ds.__getitem__ = counted
    return loader
T.fetch_dataloader = counting_fetch

cfg = RAFTStereoConfig(hidden_dims=(32, 32, 32), corr_levels=2, corr_radius=2)
tcfg = TrainConfig(name="mh", batch_size=8, image_size=(32, 48),
                   num_steps={num_steps}, train_iters=2,
                   ckpt_every={ckpt_every}, num_workers=1,
                   spatial_scale=(-0.2, 0.4))
os.chdir({workdir!r})
train(cfg, tcfg, data_root={root!r}, validate=False)
print("RAFT_MH_DECODED", os.environ["PROCESS_ID"], len(decoded),
      "steps", {num_steps}, "batch", tcfg.batch_size)
print("RAFT_MH_DONE", os.environ["PROCESS_ID"], jax.process_count(),
      len(jax.devices()))
"""


def _spawn_pod(tmp_path, root, num_steps, ckpt_every):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "COORDINATOR_ADDRESS": f"localhost:{port}",
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(pid),
        })
        # NO persistent compilation cache here: XLA:CPU AOT cache entries
        # record compile-machine features that can mismatch at load time in
        # this image ("+prefer-no-scatter is not supported on the host
        # machine ... SIGILL"), crashing one task mid-step and failing the
        # pod's shutdown barrier. Fresh compiles are slower but correct.
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        wd = str(tmp_path / f"proc{pid}")
        os.makedirs(wd, exist_ok=True)
        code = CHILD.format(repo=REPO, root=root, workdir=wd,
                            num_steps=num_steps, ckpt_every=ckpt_every)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    return procs


def _finish(procs):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    finally:
        for p in procs:  # never leak live training children on failure
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs), \
        "\n".join(o[-2000:] for o in outs)
    for i, out in enumerate(outs):
        assert f"RAFT_MH_DONE {i} 2 8" in out, out[-2000:]
    return outs


def _ckpts(tmp_path, pid):
    d = tmp_path / f"proc{pid}" / "checkpoints"
    return sorted(os.listdir(d)) if d.is_dir() else []


def test_two_process_pod_trains_and_lead_writes(tmp_path):
    root = _tiny_things_tree(tmp_path)
    procs = _spawn_pod(tmp_path, root, num_steps=3, ckpt_every=100)
    outs = _finish(procs)
    assert "mh.msgpack" in _ckpts(tmp_path, 0)  # lead wrote the final state
    assert _ckpts(tmp_path, 1) == []            # non-lead wrote nothing
    # Process-sharded input: each process decodes only ITS half of every
    # global batch (4 of 8 rows) — 3 consumed steps + up to 2 prefetched
    # batches, never the full-batch 24-40 a replicated loader would touch.
    for i, out in enumerate(outs):
        line = next(l for l in out.splitlines()
                    if l.startswith(f"RAFT_MH_DECODED {i} "))
        n = int(line.split()[2])
        assert 12 <= n <= 20, line


def test_preemption_of_one_process_stops_the_pod(tmp_path):
    """SIGTERM only the NON-lead; the allgather must stop both processes at
    the same step and the lead must save a preempt (not final) checkpoint."""
    root = _tiny_things_tree(tmp_path)
    procs = _spawn_pod(tmp_path, root, num_steps=500, ckpt_every=1)
    try:
        # Deterministic signal point: wait until the lead has checkpointed
        # step 1 (training is provably past compile and the SIGTERM handler
        # installed).
        sentinel = tmp_path / "proc0" / "checkpoints" / "1_mh.msgpack"
        deadline = time.time() + 600
        while not sentinel.exists():
            assert time.time() < deadline, "pod never reached step 1"
            assert all(p.poll() is None for p in procs), \
                "a process died early"
            time.sleep(2)
        procs[1].send_signal(signal.SIGTERM)
    except BaseException:
        for p in procs:
            p.kill()
        raise
    _finish(procs)
    lead = _ckpts(tmp_path, 0)
    assert any("_preempt_" in f for f in lead), lead
    assert "mh.msgpack" not in lead  # preempted ≠ finished
    assert _ckpts(tmp_path, 1) == []

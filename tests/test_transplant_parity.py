"""Golden parity: transplanted weights must reproduce the torch reference.

The reference implementation mounted at /root/reference is imported (read-only)
purely as a numerical oracle; with shared random weights the transplanted JAX
model must match its forward pass. This is the SURVEY.md §7 step-4 gate.
Skipped automatically when the reference checkout is unavailable.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import raft_stereo_forward
from raft_stereo_tpu.models.extractor import apply_basic_encoder, apply_multi_basic_encoder
from raft_stereo_tpu.transplant import transplant_state_dict

REFERENCE = Path("/root/reference")

pytestmark = pytest.mark.skipif(not (REFERENCE / "core").is_dir(),
                                reason="reference checkout not available")


def _make_reference_model(**overrides):
    import argparse
    import torch
    if str(REFERENCE) not in sys.path:
        sys.path.insert(0, str(REFERENCE))
    from core.raft_stereo import RAFTStereo
    defaults = dict(corr_implementation="reg", shared_backbone=False,
                    corr_levels=4, corr_radius=4, n_downsample=2,
                    slow_fast_gru=False, n_gru_layers=3,
                    hidden_dims=[128, 128, 128], mixed_precision=False)
    defaults.update(overrides)
    torch.manual_seed(1234)
    model = RAFTStereo(argparse.Namespace(**defaults))
    model.eval()
    return model, RAFTStereoConfig(**overrides)


def _images(rng, h=64, w=96, b=1):
    import torch
    img1 = rng.uniform(0, 255, size=(b, 3, h, w)).astype(np.float32)
    img2 = rng.uniform(0, 255, size=(b, 3, h, w)).astype(np.float32)
    return (torch.from_numpy(img1), torch.from_numpy(img2),
            jnp.asarray(img1.transpose(0, 2, 3, 1)),
            jnp.asarray(img2.transpose(0, 2, 3, 1)))


def test_parameter_count_matches_reference():
    model, cfg = _make_reference_model()
    ref_count = sum(p.numel() for p in model.parameters() if p.requires_grad)
    params = transplant_state_dict(model.state_dict(), cfg)
    # Frozen-BN running stats are buffers in torch (not counted); exclude the
    # matching leaves here: each batch-norm dict contributes mean/var extras.
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    ours = sum(int(np.prod(l.shape)) for path, l in leaves
               if path[-1].key not in ("mean", "var"))
    assert ours == ref_count


def test_fnet_parity(rng):
    import torch
    model, cfg = _make_reference_model()
    t1, t2, j1, j2 = _images(rng)
    params = transplant_state_dict(model.state_dict(), cfg)
    with torch.no_grad():
        fmap1, fmap2 = model.fnet([2 * (t1 / 255.0) - 1.0, 2 * (t2 / 255.0) - 1.0])
    ours = apply_basic_encoder(
        params["fnet"], jnp.concatenate([2 * (j1 / 255.0) - 1.0,
                                         2 * (j2 / 255.0) - 1.0], axis=0),
        norm_fn="instance", downsample=cfg.n_downsample)
    ref = np.concatenate([fmap1.numpy(), fmap2.numpy()], axis=0).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=5e-4)


def test_cnet_parity(rng):
    import torch
    model, cfg = _make_reference_model()
    t1, _, j1, _ = _images(rng)
    params = transplant_state_dict(model.state_dict(), cfg)
    with torch.no_grad():
        ref_list = model.cnet(2 * (t1 / 255.0) - 1.0, num_layers=3)
    ours = apply_multi_basic_encoder(params["cnet"], 2 * (j1 / 255.0) - 1.0,
                                     norm_fn="batch", downsample=cfg.n_downsample,
                                     num_layers=3)
    for scale, (ref_pair, our_pair) in enumerate(zip(ref_list, ours)):
        for branch, (r, o) in enumerate(zip(ref_pair, our_pair)):
            np.testing.assert_allclose(
                np.asarray(o), r.numpy().transpose(0, 2, 3, 1), atol=5e-4,
                err_msg=f"scale {scale} branch {branch}")


@pytest.mark.parametrize("overrides", [
    dict(),
    dict(n_gru_layers=2),
    dict(shared_backbone=True, n_downsample=3, n_gru_layers=2, slow_fast_gru=True),
])
def test_full_forward_parity(rng, overrides):
    import torch
    model, cfg = _make_reference_model(**overrides)
    # W=128: the reference builds one unused extra pyramid level
    # (core/corr.py:122-125) and crashes if W/2^(n_downsample+4) rounds to 0.
    t1, t2, j1, j2 = _images(rng, w=128)
    params = transplant_state_dict(model.state_dict(), cfg)
    iters = 8
    with torch.no_grad():
        flow_lr_ref, flow_up_ref = model(t1, t2, iters=iters, test_mode=True)
    flow_lr, flow_up = raft_stereo_forward(params, cfg, j1, j2, iters=iters,
                                           test_mode=True)
    # fp noise (~1e-6/step between frameworks) is amplified by 8 recurrent
    # iterations of saturating nonlinearities; 1e-2 px on random weights is
    # far below any metric-visible difference.
    np.testing.assert_allclose(np.asarray(flow_up),
                               flow_up_ref.numpy().transpose(0, 2, 3, 1),
                               atol=1e-2)
    np.testing.assert_allclose(np.asarray(flow_lr),
                               flow_lr_ref.numpy().transpose(0, 2, 3, 1),
                               atol=1e-2)


def test_train_mode_prediction_list_parity(rng):
    import torch
    model, cfg = _make_reference_model()
    t1, t2, j1, j2 = _images(rng)
    params = transplant_state_dict(model.state_dict(), cfg)
    with torch.no_grad():
        ref_preds = model(t1, t2, iters=3, test_mode=False)
    preds = raft_stereo_forward(params, cfg, j1, j2, iters=3)
    assert len(ref_preds) == preds.shape[0] == 3
    for i in range(3):
        np.testing.assert_allclose(np.asarray(preds[i]),
                                   ref_preds[i].numpy().transpose(0, 2, 3, 1),
                                   atol=5e-3, err_msg=f"iteration {i}")


def test_reverse_transplant_round_trip():
    """params -> state_dict -> strict torch load must reproduce every tensor
    (VERDICT r2 item 7: checkpoints trained here must feed the torch
    ecosystem the reference's consumers expect)."""
    import torch
    from raft_stereo_tpu.transplant import export_state_dict
    model, cfg = _make_reference_model()
    ref_sd = model.state_dict()
    params = transplant_state_dict(ref_sd, cfg)
    out = export_state_dict(params, cfg, module_prefix=False)
    assert set(out) == set(ref_sd)
    for k, v in out.items():
        if k.endswith("num_batches_tracked"):
            continue  # counter value is unused in (always-frozen) eval BN
        np.testing.assert_array_equal(v, ref_sd[k].numpy(), err_msg=k)
    # Strict load back into the reference model (the real consumer check).
    model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v))
                           for k, v in out.items()}, strict=True)
    # And the module-prefixed spelling matches the reference's on-disk
    # checkpoints (saved DataParallel-wrapped, train_stereo.py:184).
    pref = export_state_dict(params, cfg)
    assert all(k.startswith("module.") for k in pref)
